"""Batched NIST P-256 ECDSA verification on TPU.

The reference verifies every transaction input serially through fastecdsa's
C extension (transaction_input.py:100-109, called per input inside the block
accept hot loop manager.py:628-632).  Here the whole block's signatures are
verified in ONE jitted program: a fixed-window (w = 4) Strauss double-scalar
ladder u₁·G + u₂·Q over *complete* projective addition formulas
(Renes–Costello–Batina 2016, Algorithm 4, a = −3), batched across the lane
axis in 13-bit-limb lazy Montgomery arithmetic (:mod:`.fp`).

The window structure: 64 iterations, each doing 4 doublings plus one add
from a host-precomputed 16-entry G table (constants) and one add from an
on-device 16-entry Q table (14 setup adds per batch) — 6 complete adds per
4 scalar bits versus 12 for the bit-serial ladder.  Window digits are
extracted on the host (u₁/u₂ are host bigints already) and shipped as
(64, N) int32 arrays, MSB-digit first.

Complete formulas are the consensus-safety choice: they are correct for
EVERY input pair — identity, doubling, inverses — so adversarial signatures
cannot steer the ladder into an exceptional case and flip a verdict.

The final check avoids field inversion entirely: with R = (X : Y : Z),
x = X/Z, and accept ⇔ x mod n == r ⇔ X ≡ r·Z or X ≡ (r+n)·Z (mod p)
(valid because p < 2n on P-256).  Both are Montgomery products followed by
one exact canonical reduction (:func:`fp.is_zero_mod_p`).

Scalar prep (s⁻¹ mod n, u₁, u₂, range checks, on-curve checks) stays on the
host: per-signature Python bigint work is ~µs and latency-insensitive.
"""

from __future__ import annotations

import functools
import hashlib
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.constants import CURVE_B, CURVE_GX, CURVE_GY, CURVE_N, CURVE_P
from ..core.codecs import is_on_curve
from . import fp
from .fp import FE

_FS = fp.make_field(CURVE_P)
_B_M = fp.to_mont(CURVE_B, _FS)
_GX_M = fp.to_mont(CURVE_GX, _FS)
_GY_M = fp.to_mont(CURVE_GY, _FS)
_ONE_M = _FS.r_mod_p

# Loop-invariant value bound for ladder point coordinates: the complete-add
# output coords are (sub of two ≤3p products) / (add of two) — ≤ 7p; the
# static bound tracking in fp asserts this at trace time.
_COORD_BOUND = 8 * CURVE_P

Proj = Tuple[FE, FE, FE]  # (X, Y, Z), Montgomery domain


def _point_add_complete(P1: Proj, P2: Proj, b_m: FE) -> Proj:
    """RCB16 Algorithm 4: complete addition for a=-3, homogeneous projective.

    12 generic muls + 2 muls by curve-b; handles P1==P2, inverses and the
    identity (0:1:0) with no branches — a fixed straight-line program, which
    is exactly what XLA wants.
    """
    fs = _FS
    return _point_add_rcb16(
        P1, P2, b_m,
        mul=lambda x, y: fp.mont_mul(x, y, fs),
        add_=fp.add,
        sub_=lambda x, y: fp.sub(x, y, fs),
    )


def _point_add_complete_l(P1, P2, b_m):
    """Same RCB16 program over limb-list elements (Pallas kernel layout)."""
    fs = _FS
    return _point_add_rcb16(
        P1, P2, b_m,
        mul=lambda x, y: fp.l_mont_mul(x, y, fs),
        add_=fp.l_add,
        sub_=lambda x, y: fp.l_sub(x, y, fs),
    )


def _point_dbl_complete_l(P, b_m):
    """Limb-list doubling via :func:`_point_dbl_rcb16` (the layout the
    Pallas kernel runs in).  The stacked jnp path deliberately does NOT
    route doublings through a second program: ``_verify_device`` keeps a
    single scanned add site precisely to bound XLA:CPU compile time
    (see its docstring), and a dedicated doubling would double it."""
    fs = _FS
    return _point_dbl_rcb16(
        P, b_m,
        mul=lambda x, y: fp.l_mont_mul(x, y, fs),
        sqr=lambda x: fp.l_mont_sqr(x, fs),
        add_=fp.l_add,
        sub_=lambda x, y: fp.l_sub(x, y, fs),
    )


def _point_dbl_rcb16(P, b_m, mul, sqr, add_, sub_):
    """Doubling through the SAME RCB16 Algorithm-4 sequence as
    :func:`_point_add_rcb16` with the six same-operand products routed to
    the Montgomery square (~40% cheaper MAC count each).  Not a different
    formula — completeness and the bound discipline carry over verbatim
    from the addition program."""
    X1, Y1, Z1 = P

    t0 = sqr(X1)            # X1·X2
    t1 = sqr(Y1)            # Y1·Y2
    t2 = sqr(Z1)            # Z1·Z2
    t3 = add_(X1, Y1)
    t3 = sqr(t3)            # (X1+Y1)·(X2+Y2)
    t4 = add_(t0, t1)
    t3 = sub_(t3, t4)
    t4 = add_(Y1, Z1)
    t4 = sqr(t4)            # (Y1+Z1)·(Y2+Z2)
    X3 = add_(t1, t2)
    t4 = sub_(t4, X3)
    X3 = add_(X1, Z1)
    X3 = sqr(X3)            # (X1+Z1)·(X2+Z2)
    Y3 = add_(t0, t2)
    Y3 = sub_(X3, Y3)
    Z3 = mul(b_m, t2)
    X3 = sub_(Y3, Z3)
    Z3 = add_(X3, X3)
    X3 = add_(X3, Z3)
    Z3 = sub_(t1, X3)
    X3 = add_(t1, X3)
    Y3 = mul(b_m, Y3)
    t1 = add_(t2, t2)
    t2 = add_(t1, t2)
    Y3 = sub_(Y3, t2)
    Y3 = sub_(Y3, t0)
    t1 = add_(Y3, Y3)
    Y3 = add_(t1, Y3)
    t1 = add_(t0, t0)
    t0 = add_(t1, t0)
    t0 = sub_(t0, t2)
    t1 = mul(t4, Y3)
    t2 = mul(t0, Y3)
    Y3 = mul(X3, Z3)
    Y3 = add_(Y3, t2)
    t2 = mul(t3, X3)
    X3 = sub_(t2, t1)
    t2 = mul(t4, Z3)
    t1 = mul(t3, t0)
    Z3 = add_(t2, t1)
    return (X3, Y3, Z3)


def _point_add_rcb16(P1, P2, b_m, mul, add_, sub_):
    X1, Y1, Z1 = P1
    X2, Y2, Z2 = P2

    t0 = mul(X1, X2)
    t1 = mul(Y1, Y2)
    t2 = mul(Z1, Z2)
    t3 = add_(X1, Y1)
    t4 = add_(X2, Y2)
    t3 = mul(t3, t4)
    t4 = add_(t0, t1)
    t3 = sub_(t3, t4)
    t4 = add_(Y1, Z1)
    X3 = add_(Y2, Z2)
    t4 = mul(t4, X3)
    X3 = add_(t1, t2)
    t4 = sub_(t4, X3)
    X3 = add_(X1, Z1)
    Y3 = add_(X2, Z2)
    X3 = mul(X3, Y3)
    Y3 = add_(t0, t2)
    Y3 = sub_(X3, Y3)
    Z3 = mul(b_m, t2)
    X3 = sub_(Y3, Z3)
    Z3 = add_(X3, X3)
    X3 = add_(X3, Z3)
    Z3 = sub_(t1, X3)
    X3 = add_(t1, X3)
    Y3 = mul(b_m, Y3)
    t1 = add_(t2, t2)
    t2 = add_(t1, t2)
    Y3 = sub_(Y3, t2)
    Y3 = sub_(Y3, t0)
    t1 = add_(Y3, Y3)
    Y3 = add_(t1, Y3)
    t1 = add_(t0, t0)
    t0 = add_(t1, t0)
    t0 = sub_(t0, t2)
    t1 = mul(t4, Y3)
    t2 = mul(t0, Y3)
    Y3 = mul(X3, Z3)
    Y3 = add_(Y3, t2)
    t2 = mul(t3, X3)
    X3 = sub_(t2, t1)
    t2 = mul(t4, Z3)
    t1 = mul(t3, t0)
    Z3 = add_(t2, t1)
    return (X3, Y3, Z3)


def _select_point(cond, a: Proj, b: Proj) -> Proj:
    return tuple(fp.select(cond, a[i], b[i]) for i in range(3))  # type: ignore


def _clamp_point(P: Proj) -> Proj:
    """Re-declare coords at the loop-invariant bound (trace-time assert)."""
    for c in P:
        assert c.bound <= _COORD_BOUND, c.bound
    return tuple(fp.wrap(c.arr, _COORD_BOUND) for c in P)  # type: ignore


_WINDOW = 4
_DIGITS = 256 // _WINDOW  # 64 ladder iterations


def _scalar_digits(xs: Sequence[int]) -> np.ndarray:
    """Host bigints -> (64, N) int32 w=4 window digits, MSB digit first.

    Vectorized via per-int ``to_bytes`` + one numpy nibble split (the
    per-digit Python loop was ~0.3 s per 8k batch)."""
    n = len(xs)
    if n == 0:
        return np.zeros((_DIGITS, 0), dtype=np.int32)
    raw = b"".join(x.to_bytes(32, "little") for x in xs)
    by = np.frombuffer(raw, dtype=np.uint8).reshape(n, 32).astype(np.int32)
    nibbles = np.empty((n, 64), dtype=np.int32)  # nibble k = (x >> 4k) & 0xF
    nibbles[:, 0::2] = by & 0xF
    nibbles[:, 1::2] = by >> 4
    return np.ascontiguousarray(nibbles[:, ::-1].T)  # MSB digit first


def _g_window_table() -> np.ndarray:
    """(3, 16, 21) int32 — Montgomery projective [k]G for k in 0..15.

    Entry 0 is the identity (0 : 1 : 0); complete addition makes adding it
    a no-op, so zero digits need no branch."""
    from ..core import curve as host_curve

    rows = np.zeros((3, 16, fp.NUM_LIMBS), dtype=np.int32)
    rows[1, 0] = fp.int_to_limbs(_ONE_M)  # identity: (0, R mod p, 0)
    for k in range(1, 16):
        x, y = host_curve.point_mul(k, (CURVE_GX, CURVE_GY))
        rows[0, k] = fp.int_to_limbs(fp.to_mont(x, _FS))
        rows[1, k] = fp.int_to_limbs(fp.to_mont(y, _FS))
        rows[2, k] = fp.int_to_limbs(_ONE_M)
    return rows


_G_TABLE = _g_window_table()

# --- device-side scalar prep ----------------------------------------------
# The per-signature host work (s⁻¹ mod n via pow, u₁/u₂, Montgomery
# conversions, on-curve check, window-digit extraction) costs ~1 s of
# Python bigint time per 8k batch — 5x the ladder kernel itself.  This
# program does all of it on-device from raw little-endian limbs; the host
# only unpacks bytes (numpy) and checks scalar ranges.

_NS = fp.make_field(CURVE_N)
_SCALAR_BOUND = 4 * CURVE_N  # stable lazy bound for the mod-n mul chain
_INV_DIGITS = np.array(  # w=4 digits of n-2, MSB first (fixed exponent)
    [((CURVE_N - 2) >> (_WINDOW * (_DIGITS - 1 - k))) & 0xF
     for k in range(_DIGITS)], dtype=np.int32)


def _mod_n_inv_mont(s_m: FE) -> FE:
    """s_m (Montgomery domain mod n) -> s⁻¹ in Montgomery domain, via
    Fermat x^(n-2) with a 4-bit fixed window: 15-entry table (14 muls)
    then 64 scanned steps of 4 squarings + one table mul (~334 muls —
    ~6% of the ladder's budget)."""
    ns = _NS
    n_lanes = s_m.arr.shape[1]
    one_m = fp.const(ns.r_mod_p, n_lanes, _SCALAR_BOUND)
    table = [one_m.arr, s_m.arr]
    for _ in range(14):
        table.append(fp.mont_mul(fp.wrap(table[-1], _SCALAR_BOUND), s_m, ns).arr)
    table = jnp.stack(table)  # (16, 21, N)

    def step(acc, digit):
        x = fp.wrap(acc, _SCALAR_BOUND)
        for _ in range(_WINDOW):
            x = fp.mont_mul(x, x, ns)
        oh = jax.nn.one_hot(digit, 16, dtype=jnp.int32)  # (16,)
        pick = fp.wrap((oh[:, None, None] * table).sum(axis=0), _SCALAR_BOUND)
        return fp.mont_mul(x, pick, ns).arr, None

    out, _ = jax.lax.scan(step, one_m.arr, jnp.asarray(_INV_DIGITS))
    return fp.wrap(out, _SCALAR_BOUND)


def _words_to_limbs(w) -> jnp.ndarray:
    """(8, N) uint32 little-endian words -> (21, N) int32 13-bit limbs.

    The host ships 256-bit scalars as 32 raw bytes instead of 84 bytes
    of pre-split limbs (2.6x less host->device transfer on the tunneled
    chip); the split is ~4 static shift/mask ops per limb here."""
    lb = fp.LIMB_BITS
    rows = []
    for j in range(fp.NUM_LIMBS):
        lo_bit = lb * j
        a, r = divmod(lo_bit, 32)
        if a >= 8:
            rows.append(jnp.zeros_like(w[0], dtype=jnp.int32))
            continue
        v = w[a] >> jnp.uint32(r)
        if r + lb > 32 and a + 1 < 8:
            v = v | (w[a + 1] << jnp.uint32(32 - r))
        rows.append((v & jnp.uint32(fp.LIMB_MASK)).astype(jnp.int32))
    return jnp.stack(rows, axis=0)


def _pack_words(xs, pad: int) -> np.ndarray:
    """Host ints (< 2^256) -> (8, N+pad) uint32 little-endian words."""
    n = len(xs)
    raw = b"".join(x.to_bytes(32, "little") for x in xs)
    w = np.frombuffer(raw, dtype="<u4").reshape(n, 8).T
    return np.pad(w, ((0, 0), (0, pad)), constant_values=0)


def _digits_from_limbs(limbs, w: int = _WINDOW) -> jnp.ndarray:
    """(21, N) canonical 13-bit limbs -> (rounds, N) w-bit digits, MSB
    first.  Static bit surgery: a digit spans at most two limbs for any
    w <= 13; the top digit of an uneven split reads zero high bits."""
    lb = fp.LIMB_BITS
    mask = (1 << w) - 1
    rows = []
    for k in range(_jac_rounds(w)):
        j, off = divmod(w * k, lb)
        v = limbs[j] >> off
        if off + w > lb and j + 1 < fp.NUM_LIMBS:
            v = v | (limbs[j + 1] << (lb - off))
        rows.append(v & mask)
    return jnp.stack(rows[::-1], axis=0)


@functools.partial(jax.jit, static_argnames=("w",))
def _scalar_prep(z, r, s, qx, qy, range_ok, rn_ok, w: int = _WINDOW):
    """Packed 256-bit scalars -> ladder inputs, all on device.

    z/r/s/qx/qy: (8, N) uint32 little-endian words of the digest int,
    signature pair and affine pubkey (values < 2^256, unreduced; see
    :func:`_pack_words`).  range_ok: host-checked 0 < r,s < n and
    (qx,qy) != (0,0).  rn_ok: r + n < p.

    Returns (d1, d2, qx_m, qy_m, r_mp, rn_mp, flags) matching the ladder
    kernel's operands: canonical Montgomery limbs + (2, N) int32 flags.
    """
    fs, ns = _FS, _NS
    z, r, s, qx, qy = (_words_to_limbs(x) for x in (z, r, s, qx, qy))
    n_lanes = z.shape[1]
    raw = 1 << 256  # bound of any 256-bit input

    # mod-n: w = s^-1, u1 = z·w, u2 = r·w  (Montgomery domain throughout)
    r2n = fp.const(ns.r2_mod_p, n_lanes, ns.p)
    s_m = fp.mont_mul(fp.wrap(s, raw), r2n, ns)
    w_m = _mod_n_inv_mont(fp.wrap(s_m.arr, _SCALAR_BOUND))
    z_m = fp.mont_mul(fp.wrap(z, raw), r2n, ns)
    r_mn = fp.mont_mul(fp.wrap(r, raw), r2n, ns)
    one = fp.const(1, n_lanes, 2)
    u1 = fp.canon(fp.mont_mul(fp.mont_mul(z_m, w_m, ns), one, ns), ns)
    u2 = fp.canon(fp.mont_mul(fp.mont_mul(r_mn, w_m, ns), one, ns), ns)
    d1 = _digits_from_limbs(u1, w)
    d2 = _digits_from_limbs(u2, w)

    # mod-p: Montgomery forms of qx, qy, r, (r+n) mod p + on-curve check
    r2p = fp.const(fs.r2_mod_p, n_lanes, fs.p)
    qx_m = fp.mont_mul(fp.wrap(qx, raw), r2p, fs)
    qy_m = fp.mont_mul(fp.wrap(qy, raw), r2p, fs)
    r_mp = fp.canon(fp.mont_mul(fp.wrap(r, raw), r2p, fs), fs)
    rn = fp.add(fp.wrap(r, raw), fp.const(CURVE_N, n_lanes, CURVE_N + 1))
    rn_mp = fp.canon(fp.mont_mul(rn, r2p, fs), fs)

    # y² == x³ - 3x + b  (all Montgomery domain)
    b_m = fp.const(_B_M, n_lanes, fs.p)
    y2 = fp.mont_mul(qy_m, qy_m, fs)
    x2 = fp.mont_mul(qx_m, qx_m, fs)
    x3 = fp.mont_mul(x2, qx_m, fs)
    three_x = fp.add(fp.add(qx_m, qx_m), qx_m)
    rhs = fp.add(fp.sub(x3, three_x, fs), b_m)
    on_curve = fp.is_zero_mod_p(fp.sub(y2, rhs, fs), fs)

    valid = range_ok & on_curve
    flags = jnp.stack([rn_ok.astype(jnp.int32), valid.astype(jnp.int32)])
    return (d1, d2, fp.canon(qx_m, fs), fp.canon(qy_m, fs), r_mp, rn_mp,
            flags)


@jax.jit
def _verify_device(d1, d2, qx, qy, r_m, rn_m, rn_ok, valid):
    """d1/d2: (64, N) int32 window digits (MSB first); qx/qy/r_m/rn_m:
    (21, N) int32 canonical Montgomery limbs; rn_ok/valid: (N,) bool.

    Returns (N,) bool accept verdicts.

    Compile-cost discipline: one traced complete-add costs XLA:CPU ~15 s
    to compile, so the whole program keeps exactly TWO add call-sites —
    one inside the Q-table ``scan`` and one inside the ladder's inner
    6-step ``scan`` (4 doublings + G-add + Q-add are the *same* site with
    the second operand selected by step index).  Cold compile lands in
    well under a minute; the persistent cache makes reruns instant.
    """
    fs = _FS
    n = qx.shape[1]
    p = fs.p
    b_m = fp.const(_B_M, n, p)
    Q: Proj = (fp.wrap(qx, p), fp.wrap(qy, p), fp.const(_ONE_M, n, p))
    identity: Proj = (fp.const(0, n, p), fp.const(_ONE_M, n, p), fp.const(0, n, p))

    def stack_point(P: Proj):
        return jnp.stack([c.arr for c in P], axis=0)  # (3, 21, N)

    def unstack_point(a, bound: int) -> Proj:
        return tuple(fp.wrap(a[i], bound) for i in range(3))  # type: ignore

    # --- Q window table: [k]Q for k=0..15, one scanned add site ----------
    def qstep(carry, _):
        P = unstack_point(carry, _COORD_BOUND)
        nxt = stack_point(_clamp_point(_point_add_complete(P, Q, b_m)))
        return nxt, nxt

    q1 = stack_point(_clamp_point(Q))
    _, q_rest = jax.lax.scan(qstep, q1, None, length=14)  # (14, 3, 21, N)
    q_table = jnp.concatenate(
        [stack_point(_clamp_point(identity))[None], q1[None], q_rest], axis=0
    )  # (16, 3, 21, N)
    g_table = jnp.asarray(_G_TABLE.transpose(1, 0, 2))  # (16, 3, 21)

    # --- ladder: 64 digit rounds × (4 dbl + G-add + Q-add), 1 add site ---
    def round_body(k, carry):
        dg1 = jax.lax.dynamic_index_in_dim(d1, k, axis=0, keepdims=False)
        dg2 = jax.lax.dynamic_index_in_dim(d2, k, axis=0, keepdims=False)
        # table picks as one-hot contractions, not gathers: a (16,N) one-hot
        # against the shared G table is a plain matmul, and the Q pick is a
        # regular masked reduction — both orders of magnitude faster on TPU
        # than per-lane gather + transpose of (N,3,21) blocks
        oh1 = jax.nn.one_hot(dg1, 16, dtype=jnp.int32, axis=0)  # (16, N)
        oh2 = jax.nn.one_hot(dg2, 16, dtype=jnp.int32, axis=0)
        g_pick = jnp.einsum("kcl,kn->cln", g_table, oh1)  # (3, 21, N)
        q_pick = (q_table * oh2[:, None, None, :]).sum(axis=0)  # (3, 21, N)

        def step(r_arrs, j):
            R = unstack_point(r_arrs, _COORD_BOUND)
            operand = jnp.where(j < 4, r_arrs, jnp.where(j == 4, g_pick, q_pick))
            P2 = unstack_point(operand, _COORD_BOUND)
            out = stack_point(_clamp_point(_point_add_complete(R, P2, b_m)))
            return out, None

        out, _ = jax.lax.scan(step, carry, jnp.arange(6))
        return out

    carry0 = stack_point(_clamp_point(identity))
    final = jax.lax.fori_loop(0, _DIGITS, round_body, carry0)
    Xa, Ya, Za = final[0], final[1], final[2]
    X = fp.wrap(Xa, _COORD_BOUND)
    Z = fp.wrap(Za, _COORD_BOUND)

    rz = fp.mont_mul(fp.wrap(r_m, p), Z, fs)
    rnz = fp.mont_mul(fp.wrap(rn_m, p), Z, fs)
    at_infinity = fp.is_zero_mod_p(Z, fs)
    ok = fp.is_zero_mod_p(fp.sub(X, rz, fs), fs) | (
        rn_ok & fp.is_zero_mod_p(fp.sub(X, rnz, fs), fs)
    )
    return ok & (~at_infinity) & valid


def _ladder_kernel(d1_ref, d2_ref, qx_ref, qy_ref, rm_ref, rnm_ref,
                   flags_ref, gtab_ref, out_ref, qtab_ref):
    """Pallas TPU kernel: the whole double-scalar ladder for one batch
    tile, with every intermediate in VMEM/registers.

    The jnp program (:func:`_verify_device`) is HBM-bound: each of its
    ~5.4k Montgomery muls round-trips a (42, N) working buffer through
    HBM (measured ~75 µs/mul at N=8192 — ~100x below VPU arithmetic
    peak).  Here the working set (ladder state, Q window table, mul
    temporaries) lives in VMEM for the kernel's lifetime, so the ladder
    runs at VPU speed.  Same math, same two-complete-adds structure.
    """
    fs = _FS
    tile = qx_ref.shape[1]
    p = fs.p
    b_m = fp.const(_B_M, tile, p)

    def stack_point(P):
        return jnp.stack([c.arr for c in P], axis=0)  # (3, 21, tile)

    def unstack_point(a, bound: int):
        return tuple(fp.wrap(a[i], bound) for i in range(3))

    Q = (fp.wrap(qx_ref[...], p), fp.wrap(qy_ref[...], p),
         fp.const(_ONE_M, tile, p))
    identity = (fp.const(0, tile, p), fp.const(_ONE_M, tile, p),
                fp.const(0, tile, p))

    # Q window table in VMEM scratch: [k]Q for k=0..15
    qtab_ref[0] = stack_point(_clamp_point(identity))
    qtab_ref[1] = stack_point(_clamp_point(Q))
    def qstep(k, prev):
        nxt = stack_point(_clamp_point(_point_add_complete(
            unstack_point(prev, _COORD_BOUND), Q, b_m)))
        qtab_ref[k] = nxt
        return nxt
    _ = jax.lax.fori_loop(1, 15, lambda k, prev: qstep(k + 1, prev),
                          qtab_ref[1])

    def pick(table_read, digit, entries: int = 16):
        """Masked-sum table pick: acc += (digit == k) * table[k]."""
        acc = jnp.zeros((3, fp.NUM_LIMBS, tile), dtype=jnp.int32)
        for k in range(entries):
            mask = (digit == k).astype(jnp.int32)[None, None, :]
            acc = acc + table_read(k) * mask
        return acc

    def round_body(k, carry):
        dg1 = d1_ref[k]  # (tile,) int32
        dg2 = d2_ref[k]

        def dbl(_, a):
            R = unstack_point(a, _COORD_BOUND)
            return stack_point(_clamp_point(_point_add_complete(R, R, b_m)))

        a = jax.lax.fori_loop(0, _WINDOW, dbl, carry)
        g_pick = pick(lambda i: gtab_ref[i][:, :, None], dg1)
        a = stack_point(_clamp_point(_point_add_complete(
            unstack_point(a, _COORD_BOUND),
            unstack_point(g_pick, p), b_m)))
        q_pick = pick(lambda i: qtab_ref[i], dg2)
        return stack_point(_clamp_point(_point_add_complete(
            unstack_point(a, _COORD_BOUND),
            unstack_point(q_pick, _COORD_BOUND), b_m)))

    carry0 = stack_point(_clamp_point(identity))
    final = jax.lax.fori_loop(0, _DIGITS, round_body, carry0)
    X = fp.wrap(final[0], _COORD_BOUND)
    Z = fp.wrap(final[2], _COORD_BOUND)

    rz = fp.mont_mul(fp.wrap(rm_ref[...], p), Z, fs)
    rnz = fp.mont_mul(fp.wrap(rnm_ref[...], p), Z, fs)
    at_infinity = fp.is_zero_mod_p(Z, fs)
    rn_ok = flags_ref[0] != 0
    valid = flags_ref[1] != 0
    ok = fp.is_zero_mod_p(fp.sub(X, rz, fs), fs) | (
        rn_ok & fp.is_zero_mod_p(fp.sub(X, rnz, fs), fs))
    out_ref[0] = (ok & (~at_infinity) & valid).astype(jnp.int32)


def _ladder_kernel_list(d1_ref, d2_ref, qx_ref, qy_ref, rm_ref, rnm_ref,
                        flags_ref, out_ref, qtab_ref):
    """Limb-list ladder kernel: every limb of every element is one full
    (S, 128) VMEM tile, and limb shifts inside the Montgomery multiply
    are Python indexing instead of the stacked layout's concatenates.

    Measured against :func:`_ladder_kernel` (stacked (L, N) layout): the
    stacked kernel spends ~2/3 of its time materializing shift
    concatenates; this layout removes them entirely, so every VPU op is
    a productive MAC on a full tile."""
    fs = _FS
    S = qx_ref.shape[1]  # sublane rows per tile (lanes = S * 128)
    shape = (S, 128)
    p = fs.p
    b_m = fp.l_const(_B_M, shape, p)

    def read_fl(ref, bound):
        return fp.l_wrap([ref[i] for i in range(fp.NUM_LIMBS)], bound)

    Q = (read_fl(qx_ref, p), read_fl(qy_ref, p),
         fp.l_const(_ONE_M, shape, p))
    identity = (fp.l_const(0, shape, p), fp.l_const(_ONE_M, shape, p),
                fp.l_const(0, shape, p))

    def clamp(P):
        for c in P:
            assert c.bound <= _COORD_BOUND, c.bound
        return tuple(fp.l_wrap(c.limbs, _COORD_BOUND) for c in P)

    def flatten(P):  # point -> nested tuple of arrays (fori_loop carry)
        return tuple(tuple(c.limbs) for c in P)

    def unflatten(t, bound=_COORD_BOUND):
        return tuple(fp.l_wrap(limbs, bound) for limbs in t)

    # --- Q window table in VMEM scratch: [k]Q for k = 0..15 --------------
    def store_entry(k, t):
        for c in range(3):
            for l in range(fp.NUM_LIMBS):
                qtab_ref[k, c, l] = t[c][l]

    store_entry(0, flatten(clamp(identity)))
    q1 = flatten(clamp(Q))
    store_entry(1, q1)

    def qstep(k, prev):
        nxt = flatten(clamp(_point_add_complete_l(unflatten(prev), Q, b_m)))
        store_entry(k + 1, nxt)
        return nxt

    _ = jax.lax.fori_loop(1, 15, qstep, q1)

    # --- 64 digit rounds x (4 dbl + G add + Q add) -----------------------
    def round_body(k, carry):
        dg1 = d1_ref[k]  # (S, 128) int32
        dg2 = d2_ref[k]

        def dbl(_, t):
            R = unflatten(t)
            return flatten(clamp(_point_dbl_complete_l(R, b_m)))

        a = jax.lax.fori_loop(0, _WINDOW, dbl, carry)

        masks1 = [(dg1 == kk).astype(jnp.int32) for kk in range(16)]
        masks2 = [(dg2 == kk).astype(jnp.int32) for kk in range(16)]

        # G pick: the table entries are compile-time scalars, so the pick
        # is a masked sum of constants with zero terms skipped
        g_pick = []
        for c in range(3):
            limbs = []
            for l in range(fp.NUM_LIMBS):
                acc = None
                for kk in range(16):
                    g = int(_G_TABLE[c, kk, l])
                    if g == 0:
                        continue
                    term = masks1[kk] * g
                    acc = term if acc is None else acc + term
                limbs.append(jnp.zeros(shape, jnp.int32) if acc is None
                             else acc)
            g_pick.append(fp.l_wrap(limbs, p))
        a = flatten(clamp(_point_add_complete_l(
            unflatten(a), tuple(g_pick), b_m)))

        # Q pick: masked sum over the VMEM table (static entry reads)
        q_pick = []
        for c in range(3):
            limbs = []
            for l in range(fp.NUM_LIMBS):
                acc = masks2[0] * qtab_ref[0, c, l]
                for kk in range(1, 16):
                    acc = acc + masks2[kk] * qtab_ref[kk, c, l]
                limbs.append(acc)
            q_pick.append(fp.l_wrap(limbs, _COORD_BOUND))
        return flatten(clamp(_point_add_complete_l(
            unflatten(a), tuple(q_pick), b_m)))

    carry0 = flatten(clamp(identity))
    final = jax.lax.fori_loop(0, _DIGITS, round_body, carry0)
    X, _, Z = unflatten(final)

    rz = fp.l_mont_mul(read_fl(rm_ref, p), Z, fs)
    rnz = fp.l_mont_mul(read_fl(rnm_ref, p), Z, fs)
    at_infinity = fp.l_is_zero_mod_p(Z, fs)
    rn_ok = flags_ref[0] != 0
    valid = flags_ref[1] != 0
    ok = fp.l_is_zero_mod_p(fp.l_sub(X, rz, fs), fs) | (
        rn_ok & fp.l_is_zero_mod_p(fp.l_sub(X, rnz, fs), fs))
    out_ref[...] = (ok & (~at_infinity) & valid).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_device_pallas(d1, d2, qx, qy, r_m, rn_m, flags,
                          tile: int = 1024, interpret: bool = False):
    """Run the limb-list ladder kernel over a (…, N) batch.

    ``tile`` = lanes per grid step, a multiple of 128 (the batch axis is
    reshaped to (rows, 128) so each limb is a full VPU tile)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = qx.shape[1]
    assert n % 128 == 0 and tile % 128 == 0 and n % tile == 0, (n, tile)
    rows, sub = n // 128, tile // 128
    grid = rows // sub

    def rs(x):  # (rows-major lane split)
        return x.reshape(x.shape[0], rows, 128)

    spec = lambda r: pl.BlockSpec(
        (r, sub, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _ladder_kernel_list,
        grid=(grid,),
        in_specs=[
            spec(_DIGITS), spec(_DIGITS),
            spec(fp.NUM_LIMBS), spec(fp.NUM_LIMBS),
            spec(fp.NUM_LIMBS), spec(fp.NUM_LIMBS),
            spec(2),
        ],
        out_specs=pl.BlockSpec((sub, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((16, 3, fp.NUM_LIMBS, sub, 128), jnp.int32)],
        interpret=interpret,
    )(rs(d1), rs(d2), rs(qx), rs(qy), rs(r_m), rs(rn_m), rs(flags))
    return out.reshape(n) != 0


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def _verify_device_pallas_stacked(d1, d2, qx, qy, r_m, rn_m, flags,
                                  tile: int = 256, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = qx.shape[1]
    assert n % tile == 0, (n, tile)
    grid = n // tile
    lane = lambda rows: pl.BlockSpec(
        (rows, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _ladder_kernel,
        grid=(grid,),
        in_specs=[
            lane(_DIGITS), lane(_DIGITS),
            lane(fp.NUM_LIMBS), lane(fp.NUM_LIMBS),
            lane(fp.NUM_LIMBS), lane(fp.NUM_LIMBS),
            lane(2),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # g_table, shared
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        scratch_shapes=[pltpu.VMEM((16, 3, fp.NUM_LIMBS, tile), jnp.int32)],
        interpret=interpret,
    )(d1, d2, qx, qy, r_m, rn_m, flags,
      jnp.asarray(_G_TABLE.transpose(1, 0, 2)))
    return out[0] != 0


# --- Jacobian ladder (the fast production kernel) --------------------------
# The RCB16 complete-addition ladder above is branch-free and safe for any
# input, but pays ~14 Montgomery products per add and 14 per doubling-as-
# addition.  Jacobian coordinates cut the per-round product count ~1.5x:
# doubling is 3M+5S (dbl-2001-b, a = -3), the G-add is a mixed affine add
# (madd-2007-bl, 7M+4S) and the Q-add a general add (add-2007-bl, 11M+5S).
#
# Jacobian formulas are NOT complete — they break when an operand is the
# identity or when P1 = ±P2.  Consensus safety is preserved structurally:
#
# * identity operands never reach the formulas: a zero window digit keeps
#   the accumulator (digit==0 mask select), and an all-zero-so-far scalar
#   prefix ("started" flag) replaces the result with the picked point;
#   the identity encoding (R, R, 0) is an exact fixed point of the
#   doubling program, so untouched lanes stay canonical through the 4
#   doublings per round;
# * the remaining exceptional case — H ≡ 0 with both operands real, i.e.
#   the accumulator colliding with ±(table pick) — sets a per-lane
#   EXCEPTION FLAG, and flagged lanes are re-verified on the host oracle
#   (:func:`_host_verify_prehashed`).  For honest signatures a collision
#   has probability ~2⁻²⁵⁰; a crafted signature can at worst force its
#   own lane onto the host path (one ~ms verify), never flip a verdict.
#
# Both sub-cases of H ≡ 0 are flagged (P1 = P2, which needs a doubling,
# and P1 = −P2, which yields the identity), so the ladder never has to
# distinguish them on device.

_JB = 64 * CURVE_P  # Jacobian ladder loop-invariant coordinate bound


def _jac_clamp(P):
    for c in P:
        assert c.bound <= _JB, c.bound.bit_length()
    return tuple(fp.l_wrap(c.limbs, _JB) for c in P)


def _jac_dbl(P, fs=_FS):
    """dbl-2001-b (a = -3): 3M + 5S.  Identity-safe: (X, Y, 0) maps to
    Z3 = (Y+0)² − Y² − 0 = 0, and the (R, R, 0) encoding is an exact
    fixed point (alpha = 3R, X3 = 9R − 8R = R, Y3 = 3R·3R − 8R = R)."""
    X, Y, Z = P
    delta = fp.l_mont_sqr(Z, fs)
    gamma = fp.l_mont_sqr(Y, fs)
    beta = fp.l_mont_mul(X, gamma, fs)
    alpha = fp.l_mont_mul(fp.l_sub(X, delta, fs), fp.l_add(X, delta), fs)
    alpha = fp.l_add(fp.l_add(alpha, alpha), alpha)
    beta2 = fp.l_add(beta, beta)
    beta4 = fp.l_add(beta2, beta2)
    beta8 = fp.l_add(beta4, beta4)
    X3 = fp.l_sub(fp.l_mont_sqr(alpha, fs), beta8, fs)
    g2 = fp.l_mont_sqr(gamma, fs)
    g4 = fp.l_add(g2, g2)
    g8 = fp.l_add(g4, g4)
    Y3 = fp.l_sub(
        fp.l_mont_mul(alpha, fp.l_sub(beta4, X3, fs), fs),
        fp.l_add(g8, g8), fs)
    Z3 = fp.l_sub(fp.l_sub(fp.l_mont_sqr(fp.l_add(Y, Z), fs), gamma, fs),
                  delta, fs)
    return X3, Y3, Z3


def _jac_madd(P1, x2, y2, fs=_FS):
    """madd-2007-bl (P2 affine, Z2 = 1): 7M + 4S.  Returns (P3, H); the
    caller must select away P1-identity / P2-identity lanes and flag
    H ≡ 0 lanes (P1 = ±P2)."""
    X1, Y1, Z1 = P1
    z1z1 = fp.l_mont_sqr(Z1, fs)
    u2 = fp.l_mont_mul(x2, z1z1, fs)
    s2 = fp.l_mont_mul(y2, fp.l_mont_mul(Z1, z1z1, fs), fs)
    H = fp.l_sub(u2, X1, fs)
    hh = fp.l_mont_sqr(H, fs)
    i2 = fp.l_add(hh, hh)
    i4 = fp.l_add(i2, i2)
    j = fp.l_mont_mul(H, i4, fs)
    rr = fp.l_sub(s2, Y1, fs)
    rr = fp.l_add(rr, rr)
    v = fp.l_mont_mul(X1, i4, fs)
    X3 = fp.l_sub(fp.l_sub(fp.l_mont_sqr(rr, fs), j, fs),
                  fp.l_add(v, v), fs)
    y1j = fp.l_mont_mul(Y1, j, fs)
    Y3 = fp.l_sub(fp.l_mont_mul(rr, fp.l_sub(v, X3, fs), fs),
                  fp.l_add(y1j, y1j), fs)
    Z3 = fp.l_sub(fp.l_sub(fp.l_mont_sqr(fp.l_add(Z1, H), fs), z1z1, fs),
                  hh, fs)
    return (X3, Y3, Z3), H


def _jac_add(P1, P2, fs=_FS):
    """add-2007-bl (both Jacobian): 11M + 5S.  Returns (P3, H); same
    caller obligations as :func:`_jac_madd`."""
    X1, Y1, Z1 = P1
    X2, Y2, Z2 = P2
    z1z1 = fp.l_mont_sqr(Z1, fs)
    z2z2 = fp.l_mont_sqr(Z2, fs)
    u1 = fp.l_mont_mul(X1, z2z2, fs)
    u2 = fp.l_mont_mul(X2, z1z1, fs)
    s1 = fp.l_mont_mul(Y1, fp.l_mont_mul(Z2, z2z2, fs), fs)
    s2 = fp.l_mont_mul(Y2, fp.l_mont_mul(Z1, z1z1, fs), fs)
    H = fp.l_sub(u2, u1, fs)
    h2 = fp.l_add(H, H)
    i = fp.l_mont_sqr(h2, fs)
    j = fp.l_mont_mul(H, i, fs)
    rr = fp.l_sub(s2, s1, fs)
    rr = fp.l_add(rr, rr)
    v = fp.l_mont_mul(u1, i, fs)
    X3 = fp.l_sub(fp.l_sub(fp.l_mont_sqr(rr, fs), j, fs),
                  fp.l_add(v, v), fs)
    s1j = fp.l_mont_mul(s1, j, fs)
    Y3 = fp.l_sub(fp.l_mont_mul(rr, fp.l_sub(v, X3, fs), fs),
                  fp.l_add(s1j, s1j), fs)
    Z3 = fp.l_mont_mul(
        fp.l_sub(fp.l_sub(fp.l_mont_sqr(fp.l_add(Z1, Z2), fs), z1z1, fs),
                 z2z2, fs), H, fs)
    return (X3, Y3, Z3), H


def _jac_rounds(w: int) -> int:
    """Ladder rounds for a w-bit window (ceil; the top digit of an
    uneven split reads zero bits past 256 — limbs carry 273)."""
    return -(-256 // w)


@functools.lru_cache(maxsize=None)
def _g_affine_table(w: int = _WINDOW) -> np.ndarray:
    """(2, 2^w, 21) int32 — affine Montgomery (x, y) of [k]G, k >= 1.

    Entry 0 is a placeholder: zero digits select the accumulator before
    the pick is ever used."""
    from ..core import curve as host_curve

    size = 1 << w
    rows = np.zeros((2, size, fp.NUM_LIMBS), dtype=np.int32)
    for k in range(1, size):
        x, y = host_curve.point_mul(k, (CURVE_GX, CURVE_GY))
        rows[0, k] = fp.int_to_limbs(fp.to_mont(x, _FS))
        rows[1, k] = fp.int_to_limbs(fp.to_mont(y, _FS))
    return rows




def _jac_identity(like):
    """The (R, R, 0) identity encoding, matching ``like``'s namespace."""
    return (fp.l_full(_ONE_M, like, CURVE_P),
            fp.l_full(_ONE_M, like, CURVE_P),
            fp.l_full(0, like, CURVE_P))


def _jac_lift_affine(x2, y2):
    return (fp.l_wrap(x2.limbs, _JB), fp.l_wrap(y2.limbs, _JB),
            fp.l_full(_ONE_M, x2.limbs[0], _JB))


def _jac_qtable(qx, qy, fs=_FS, size: int = 16):
    """Entries [1..size-1] = [k]Q as Jacobian FL points (bound <= _JB).

    Exception-free for on-curve Q: [k]Q = ±Q would need (k∓1)Q = identity
    with k−1 < size ≪ n (prime group order).  Off-curve garbage (already
    doomed by the `valid` flag) may produce garbage entries — harmless,
    the verdict is masked and any spurious exception flag just routes the
    lane to the host oracle, which rejects it."""
    e1 = _jac_clamp((fp.l_wrap(qx.limbs, CURVE_P),
                     fp.l_wrap(qy.limbs, CURVE_P),
                     fp.l_full(_ONE_M, qx.limbs[0], CURVE_P)))
    entries = [e1, _jac_clamp(_jac_dbl(e1, fs))]
    for _ in range(3, size):
        nxt, _h = _jac_madd(entries[-1], qx, qy, fs)
        entries.append(_jac_clamp(nxt))
    return entries


def _jac_round(acc, started, exc, dg1, dg2, g_pick_fn, q_pick_fn, fs=_FS,
               w: int = _WINDOW):
    """One w-bit digit round: w doublings, G mixed add, Q general add —
    with the structural identity selects and exception flagging described
    in the section comment.  ``started``/``exc`` are int32 masks of the
    limb shape; ``g_pick_fn(dg) -> (x2, y2)`` affine FLs, ``q_pick_fn(dg)
    -> Jacobian FL point``.  Returns (acc, started, exc)."""
    for _ in range(w):
        acc = _jac_clamp(_jac_dbl(acc, fs))

    gx, gy = g_pick_fn(dg1)
    res, H = _jac_madd(acc, gx, gy, fs)
    acc, started, exc = _jac_apply_add(
        acc, res, H, _jac_lift_affine(gx, gy), dg1, started, exc, fs)

    q_pick = q_pick_fn(dg2)
    res, H = _jac_add(acc, q_pick, fs)
    acc, started, exc = _jac_apply_add(
        acc, res, H, q_pick, dg2, started, exc, fs)
    return acc, started, exc


def _jac_apply_add(acc, res, H, pick_point, dg, started, exc, fs=_FS):
    """The single-sourced post-add masking invariant for both add sites:

    * digit == 0 (identity pick)            -> keep the accumulator;
    * accumulator still identity, real pick -> take the picked point;
    * H ≡ 0 with both operands real         -> flag the lane (P1 = ±P2,
      the formula output is unusable; host oracle decides);
    * otherwise                             -> the formula result.

    ``started`` flips once any nonzero digit lands."""
    pick_id = (dg == 0)
    acc_inf = started == 0
    h0 = fp.l_is_zero_mod_p(H, fs)
    exc = exc | (h0 & ~pick_id & ~acc_inf).astype(np.int32)
    out = []
    for c_res, c_acc, c_pick in zip(res, acc, pick_point):
        c = fp.l_select(pick_id, c_acc, fp.l_wrap(c_res.limbs, _JB))
        c = fp.l_select(acc_inf & ~pick_id, fp.l_wrap(c_pick.limbs, _JB), c)
        out.append(c)
    return (_jac_clamp(tuple(out)), started | (~pick_id).astype(np.int32),
            exc)


def _jac_final(acc, started, r_m, rn_m, rn_ok, valid, fs=_FS):
    """Jacobian accept check: x = X/Z², so accept ⇔ X ≡ r·Z² or
    (r + n < p and X ≡ (r+n)·Z²) (mod p), R not the identity."""
    X, _Y, Z = acc
    z2 = fp.l_mont_sqr(Z, fs)
    rz = fp.l_mont_mul(fp.l_wrap(r_m.limbs, CURVE_P), z2, fs)
    rnz = fp.l_mont_mul(fp.l_wrap(rn_m.limbs, CURVE_P), z2, fs)
    at_inf = fp.l_is_zero_mod_p(Z, fs) | (started == 0)
    ok = fp.l_is_zero_mod_p(fp.l_sub(X, rz, fs), fs) | (
        rn_ok & fp.l_is_zero_mod_p(fp.l_sub(X, rnz, fs), fs))
    return ok & ~at_inf & valid


def _jac_verify_eager(d1, d2, qx, qy, r_m, rn_m, rn_ok, valid,
                      n_rounds: Optional[int] = None, w: int = _WINDOW):
    """Host twin of the Pallas Jacobian kernel, same round logic via the
    shared helpers — runs on plain numpy (no jit, no device) so tests can
    drive short crafted ladders cheaply.  d1/d2: (n_rounds, N) int32
    digits; qx..rn_m: (21, N) canonical Montgomery limb numpy arrays;
    rn_ok/valid: (N,) bool.  Returns (ok, exc) bool arrays."""
    def to_fl(a, bound):
        return fp.l_wrap([np.asarray(a[i]) for i in range(fp.NUM_LIMBS)],
                         bound)

    if n_rounds is None:
        n_rounds = _jac_rounds(w)
    size = 1 << w
    g_tab = _g_affine_table(w)
    qx_f, qy_f = to_fl(qx, CURVE_P), to_fl(qy, CURVE_P)
    n = d1.shape[1]
    qtab = _jac_qtable(qx_f, qy_f, size=size)

    def g_pick_fn(dg):
        out = []
        for c in range(2):
            limbs = []
            for l in range(fp.NUM_LIMBS):
                acc = np.zeros((n,), np.int32)
                for k in range(1, size):
                    g = int(g_tab[c, k, l])
                    if g:
                        acc = acc + np.where(dg == k, g, 0)
                limbs.append(acc)
            out.append(fp.l_wrap(limbs, CURVE_P))
        return tuple(out)

    def q_pick_fn(dg):
        out = []
        for c in range(3):
            limbs = []
            for l in range(fp.NUM_LIMBS):
                acc = np.zeros((n,), np.int32)
                for k in range(1, size):
                    acc = acc + np.where(dg == k, qtab[k - 1][c].limbs[l], 0)
                limbs.append(acc)
            out.append(fp.l_wrap(limbs, _JB))
        return tuple(out)

    d1, d2 = np.asarray(d1), np.asarray(d2)
    acc = _jac_identity(np.zeros((n,), np.int32))
    started = np.zeros((n,), np.int32)
    exc = np.zeros((n,), np.int32)
    for k in range(n_rounds):
        acc, started, exc = _jac_round(acc, started, exc, d1[k], d2[k],
                                       g_pick_fn, q_pick_fn, w=w)
    ok = _jac_final(acc, started, to_fl(r_m, CURVE_P), to_fl(rn_m, CURVE_P),
                    rn_ok, valid)
    return np.asarray(ok), np.asarray(exc != 0)


def _ladder_kernel_jac(d1_ref, d2_ref, qx_ref, qy_ref, rm_ref, rnm_ref,
                       flags_ref, out_ref, qtab_ref, *, w: int = _WINDOW):
    """Pallas limb-list Jacobian ladder.  Same structure as
    :func:`_ladder_kernel_list` but ~1.5x fewer Montgomery products per
    round; emits bit0 = verdict, bit1 = exception flag per lane."""
    fs = _FS
    S = qx_ref.shape[1]
    shape = (S, 128)
    size = 1 << w
    g_tab = _g_affine_table(w)

    def read_fl(ref, bound):
        return fp.l_wrap([ref[i] for i in range(fp.NUM_LIMBS)], bound)

    qx_f, qy_f = read_fl(qx_ref, CURVE_P), read_fl(qy_ref, CURVE_P)

    # --- Q table (entries 1..size-1) into VMEM scratch -------------------
    entries = _jac_qtable(qx_f, qy_f, fs, size=size)
    for k, e in enumerate(entries):
        for c in range(3):
            for l in range(fp.NUM_LIMBS):
                qtab_ref[k, c, l] = e[c].limbs[l]

    def g_pick_fn(dg):
        masks = [(dg == k).astype(jnp.int32) for k in range(size)]
        out = []
        for c in range(2):
            limbs = []
            for l in range(fp.NUM_LIMBS):
                acc = None
                for k in range(1, size):
                    g = int(g_tab[c, k, l])
                    if g == 0:
                        continue
                    term = masks[k] * g
                    acc = term if acc is None else acc + term
                limbs.append(jnp.zeros(shape, jnp.int32) if acc is None
                             else acc)
            out.append(fp.l_wrap(limbs, CURVE_P))
        return tuple(out)

    def q_pick_fn(dg):
        masks = [(dg == k).astype(jnp.int32) for k in range(size)]
        out = []
        for c in range(3):
            limbs = []
            for l in range(fp.NUM_LIMBS):
                acc = masks[1] * qtab_ref[0, c, l]
                for k in range(2, size):
                    acc = acc + masks[k] * qtab_ref[k - 1, c, l]
                limbs.append(acc)
            out.append(fp.l_wrap(limbs, _JB))
        return tuple(out)

    def flatten(acc, started, exc):
        return tuple(tuple(c.limbs) for c in acc) + (started, exc)

    def round_body(k, carry):
        acc = tuple(fp.l_wrap(limbs, _JB) for limbs in carry[:3])
        started, exc = carry[3], carry[4]
        acc, started, exc = _jac_round(acc, started, exc,
                                       d1_ref[k], d2_ref[k],
                                       g_pick_fn, q_pick_fn, fs, w=w)
        return flatten(acc, started, exc)

    acc0 = _jac_identity(qx_f.limbs[0])
    z = jnp.zeros(shape, jnp.int32)
    carry = jax.lax.fori_loop(0, _jac_rounds(w), round_body,
                              flatten(acc0, z, z))
    acc = tuple(fp.l_wrap(limbs, _JB) for limbs in carry[:3])
    started, exc = carry[3], carry[4]

    rn_ok = flags_ref[0] != 0
    valid = flags_ref[1] != 0
    ok = _jac_final(acc, started, read_fl(rm_ref, CURVE_P),
                    read_fl(rnm_ref, CURVE_P), rn_ok, valid, fs)
    out_ref[...] = ok.astype(jnp.int32) + 2 * exc


@functools.partial(jax.jit, static_argnames=("tile", "interpret", "w"))
def _verify_device_pallas_jac(d1, d2, qx, qy, r_m, rn_m, flags,
                              tile: int = 1024, interpret: bool = False,
                              w: int = _WINDOW):
    """Run the Jacobian ladder kernel; returns (ok, exc) bool (N,) arrays."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = qx.shape[1]
    assert n % 128 == 0 and tile % 128 == 0 and n % tile == 0, (n, tile)
    rows, sub = n // 128, tile // 128
    grid = rows // sub

    def rs(x):
        return x.reshape(x.shape[0], rows, 128)

    spec = lambda r: pl.BlockSpec(
        (r, sub, 128), lambda i: (0, i, 0), memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_ladder_kernel_jac, w=w),
        grid=(grid,),
        in_specs=[
            spec(_jac_rounds(w)), spec(_jac_rounds(w)),
            spec(fp.NUM_LIMBS), spec(fp.NUM_LIMBS),
            spec(fp.NUM_LIMBS), spec(fp.NUM_LIMBS),
            spec(2),
        ],
        out_specs=pl.BlockSpec((sub, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM(((1 << w) - 1, 3, fp.NUM_LIMBS, sub, 128),
                       jnp.int32)],
        interpret=interpret,
    )(rs(d1), rs(d2), rs(qx), rs(qy), rs(r_m), rs(rn_m), rs(flags))
    out = out.reshape(n)
    return (out & 1) != 0, (out & 2) != 0


def _host_verify_prehashed(z: int, r: int, s: int, qx: int, qy: int) -> bool:
    """Host oracle for exception-flagged lanes — the exact device
    semantics: range checks, coordinate reduction mod p (fastecdsa
    parity), on-curve check, then x(u₁G + u₂Q) ≡ r (mod n)."""
    from ..core import curve as host_curve

    if not (0 < r < CURVE_N and 0 < s < CURVE_N):
        return False
    if qx == 0 and qy == 0:
        return False
    qx, qy = qx % CURVE_P, qy % CURVE_P
    if not is_on_curve((qx, qy)):
        return False
    w = pow(s, -1, CURVE_N)
    u1, u2 = z * w % CURVE_N, r * w % CURVE_N
    pt = host_curve.point_add(host_curve.point_mul(u1, host_curve.G),
                              host_curve.point_mul(u2, (qx, qy)))
    return pt is not None and pt[0] % CURVE_N == r


PALLAS_STRICT = False  # True: never fall back (tests assert kernel health)
# "jac" (fast, default) | "complete" (RCB16, for A/B).  Only consulted on
# the production path (backend="pallas" + scalar_prep="device"); the
# host-prep pallas branch always runs the RCB16 kernels (it exists for
# the interpret-mode kernel test, which targets them explicitly).
PALLAS_KERNEL = "jac"
# Jacobian ladder window bits.  w=4: 64 rounds, 16-entry tables.  w=5:
# 52 rounds (fewer adds/tests per bit) but 32-entry tables (pricier
# picks/setup) — measured A/B on the chip decides; both are covered by
# the eager-twin differentials.  UPOW_JAC_WINDOW overrides, so the
# chip-window A/B harness (tpu_ab.py) can flip it per-subprocess
# without editing source mid-queue.


def _env_choice(name: str, default: int, allowed) -> int:
    """Env-knob parse that can't take down an importer: only the
    differential-covered values are accepted; anything else (typo,
    stray export, untested window) logs and falls back to the default —
    a consensus node must not boot into an unvetted kernel config."""
    import logging
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw.strip())
    except ValueError:
        val = None
    if val not in allowed:
        logging.getLogger("upow_tpu.crypto").warning(
            "%s=%r invalid (allowed %s); using %d", name, raw,
            sorted(allowed), default)
        return default
    return val


PALLAS_JAC_WINDOW = _env_choice("UPOW_JAC_WINDOW", 4, {4, 5})


def _pallas_or_jnp(pallas_thunk, jnp_thunk) -> np.ndarray:
    """Run the Pallas program, materialized; on ANY failure — lowering or
    async runtime (which only surfaces at materialization) — recompute via
    the jnp program.  Same math either way; a broken kernel must degrade a
    validating node to the slow path, never take it down."""
    try:
        return np.asarray(pallas_thunk())
    except Exception:
        if PALLAS_STRICT:
            raise
        import logging

        logging.getLogger("upow_tpu.crypto").warning(
            "pallas verify kernel failed; falling back to jnp",
            exc_info=True)
        return np.asarray(jnp_thunk())


# tile caps: 128-multiples that divide the 8192-lane bench/production
# pad shapes; the sweep only needs these three
_TILE_CAP = _env_choice("UPOW_TILE_CAP", 1024, {128, 256, 512, 1024})


def _pick_tile(padded: int, cap: int = _TILE_CAP) -> int:
    """Largest 128-multiple divisor of ``padded`` that is <= ``cap``
    (``padded`` is always a multiple of 128 on the pallas path;
    UPOW_TILE_CAP overrides the default 1024 for the chip tile sweep)."""
    rows = padded // 128
    for k in range(min(cap // 128, rows), 0, -1):
        if rows % k == 0:
            return 128 * k
    return 128


def _pad_to_block(n: int, block: int = 128) -> int:
    """Round up to a power-of-two multiple of ``block`` (>= block).

    ``block`` = 128 fills TPU lanes; small blocks (e.g. 8) keep the CPU
    dryrun/interpret paths cheap."""
    padded = max(block, 1 << (n - 1).bit_length())
    return ((padded + block - 1) // block) * block


def verify_batch(
    messages: Sequence[bytes],
    signatures: Sequence[Tuple[int, int]],
    pubkeys: Sequence[Tuple[int, int]],
    pad_block: int = 128,
) -> np.ndarray:
    """Batch-verify ECDSA signatures over sha256(message).  Returns (N,) bool.

    Semantics match ``fastecdsa.ecdsa.verify`` as used by the reference
    (transaction_input.py:100-109): sha256 digest, bits2int truncation,
    range-checked r/s, and on-curve pubkeys.  Invalid-by-construction
    entries short-circuit to False on the host and never reach the device.
    """
    digests = [hashlib.sha256(m).digest() for m in messages]
    return verify_batch_prehashed(digests, signatures, pubkeys, pad_block)


def _unpack_fused(packed):
    """(42, N) uint32 fused input -> the 7 logical scalar-prep operands.

    Rows 0-39 are five (8, N) little-endian word arrays (z, r, s, qx,
    qy); rows 40/41 are the host-checked range_ok / rn_ok masks.  Fusing
    the operands into one array keeps the host->device path at ONE
    transfer per batch — over the tunneled chip each separate transfer
    pays a full round trip, which dominated the pipelined verify rate."""
    z, r, s, qx, qy = (packed[8 * i:8 * i + 8] for i in range(5))
    return z, r, s, qx, qy, packed[40] != 0, packed[41] != 0


@functools.partial(jax.jit, static_argnames=("tile",))
def _prep_and_verify_pallas(packed, tile: int):
    """One dispatch: device scalar prep -> Pallas ladder kernel (RCB16)."""
    args = _scalar_prep(*_unpack_fused(packed))
    return _verify_device_pallas(*args, tile=tile)


def _jac_body(packed, tile: int, w: int):
    """Shared trace body: fused input -> device scalar prep -> Jacobian
    ladder kernel -> stacked (2, N) bool (row 0 accept verdicts, row 1
    exception flags; those lanes need the host oracle).  One input and
    one output array = one transfer each way."""
    args = _scalar_prep(*_unpack_fused(packed), w=w)
    ok, exc = _verify_device_pallas_jac(*args, tile=tile, w=w)
    return jnp.stack([ok, exc])


@functools.partial(jax.jit, static_argnames=("tile", "w"))
def _prep_and_verify_pallas_jac(packed, tile: int, w: int = _WINDOW):
    """One dispatch: device scalar prep -> Jacobian ladder kernel."""
    return _jac_body(packed, tile, w)


@functools.partial(jax.jit, static_argnames=("tile", "mesh", "w"))
def _prep_and_verify_pallas_jac_sharded(packed, tile: int, mesh,
                                        w: int = _WINDOW):
    """Mesh-DP variant: every device runs scalar prep + the Pallas ladder
    on its own batch shard (the program is elementwise over lanes, so the
    only communication is the output gather).  ``shard_map`` is required
    — pallas_call has no SPMD partitioning rule, so plain jit + sharded
    inputs cannot split it."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    shard_map, check_kw = shard_map_compat()

    def per_device(packed_):
        return _jac_body(packed_, tile, w)

    lanes = P(None, "dp")
    return shard_map(
        per_device, mesh=mesh,
        in_specs=(lanes,), out_specs=lanes, **check_kw,
    )(packed)


@jax.jit
def _prep_and_verify_jnp(packed):
    d1, d2, qxm, qym, rmp, rnmp, flags = _scalar_prep(*_unpack_fused(packed))
    return _verify_device(d1, d2, qxm, qym, rmp, rnmp,
                          flags[0] != 0, flags[1] != 0)


def _pack_device_inputs(digests, signatures, pubkeys, padded: int):
    """Host side of the device-prep path: sanitize scalars and pack them
    into ONE fused (42, padded) uint32 array (see :func:`_unpack_fused`)
    moved to the device in a single transfer.  Returns
    (fused_device_array, zs, rs, ss, qxs, qys) — the python-int lists
    feed the host oracle for exception-flagged lanes.  Split out so the
    bench can pipeline this host stage against in-flight device batches
    (the chain-sync ingest profile)."""
    n = len(digests)

    def sane(x):  # out-of-[0, 2^256) scalars never reach the word packer
        return x if 0 <= x < (1 << 256) else 0

    def coord(x):
        # the reference's fastecdsa computes everything mod p, so a
        # coordinate in [p, 2^256) encodes the reduced point — accept
        # it identically (consensus parity); reduce oversized/negative
        # ints the way Python % does on the host oracle path
        return x if 0 <= x < (1 << 256) else x % CURVE_P

    # u1 depends only on z mod n, so oversized digests (a direct API
    # caller hashing with sha512, say) reduce exactly like the host's
    # z*w % n — never an exception where the host returns a verdict
    zs = [z if z < (1 << 256) else z % CURVE_N
          for z in (int.from_bytes(d, "big") for d in digests)]
    rs = [sig[0] for sig in signatures]
    ss = [sig[1] for sig in signatures]
    qxs = [coord(pk[0]) for pk in pubkeys]
    qys = [coord(pk[1]) for pk in pubkeys]
    range_ok = np.array(
        [0 < r_ < CURVE_N and 0 < s_ < CURVE_N
         and not (qx_ == 0 and qy_ == 0)
         for r_, s_, (qx_, qy_) in zip(rs, ss, pubkeys)], dtype=bool)
    rn_ok = np.array([0 < r_ and r_ + CURVE_N < CURVE_P for r_ in rs],
                     dtype=bool)
    fused = np.zeros((42, padded), dtype=np.uint32)
    for i, xs in enumerate((zs, [sane(r_) for r_ in rs],
                            [sane(s_) for s_ in ss], qxs, qys)):
        fused[8 * i:8 * i + 8, :n] = _pack_words(xs, 0)
    fused[40, :n] = range_ok
    fused[41, :n] = rn_ok
    return jnp.asarray(fused), zs, rs, ss, qxs, qys


def verify_batch_prehashed(
    digests: Sequence[bytes],
    signatures: Sequence[Tuple[int, int]],
    pubkeys: Sequence[Tuple[int, int]],
    pad_block: int = 128,
    backend: Optional[str] = None,
    mesh=None,
    scalar_prep: Optional[str] = None,
) -> np.ndarray:
    """``mesh``: a jax.sharding.Mesh — the padded batch is placed with
    its lane axis sharded over the mesh ("dp"), so the elementwise
    verify program runs SPMD with zero collectives (SURVEY §2.3 DP
    verify).  Without it, inputs live on one device.  The jnp backend
    shards via plain jit; the pallas backend (jac kernel + device prep)
    wraps the kernel in shard_map — pallas_call has no partitioning
    rule, so each device runs the grid on its own shard.

    ``scalar_prep``: "device" moves s⁻¹ mod n, u₁/u₂, Montgomery
    conversions, the on-curve check and digit extraction into the jitted
    program (default on TPU — the host bigint loop costs 5x the ladder
    kernel); "host" keeps them in Python (default on CPU, where compile
    time matters more than per-batch host microseconds)."""
    n = len(digests)
    assert len(signatures) == n and len(pubkeys) == n
    if mesh is not None:
        import math

        n_dev = mesh.devices.size
        # padded length must split evenly across the mesh
        pad_block = pad_block * n_dev // math.gcd(pad_block, n_dev)
    if n == 0:
        return np.zeros(0, dtype=bool)
    # "axon" = the tunnel plugin's PJRT client name for the same TPU
    # hardware (lowering tables are aliased to tpu's) — route it like tpu
    if backend is None or scalar_prep is None:
        from ..device.runtime import get_runtime

        platform = get_runtime().platform()  # probe normalizes axon->tpu
        if backend is None:
            backend = "pallas" if platform == "tpu" else "jnp"
        if scalar_prep is None:
            scalar_prep = "device" if platform == "tpu" else "host"
    if mesh is not None and backend == "pallas":
        if PALLAS_KERNEL != "jac" or scalar_prep != "device":
            raise ValueError(
                "mesh + pallas is wired for the jac kernel with device "
                "scalar prep; pass backend='jnp' otherwise")
        import math

        # the one real invariant: padded must be a multiple of
        # 128 * n_dev, so every device's shard fills whole kernel tiles
        unit = 128 * mesh.devices.size
        pad_block = pad_block * unit // math.gcd(pad_block, unit)
    elif backend == "pallas":
        # the limb-list kernel reshapes the batch axis to (rows, 128)
        pad_block = max(pad_block, 128)

    # occupancy + in-process jit hit/miss telemetry: real lanes vs the
    # padded batch actually dispatched; the compile key mirrors what
    # jit retraces on (padded shape + static kernel choices)
    from ..telemetry import device as _ktel

    _ktel.record_batch(
        "p256_verify", real=n, padded=_pad_to_block(n, pad_block),
        compile_key=(backend, scalar_prep, _pad_to_block(n, pad_block),
                     PALLAS_KERNEL,
                     mesh.devices.size if mesh is not None else 0))

    if scalar_prep == "device":
        padded = _pad_to_block(n, pad_block)
        inputs, zs, rs, ss, qxs, qys = _pack_device_inputs(
            digests, signatures, pubkeys, padded)
        if backend == "pallas" and PALLAS_KERNEL == "jac":
            if mesh is not None:
                from ..parallel.mesh import shard_batch_arrays

                inputs, = shard_batch_arrays(mesh, inputs)

            def pallas_thunk():
                if mesh is not None:
                    res = _prep_and_verify_pallas_jac_sharded(
                        inputs,
                        tile=_pick_tile(padded // mesh.devices.size),
                        mesh=mesh, w=PALLAS_JAC_WINDOW)
                else:
                    res = _prep_and_verify_pallas_jac(
                        inputs, tile=_pick_tile(padded),
                        w=PALLAS_JAC_WINDOW)
                return np.asarray(res)

            def jnp_thunk():
                # the jnp fallback's complete formulas have no exceptions
                # (sharded inputs partition the plain-jit program too)
                ok = np.asarray(_prep_and_verify_jnp(inputs))
                return np.stack([ok, np.zeros_like(ok)])

            res = _pallas_or_jnp(pallas_thunk, jnp_thunk)
            out, exc = res[0], res[1]
            if exc[:n].any():
                out = out.copy()
                for i in np.nonzero(exc[:n])[0]:
                    out[i] = _host_verify_prehashed(
                        zs[i], rs[i], ss[i], qxs[i], qys[i])
            return out[:n]
        if backend == "pallas":
            out = _pallas_or_jnp(
                lambda: _prep_and_verify_pallas(inputs,
                                                tile=_pick_tile(padded)),
                lambda: _prep_and_verify_jnp(inputs))
        else:
            if mesh is not None:
                from ..parallel.mesh import shard_batch_arrays

                inputs, = shard_batch_arrays(mesh, inputs)
            out = np.asarray(_prep_and_verify_jnp(inputs))
        return out[:n]

    u1s, u2s, qxs, qys, rms, rnms, rnoks, valids = [], [], [], [], [], [], [], []
    for digest, (r, s), (qx, qy) in zip(digests, signatures, pubkeys):
        ok = 0 < r < CURVE_N and 0 < s < CURVE_N and is_on_curve((qx, qy)) \
            and not (qx == 0 and qy == 0)
        if ok:
            z = int.from_bytes(digest, "big")
            w = pow(s, -1, CURVE_N)
            u1, u2 = z * w % CURVE_N, r * w % CURVE_N
        else:
            u1, u2, qx, qy, r = 1, 1, CURVE_GX, CURVE_GY, 1
        rn = r + CURVE_N
        u1s.append(u1)
        u2s.append(u2)
        qxs.append(fp.to_mont(qx, _FS))
        qys.append(fp.to_mont(qy, _FS))
        rms.append(fp.to_mont(r, _FS))
        rnms.append(fp.to_mont(rn % CURVE_P, _FS))
        rnoks.append(rn < CURVE_P)
        valids.append(ok)

    padded = _pad_to_block(n, pad_block)
    pad = padded - n

    def arr(xs):
        return jnp.asarray(
            np.pad(fp.ints_to_limbs(xs), ((0, 0), (0, pad)), constant_values=0)
        )

    def digits(xs):
        return jnp.asarray(
            np.pad(_scalar_digits(xs), ((0, 0), (0, pad)), constant_values=0)
        )

    if backend == "pallas":
        flags = jnp.asarray(np.stack([
            np.pad(np.array(rnoks, dtype=np.int32), (0, pad)),
            np.pad(np.array(valids, dtype=np.int32), (0, pad)),
        ]))
        out = _pallas_or_jnp(
            lambda: _verify_device_pallas(
                digits(u1s), digits(u2s), arr(qxs), arr(qys), arr(rms),
                arr(rnms), flags, tile=_pick_tile(padded)),
            lambda: _verify_device(
                digits(u1s), digits(u2s), arr(qxs), arr(qys), arr(rms),
                arr(rnms),
                jnp.asarray(np.pad(np.array(rnoks, dtype=bool), (0, pad))),
                jnp.asarray(np.pad(np.array(valids, dtype=bool), (0, pad)))))
        return out[:n]
    else:
        inputs = (
            digits(u1s), digits(u2s), arr(qxs), arr(qys), arr(rms), arr(rnms),
            jnp.asarray(np.pad(np.array(rnoks, dtype=bool), (0, pad))),
            jnp.asarray(np.pad(np.array(valids, dtype=bool), (0, pad))),
        )
        if mesh is not None:
            from ..parallel.mesh import shard_batch_arrays

            inputs = shard_batch_arrays(mesh, *inputs)
        out = _verify_device(*inputs)
    return np.asarray(out)[:n]
