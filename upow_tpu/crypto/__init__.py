"""Backend-abstracted crypto primitives (SURVEY.md §2.2).

The reference leans on three native deps — hashlib/OpenSSL sha256
(miner.py:52,61,87), fastecdsa's C extension for P-256 ECDSA
(transaction_input.py:84-109), and GMP underneath.  Here the hot paths are
TPU kernels with CPU fallbacks:

* sha256 PoW search — :mod:`.sha256` (jnp + Pallas midstate kernels)
* batched P-256 ECDSA verify — :mod:`.p256` (limb Montgomery, jnp)
* host sign/keygen — :mod:`upow_tpu.core.curve` (pure Python, RFC6979)
* C++ CPU fast paths — :mod:`upow_tpu.native` (ctypes, built on demand)
"""

from .sha256 import (
    SearchTemplate,
    TargetSpec,
    make_template,
    target_spec,
    pow_search_jnp,
    pow_search_pallas,
    sha256_batch_jnp,
    sha256_py,
    SENTINEL,
)
