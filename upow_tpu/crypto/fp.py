"""Batched 256-bit prime-field arithmetic for TPU: 21×13-bit limbs, lazy.

Design (SURVEY.md §2.2 — the role fastecdsa's C/GMP extension plays in the
reference, transaction_input.py:100-109):

* **13-bit limbs in int32 lanes** — a limb product is < 2²⁶ and a 21-term
  accumulation stays < 2³¹, so schoolbook multiply + Montgomery reduction
  run in plain int32 VPU ops with no u64 widening.
* **Non-negative lazy representation with static bounds** — an element is
  a (21, N) int32 array with limbs in [0, 2¹³] plus a *Python-side* upper
  bound on the represented value, tracked exactly while tracing (the
  fiat-crypto discipline).  Values stay congruent mod p but unreduced;
  adds are one vector add + one carry sweep; subtraction is
  ``a + (K·p − b)`` with the multiple K chosen statically from b's bound,
  so limbs never go negative and carry sweeps can never lose a top carry
  (every bound is asserted ≪ 2²⁷³ at trace time).
* **One guard limb** (21 limbs = 273 bits for a 256-bit field) — gives
  Montgomery products the slack that makes the lazy bounds self-stable:
  with R = 2²⁷³, inputs bounded by ~2²⁶⁴ still return below 2p + ε.
* **Array layout (L, N)** — limb index on the sublane axis, batch on the
  lane axis; every op is a handful of large fused VPU instructions, which
  keeps both the XLA graph small (fast compiles) and the TPU busy.  The
  only sequential pieces are the per-site borrow chain inside ``sub`` and
  the one exact reduction in :func:`canon` at the end of a verification.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

LIMB_BITS = 13
NUM_LIMBS = 21
LIMB_MASK = (1 << LIMB_BITS) - 1
R_BITS = LIMB_BITS * NUM_LIMBS  # Montgomery R = 2^273

# Hard cap on any element's value bound: far enough below 2^273 that a
# carry sweep's top limb is always < 2^13 (no dropped carries), with room
# for the K·p subtraction offsets.
_BOUND_CAP = 1 << 270


class FieldSpec(NamedTuple):
    """Host-side constants for one prime field."""

    p: int
    p_limbs: tuple             # 21 Python-int limbs (scalar constants only:
                               # non-scalar closures are illegal in Pallas)
    pinv: int                  # -p^-1 mod 2^13
    r_mod_p: int               # R mod p  (Montgomery form of 1)
    r2_mod_p: int              # R^2 mod p


def make_field(p: int) -> FieldSpec:
    return FieldSpec(
        p=p,
        p_limbs=tuple(int(x) for x in int_to_limbs(p)),
        pinv=(-pow(p, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS),
        r_mod_p=(1 << R_BITS) % p,
        r2_mod_p=pow(1 << R_BITS, 2, p),
    )


# --- host conversions -----------------------------------------------------

def int_to_limbs(x: int) -> np.ndarray:
    out = np.zeros(NUM_LIMBS, dtype=np.int32)
    for i in range(NUM_LIMBS):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    assert x == 0, "value exceeds 273 bits"
    return out


def ints_to_limbs(xs) -> np.ndarray:
    """list of ints -> (21, N) int32 batch.

    Vectorized: per-int ``to_bytes`` (C speed) then one numpy unpack —
    the per-limb Python loop was the host-side bottleneck of an 8k-sig
    batch verify (~0.7 s/call before, ~10 ms now)."""
    n = len(xs)
    if n == 0:
        return np.zeros((NUM_LIMBS, 0), dtype=np.int32)
    raw = b"".join(x.to_bytes(35, "little") for x in xs)  # 273 bits < 280
    assert max(xs) < (1 << R_BITS), "value exceeds 273 bits"
    bits = np.unpackbits(
        np.frombuffer(raw, dtype=np.uint8).reshape(n, 35),
        axis=1, bitorder="little")[:, :NUM_LIMBS * LIMB_BITS]
    weights = (1 << np.arange(LIMB_BITS, dtype=np.int32))
    out = bits.reshape(n, NUM_LIMBS, LIMB_BITS).astype(np.int32) @ weights
    return np.ascontiguousarray(out.T)


def limbs_to_int(limbs) -> int:
    # Host-side exact reassembly of a 256-bit value from limbs; int64
    # never reaches a traced computation.
    limbs = np.asarray(limbs, dtype=np.int64)  # upowlint: disable=DT001
    return sum(int(limbs[i]) << (LIMB_BITS * i) for i in range(limbs.shape[0]))


def limbs_to_ints(limbs) -> list:
    limbs = np.asarray(limbs)
    return [limbs_to_int(limbs[:, j]) for j in range(limbs.shape[1])]


def to_mont(x: int, fs: FieldSpec) -> int:
    return x * (1 << R_BITS) % fs.p


# --- the element type -----------------------------------------------------

@dataclass(frozen=True)
class FE:
    """Field-element batch: (21, N) int32 limbs + static value bound.

    ``bound`` is exclusive, tracked in Python while tracing — it never
    touches the device.  Stacked-layout limbs are in [0, 2^13 + 22]
    (the residue after mont_mul's two one-hop sweeps: 8191 + a round-2
    carry of at most 22); limb-list (FL) limbs are in [0, 2^13 − 1]
    (:func:`_l_sweep` is a full ripple).  Values are >= 0 and < bound.
    21-term product accumulations stay < 2^31 at either cap
    (21 · 8213² ≈ 1.42e9).
    """

    arr: jnp.ndarray
    bound: int

    def __post_init__(self):
        assert self.bound <= _BOUND_CAP, (
            f"fp bound overflow: {self.bound.bit_length()} bits — "
            "missing a mont_mul in the chain?")


def wrap(arr, bound: int) -> FE:
    return FE(arr, bound)


def from_ints(xs, fs: FieldSpec) -> FE:
    """Host canonical ints (< p) -> device FE."""
    assert all(0 <= x < fs.p for x in xs)
    return FE(jnp.asarray(ints_to_limbs(xs)), fs.p)


def const(x: int, n: int, bound: int) -> FE:
    """Broadcast one host int (< bound) to a (21, N) batch.

    Built from scalar fills (not a closed-over (21, 1) array) so the same
    code is legal inside a Pallas kernel."""
    limbs = int_to_limbs(x)
    return FE(
        jnp.stack([jnp.full((n,), int(l), dtype=jnp.int32) for l in limbs]),
        bound,
    )


# --- device ops -----------------------------------------------------------

def _sweep(t, rounds: int):
    """Carry sweep: re-digitize non-negative limbs toward [0, 2^13].

    Each round keeps the low 13 bits and moves the carry one limb up.
    Safe to drop the top-limb carry: all values are non-negative and
    bounded < 2^270 ≪ 2^273, so that carry is provably zero.
    """
    for _ in range(rounds):
        c = t >> LIMB_BITS
        t = (t & LIMB_MASK) + jnp.concatenate(
            [jnp.zeros_like(c[:1]), c[:-1]], axis=0
        )
    return t


def add(a: FE, b: FE) -> FE:
    return FE(_sweep(a.arr + b.arr, 1), a.bound + b.bound)


def _pow2_p_multiple(bound: int, p: int) -> int:
    """Smallest K = 2^k · p with K >= bound (so K − b is non-negative)."""
    k = 1
    while k * p < bound:
        k <<= 1
    return k * p


def sub(a: FE, b: FE, fs: FieldSpec) -> FE:
    """a − b computed as a + (K·p − b), K statically chosen from b.bound."""
    K = _pow2_p_multiple(b.bound, fs.p)
    k_limbs = int_to_limbs(K)
    # exact borrow chain for K − b (non-negative by construction of K)
    limbs = []
    c = jnp.zeros_like(b.arr[0])
    for i in range(NUM_LIMBS):
        v = int(k_limbs[i]) - b.arr[i] + c
        limbs.append(v & LIMB_MASK)
        c = v >> LIMB_BITS
    neg_b = jnp.stack(limbs, axis=0)
    return FE(_sweep(a.arr + neg_b, 1), a.bound + K)


def _shift_add(t, x, off: int):
    """t (2L, N) + x ((rows), N) placed at static row offset ``off``.

    Built from a concatenate of zero pads instead of ``t.at[...].add`` —
    indexed-add lowers to scatter-add, which has no Pallas TPU lowering;
    a static-offset concatenate lowers on both XLA and Pallas TPU.
    """
    rows = x.shape[0]
    n = t.shape[1]
    parts = []
    if off:
        parts.append(jnp.zeros((off, n), dtype=jnp.int32))
    parts.append(x)
    top = t.shape[0] - off - rows
    if top:
        parts.append(jnp.zeros((top, n), dtype=jnp.int32))
    return t + jnp.concatenate(parts, axis=0)


def mont_mul(a: FE, b: FE, fs: FieldSpec) -> FE:
    """Montgomery product a·b·R⁻¹ mod p; bound resets to ~2p for sane inputs."""
    L = NUM_LIMBS
    n = a.arr.shape[1]
    t = jnp.zeros((2 * L, n), dtype=jnp.int32)
    for i in range(L):
        t = _shift_add(t, a.arr[i] * b.arr, i)
    # sweep counts: pre 1 one-hop round (rows ≤ 2^13 + 2^17.4; the
    # reduction-round budget in _l_mont_reduce's proof absorbs it);
    # post 2 one-hop rounds (limbs ≤ 2^13 + 22 — see the FE docstring)
    t = _sweep(t, 1)
    # Montgomery rounds: zero the bottom L limbs; the single-limb carry per
    # round keeps m exact (t[i] ≡ value/b^i mod b at round i).  p's limbs
    # enter as scalar constants (Pallas-legal; see FieldSpec.p_limbs).
    for i in range(L):
        m = (t[i] * fs.pinv) & LIMB_MASK
        mp = jnp.stack([m * pl for pl in fs.p_limbs])
        t = _shift_add(t, mp, i)
        t = _shift_add(t, (t[i] >> LIMB_BITS)[None], i + 1)
    out = _sweep(t[L:], 2)
    return FE(out, a.bound * b.bound // (1 << R_BITS) + 2 * fs.p)


# --- limb-list variant (Pallas kernel layout) ------------------------------
# Same arithmetic, but an element is a Python TUPLE of 21 per-limb arrays
# (each typically an (8, 128) int32 tile = 1024 batch lanes).  Limb shifts
# become Python indexing — zero data movement — where the stacked (L, N)
# layout pays a concatenate per shifted add.  This is the layout the
# VMEM-resident ladder kernel runs in; bounds are tracked identically.


@dataclass(frozen=True)
class FL:
    """Field-element batch as a limb tuple + static value bound."""

    limbs: tuple  # length NUM_LIMBS, arrays of identical shape
    bound: int

    def __post_init__(self):
        assert self.bound <= _BOUND_CAP, (
            f"fp bound overflow: {self.bound.bit_length()} bits")


def _xp(*arrs):
    """numpy when every input is a host numpy array (eager differential
    tests run the limb-list programs at C speed), jax otherwise (tracers,
    device arrays, Pallas ref reads).  Most limb ops are dunder-dispatched
    and need no shim — this covers the explicit ``where``/``zeros`` calls."""
    return np if all(isinstance(a, np.ndarray) for a in arrs) else jnp


def l_full(x: int, like, bound: int) -> FL:
    """Broadcast a host int against a sample limb array, matching its
    array namespace (see :func:`_xp`)."""
    xp = _xp(like)
    limbs = int_to_limbs(x)
    return FL(tuple(xp.full(like.shape, int(l), dtype=xp.int32)
                    for l in limbs), bound)


def l_wrap(limbs, bound: int) -> FL:
    return FL(tuple(limbs), bound)


def l_const(x: int, shape, bound: int) -> FL:
    limbs = int_to_limbs(x)
    return FL(tuple(jnp.full(shape, int(l), dtype=jnp.int32) for l in limbs),
              bound)


def _l_sweep(t: list, rounds: int) -> list:
    """In-place-style carry sweep over a limb list (top carry provably 0)."""
    t = list(t)
    for _ in range(rounds):
        carry = None
        for i in range(len(t)):
            v = t[i] if carry is None else t[i] + carry
            carry = v >> LIMB_BITS
            t[i] = v & LIMB_MASK
    return t


def l_add(a: FL, b: FL) -> FL:
    t = [x + y for x, y in zip(a.limbs, b.limbs)]
    return FL(tuple(_l_sweep(t, 1)), a.bound + b.bound)


def l_sub(a: FL, b: FL, fs: FieldSpec) -> FL:
    K = _pow2_p_multiple(b.bound, fs.p)
    k_limbs = int_to_limbs(K)
    limbs = []
    c = None
    for i in range(NUM_LIMBS):
        v = int(k_limbs[i]) - b.limbs[i] + (0 if c is None else c)
        limbs.append(v & LIMB_MASK)
        c = v >> LIMB_BITS
    t = [x + y for x, y in zip(a.limbs, limbs)]
    return FL(tuple(_l_sweep(t, 1)), a.bound + K)


def _l_mont_reduce(t: list, bound_product: int, fs: FieldSpec) -> FL:
    """Shared tail of the limb-list Montgomery entry points: sweep the
    double-width accumulator, run the 21 reduction rounds, sweep the top
    half.  ``t`` rows may be None (rows no product reached).

    Sweep-count proof (int32 overflow is the only constraint — m's
    exactness needs just "every contribution into row i lands before
    round i", which product accumulation + the single round-carry chain
    guarantee at any sweep count).  Unlike the stacked :func:`_sweep`
    (one carry hop per round), :func:`_l_sweep` is a full sequential
    ripple — ONE round leaves every limb ≤ 2¹³ − 1:

    * pre-sweep 1: raw rows ≤ 21·2²⁶ ≈ 2³⁰·⁴ — one ripple normalizes.
      Each reduction round then adds ≤ 21 m·p products (< 2²⁶ each)
      plus one carry (< 2¹⁸) to a row — worst row value
      2¹³ + 21·2²⁶ + 2¹⁸ < 2³⁰·⁵ < 2³¹.  (A formula accumulating more
      than NUM_LIMBS products per row would break this — re-derive
      before changing the multiply structure.)
    * post-sweep 1: the output rows (≤ 2³⁰·⁵) ripple back to ≤ 2¹³ − 1
      in one round, restoring the canonical limb range.
    """
    L = NUM_LIMBS
    sample = next(x for x in t if x is not None)
    t = [_xp(sample).zeros_like(sample) if r is None else r for r in t]
    t = _l_sweep(t, 1)
    for i in range(L):
        m = (t[i] * fs.pinv) & LIMB_MASK
        for j in range(L):
            t[i + j] = t[i + j] + m * fs.p_limbs[j]
        t[i + 1] = t[i + 1] + (t[i] >> LIMB_BITS)
    out = _l_sweep(t[L:], 1)
    return FL(tuple(out), bound_product // (1 << R_BITS) + 2 * fs.p)


def l_mont_mul(a: FL, b: FL, fs: FieldSpec) -> FL:
    """Montgomery product in limb-list form: the anti-diagonal accumulation
    is Python indexing (t[i+j] += a_i·b_j) — no concatenates, every MAC one
    full-tile VPU op."""
    L = NUM_LIMBS
    t = [None] * (2 * L)
    for i in range(L):
        ai = a.limbs[i]
        for j in range(L):
            p_ij = ai * b.limbs[j]
            k = i + j
            t[k] = p_ij if t[k] is None else t[k] + p_ij
    return _l_mont_reduce(t, a.bound * b.bound, fs)


def l_mont_sqr(a: FL, fs: FieldSpec) -> FL:
    """Montgomery square: the schoolbook product's symmetry halves the
    cross-term MACs (t[i+j] gets 2·aᵢaⱼ once instead of aᵢaⱼ twice; the
    factor 2 is applied once per row after accumulation).

    Bound safety: a row collects ≤10 doubled cross products (< 2²⁷ each)
    plus one square (< 2²⁶) — under 2³¹ in int32, same margin as
    :func:`l_mont_mul`'s 21-term accumulation."""
    L = NUM_LIMBS
    cross = [None] * (2 * L)  # Σ_{i<j} a_i·a_j per row (to be doubled)
    for i in range(L):
        ai = a.limbs[i]
        for j in range(i + 1, L):
            k = i + j
            p_ij = ai * a.limbs[j]
            cross[k] = p_ij if cross[k] is None else cross[k] + p_ij
    t = [None] * (2 * L)
    for k in range(2 * L):
        if cross[k] is not None:
            t[k] = cross[k] + cross[k]
    for i in range(L):  # diagonal squares
        k = 2 * i
        sq = a.limbs[i] * a.limbs[i]
        t[k] = sq if t[k] is None else t[k] + sq
    return _l_mont_reduce(t, a.bound * a.bound, fs)


def l_canon(a: FL, fs: FieldSpec) -> list:
    limbs = []
    c = None
    for i in range(NUM_LIMBS):
        v = a.limbs[i] if c is None else a.limbs[i] + c
        limbs.append(v & LIMB_MASK)
        c = v >> LIMB_BITS
    k = 1
    while k * fs.p < a.bound:
        k <<= 1
    while k >= 1:
        limbs = _l_cond_sub(limbs, k * fs.p)
        k //= 2
    return limbs


def _l_cond_sub(t: list, m: int) -> list:
    mc = int_to_limbs(m)
    limbs = []
    c = None
    for i in range(NUM_LIMBS):
        v = t[i] - int(mc[i]) + (0 if c is None else c)
        limbs.append(v & LIMB_MASK)
        c = v >> LIMB_BITS
    ge = c == 0
    xp = _xp(*t)
    return [xp.where(ge, d, orig) for d, orig in zip(limbs, t)]


def l_select(cond, a: FL, b: FL) -> FL:
    """cond ? a : b per lane; ``cond`` is a bool array of the limb shape."""
    xp = _xp(*a.limbs, *b.limbs)
    return FL(tuple(xp.where(cond, x, y) for x, y in zip(a.limbs, b.limbs)),
              max(a.bound, b.bound))


def l_is_zero_mod_p(a: FL, fs: FieldSpec):
    limbs = l_canon(a, fs)
    z = limbs[0] == 0
    for i in range(1, NUM_LIMBS):
        z = z & (limbs[i] == 0)
    return z


def canon(a: FE, fs: FieldSpec):
    """Exact canonical reduction to [0, p) with canonical limbs.

    One sequential carry chain + log2(bound/p) conditional subtractions.
    Used once per verification (final equality), not in the hot path.
    """
    limbs = []
    c = jnp.zeros_like(a.arr[0])
    for i in range(NUM_LIMBS):
        v = a.arr[i] + c
        limbs.append(v & LIMB_MASK)
        c = v >> LIMB_BITS
    t = jnp.stack(limbs, axis=0)
    k = 1
    while k * fs.p < a.bound:
        k <<= 1
    while k >= 1:
        t = _cond_sub(t, k * fs.p)
        k //= 2
    return t


def _cond_sub(t, m: int):
    """t (canonical limbs) -> t − m if t >= m else t (exact borrow chain)."""
    mc = int_to_limbs(m)
    limbs = []
    c = jnp.zeros_like(t[0])
    for i in range(NUM_LIMBS):
        v = t[i] - int(mc[i]) + c
        limbs.append(v & LIMB_MASK)
        c = v >> LIMB_BITS
    ge = c == 0  # no net borrow -> t >= m
    d = jnp.stack(limbs, axis=0)
    return jnp.where(ge, d, t)


def eq_zero_canon(a):
    """all-limbs-zero test for an already-canonical array."""
    return jnp.all(a == 0, axis=0)


def is_zero_mod_p(a: FE, fs: FieldSpec):
    return eq_zero_canon(canon(a, fs))


def select(cond, a: FE, b: FE) -> FE:
    """cond ? a : b; cond has shape (N,)."""
    return FE(jnp.where(cond[None, :], a.arr, b.arr), max(a.bound, b.bound))
