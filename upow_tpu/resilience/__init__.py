"""Resilience layer: retry/backoff, circuit breakers, TPU degradation,
and deterministic fault injection.

Four independent pieces (policy, breaker, degrade, faultinject) plus the
:class:`ResilienceContext` glue that the node builds once from
``ResilienceConfig`` and hands to every :class:`NodeInterface`.  Nothing
in here touches consensus state — two nodes with different resilience
settings stay bit-identical on chain.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

from .breaker import (BreakerRegistry, CircuitBreaker, CircuitOpenError,
                      CLOSED, HALF_OPEN, OPEN)
from .degrade import DegradeManager
from .faultinject import (FaultInjected, FaultInjector, get_injector,
                          install, uninstall)
from .policy import DeadlineExceeded, RetryPolicy, call_with_retry

__all__ = [
    "BreakerRegistry", "CircuitBreaker", "CircuitOpenError",
    "CLOSED", "HALF_OPEN", "OPEN",
    "DegradeManager",
    "FaultInjected", "FaultInjector", "get_injector", "install",
    "uninstall",
    "DeadlineExceeded", "RetryPolicy", "call_with_retry",
    "ResilienceContext",
]


@dataclass
class ResilienceContext:
    """Everything an outbound-RPC wrapper needs, built once per node."""

    policy: RetryPolicy
    breakers: BreakerRegistry
    injector: Optional[FaultInjector] = None
    rng: Optional[random.Random] = None

    @classmethod
    def from_config(cls, rcfg, breakers: Optional[BreakerRegistry] = None,
                    injector: Optional[FaultInjector] = None
                    ) -> "ResilienceContext":
        policy = RetryPolicy(
            attempts=rcfg.rpc_attempts,
            base_delay=rcfg.rpc_backoff_base,
            max_delay=rcfg.rpc_backoff_max,
            multiplier=rcfg.rpc_backoff_multiplier,
            jitter=rcfg.rpc_jitter,
            deadline=rcfg.rpc_deadline,
        )
        if breakers is None:
            breakers = BreakerRegistry(
                failure_threshold=rcfg.breaker_failure_threshold,
                open_secs=rcfg.breaker_open_secs,
                half_open_max=rcfg.breaker_half_open_max,
            )
        return cls(policy=policy, breakers=breakers, injector=injector,
                   rng=random.Random(rcfg.faults_seed))
