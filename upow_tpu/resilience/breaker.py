"""Per-peer circuit breakers + health scores for the gossip/sync planes.

Classic three-state breaker per peer URL:

* **closed** — requests flow; ``failure_threshold`` consecutive failures
  trip it open.
* **open** — requests are refused locally (``CircuitOpenError``) for
  ``open_secs``; the peer costs nothing while it is down.
* **half-open** — after ``open_secs`` the next ``half_open_max`` calls
  are let through as trials: one success closes the breaker, one failure
  re-opens it for another ``open_secs``.

Alongside the state machine each breaker keeps an EWMA **health score**
in [0, 1] (1 = every recent call succeeded).  The :class:`PeerBook` uses
scores to prefer healthy peers for gossip fan-out and sync source
selection, and the ``/metrics`` endpoint exports per-state counts.

The clock is injectable so tests drive open→half-open transitions
without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_SCORE_ALPHA = 0.3  # EWMA weight of the newest observation


class CircuitOpenError(ConnectionError):
    """Raised locally instead of contacting a peer whose circuit is open."""

    def __init__(self, key: str):
        super().__init__(f"circuit open for {key}")
        self.key = key


class CircuitBreaker:
    """One peer's breaker state + health score."""

    def __init__(self, failure_threshold: int = 5, open_secs: float = 30.0,
                 half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 key: str = ""):
        self.failure_threshold = failure_threshold
        self.open_secs = open_secs
        self.half_open_max = half_open_max
        self._clock = clock
        self.key = key  # peer URL when registry-owned; "" standalone
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._half_open_trials = 0
        self.score = 1.0
        self.transitions: List[str] = [CLOSED]  # observable cycle history

    # ---------------------------------------------------------- state ----
    @property
    def state(self) -> str:
        """Current state, applying the time-based open→half-open move."""
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.open_secs:
            self._set_state(HALF_OPEN)
            self._half_open_trials = 0
        return self._state

    def _set_state(self, state: str) -> None:
        if state != self._state:
            previous = self._state
            self._state = state
            self.transitions.append(state)
            from .. import trace

            trace.event("breaker", peer=self.key or None, state=state,
                        previous=previous,
                        failures=self._consecutive_failures)

    def available(self) -> bool:
        """May a request be sent now?  Half-open admits up to
        ``half_open_max`` concurrent trials (accounted per call here —
        a refused trial does not consume a slot)."""
        state = self.state
        if state == CLOSED:
            return True
        if state == HALF_OPEN:
            if self._half_open_trials < self.half_open_max:
                self._half_open_trials += 1
                return True
            return False
        return False

    def usable(self) -> bool:
        """Non-consuming peek for peer *selection*: open = skip, closed
        or half-open = a candidate.  Unlike :meth:`available` this never
        spends a half-open trial slot."""
        return self.state != OPEN

    # -------------------------------------------------------- outcomes ----
    def record_success(self) -> None:
        self._consecutive_failures = 0
        self.score += _SCORE_ALPHA * (1.0 - self.score)
        if self.state == HALF_OPEN:
            self._set_state(CLOSED)

    def record_failure(self) -> None:
        self._consecutive_failures += 1
        self.score -= _SCORE_ALPHA * self.score
        state = self.state
        if state == HALF_OPEN or (
                state == CLOSED and
                self._consecutive_failures >= self.failure_threshold):
            self._set_state(OPEN)
            self._opened_at = self._clock()

    def snapshot(self) -> dict:
        """Observable state for /debug/breakers and swarm assertions:
        the ranking inputs (state + EWMA score) plus the cumulative
        flip count so "did this peer's circuit cycle during the
        scenario" is a direct read, not a transition-log diff."""
        return {"state": self.state, "score": round(self.score, 4),
                "consecutive_failures": self._consecutive_failures,
                "flips": len(self.transitions) - 1}


class BreakerRegistry:
    """Breakers keyed by peer URL, created on first touch.

    Thread-safe on the registry dict only: individual breakers are
    mutated from the event loop, which is single-threaded per node.
    Unknown peers read as healthy (score 1.0, available) so a fresh
    peer book behaves exactly as before the resilience layer existed.
    """

    def __init__(self, failure_threshold: int = 5, open_secs: float = 30.0,
                 half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self._kw = dict(failure_threshold=failure_threshold,
                        open_secs=open_secs, half_open_max=half_open_max)
        self._clock = clock
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            breaker = self._breakers.get(key)
            if breaker is None:
                breaker = CircuitBreaker(clock=self._clock, key=key,
                                         **self._kw)
                self._breakers[key] = breaker
            return breaker

    def peek(self, key: str) -> Optional[CircuitBreaker]:
        return self._breakers.get(key)

    # ------------------------------------------------------- delegation ---
    def available(self, key: str) -> bool:
        breaker = self.peek(key)
        return True if breaker is None else breaker.available()

    def usable(self, key: str) -> bool:
        breaker = self.peek(key)
        return True if breaker is None else breaker.usable()

    def score(self, key: str) -> float:
        breaker = self.peek(key)
        return 1.0 if breaker is None else breaker.score

    def record_success(self, key: str) -> None:
        self.get(key).record_success()

    def record_failure(self, key: str) -> None:
        self.get(key).record_failure()

    # ------------------------------------------------------------ views ---
    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._breakers.items())
        return {key: b.snapshot() for key, b in items}

    def state_counts(self) -> Dict[str, int]:
        counts = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        for snap in self.snapshot().values():
            counts[snap["state"]] += 1
        return counts
