"""Seeded, deterministic fault injection for the chaos suite.

A :class:`FaultInjector` holds a list of :class:`Fault` rules parsed
from a compact spec string (``ResilienceConfig.faults`` or
``UPOW_RESILIENCE_FAULTS``)::

    site:kind[:k=v,...][;site:kind...]

    rpc:error:p=0.5,key=9001        every other RPC to a :9001 peer errors
    device.verify:error:times=3     first three device verifies error
    ws.send:latency:delay=0.2       every ws send stalls 200 ms
    rpc:hang:times=1,delay=30       one RPC hangs 30 s (deadline food)
    swarm.link:error:p=0.3          a third of simulated link transfers die

Registered sites: ``rpc.<path>`` (peers.py, per peer RPC attempt),
``ws.send`` (ws/hub.py, per outbound frame), ``device.verify``
(txverify.py), ``device.runtime`` (device/runtime.py — fires once per
drained dispatch with key ``"sig:<sources>"`` for coalesced signature
groups or ``"call:<kernel>"`` for single-kernel calls, so ``key=`` can
target one subsystem's traffic), ``swarm.link`` (swarm/links.py —
fires once per simulated transfer with key ``"src->dst"``, so ``key=``
can target one direction of one link), ``snapshot.serve`` (node/app.py
— per /snapshot/manifest and /snapshot/chunk response, key
``"manifest"`` or ``"chunk/<i>"``; the ``corrupt`` kind flips served
chunk bytes instead of erroring), ``snapshot.fetch``
(snapshot/client.py, per bootstrap RPC attempt inside the retry
policy, key ``"<source url>#manifest"`` or ``"<source url>#chunk/<i>"``),
``archive.compact`` (archive/compactor.py — fires at each phase of a
compaction cycle with key ``"closure"``, ``"segment/<lo>"``,
``"publish"`` or ``"prune"``; an ``error`` kind between publish and
prune simulates a kill -9 between archive-commit and hot-delete) and
``archive.fetch`` (archive/reader.py fetch_archive, key ``"manifest"``
or ``"segment/<i>"``; ``corrupt`` rewrites fetched payload bytes so
integrity rejection paths can be exercised).

Sites are prefix-matched (``rpc`` matches ``rpc.get_blocks``); ``key``
substring-filters the per-call key (usually the peer URL).  ``kind`` is
``error`` (raise :class:`FaultInjected`), ``latency`` (sleep ``delay``
then proceed), ``hang`` (sleep ``delay``, default far beyond any
deadline, then raise) or ``corrupt`` (only consulted by sites that
pass payload bytes through :meth:`FaultInjector.fire_mutate`: the
payload comes back bit-flipped, modelling a peer serving damaged data
that only an integrity check can catch).  ``p`` draws from ONE seeded
``random.Random`` so a fixed ``faults_seed`` replays the exact fault
schedule; ``times`` caps how often a rule fires (-1 = unlimited).

Production stance: the hooks in peers.py / hub.py / txverify.py call
:func:`get_injector` which returns ``None`` unless :func:`install` ran
with a non-empty spec — the disabled cost is one module attribute read.
"""

from __future__ import annotations

import asyncio
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..logger import get_logger

log = get_logger("faultinject")

KINDS = ("error", "latency", "hang", "corrupt")
#: Kinds the control-flow injection points (fire / fire_sync) act on —
#: a ``corrupt`` rule must never raise there, it only rewrites payloads
#: at fire_mutate sites.
_FLOW_KINDS = ("error", "latency", "hang")
_HANG_DEFAULT = 3600.0  # beyond any sane deadline; boxed/wait_for food


class FaultInjected(ConnectionError):
    """An injected failure.  Subclasses ConnectionError so the retry and
    breaker layers treat it exactly like a real transport fault."""

    def __init__(self, site: str, key: str = ""):
        super().__init__(f"injected fault at {site}"
                         + (f" ({key})" if key else ""))
        self.site = site


@dataclass
class Fault:
    site: str                   # prefix match against the fire() site
    kind: str                   # error | latency | hang
    p: float = 1.0              # fire probability per matching call
    times: int = -1             # max fires (-1 = unlimited)
    delay: float = 0.0          # latency/hang sleep (hang defaults 3600)
    key: str = ""               # substring filter on the per-call key
    fired: int = 0              # observability: how often it has fired

    def matches(self, site: str, key: str) -> bool:
        if self.times >= 0 and self.fired >= self.times:
            return False
        if not (site == self.site or site.startswith(self.site + ".")):
            return False
        return self.key in key if self.key else True


def parse_spec(spec: str) -> List[Fault]:
    faults = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        bits = part.split(":", 2)
        if len(bits) < 2:
            raise ValueError(f"fault spec {part!r}: want site:kind[:k=v,..]")
        site, kind = bits[0], bits[1]
        if kind not in KINDS:
            raise ValueError(f"fault kind {kind!r} not in {KINDS}")
        kwargs: Dict[str, object] = {}
        if len(bits) == 3 and bits[2]:
            for pair in bits[2].split(","):
                name, _, raw = pair.partition("=")
                if name == "p":
                    kwargs["p"] = float(raw)
                elif name == "times":
                    kwargs["times"] = int(raw)
                elif name == "delay":
                    kwargs["delay"] = float(raw)
                elif name == "key":
                    kwargs["key"] = raw
                else:
                    raise ValueError(f"fault spec {part!r}: unknown "
                                     f"option {name!r}")
        fault = Fault(site=site, kind=kind, **kwargs)
        if fault.kind == "hang" and not fault.delay:
            fault.delay = _HANG_DEFAULT
        faults.append(fault)
    return faults


class FaultInjector:
    """Evaluates fault rules at named sites, deterministically."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.faults = parse_spec(spec)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def _pick(self, site: str, key: str,
              kinds=_FLOW_KINDS) -> Optional[Fault]:
        with self._lock:
            for fault in self.faults:
                if fault.kind not in kinds:
                    continue
                if fault.matches(site, key) and \
                        (fault.p >= 1.0 or self._rng.random() < fault.p):
                    fault.fired += 1
                    return fault
        return None

    async def fire(self, site: str, key: str = "") -> None:
        """Async injection point: sleep and/or raise per the first
        matching armed rule.  No-op when nothing matches."""
        fault = self._pick(site, key)
        if fault is None:
            return
        self._count(fault, site, key)
        if fault.kind == "latency":
            await asyncio.sleep(fault.delay)
            return
        if fault.kind == "hang":
            await asyncio.sleep(fault.delay)
        raise FaultInjected(site, key)

    def fire_sync(self, site: str, key: str = "") -> None:
        """Blocking injection point for executor-thread sites
        (device.verify runs inside boxed_call's worker thread — a hang
        here is exactly what the box is designed to absorb)."""
        fault = self._pick(site, key)
        if fault is None:
            return
        self._count(fault, site, key)
        if fault.kind == "latency":
            time.sleep(fault.delay)
            return
        if fault.kind == "hang":
            time.sleep(fault.delay)
        raise FaultInjected(site, key)

    def fire_mutate(self, site: str, key: str, data: bytes) -> bytes:
        """Payload injection point: a matching ``corrupt`` rule returns
        the data with one deterministically-chosen byte flipped (seeded
        RNG picks the offset), so downstream integrity checks — not
        transport error handling — are what must catch it."""
        fault = self._pick(site, key, kinds=("corrupt",))
        if fault is None or not data:
            return data
        self._count(fault, site, key)
        with self._lock:
            offset = self._rng.randrange(len(data))
        out = bytearray(data)
        out[offset] ^= 0xFF
        return bytes(out)

    def _count(self, fault: Fault, site: str, key: str) -> None:
        from .. import trace

        trace.inc("resilience.faults_injected")
        trace.event("fault_injected", site=site, fault=fault.kind,
                    key=key or None, fire=fault.fired)
        log.info("fault injected: %s at %s key=%s (fire #%d)",
                 fault.kind, site, key or "-", fault.fired)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"site": f.site, "kind": f.kind, "fired": f.fired,
                     "times": f.times} for f in self.faults]


# ---------------------------------------------------------------- global ---
# One injector per process, None when disabled.  Hooks read this via
# get_injector(); tests install/uninstall around each scenario.
_injector: Optional[FaultInjector] = None


def install(spec: str, seed: int = 0) -> Optional[FaultInjector]:
    """Install a process-wide injector; empty spec uninstalls."""
    global _injector
    _injector = FaultInjector(spec, seed) if spec else None
    if _injector is not None:
        log.warning("fault injection ACTIVE: %s (seed=%d)", spec, seed)
    return _injector


def uninstall() -> None:
    global _injector
    _injector = None


def get_injector() -> Optional[FaultInjector]:
    return _injector
