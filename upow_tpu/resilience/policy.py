"""Retry with jittered exponential backoff under a total deadline budget.

One policy object describes how a *logical* call may be retried:
``attempts`` tries, exponentially spaced (``base_delay`` ×
``multiplier``^n, capped at ``max_delay``), each delay jittered ±
``jitter`` so a fleet of nodes retrying the same dead peer does not
synchronize into thundering herds.  ``deadline`` bounds the WHOLE call —
attempts plus backoffs — so a retried RPC can never exceed its budget no
matter how the per-attempt transport timeouts land.

Determinism: all randomness flows through an injectable ``random.Random``
(the chaos suite pins it), and time/sleep are injectable for unit tests.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

_DEFAULT_RNG = random.Random()


class DeadlineExceeded(TimeoutError):
    """The policy's total deadline ran out (before or between attempts)."""


@dataclass
class RetryPolicy:
    attempts: int = 3           # total tries (1 = no retry)
    base_delay: float = 0.25    # delay before the first retry
    max_delay: float = 2.0      # per-delay ceiling
    multiplier: float = 2.0     # exponential growth factor
    jitter: float = 0.5         # each delay scaled by [1-j, 1+j]
    deadline: float = 45.0      # total budget in seconds; 0 = unbounded

    def delay_for(self, retry_no: int, rng: Optional[random.Random] = None
                  ) -> float:
        """Backoff before retry ``retry_no`` (1-based), jittered."""
        delay = min(self.max_delay,
                    self.base_delay * self.multiplier ** (retry_no - 1))
        if self.jitter:
            rng = rng or _DEFAULT_RNG
            delay *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, delay)


async def call_with_retry(fn: Callable, policy: RetryPolicy, *,
                          retry_on: Tuple[Type[BaseException], ...] = (
                              Exception,),
                          rng: Optional[random.Random] = None,
                          on_retry: Optional[Callable] = None,
                          clock: Callable[[], float] = time.monotonic,
                          sleep: Callable = asyncio.sleep):
    """Await ``fn()`` with the policy's retry/backoff/deadline semantics.

    ``fn`` is a zero-arg coroutine *factory* (each attempt gets a fresh
    coroutine).  Each attempt is bounded by the remaining deadline via
    ``asyncio.wait_for``; exceptions not in ``retry_on`` propagate
    immediately.  ``on_retry(exc, retry_no)`` fires before each backoff
    sleep (metrics hook).
    """
    start = clock()
    retry_no = 0
    while True:
        remaining = None
        if policy.deadline:
            remaining = policy.deadline - (clock() - start)
            if remaining <= 0:
                raise DeadlineExceeded(
                    f"retry deadline {policy.deadline}s exceeded")
        try:
            if remaining is not None:
                return await asyncio.wait_for(fn(), remaining)
            return await fn()
        except retry_on as e:
            retry_no += 1
            if retry_no >= policy.attempts:
                raise
            delay = policy.delay_for(retry_no, rng)
            if policy.deadline:
                budget = policy.deadline - (clock() - start)
                if budget <= 0:
                    raise
                delay = min(delay, budget)
            if on_retry is not None:
                on_retry(e, retry_no)
            await sleep(delay)
