"""TPU → CPU graceful degradation for the verify hot path.

The signature-verify dispatch (verify/txverify.py) already survives a
sick accelerator — errors fall back to the host batch, hangs are
time-boxed — but before this module the policy was a one-way door: a few
consecutive device errors *poisoned* the device path for the life of the
process, so one transient XLA blip (tunnel flap, OOM during an unrelated
compile) cost the node its accelerator forever.

:class:`DegradeManager` replaces the globals with a three-state machine:

* **ok** — device dispatches flow.
* **degraded** — after ``failure_limit`` consecutive *raised* errors
  (compile failure, transport error) the device path is benched and the
  CPU reference verifier serves every block; after ``cooldown`` seconds
  ONE dispatch is let through as a re-probe — success restores **ok**,
  failure re-benches for another cooldown.
* **poisoned** — a *hang* (boxed-call timeout) is unrecoverable: the
  stuck daemon thread holds the PJRT client, so the device path stays
  off for the life of the process, exactly as before.

Every transition and every blocked dispatch is counted through
``trace.inc`` so the ``/metrics`` endpoint and the chaos suite can
observe degradation and recovery.

The manager is mutated from executor threads (the verify dispatch runs
off-loop) — all state moves under one lock.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ..logger import get_logger

log = get_logger("degrade")

OK = "ok"
DEGRADED = "degraded"
POISONED = "poisoned"

_STATE_GAUGE = {OK: 0, DEGRADED: 1, POISONED: 2}


class DegradeManager:
    """Device-health state machine feeding the verify backend router."""

    def __init__(self, failure_limit: int = 3, cooldown: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_limit = failure_limit
        self.cooldown = cooldown
        self._clock = clock
        self._lock = threading.Lock()
        self._state = OK
        self._consecutive_failures = 0
        self._degraded_at = 0.0
        self._probe_in_flight = False

    def configure(self, failure_limit: int, cooldown: float) -> None:
        """Apply config knobs (Node startup); state is preserved."""
        with self._lock:
            self.failure_limit = failure_limit
            self.cooldown = cooldown

    # ------------------------------------------------------------ gates ---
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def state_gauge(self) -> int:
        """0 = ok, 1 = degraded, 2 = poisoned (the /metrics encoding)."""
        return _STATE_GAUGE[self.state]

    def allow(self) -> bool:
        """May the next verify batch go to the device?

        In ``degraded`` this is False until ``cooldown`` has elapsed,
        then True (the re-probe) until that probe resolves via
        :meth:`record_success` / :meth:`record_failure` — the backend
        resolver consults this more than once per dispatch (cached and
        uncached layers), so an in-flight probe keeps answering True
        rather than bouncing its own dispatch back to the host.  Each
        refusal is counted as a CPU fallback.
        """
        from .. import trace

        with self._lock:
            if self._state == OK:
                return True
            if self._state == POISONED:
                trace.inc("resilience.device_fallback")
                return False
            if self._probe_in_flight:
                return True
            if self._clock() - self._degraded_at < self.cooldown:
                trace.inc("resilience.device_fallback")
                return False
            self._probe_in_flight = True
            trace.inc("resilience.device_reprobe")
            log.info("device cooldown elapsed; re-probing the device "
                     "verify path")
            return True

    # --------------------------------------------------------- outcomes ---
    def record_success(self) -> None:
        from .. import trace

        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state == DEGRADED:
                self._state = OK
                trace.inc("resilience.device_recovered")
                trace.event("degrade", state=OK, previous=DEGRADED)
                log.warning("device verify path recovered; leaving "
                            "CPU-degraded mode")

    def record_failure(self, error: BaseException = None) -> None:
        from .. import trace

        with self._lock:
            trace.inc("resilience.device_error")
            self._consecutive_failures += 1
            self._probe_in_flight = False
            if self._state == DEGRADED:
                self._degraded_at = self._clock()  # failed probe: re-bench
                return
            if self._state == OK and \
                    self._consecutive_failures >= self.failure_limit:
                self._state = DEGRADED
                self._degraded_at = self._clock()
                trace.inc("resilience.device_degraded")
                trace.event("degrade", state=DEGRADED, previous=OK,
                            failures=self._consecutive_failures,
                            error=str(error) if error else None)
                log.warning(
                    "device verify path degraded after %d consecutive "
                    "errors (%s); falling back to the CPU reference "
                    "verifier, re-probe in %.0fs",
                    self._consecutive_failures, error, self.cooldown)

    def poison(self, reason: str = "") -> None:
        """A hang: the stuck thread cannot be reclaimed — device off for
        the life of the process."""
        from .. import trace

        with self._lock:
            if self._state != POISONED:
                prev = self._state
                self._state = POISONED
                trace.inc("resilience.device_poisoned")
                trace.event("degrade", state=POISONED, previous=prev,
                            reason=reason or None)
                log.warning("device verify path poisoned%s; CPU path for "
                            "the rest of this process",
                            f" ({reason})" if reason else "")

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "cooldown": self.cooldown,
                    "failure_limit": self.failure_limit}
