"""Cross-node trace stitcher: per-node span trees → one fleet trace.

A push_tx or block propagation carries ONE trace id across nodes
(``X-Upow-Trace``: the middleware adopts inbound ids, gossip clients
attach the current id outbound).  Each node records its own root span
tree into its own buffer; this module joins the trees that share a
trace id into a single fleet trace ordered by wall-clock start, with
per-hop latencies (start-to-start between consecutive hops on
different nodes).

Wall clocks in the swarm are one process clock, so hop latencies are
exact; on real deployments they carry the usual NTP caveat.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _roots(traces_by_node: Dict[str, dict]) -> List[dict]:
    out = []
    for label, buf in traces_by_node.items():
        for root in buf.get("recent", []):
            if root.get("trace_id"):
                out.append({**root, "node": label})
    out.sort(key=lambda t: (t.get("start_ts") or 0, t.get("node") or ""))
    return out


def _span_count(root: dict) -> int:
    return 1 + sum(_span_count(c) for c in root.get("spans", []))


def stitch(traces_by_node: Dict[str, dict],
           trace_id: Optional[str] = None) -> Dict[str, dict]:
    """{trace_id: fleet trace} over every id (or just ``trace_id``).

    A fleet trace:

    * ``hops`` — every root sharing the id, start-ordered, labelled
      with its node, name, start_ts, duration_ms and span count;
    * ``nodes`` — distinct nodes in hop order;
    * ``hop_latencies_ms`` — start-to-start deltas between
      consecutive hops that changed node (the wire+queue cost of
      each fan-out edge);
    * ``duration_ms`` — first hop start to last hop end.
    """
    grouped: Dict[str, List[dict]] = {}
    for root in _roots(traces_by_node):
        tid = root["trace_id"]
        if trace_id is not None and tid != trace_id:
            continue
        grouped.setdefault(tid, []).append(root)

    fleet: Dict[str, dict] = {}
    for tid, roots in grouped.items():
        nodes: List[str] = []
        for r in roots:
            if r["node"] not in nodes:
                nodes.append(r["node"])
        hops = [{
            "node": r["node"],
            "name": r.get("name"),
            "start_ts": r.get("start_ts"),
            "duration_ms": r.get("duration_ms"),
            "spans": _span_count(r),
            "error": r.get("error"),
        } for r in roots]
        hop_latencies = []
        for prev, cur in zip(roots, roots[1:]):
            if cur["node"] != prev["node"]:
                hop_latencies.append({
                    "from": prev["node"], "to": cur["node"],
                    "latency_ms": round(
                        (cur["start_ts"] - prev["start_ts"]) * 1000.0, 3),
                })
        t0 = roots[0].get("start_ts") or 0
        t_end = max((r.get("start_ts") or 0)
                    + (r.get("duration_ms") or 0) / 1000.0 for r in roots)
        fleet[tid] = {
            "trace_id": tid,
            "nodes": nodes,
            "node_count": len(nodes),
            "hops": hops,
            "hop_latencies_ms": hop_latencies,
            "duration_ms": round((t_end - t0) * 1000.0, 3),
        }
    return fleet


def stitch_one(traces_by_node: Dict[str, dict],
               trace_id: str) -> Optional[dict]:
    return stitch(traces_by_node, trace_id=trace_id).get(trace_id)
