"""Fleet scraper: one merged snapshot over every node's telemetry.

Two collection paths, same fleet:

* :func:`scrape` goes through the front door — each node's /metrics,
  /debug/traces and /debug/events over the loopback hub, exactly what
  an external Prometheus + trace collector would see (including the
  per-node middleware scope binding that keeps 50 in-process nodes
  from serving each other's registries).
* :func:`local_snapshot` reads each node's ``telemetry_scope``
  directly — no HTTP, no shaped-link latency — for scenario
  assertions and the flight recorder.

:func:`render_fleet` folds a scrape into the ``upow_fleet_*``
exposition families (validated by ``make metrics-check``).
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional

from .. import telemetry
from ..telemetry.exposition import Exposition
from . import propagation

#: bucket bounds for fleet propagation histograms — wider than request
#: latency buckets: cross-continent gossip legitimately takes ~100ms.
PROPAGATION_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def _scope(node):
    return getattr(node, "telemetry_scope", None)


# ------------------------------------------------------- HTTP scrape ----

async def scrape(swarm) -> dict:
    """Collect every node's observability surface via the hub."""
    nodes: Dict[str, dict] = {}
    for i, url in enumerate(swarm.urls):
        ms, mbody = await swarm.hub.request(
            swarm.driver, url, "GET", "/metrics")
        _, tbody = await swarm.hub.request(
            swarm.driver, url, "GET", "/debug/traces")
        _, ebody = await swarm.hub.request(
            swarm.driver, url, "GET", "/debug/events")
        nodes[f"node{i}"] = {
            "url": url,
            "metrics_status": ms,
            "metrics_text": mbody.decode(),
            "traces": json.loads(tbody.decode()).get("result", {}),
            "events": json.loads(ebody.decode()).get("result", []),
        }
    return {"kind": "fleet_snapshot", "nodes": nodes}


# ------------------------------------------------------ direct reads ----

def local_snapshot(swarm) -> dict:
    """Direct per-scope reads (no HTTP): registries + in-flight traces."""
    nodes: Dict[str, dict] = {}
    for i, node in enumerate(swarm.nodes):
        sc = _scope(node)
        if sc is None:
            continue
        nodes[f"node{i}"] = {
            "url": swarm.urls[i],
            "counters": sc.metrics.counters(),
            "stats": sc.metrics.stats(),
            "histograms": sc.metrics.histograms(),
            "traces": sc.traces.snapshot(),
            "open_traces": sc.traces.open_snapshot(),
            "events": sc.events.snapshot(),
        }
    return {
        "kind": "fleet_local_snapshot",
        # the driver context (scenario code itself) runs unscoped
        "driver": {"traces": telemetry.traces(),
                   "events": telemetry.events.snapshot()},
        "nodes": nodes,
    }


def events_by_node(swarm, kind: Optional[str] = None) -> Dict[str, list]:
    """{node label: events oldest-first}, driver ring under "driver"."""
    out: Dict[str, list] = {"driver": telemetry.events.snapshot(kind=kind)}
    for i, node in enumerate(swarm.nodes):
        sc = _scope(node)
        if sc is not None:
            out[f"node{i}"] = sc.events.snapshot(kind=kind)
    return out


def merged_events(swarm, kind: Optional[str] = None) -> List[dict]:
    """All nodes' + driver events, globally ordered by timestamp."""
    out: List[dict] = []
    for recs in events_by_node(swarm, kind=kind).values():
        out.extend(recs)
    out.sort(key=lambda e: e.get("ts") or 0)
    return out


def traces_by_node(swarm) -> Dict[str, dict]:
    """{node label: TraceBuffer snapshot}, driver buffer included."""
    out: Dict[str, dict] = {"driver": telemetry.traces()}
    for i, node in enumerate(swarm.nodes):
        sc = _scope(node)
        if sc is not None:
            out[f"node{i}"] = sc.traces.snapshot()
    return out


def merged_trace_roots(swarm, trace_id: Optional[str] = None) -> List[dict]:
    """Recent trace roots across the fleet, optionally one trace id."""
    out: List[dict] = []
    for label, buf in traces_by_node(swarm).items():
        for root in buf.get("recent", []):
            if trace_id is None or root.get("trace_id") == trace_id:
                out.append({**root, "node": label})
    out.sort(key=lambda t: t.get("start_ts") or 0)
    return out


# -------------------------------------------------- fleet exposition ----

def _gauge_value(text: str, family: str) -> Optional[float]:
    for ln in text.splitlines():
        if ln.startswith(family + " "):
            try:
                return float(ln.split()[1])
            except ValueError:
                return None
    return None


def _hist_shape(values_s: List[float], bounds) -> dict:
    counts = [0] * (len(bounds) + 1)
    for v in values_s:
        for i, bound in enumerate(bounds):
            if v <= bound:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    return {"bounds": bounds, "counts": counts,
            "count": len(values_s), "sum": float(sum(values_s))}


def render_fleet(snapshot: dict, prop: Optional[dict] = None) -> str:
    """Render the merged ``upow_fleet_*`` families from a scrape.

    ``prop`` is a propagation report (propagation.report); when
    omitted it is derived from the scraped event rings."""
    nodes = snapshot.get("nodes", {})
    if prop is None:
        prop = propagation.report(
            {label: rec.get("events", []) for label, rec in nodes.items()},
            n_nodes=len(nodes))

    e = Exposition(prefix="upow")
    e.gauge("fleet.nodes", len(nodes),
            "nodes aggregated into this fleet snapshot")
    heights = [h for h in
               (_gauge_value(rec.get("metrics_text", ""),
                             "upow_block_height")
                for rec in nodes.values()) if h is not None]
    if heights:
        e.gauge("fleet.height_min", min(heights))
        e.gauge("fleet.height_max", max(heights))
        e.gauge("fleet.height_spread", max(heights) - min(heights),
                "max-min chain height across nodes (0 = converged)")
    pools = [p for p in
             (_gauge_value(rec.get("metrics_text", ""),
                           "upow_mempool_transactions")
              for rec in nodes.values()) if p is not None]
    if pools:
        e.gauge("fleet.mempool_total", sum(pools))
    e.counter("fleet.events",
              sum(len(rec.get("events", [])) for rec in nodes.values()),
              "events retained across all node rings")
    e.counter("fleet.traces",
              sum(len(rec.get("traces", {}).get("recent", []))
                  for rec in nodes.values()),
              "completed traces retained across all node buffers")

    for family, rep in (("fleet.block_propagation", prop["blocks"]),
                        ("fleet.tx_propagation", prop["txs"])):
        e.gauge(family + "_p50_ms", rep["p50_ms"])
        e.gauge(family + "_p95_ms", rep["p95_ms"])
        e.gauge(family + "_p99_ms", rep["p99_ms"])
        spreads = [s for s in rep.get("spreads_ms", [])
                   if not math.isnan(s)]
        h = _hist_shape([s / 1000.0 for s in spreads],
                        PROPAGATION_BUCKETS)
        e.histogram(family + "_seconds", h["bounds"], h["counts"],
                    h["count"], h["sum"])
    return e.render()
