"""CLI entry: run the geo-soak, print the fleet view, gate or merge.

    python -m upow_tpu.fleet                          # geo-soak, print rows
    python -m upow_tpu.fleet --check-determinism      # two runs, compare fp
    python -m upow_tpu.fleet --merge-observatory observatory.json
    python -m upow_tpu.fleet --out fleet.json --trace

Exit status is non-zero when a core assertion failed, the stitched
push_tx trace did not cross three nodes, or (under
``--check-determinism``) the two same-seed fingerprints differ — so
CI's ``fleet-smoke`` job can gate on the run directly.
"""

from __future__ import annotations

import argparse
import json
import sys

from .geosoak import (GEO_NODES, GEO_SEED, fleet_rows, merge_into_observatory,
                      run_geo_artifact)


def _core_ok(core: dict) -> bool:
    return all(v for v in core.values() if isinstance(v, bool))


def _print_run(artifact: dict) -> bool:
    core = artifact["core"]
    good = _core_ok(core)
    print(f"{'ok  ' if good else 'FAIL'} {artifact['scenario']:>16} "
          f"n={artifact['nodes']} seed={artifact['seed']} "
          f"{artifact['observed']['elapsed_s']:.2f}s "
          f"fp={artifact['fingerprint'][:16]}")
    if not good:
        for key, val in sorted(core.items()):
            if isinstance(val, bool) and not val:
                print(f"     core failed: {key}", file=sys.stderr)
    return good


def _print_propagation(artifact: dict) -> None:
    prop = artifact["observed"].get("propagation") or {}
    for family in ("blocks", "txs"):
        row = prop.get(family)
        if not row:
            continue
        print(f"     {family:>6}: hashes={row['hashes']} "
              f"covered={row['covered']} "
              f"p50={row['p50_ms']}ms p95={row['p95_ms']}ms "
              f"p99={row['p99_ms']}ms")


def _print_trace(artifact: dict) -> None:
    stitched = artifact["observed"].get("stitched_push_tx")
    if not stitched:
        print("     no stitched push_tx trace", file=sys.stderr)
        return
    print(f"     trace {stitched['trace_id'][:16]} crossed "
          f"{stitched['node_count']} nodes in "
          f"{stitched['duration_ms']}ms:")
    for hop in stitched["hops"]:
        print(f"       {hop['node']:>8} {hop['name']:<28} "
              f"{hop['duration_ms']}ms spans={hop['spans']}"
              + (" ERROR" if hop.get("error") else ""))
    for edge in stitched["hop_latencies_ms"]:
        print(f"       edge {edge['from']} -> {edge['to']}: "
              f"{edge['latency_ms']}ms")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m upow_tpu.fleet",
        description="fleet observatory: deterministic geo-soak, "
                    "propagation percentiles, stitched traces")
    parser.add_argument("--nodes", type=int, default=GEO_NODES,
                        help=f"swarm size (default {GEO_NODES})")
    parser.add_argument("--seed", type=int, default=GEO_SEED)
    parser.add_argument("--out", help="write the JSON artifact here")
    parser.add_argument("--trace", action="store_true",
                        help="print the stitched push_tx fleet trace")
    parser.add_argument("--check-determinism", action="store_true",
                        help="run twice with the same seed and fail "
                             "unless the core fingerprints are identical")
    parser.add_argument("--merge-observatory", metavar="PATH",
                        help="merge the fleet kernel/SLO rows into an "
                             "existing observatory artifact (the "
                             "perf-smoke baseline)")
    parser.add_argument("--gate-against", metavar="PATH",
                        help="after the run, gate the fleet rows "
                             "against this observatory baseline "
                             "(fleet_core_ok enforced, propagation "
                             "quantiles report-only)")
    args = parser.parse_args(argv)

    if args.merge_observatory:
        merged = merge_into_observatory(args.merge_observatory,
                                        nodes=args.nodes, seed=args.seed)
        fleet = merged["section"]
        good = bool(fleet["core_ok"])
        print(f"{'ok  ' if good else 'FAIL'} merged fleet rows into "
              f"{args.merge_observatory} "
              f"(fp={fleet['fingerprint'][:16]})")
        return 0 if good else 1

    artifact = run_geo_artifact(nodes=args.nodes, seed=args.seed)
    ok = _print_run(artifact)
    _print_propagation(artifact)
    if args.trace:
        _print_trace(artifact)

    stitched = artifact["observed"].get("stitched_push_tx") or {}
    if (stitched.get("node_count") or 0) < 3:
        print("fleet: stitched push_tx trace crossed "
              f"{stitched.get('node_count', 0)} nodes (< 3)",
              file=sys.stderr)
        ok = False

    if args.check_determinism:
        again = run_geo_artifact(nodes=args.nodes, seed=args.seed)
        if again["fingerprint"] != artifact["fingerprint"]:
            print("fleet: DETERMINISM BROKEN "
                  f"{artifact['fingerprint'][:16]} != "
                  f"{again['fingerprint'][:16]}", file=sys.stderr)
            ok = False
        else:
            print(f"ok   determinism: fp={artifact['fingerprint'][:16]} "
                  "reproduced")

    if args.out:
        from ..loadgen.observatory import write_artifact

        write_artifact(artifact, args.out)

    rows = fleet_rows(artifact)
    print(json.dumps({"kind": "fleet_observatory",
                      "fingerprint": artifact["fingerprint"],
                      "kernels": {k: v["value"]
                                  for k, v in rows["kernels"].items()}},
                     sort_keys=True))

    if args.gate_against:
        import os
        import tempfile

        from ..loadgen import gate

        # shape the fleet rows like an observatory artifact so
        # gate.flatten compares them against the committed baseline
        current = {"kernels": rows["kernels"],
                   "slo": {"endpoints": rows["slo_endpoints"]}}
        fd, tmp = tempfile.mkstemp(prefix="fleet-gate-", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(current, f)
            rc = gate.main([
                "--against", args.gate_against, "--current", tmp,
                "--report-only",
                "--enforce", "kernel.fleet_core_ok",
                # wall-clock quantiles on shared CI hosts are noisy;
                # the correctness trip is fleet_core_ok's zeroing,
                # which defeats any tolerance
                "--metric-tolerance", "kernel.fleet_block_prop_p50_ms=3.0",
                "--metric-tolerance", "kernel.fleet_block_prop_p95_ms=3.0",
                "--metric-tolerance", "kernel.fleet_tx_prop_p50_ms=3.0",
                "--metric-tolerance", "kernel.fleet_tx_prop_p95_ms=3.0",
            ])
        finally:
            os.unlink(tmp)
        ok = ok and rc == 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
