"""Fleet propagation tracker: first-seen stamps → p50/p95/p99.

Every node emits ``block_seen`` (on commit, both accept paths) and
``tx_seen`` (on mempool accept) into its own event ring.  With one
ring per node (telemetry/scope.py) the fleet-wide first-seen matrix
falls out of the merged snapshot:

* **block spread** (per block hash): time from the FIRST node that
  committed it to the moment 90% of nodes (``coverage``) have — the
  paper's propagation question, "how long until the fleet agrees".
* **tx-to-mempool** (per tx hash): first acceptance to the last
  node's acceptance among the nodes that saw it.

Quantiles run over the per-hash spreads; a hash seen by fewer nodes
than the coverage threshold is excluded from block quantiles (it
never propagated — that is a convergence failure for the scenario
core to flag, not a latency number).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

#: labels in an events-by-node mapping that are not nodes
_NON_NODE_LABELS = ("driver",)


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Linear interpolation on sorted values; NaN when empty."""
    if not sorted_vals:
        return math.nan
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def first_seen(events_by_node: Dict[str, List[dict]],
               kind: str) -> Dict[str, Dict[str, float]]:
    """{hash: {node: first-seen ts}} for one event kind."""
    out: Dict[str, Dict[str, float]] = {}
    for node, recs in events_by_node.items():
        if node in _NON_NODE_LABELS:
            continue
        for rec in recs:
            if rec.get("kind") != kind:
                continue
            h = rec.get("hash")
            ts = rec.get("ts")
            if not h or ts is None:
                continue
            seen = out.setdefault(h, {})
            if node not in seen or ts < seen[node]:
                seen[node] = ts
    return out


def _spread_stats(seen: Dict[str, Dict[str, float]], n_nodes: int,
                  coverage: float) -> dict:
    need = max(1, math.ceil(coverage * n_nodes))
    spreads_ms: List[float] = []
    covered = 0
    for stamps in seen.values():
        times = sorted(stamps.values())
        if len(times) < need:
            continue
        covered += 1
        spreads_ms.append((times[need - 1] - times[0]) * 1000.0)
    ordered = sorted(spreads_ms)
    return {
        "hashes": len(seen),
        "covered": covered,
        "coverage_nodes": need,
        "p50_ms": round(_quantile(ordered, 0.50), 3),
        "p95_ms": round(_quantile(ordered, 0.95), 3),
        "p99_ms": round(_quantile(ordered, 0.99), 3),
        "max_ms": round(max(spreads_ms), 3) if spreads_ms else math.nan,
        "spreads_ms": [round(s, 3) for s in spreads_ms],
    }


def report(events_by_node: Dict[str, List[dict]],
           n_nodes: Optional[int] = None,
           coverage: float = 0.9) -> dict:
    """Fleet propagation report over merged event rings.

    Block quantiles measure first-commit → coverage-th node; tx
    quantiles measure first-accept → full fan-out among seen nodes
    (tx gossip has no coverage contract — a tx mined quickly may
    legally never reach laggards)."""
    if n_nodes is None:
        n_nodes = len([k for k in events_by_node
                       if k not in _NON_NODE_LABELS])
    blocks = first_seen(events_by_node, "block_seen")
    txs = first_seen(events_by_node, "tx_seen")
    rep_blocks = _spread_stats(blocks, n_nodes, coverage)
    # per-tx spread across however many nodes saw it (min 2)
    tx_spreads = []
    for stamps in txs.values():
        times = sorted(stamps.values())
        if len(times) >= 2:
            tx_spreads.append((times[-1] - times[0]) * 1000.0)
    ordered = sorted(tx_spreads)
    rep_txs = {
        "hashes": len(txs),
        "covered": len(tx_spreads),
        "p50_ms": round(_quantile(ordered, 0.50), 3),
        "p95_ms": round(_quantile(ordered, 0.95), 3),
        "p99_ms": round(_quantile(ordered, 0.99), 3),
        "max_ms": round(max(tx_spreads), 3) if tx_spreads else math.nan,
        "spreads_ms": [round(s, 3) for s in tx_spreads],
    }
    return {"kind": "fleet_propagation", "n_nodes": n_nodes,
            "coverage": coverage, "blocks": rep_blocks, "txs": rep_txs}


def gate_rows(prop: dict, prefix: str = "fleet") -> Dict[str, dict]:
    """Propagation quantiles in the gate's slo-endpoint row shape
    (loadgen/gate.py flatten: slo.{name}.{p50_ms,p95_ms,p99_ms})."""
    rows: Dict[str, dict] = {}
    for name, rep in (("block_prop", prop["blocks"]),
                      ("tx_prop", prop["txs"])):
        if rep["covered"] and not math.isnan(rep["p50_ms"]):
            rows[f"{prefix}.{name}"] = {
                "requests": rep["covered"],
                "p50_ms": rep["p50_ms"],
                "p95_ms": rep["p95_ms"],
                "p99_ms": rep["p99_ms"],
            }
    return rows
