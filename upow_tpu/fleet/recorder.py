"""Flight recorder: bounded per-node black box for post-hoc diagnosis.

Chaos and soak scenarios fail rarely and asynchronously; by the time
the assertion fires, the interesting state is gone.  The recorder
keeps, per node, a small ring of *frames* — each frame holds the
counter DELTAS since the previous mark, the tail of new events, and
the traces in flight at mark time (tracing.TraceBuffer open roots).
Scenario drivers ``mark()`` at phase boundaries; on any core
assertion failure, injected fault, or SLO breach the ``dump()`` is
attached to the scenario artifact (swarm/scenarios.py run_scenario),
so the black box lands next to the failure it explains.

Everything is bounded: frames per node, events per frame, open-trace
snapshots per buffer — a recorder left armed for a long soak cannot
grow without limit.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional


class FlightRecorder:
    """Per-node frame rings over a swarm's telemetry scopes."""

    def __init__(self, frames: int = 8, event_tail: int = 64):
        self._max_frames = max(1, int(frames))
        self._event_tail = max(1, int(event_tail))
        self._frames: Dict[str, deque] = {}
        self._counter_base: Dict[str, Dict[str, int]] = {}
        self._event_mark: Dict[str, float] = {}
        self.marks = 0

    def mark(self, swarm, label: str = "") -> None:
        """Snapshot one frame per node: deltas since the last mark."""
        now = round(time.time(), 6)
        for i, node in enumerate(swarm.nodes):
            sc = getattr(node, "telemetry_scope", None)
            if sc is None:
                continue
            key = f"node{i}"
            counters = sc.metrics.counters()
            base = self._counter_base.get(key, {})
            deltas = {k: v - base.get(k, 0) for k, v in counters.items()
                      if v != base.get(k, 0)}
            watermark = self._event_mark.get(key, 0.0)
            tail = [e for e in sc.events.snapshot()
                    if (e.get("ts") or 0) > watermark][-self._event_tail:]
            frame = {
                "label": label,
                "ts": now,
                "counter_deltas": deltas,
                "events": tail,
                "open_traces": sc.traces.open_snapshot(),
            }
            self._frames.setdefault(
                key, deque(maxlen=self._max_frames)).append(frame)
            self._counter_base[key] = counters
            if tail:
                self._event_mark[key] = tail[-1].get("ts") or watermark
        self.marks += 1

    def dump(self, reason: str) -> dict:
        return {
            "kind": "flight_recorder",
            "reason": reason,
            "marks": self.marks,
            "nodes": {k: list(v) for k, v in self._frames.items()},
        }


def trigger_reason(core_ok: bool, events: List[dict],
                   slo_rows: Optional[Dict[str, dict]] = None,
                   p99_budget_ms: Optional[float] = None) -> Optional[str]:
    """Why (if at all) the black box should land in the artifact.

    Precedence: a failed core assertion explains everything else; a
    watchtower alert that reached *firing* outranks the raw fault that
    (usually) provoked it — the alert is the judged incident, the fault
    the mechanism; an injected fault outranks a soft SLO breach."""
    if not core_ok:
        return "core_assertion_failed"
    for e in events:
        if e.get("kind") == "alert" and e.get("state") == "firing":
            return f"alert:{e.get('rule')}"
    for e in events:
        if e.get("kind") == "fault_injected":
            return "fault_injected"
    if p99_budget_ms is not None and slo_rows:
        for name, row in sorted(slo_rows.items()):
            p99 = row.get("p99_ms")
            if isinstance(p99, (int, float)) and p99 > p99_budget_ms:
                return f"slo_breach:{name}:p99_ms={p99}"
    return None
