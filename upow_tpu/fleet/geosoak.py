"""Geo soak: asymmetric-latency continent topology + churn (ROADMAP 5).

Six (by default) nodes split across three "continents" with realistic
asymmetric link latencies; blocks are mined WITHOUT direct push so the
shaped gossip links carry them — the propagation tracker then measures
real fleet spread.  The scenario then soaks through node churn (an
isolated node catches up via sync), a continent partition + heal, and
a traced push_tx crossing the fleet (stitched into one fleet trace).

The deterministic core carries only seed-functions: continent map,
convergence/coverage booleans, final height/tip.  All timing — the
propagation quantiles, per-node SLO rows, the stitched trace — goes to
``observed``/``slo``, from where :func:`observatory_section` folds it
into the committed ``observatory.json`` with explicit gate directions
(``fleet_core_ok`` zeroes on any correctness break, so the ENFORCED
perf gate also trips on broken distribution semantics, not just on
slow propagation).

Import discipline: swarm/scenarios.py registers this scenario at the
bottom of its module, so imports from scenarios here are deferred to
call time.
"""

from __future__ import annotations

import asyncio
import math
from typing import Dict, List, Optional

from .. import telemetry
from ..logger import get_logger
from . import propagation, scrape, stitch

log = get_logger("fleet")

#: canonical fleet shape used by `make fleet`, CI and the observatory —
#: keep smoke and full identical so gate rows stay comparable.
GEO_NODES = 6
GEO_SEED = 7

CONTINENTS = ("am", "eu", "ap")

#: one-way latency seconds, (src continent, dst continent) — asymmetric
#: on purpose (return routes differ in the real world).
_LATENCY = {
    ("am", "am"): 0.002, ("eu", "eu"): 0.002, ("ap", "ap"): 0.002,
    ("am", "eu"): 0.008, ("eu", "am"): 0.010,
    ("am", "ap"): 0.014, ("ap", "am"): 0.016,
    ("eu", "ap"): 0.011, ("ap", "eu"): 0.013,
}
_JITTER = 0.001


def continent_of(i: int) -> str:
    return CONTINENTS[i % len(CONTINENTS)]


def geo_soak_cfg(i: int, cfg) -> None:
    """Arm the watchtower on every soak node with the PRODUCTION rule
    pack — default thresholds, real background cadence (tightened to
    1s so a ~15s soak still gets a dozen ticks).  The clean-run gate
    (``watchtower_clean_ok``) is adversarial in the other direction:
    a healthy fleet doing churn, partitions and reorgs must not page,
    or the rule pack is too twitchy to ship."""
    cfg.watchtower.enabled = True
    cfg.watchtower.interval = 1.0


def _shape_links(swarm) -> Dict[str, str]:
    """Apply the continent latency matrix; returns {node label: continent}.

    No drop probability: the soak's determinism contract (byte-identical
    core per seed) must not hinge on retry races; churn and partition
    supply the failure pressure instead."""
    from ..swarm.links import LinkPolicy

    assign = {f"node{i}": continent_of(i) for i in range(swarm.n)}
    for i in range(swarm.n):
        for j in range(swarm.n):
            if i == j:
                continue
            pol = LinkPolicy(
                latency=_LATENCY[(continent_of(i), continent_of(j))],
                jitter=_JITTER)
            swarm.matrix.set_link(swarm.urls[i], swarm.urls[j], pol,
                                  symmetric=False)
    return assign


async def _wait_heights(swarm, height: int, rounds: int = 400,
                        delay: float = 0.01,
                        exclude: tuple = ()) -> bool:
    for _ in range(rounds):
        tips = await swarm.tips()
        if all(t["id"] >= height
               for i, t in enumerate(tips) if i not in exclude):
            return True
        await asyncio.sleep(delay)
    tips = await swarm.tips()
    return all(t["id"] >= height
               for i, t in enumerate(tips) if i not in exclude)


async def scenario_geo_soak(swarm, seed: int):
    from ..swarm.scenarios import (BREAKER_REOPEN_PAUSE, _sync_from,
                                   _wallet)
    from ..wallet.builders import WalletBuilder

    n = swarm.n
    everyone = list(range(n))
    continents = _shape_links(swarm)
    eu_idx = [i for i in everyone if continent_of(i) == "eu"]
    rest_idx = [i for i in everyone if continent_of(i) != "eu"]
    d_miner, addr = _wallet(seed, "geo_miner")
    _, addr_target = _wallet(seed, "geo_target")
    rec = swarm.recorder

    # ---- bootstrap: shared prefix, pushed directly (not under test)
    for _ in range(2):
        assert (await swarm.mine(0, addr, push_to=everyone))["ok"]
    await swarm.settle()
    bootstrap_converged = await swarm.wait_converged()
    height = (await swarm.tips())[0]["id"]
    rec.mark(swarm, label="bootstrap")

    # ---- gossip waves: rotating miners, NO direct push — the shaped
    # links carry every block; this is the propagation measurement
    waves = 4
    waves_propagated = 0
    for w in range(waves):
        miner = (w * 2 + 1) % n      # rotate across continents
        assert (await swarm.mine(miner, addr))["ok"]
        height += 1
        if await _wait_heights(swarm, height):
            waves_propagated += 1
    await swarm.settle()
    rec.mark(swarm, label="gossip_waves")

    # ---- churn: one AP node drops out, misses blocks, catches up
    victim = n - 1
    swarm.matrix.isolate(swarm.urls[victim])
    for _ in range(2):
        assert (await swarm.mine(0, addr))["ok"]
        height += 1
    gossip_sans_victim = await _wait_heights(swarm, height,
                                             exclude=(victim,))
    swarm.matrix.restore(swarm.urls[victim])
    await asyncio.sleep(BREAKER_REOPEN_PAUSE)
    await _sync_from(swarm, victim, winner=0)
    churn_caught_up = await swarm.wait_converged()
    rec.mark(swarm, label="churn")

    # ---- continent partition: EU forks off, loses, reorgs back
    swarm.matrix.partition([[swarm.urls[i] for i in eu_idx],
                            [swarm.urls[i] for i in rest_idx]])
    for _ in range(2):
        assert (await swarm.mine(0, addr))["ok"]
    assert (await swarm.mine(eu_idx[0], addr))["ok"]
    await swarm.settle()
    tips = await swarm.tips()
    partition_diverged = len({t["hash"] for t in tips}) == 2
    swarm.matrix.heal()
    await asyncio.sleep(BREAKER_REOPEN_PAUSE)
    for i in eu_idx:
        await _sync_from(swarm, i, winner=0)
    height += 2
    healed_converged = await swarm.wait_converged()
    rec.mark(swarm, label="partition_heal")

    # ---- traced push_tx across the fleet (stitch target)
    builder = WalletBuilder(swarm.nodes[0].state)
    tx = await builder.create_transaction(d_miner, addr_target, "1")
    with telemetry.request_trace("fleet.push_tx") as root:
        push_tid = root.trace_id
        res = await swarm.get(0, "push_tx", {"tx_hex": tx.hex()})
    assert res.get("ok"), res
    await swarm.settle()
    tx_nodes = 0
    for _ in range(200):
        pools = [await swarm.get(i, "get_pending_transactions")
                 for i in everyone]
        tx_nodes = sum(1 for p in pools
                       if tx.hex() in (p.get("result") or []))
        if tx_nodes == n:
            break
        await asyncio.sleep(0.01)
    stitched = stitch.stitch_one(scrape.traces_by_node(swarm), push_tid)
    stitched_nodes = [x for x in (stitched or {}).get("nodes", [])
                      if x != "driver"]

    # ---- confirm the tx, settle the world
    assert (await swarm.mine(0, addr))["ok"]
    height += 1
    final_converged = await _wait_heights(swarm, height) \
        and await swarm.wait_converged()
    await swarm.settle()          # drain gossip before teardown
    rec.mark(swarm, label="confirm")

    # ---- watchtower quiet check: the live cadence loops ran the whole
    # soak; a healthy fleet must end it without a single fired alert
    wt_stats = {f"node{i}": node.watchtower.stats()
                for i, node in enumerate(swarm.nodes)
                if getattr(node, "watchtower", None) is not None}
    wt_ticks = sum(s["evaluations"] for s in wt_stats.values())
    wt_fired = sum(s["fired_total"] for s in wt_stats.values())

    tips = await swarm.tips()
    prop = propagation.report(scrape.events_by_node(swarm), n_nodes=n)
    # blocks that must reach EVERY node: 2 bootstrap + 4 waves +
    # 2 churn + 2 partition winners + 1 confirm (the EU fork block
    # legitimately stays at 1/3 of the fleet)
    covered_expected = 11
    core = {
        "continents": continents,
        "bootstrap_converged": bootstrap_converged,
        "gossip_waves": waves,
        "waves_all_propagated": waves_propagated == waves,
        "gossip_reached_all_but_victim": gossip_sans_victim,
        "churn_victim_caught_up": churn_caught_up,
        "partition_diverged": partition_diverged,
        "healed_converged": healed_converged,
        "tx_reached_90pct_nodes": tx_nodes >= math.ceil(0.9 * n),
        "push_tx_trace_crossed_3_nodes": len(stitched_nodes) >= 3,
        "blocks_covered_90pct": prop["blocks"]["covered"]
        >= covered_expected,
        "final_converged": final_converged,
        "watchtower_armed_all_nodes": len(wt_stats) == n,
        "watchtower_ticked": wt_ticks >= 1,
        "watchtower_zero_alerts": wt_fired == 0,
        "final_height": tips[0]["id"],
        "final_tip": tips[0]["hash"],
    }
    observed = {
        "propagation": prop,
        "stitched_push_tx": stitched,
        "push_tx_trace_id": push_tid,
        "tx_pool_nodes": tx_nodes,
        "waves_propagated": waves_propagated,
        "watchtower": {"ticks": wt_ticks, "fired": wt_fired,
                       "stats": wt_stats},
    }
    return core, observed


# ------------------------------------------------- observatory bridge ----

def _num(v: float) -> float:
    return 0.0 if (v is None or (isinstance(v, float) and math.isnan(v))) \
        else float(v)


def run_geo_artifact(nodes: int = GEO_NODES, seed: int = GEO_SEED) -> dict:
    from ..swarm.scenarios import run_scenario
    return run_scenario("geo_soak", nodes=nodes, seed=seed)


def fleet_rows(art: dict) -> dict:
    """Gate-facing rows from a geo-soak artifact.

    * ``kernels`` — direction-annotated entries in the observatory
      kernel table shape.  ``fleet_core_ok`` is the correctness trip:
      any failed core boolean zeroes it, and a zero against a baseline
      of 1.0 fails the ENFORCED gate regardless of tolerance (the
      divergence-zeroing idiom the other enforced kernels use).
    * ``slo_endpoints`` — per-node latency rows plus the propagation
      quantile rows, all in gate.flatten's endpoint shape.
    """
    from ..swarm.scenarios import core_ok

    prop = art["observed"]["propagation"]
    ok = core_ok(art["core"])
    wt_clean = bool(
        art["core"].get("watchtower_armed_all_nodes")
        and art["core"].get("watchtower_ticked")
        and art["core"].get("watchtower_zero_alerts"))
    kernels = {
        "fleet_core_ok": {
            "value": 1.0 if ok else 0.0, "unit": "bool",
            "direction": "higher",
            "desc": "geo-soak core assertions all held (0 = broken)"},
        "watchtower_clean_ok": {
            "value": 1.0 if wt_clean else 0.0, "unit": "bool",
            "direction": "higher",
            "desc": "default rule pack armed + ticking on every soak "
                    "node and ZERO alerts fired on the clean run"},
        "fleet_block_prop_p50_ms": {
            "value": _num(prop["blocks"]["p50_ms"]), "unit": "ms",
            "direction": "lower",
            "desc": "block first-commit -> 90% of nodes, median"},
        "fleet_block_prop_p95_ms": {
            "value": _num(prop["blocks"]["p95_ms"]), "unit": "ms",
            "direction": "lower",
            "desc": "block first-commit -> 90% of nodes, p95"},
        "fleet_tx_prop_p50_ms": {
            "value": _num(prop["txs"]["p50_ms"]), "unit": "ms",
            "direction": "lower",
            "desc": "tx first-accept -> mempool fan-out, median"},
        "fleet_tx_prop_p95_ms": {
            "value": _num(prop["txs"]["p95_ms"]), "unit": "ms",
            "direction": "lower",
            "desc": "tx first-accept -> mempool fan-out, p95"},
    }
    slo_endpoints = {
        k.replace("swarm.", "fleet.", 1): v
        for k, v in art["slo"]["endpoints"].items()}
    slo_endpoints.update(
        propagation.gate_rows(prop, prefix="fleet.geo_soak"))
    return {"kernels": kernels, "slo_endpoints": slo_endpoints}


def observatory_section(nodes: int = GEO_NODES,
                        seed: int = GEO_SEED) -> dict:
    """Run the geo soak and shape it for the observatory artifact."""
    art = run_geo_artifact(nodes=nodes, seed=seed)
    rows = fleet_rows(art)
    prop = art["observed"]["propagation"]
    stitched = art["observed"].get("stitched_push_tx") or {}
    section = {
        "scenario": "geo_soak",
        "nodes": nodes,
        "seed": seed,
        "fingerprint": art["fingerprint"],
        "core_ok": rows["kernels"]["fleet_core_ok"]["value"] == 1.0,
        "propagation": {
            kind: {k: prop[kind][k] for k in
                   ("hashes", "covered", "p50_ms", "p95_ms", "p99_ms")}
            for kind in ("blocks", "txs")},
        "stitched_push_tx_nodes": stitched.get("node_count", 0),
        "watchtower": art["observed"].get("watchtower", {}),
        "flight_recorder": art.get("flight_recorder", {}).get("reason"),
    }
    return {"section": section, "kernels": rows["kernels"],
            "slo_endpoints": rows["slo_endpoints"], "artifact": art}


def merge_into_observatory(path: str, nodes: int = GEO_NODES,
                           seed: int = GEO_SEED) -> dict:
    """Surgically merge fresh fleet rows into a committed observatory
    artifact (leaves every CI-measured kernel untouched)."""
    import json
    import os

    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    out = observatory_section(nodes=nodes, seed=seed)
    doc.setdefault("kernels", {}).update(out["kernels"])
    doc.setdefault("slo", {}).setdefault("endpoints", {}).update(
        out["slo_endpoints"])
    doc["fleet"] = out["section"]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    os.replace(tmp, path)
    log.info("merged fleet rows into %s", path)
    return out
