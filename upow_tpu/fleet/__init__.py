"""Fleet observatory: cross-node observability over the swarm.

Per-node telemetry (instance-scoped registries, telemetry/scope.py)
stays meaningful at fleet scale only with a layer that merges it:

* :mod:`.scrape` — collect every node's /metrics + /debug/traces +
  events ring into one snapshot; render the merged ``upow_fleet_*``
  exposition families.
* :mod:`.propagation` — first-seen stamps (``block_seen``/``tx_seen``
  events) folded into fleet-wide propagation p50/p95/p99:
  block-to-90%-of-nodes and tx-to-mempool.
* :mod:`.stitch` — join per-node span trees sharing one trace id
  (``X-Upow-Trace``) into a single fleet trace with hop latencies.
* :mod:`.recorder` — bounded per-node black-box (event tails, counter
  deltas, in-flight traces) dumped into scenario artifacts on
  failure, fault injection, or SLO breach.
* :mod:`.geosoak` — the seeded asymmetric-latency geo soak scenario
  whose rows feed the committed observatory gate (imported lazily:
  it pulls in the swarm scenario registry).

``python -m upow_tpu.fleet`` is the CLI (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from . import propagation, recorder, scrape, stitch  # noqa: F401
from .recorder import FlightRecorder  # noqa: F401

__all__ = ["FlightRecorder", "propagation", "recorder", "scrape",
           "stitch"]
