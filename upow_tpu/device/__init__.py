"""The device-runtime package: the one home of device dispatch.

Everything that touches the accelerator — backend probing/arming,
thread-boxed dispatch, queueing, cross-subsystem coalescing, AOT
warmup — lives under ``upow_tpu/device/``.  The upowlint ``DR`` rules
(lint/rules/devicepurity.py) enforce the boundary: any
``jax.jit``/``pjit`` dispatch, ``boxed_call``, or backend
init/enumeration outside this package is a lint error.
"""

from .runtime import DeviceRuntime, get_runtime, reset_runtime  # noqa: F401
