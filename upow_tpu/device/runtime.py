"""Per-process device-runtime service: one owner for the TPU.

ROADMAP item 3, the kernel-server refactor.  Every device dispatch in
the package flows through this module's single drainer thread:

* **One arm.**  The drainer thread owns backend arming — one probe per
  process (thread-boxed: the axon tunnel HANGS inside ``jax.devices()``
  rather than raising), under a deadline, with the structured
  ``arm_failure_reason`` capture bench.py emits, the persistent compile
  cache enabled, and the production kernel set AOT-warmed while the
  queues are still empty.  A probe that hangs costs the process ONE
  timeout, after which every subsystem is served on the CPU paths.
* **One queue, many sources.**  Subsystems submit typed work items —
  P-256 sig batches (``submit_sig_checks``), boxed device calls
  (``run_boxed``), generic dispatch closures (``submit_call``) — tagged
  with a *source* (``block``, ``mempool``, ``mine``, ``index``,
  ``bench``...).  Per-source FIFO queues are drained by weighted
  fair-share scheduling (stride accounting: each served item charges
  ``cost / weight`` to its source's virtual pass), so a saturating
  miner stream cannot starve block verify past a bounded wait.
* **Cross-source coalescing.**  When a sig batch is served, every
  queued sig batch with the same dispatch key — across ALL sources —
  rides in the same ``run_sig_checks`` call, generalizing what
  verify/dispatch.py (now a thin client of this service) did per event
  loop.  Verdict semantics are byte-identical to the serial paths: the
  runtime changes WHO shares a dispatch, never what is computed.
* **One choke point.**  resilience/degrade.py's state is consulted at
  execution time, not submission time: a degrade flip mid-flight means
  the already-queued items execute on the host path (run_sig_checks'
  own backend resolution), with byte-identical verdicts.  The
  ``device.runtime`` fault site fires before every dispatch; injected
  faults degrade and drain to the host instead of failing callers.

Telemetry (telemetry/device.py): per-source queue-wait histograms, a
queue-depth histogram, submissions-per-dispatch coalescing, and a
``device_runtime`` kernel occupancy series for the shared dispatches.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..logger import get_logger
from ..telemetry import device as ktel
from ..telemetry import metrics

log = get_logger("device.runtime")


def _sanitizer_check(site: str) -> None:
    """Thread-affinity assertion at the submit/drain seam: under the
    test/CI concurrency sanitizer, a blocking boxed wait entered from
    an event-loop thread is recorded as a finding.  The sanitizer
    module is imported lazily so plain production imports pay nothing;
    once imported, the inactive path is a single None check."""
    sanitizer = sys.modules.get("upow_tpu.lint.sanitizer")
    if sanitizer is not None:
        sanitizer.check_blocking_wait(f"device.runtime.{site}")


def boxed_call(fn: Callable[[], Any], timeout: float):
    """Run ``fn`` on a daemon thread with a deadline.

    Returns ("ok", result) | ("err", exception) | ("timeout", None).
    The one home of the hang-survival idiom (moved here from benchutil,
    which now delegates): a call stuck inside the PJRT client can
    neither be interrupted nor joined — the daemon thread is abandoned
    and the caller decides what degraded mode means.
    """
    import contextvars

    _sanitizer_check("boxed_call")

    box: dict = {}
    # carry the caller's contextvars into the worker so telemetry
    # emitted inside the boxed call (fault events, spans) keeps the
    # caller's trace ID — a bare Thread starts with an empty context
    ctx = contextvars.copy_context()

    def run():
        try:
            box["ok"] = ctx.run(fn)
        except Exception as e:
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    if "ok" in box:
        return "ok", box["ok"]
    if "err" in box:
        return "err", box["err"]
    return "timeout", None


# Env vars that select/parameterize a PJRT plugin.  The scrubbed arm
# retry (bench satellite) clears these so a half-dead tunnel config
# cannot wedge the second attempt.
_SCRUB_PREFIXES = ("JAX_", "XLA_", "TPU_", "LIBTPU", "AXON_",
                   "PALLAS_AXON_")

_WAITS_CAP = 8192  # per-source queue-wait samples kept for stats()


class _Item:
    __slots__ = ("kind", "key", "checks", "precomputed", "fn", "timeout",
                 "kernel", "source", "fut", "t0", "ctx")

    def __init__(self, kind, *, key=None, checks=None, precomputed=None,
                 fn=None, timeout=None, kernel="call", source="other"):
        self.kind = kind            # "sig" | "call"
        self.key = key              # sig coalescing key
        self.checks = checks
        self.precomputed = precomputed
        self.fn = fn
        self.timeout = timeout      # not None -> boxed execution
        self.kernel = kernel
        self.source = source
        self.fut: Future = Future()
        self.t0 = time.perf_counter()
        # the drainer executes in the submitter's contextvars so
        # telemetry emitted inside the dispatch (degrade events, fault
        # records, spans) keeps the submitter's trace ID
        self.ctx = contextvars.copy_context()

    @property
    def cost(self) -> int:
        return max(1, len(self.checks)) if self.kind == "sig" else 1


def _resolve(fut: Future, value) -> None:
    try:
        fut.set_result(value)
    except InvalidStateError:  # cancelled by an abandoning awaiter
        pass


def _fail(fut: Future, exc: BaseException) -> None:
    try:
        fut.set_exception(exc)
    except InvalidStateError:
        pass


class DeviceRuntime:
    """The per-process device owner: queues in, results out."""

    def __init__(self, cfg=None):
        if cfg is None:
            from ..config import DeviceRuntimeConfig

            cfg = DeviceRuntimeConfig.from_env()
        self.cfg = cfg
        self._weights = cfg.parsed_weights()
        self._cv = threading.Condition()
        self._queues: Dict[str, deque] = {}
        self._passes: Dict[str, float] = {}
        self._vtime = 0.0
        self._holds = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._arm_lock = threading.Lock()
        self._arm_done = threading.Event()
        self._arm_info: Dict[str, Any] = {
            "armed": False, "platform": None, "attempt": None,
            "arm_failure_reason": None, "probe_seconds": None,
            "warmed": [],
        }
        # introspection for tests/benches
        self.submissions = 0
        self.dispatches = 0
        self.source_submissions: Dict[str, int] = {}
        self._waits: Dict[str, List[float]] = {}

    # ------------------------------------------------------------ arming --

    def arm(self, deadline: Optional[float] = None, scrub_env: bool = False,
            attempt: str = "runtime", force: bool = False) -> dict:
        """Probe/initialize the backend once, under a deadline.

        Returns the arm-info dict (platform, arm_failure_reason, AOT
        warm results).  ``scrub_env`` clears plugin env vars and the
        probe cache first (the bench retry path); ``force`` re-arms an
        already-armed runtime (same path).  Idempotent otherwise — the
        drainer thread calls this before serving its first item.
        """
        with self._arm_lock:
            info = self._arm_info
            if info["armed"] and not (force or scrub_env):
                return dict(info)
            from .. import benchutil

            if scrub_env:
                for k in [k for k in os.environ
                          if k.startswith(_SCRUB_PREFIXES)]:
                    os.environ.pop(k, None)
                os.environ["JAX_PLATFORMS"] = "cpu"
                benchutil._PROBE_CACHE.clear()
                _clear_jax_backends()
            timeout = self.cfg.arm_timeout if deadline is None else deadline
            t0 = time.perf_counter()
            platform = benchutil.probed_platform_cached(timeout)
            elapsed = time.perf_counter() - t0
            info.update(platform=platform, attempt=attempt,
                        probe_seconds=round(elapsed, 3), armed=True)
            if platform is None:
                # carry the probe's ACTUAL failure text when the cached
                # detail record has one (exception repr or explicit-hang
                # note) instead of only the generic "hung/failed"
                detail = benchutil._PROBE_CACHE.get("detail") or {}
                info["arm_failure_reason"] = detail.get("error") or (
                    "backend probe hung/failed within %.0fs" % timeout)
                info["probe_status"] = detail.get("status", "no-platform")
                info["traceback_fingerprint"] = \
                    detail.get("traceback_fingerprint")
                log.warning("device runtime armed WITHOUT a backend "
                            "(%s); all sources served on host paths",
                            info["arm_failure_reason"])
            else:
                info["arm_failure_reason"] = None
                info.pop("probe_status", None)
                info.pop("traceback_fingerprint", None)
            # platform is known: unblock platform()/devices() callers
            # before the (potentially long) AOT warm below
            self._arm_done.set()
            if platform not in (None, "cpu"):
                budget = max(5.0, timeout - elapsed)
                if self.cfg.compile_cache_dir:
                    from .. import compile_cache

                    compile_cache.enable(self.cfg.compile_cache_dir)
                if self.cfg.aot_warm:
                    info["warmed"] = self._aot_warm(platform, budget)
            try:
                from ..telemetry import events

                events.emit("device_runtime_armed",
                            platform=platform or "none",
                            attempt=attempt,
                            reason=info["arm_failure_reason"] or "")
            except Exception as e:
                log.debug("arm telemetry event not recorded: %s", e)
            return dict(info)

    def _aot_warm(self, platform: str, budget: float) -> List[dict]:
        """Compile the production kernel set through the persistent
        compile cache while the queues are empty (real accelerators
        only — the XLA fallbacks cost minutes of compile on CPU for
        throughput the host paths beat)."""
        deadline = time.perf_counter() + budget
        warmed = []

        def left() -> float:
            return max(1.0, deadline - time.perf_counter())

        def warm_p256():
            from ..verify.txverify import _canary_checks
            from ..crypto import p256

            good, bad = _canary_checks()
            out = p256.verify_batch_prehashed(
                [good[0], bad[0]], [good[2], bad[2]], [good[3], bad[3]],
                pad_block=128)
            return [bool(v) for v in out]

        def warm_sha256():
            from ..core import clock, curve, point_to_string
            from ..core.header import BlockHeader
            from ..crypto import sha256 as sk

            _, pub = curve.keygen(rng=424242)
            header = BlockHeader(
                previous_hash="00" * 32, address=point_to_string(pub),
                merkle_root="00" * 32, timestamp=clock.timestamp(),
                difficulty_x10=10, nonce=0)
            template = sk.make_template(header.prefix_bytes())
            spec = sk.target_spec("00" * 32, 1.0)
            fn = sk.pow_search_pallas if platform == "tpu" \
                else sk.pow_search_jnp
            return int(fn(template, spec, nonce_base=0, batch=256))

        def warm_utxo_probe():
            from ..state import device_index as di

            # tiny throwaway index; _probe_eval is called directly (not
            # through submit_call — this runs inside boxed_call off the
            # drainer thread, and a nested submission would deadlock on
            # the drainer blocked right here)
            index = di.DeviceUtxoIndex(
                [("ab" * 32, i) for i in range(4)],
                values=[(i + 1, "warm", 0) for i in range(4)])
            ops = [("ab" * 32, 0), ("cd" * 32, 9)]
            present, _maybe, _amounts, _c = index._probe_eval(
                ops, di.fingerprint_batch(ops), di.check_batch(ops))
            return [bool(v) for v in present]

        def warm_mesh_search():
            # resident mesh program (mine/mesh_engine.py) — multi-device
            # only; like warm_utxo_probe this is a DIRECT call (a nested
            # submit_call would deadlock the drainer blocked right here)
            from ..mine.mesh_engine import warm_resident_search

            warm_resident_search()
            return True

        for name, fn in (("p256_verify", warm_p256),
                         ("sha256_search", warm_sha256),
                         ("sha256_search_mesh", warm_mesh_search),
                         ("utxo_probe", warm_utxo_probe)):
            t0 = time.perf_counter()
            status, value = boxed_call(fn, timeout=left())
            entry = {"kernel": name, "status": status,
                     "seconds": round(time.perf_counter() - t0, 3)}
            if status == "err":
                entry["error"] = repr(value)
            warmed.append(entry)
            log.info("AOT warm %s: %s (%.2fs)", name, status,
                     entry["seconds"])
        return warmed

    def platform(self) -> Optional[str]:
        """Armed platform string ("tpu"/"cpu"/...; None = probe failed).
        Blocks until the drainer's arm resolves the platform (not the
        AOT warm, which runs after the event is set)."""
        self._ensure_thread()
        self._arm_done.wait(timeout=self.cfg.arm_timeout + 30.0)
        return self._arm_info["platform"]

    def devices(self) -> list:
        """Post-arm ``jax.devices()`` ([] when the probe failed) — the
        one sanctioned enumeration point (upowlint DR001)."""
        if self.platform() is None:
            return []
        import jax

        return jax.devices()

    # -------------------------------------------------------- submission --

    def submit_sig_checks(self, checks: Sequence[tuple], *,
                          backend: str = "auto", pad_block: int = 128,
                          device_timeout: float = 240.0,  # operational timeout  # upowlint: disable=CP001
                          mesh_devices: int = 1,
                          precomputed: Optional[dict] = None,
                          source: str = "other") -> Future:
        """Queue one P-256 sig batch; the Future resolves to its verdict
        list (txverify.run_sig_checks semantics, byte-identical).
        Batches sharing (backend, pad_block, device_timeout,
        mesh_devices, precomputed identity) coalesce into one dispatch
        across ALL sources."""
        if not checks:
            fut: Future = Future()
            fut.set_result([])
            return fut
        key = (backend, pad_block, device_timeout, mesh_devices,
               id(precomputed) if precomputed is not None else None)
        item = _Item("sig", key=key, checks=list(checks),
                     precomputed=precomputed, source=source)
        self._enqueue(item)
        return item.fut

    def submit_call(self, fn: Callable[[], Any], *, kernel: str = "call",
                    source: str = "other",
                    timeout: Optional[float] = None) -> Future:
        """Queue a device-dispatch closure.  With ``timeout`` the call
        is thread-boxed and the Future resolves to boxed_call's
        (status, value) tuple; without it the Future carries ``fn()``'s
        result (or exception).  Called from the drainer thread itself
        (a dispatch nested inside a dispatch) it executes inline —
        queueing would deadlock the single drainer."""
        if threading.current_thread() is self._thread:
            fut: Future = Future()
            try:
                if timeout is not None:
                    fut.set_result(boxed_call(fn, timeout))
                else:
                    fut.set_result(fn())
            # the exception travels to the caller inside the future
            except Exception as e:  # upowlint: disable=BE001
                fut.set_exception(e)
            return fut
        item = _Item("call", fn=fn, timeout=timeout, kernel=kernel,
                     source=source)
        self._enqueue(item)
        return item.fut

    def run_boxed(self, fn: Callable[[], Any], timeout: float, *,
                  kernel: str = "call", source: str = "other"):
        """Blocking boxed dispatch through the queue: returns
        ("ok", result) | ("err", exc) | ("timeout", None) exactly like
        boxed_call, but serialized through the device owner.  The safety
        margin on the outer wait covers arm + queue time; if even that
        is exceeded the caller sees a plain timeout."""
        _sanitizer_check("run_boxed")
        fut = self.submit_call(fn, kernel=kernel, source=source,
                               timeout=timeout)
        try:
            return fut.result(timeout=timeout + self.cfg.arm_timeout + 60.0)
        except FutureTimeoutError:
            return "timeout", None

    @contextlib.contextmanager
    def hold(self):
        """Pause draining (tests/benches: build a coalescing window
        deterministically).  Items queue while held; release drains."""
        with self._cv:
            self._holds += 1
        try:
            yield self
        finally:
            with self._cv:
                self._holds -= 1
                self._cv.notify_all()

    def stats(self) -> dict:
        """Queue/dispatch introspection snapshot (benches, tests)."""
        with self._cv:
            depths = {s: len(q) for s, q in self._queues.items() if q}
            waits = {s: list(w) for s, w in self._waits.items()}
        return {
            "submissions": self.submissions,
            "dispatches": self.dispatches,
            "per_source": dict(self.source_submissions),
            "queue_depth": depths,
            "queue_waits": waits,
            "arm": dict(self._arm_info),
        }

    # ----------------------------------------------------------- drainer --

    def _enqueue(self, item: _Item) -> None:
        with self._cv:
            if self._stop:
                raise RuntimeError("device runtime stopped")
            q = self._queues.setdefault(item.source, deque())
            if len(q) >= self.cfg.queue_max:
                raise RuntimeError(
                    "device runtime queue overflow for source %r "
                    "(max %d)" % (item.source, self.cfg.queue_max))
            if not q:
                # a source waking from idle starts at the current
                # virtual time — banked idleness must not let it
                # monopolize the device once it bursts
                self._passes[item.source] = max(
                    self._passes.get(item.source, 0.0), self._vtime)
            q.append(item)
            self.submissions += 1
            self.source_submissions[item.source] = \
                self.source_submissions.get(item.source, 0) + 1
            metrics.inc("runtime.submissions")
            metrics.inc("runtime.source.%s" % item.source)
            self._cv.notify_all()
        self._ensure_thread()

    def _ensure_thread(self) -> None:
        with self._cv:
            if self._stop or (self._thread is not None
                              and self._thread.is_alive()):
                return
            self._thread = threading.Thread(
                target=self._drain_loop, daemon=True,
                name="upow-device-runtime")
            self._thread.start()

    def _drain_loop(self) -> None:
        try:
            self.arm()
        except Exception as e:  # arm must never kill the drainer
            log.warning("device runtime arm failed: %s", e)
            self._arm_info.update(
                armed=True, platform=None,
                arm_failure_reason="arm raised: %r" % (e,))
        finally:
            self._arm_done.set()
        while True:
            with self._cv:
                while not self._stop and (
                        self._holds > 0
                        or not any(self._queues.values())):
                    self._cv.wait(timeout=1.0)
                if self._stop:
                    return
                depth = sum(len(q) for q in self._queues.values())
                group = self._pop_group_locked()
            if not group:
                continue
            try:
                self._execute(group, depth)
            except Exception as e:  # belt: futures already failed below
                log.warning("device runtime dispatch raised: %s", e)
                for m in group:
                    _fail(m.fut, e)

    def _pop_group_locked(self) -> List[_Item]:
        active = [s for s, q in self._queues.items() if q]
        if not active:
            return []
        # weighted fair share (stride): serve the source with the least
        # accumulated virtual pass; ties break on source name for
        # determinism
        src = min(active, key=lambda s: (self._passes.get(s, 0.0), s))
        head = self._queues[src].popleft()
        group = [head]
        if head.kind == "sig":
            # cross-source coalescing: pull every queued compatible sig
            # batch (same dispatch key) into this dispatch, scan order
            # fixed for determinism
            for s in sorted(self._queues):
                q = self._queues[s]
                if not q:
                    continue
                keep: deque = deque()
                while q:
                    cand = q.popleft()
                    if (len(group) < self.cfg.max_coalesce
                            and cand.kind == "sig"
                            and cand.key == head.key):
                        group.append(cand)
                    else:
                        keep.append(cand)
                self._queues[s] = keep
        for m in group:
            w = self._weights.get(m.source,
                                  self._weights.get("other", 1))
            self._passes[m.source] = self._passes.get(m.source, 0.0) \
                + m.cost / max(w, 1)
        self._vtime = self._passes.get(src, 0.0)
        return group

    def _record_waits(self, group: List[_Item], now: float) -> None:
        with self._cv:
            for m in group:
                wait = max(0.0, now - m.t0)
                lst = self._waits.setdefault(m.source, [])
                if len(lst) >= _WAITS_CAP:
                    del lst[: _WAITS_CAP // 2]
                lst.append(wait)

    def _execute(self, group: List[_Item], depth: int) -> None:
        now = time.perf_counter()
        self._record_waits(group, now)
        self.dispatches += 1
        if group[0].kind == "sig":
            self._execute_sig(group, depth, now)
        else:
            self._execute_call(group[0], depth, now)

    def _execute_sig(self, group: List[_Item], depth: int,
                     t0: float) -> None:
        flat: List[tuple] = []
        slices: List[Tuple[int, int]] = []
        for m in group:
            slices.append((len(flat), len(flat) + len(m.checks)))
            flat.extend(m.checks)
        backend, pad_block, device_timeout, mesh_devices, _ = group[0].key
        # module-attr lookup so established monkeypatch seams on
        # txverify.run_sig_checks keep intercepting the shared dispatch
        from ..verify import txverify

        waits = {m.source: time.perf_counter() - m.t0 for m in group}
        def dispatch(be: str):
            self._fire_fault("sig:" + ",".join(
                sorted({m.source for m in group})))
            return txverify.run_sig_checks(
                flat, backend=be, pad_block=pad_block,
                device_timeout=device_timeout,
                precomputed=group[0].precomputed,
                mesh_devices=mesh_devices)

        try:
            # run inside the triggering submitter's contextvars so
            # degrade/fault events raised by the shared dispatch carry
            # a real trace ID instead of the drainer's empty context
            verdicts = group[0].ctx.run(dispatch, backend)
        except Exception as e:
            from ..resilience.faultinject import FaultInjected

            if isinstance(e, FaultInjected):
                # the choke point: an injected dispatch fault degrades
                # the device path and drains this group onto the host —
                # byte-identical verdicts, callers never see the fault
                txverify.DEGRADE.record_failure(e)
                metrics.inc("runtime.faults")
                log.warning("device.runtime fault injected; group of %d "
                            "drains to host", len(group))
                try:
                    verdicts = group[0].ctx.run(
                        txverify.run_sig_checks,
                        flat, backend="host", pad_block=pad_block,
                        device_timeout=device_timeout,
                        precomputed=group[0].precomputed,
                        mesh_devices=mesh_devices)
                # exceptions travel to every submitter inside the futures
                except Exception as e2:  # upowlint: disable=BE001
                    for m in group:
                        _fail(m.fut, e2)
                    return
            else:
                for m in group:
                    _fail(m.fut, e)
                return
        finally:
            padded = max(pad_block, 1) * (
                (len(flat) + max(pad_block, 1) - 1) // max(pad_block, 1))
            ktel.record_runtime_dispatch(
                n_submissions=len(group), waits_by_source=waits,
                depth=depth, real=len(flat), padded=padded,
                seconds=time.perf_counter() - t0)
        for m, (lo, hi) in zip(group, slices):
            _resolve(m.fut, verdicts[lo:hi])

    def _execute_call(self, item: _Item, depth: int, t0: float) -> None:
        waits = {item.source: time.perf_counter() - item.t0}

        def wrapped():
            self._fire_fault("call:%s" % item.kernel)
            return item.fn()

        try:
            if item.timeout is not None:
                # boxed mode: faults/hangs become the status tuple, the
                # caller applies its own degrade policy (txverify,
                # sha256 crossover).  Entered inside the submitter's
                # context so boxed_call's own context copy carries the
                # submitter's trace ID into the worker thread.
                result = item.ctx.run(boxed_call, wrapped, item.timeout)
                _resolve(item.fut, result)
            else:
                _resolve(item.fut, item.ctx.run(wrapped))
        # the exception travels to the caller inside the future
        except Exception as e:  # upowlint: disable=BE001
            _fail(item.fut, e)
        finally:
            ktel.record_runtime_dispatch(
                n_submissions=1, waits_by_source=waits, depth=depth,
                real=1, padded=1, seconds=time.perf_counter() - t0)

    def _fire_fault(self, key: str) -> None:
        from ..resilience.faultinject import get_injector

        injector = get_injector()
        if injector is not None:
            injector.fire_sync("device.runtime", key=key)

    def close(self) -> None:
        """Stop the drainer and fail anything still queued (tests)."""
        with self._cv:
            self._stop = True
            pending = [m for q in self._queues.values() for m in q]
            self._queues.clear()
            self._cv.notify_all()
        for m in pending:
            _fail(m.fut, RuntimeError("device runtime stopped"))
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2.0)


def _clear_jax_backends() -> None:
    """Best-effort jax backend-cache reset for the scrubbed arm retry.
    If jax was never imported (or the API moved) this is a no-op — a
    thread stuck inside a dead PJRT client stays stuck regardless; the
    value here is rescuing the raised-error (not hung) init failures."""
    import sys

    if "jax" not in sys.modules:
        return
    try:
        sys.modules["jax"].clear_backends()
    except Exception as e:
        log.debug("jax.clear_backends failed (continuing): %s", e)


_RUNTIME: Optional[DeviceRuntime] = None
_RUNTIME_LOCK = threading.Lock()


def get_runtime() -> DeviceRuntime:
    """The process-wide device runtime (lazily created; the drainer
    thread starts on first submission)."""
    global _RUNTIME
    with _RUNTIME_LOCK:
        if _RUNTIME is None:
            _RUNTIME = DeviceRuntime()
        return _RUNTIME


def reset_runtime() -> None:
    """Tear down the singleton (tests): stops the drainer, fails queued
    futures, and lets the next get_runtime() build a fresh service."""
    global _RUNTIME
    with _RUNTIME_LOCK:
        rt, _RUNTIME = _RUNTIME, None
    if rt is not None:
        rt.close()
