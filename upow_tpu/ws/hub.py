"""WebSocket push hub: channels, caps, heartbeats, typed broadcasts.

One class covers what the reference spreads over five modules
(websocket/socket_endpoint.py, socket_manager.py, socket_connection.py,
socket_handlers.py, socket_utils.py — ~1.1k LoC): per-channel subscriber
sets, per-IP connection caps, a token-bucket message rate limit, 64 KB
message cap, heartbeat pings with idle expiry, and the two typed
broadcasts ``new_block`` / ``new_transaction``.

Wire compatibility with the reference client protocol:
``{"type": "subscribe_block"|"unsubscribe_block"|"ping"|"pong"}`` in,
``{"type": "new_block"|"new_transaction", "data": ..., "timestamp": ...}``
out.  ``subscribe_transaction`` is ALSO accepted here: the reference
routes it (socket_handlers.py:23-31) but forgot it in
ALLOWED_MESSAGE_TYPES (socket_config.py:18-23), making it unreachable —
an evident bug, fixed rather than replicated since no working reference
client can depend on the broken behavior.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from datetime import datetime, timezone
from typing import Dict, Optional, Set

from aiohttp import WSMsgType, web

from ..config import WsConfig
from ..logger import get_logger

log = get_logger("ws")

_SUBSCRIBE = {
    "subscribe_block": ("block", True),
    "unsubscribe_block": ("block", False),
    "subscribe_transaction": ("transaction", True),
    "unsubscribe_transaction": ("transaction", False),
}


class WsConnection:
    """Per-connection state: socket, subscriptions, rate bucket, stats."""

    def __init__(self, ws: web.WebSocketResponse, ip: str, cfg: WsConfig):
        self.id = uuid.uuid4().hex[:12]
        self.ws = ws
        self.ip = ip
        self.cfg = cfg
        self.channels: Set[str] = set()
        self.connected_at = time.monotonic()
        self.last_activity = time.monotonic()
        self.messages_in = 0
        self.messages_out = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self._bucket_times: list = []

    def rate_ok(self) -> bool:
        now = time.monotonic()
        self._bucket_times = [t for t in self._bucket_times if now - t < 60.0]
        if len(self._bucket_times) >= self.cfg.rate_limit_per_minute:
            return False
        self._bucket_times.append(now)
        return True

    async def send(self, message: dict) -> bool:
        try:
            from ..resilience.faultinject import get_injector

            injector = get_injector()
            if injector is not None:
                # chaos hook: a hung/errored subscriber — the hub must
                # reap it and keep broadcasting to everyone else
                await injector.fire("ws.send", self.ip)
            payload = json.dumps(message)
            await self.ws.send_str(payload)
            self.messages_out += 1
            self.bytes_out += len(payload)
            return True
        except (ConnectionError, RuntimeError):
            return False

    async def send_error(self, code: str, text: str) -> None:
        await self.send({"type": "error", "error_code": code, "message": text})

    async def send_success(self, text: str, data: Optional[dict] = None) -> None:
        await self.send({"type": "success", "message": text, "data": data or {}})


class WsHub:
    """Connection registry + channel broadcast + lifecycle loops."""

    def __init__(self, cfg: Optional[WsConfig] = None):
        self.cfg = cfg or WsConfig()
        self.connections: Dict[str, WsConnection] = {}
        self.by_ip: Dict[str, Set[str]] = {}
        self.channels: Dict[str, Set[str]] = {c: set() for c in self.cfg.channels}
        self._loops_started = False
        # cumulative lifecycle counters: get_stats() sums over LIVE
        # connections only, so subscriber churn (the loadgen's ws
        # scenario) was invisible before these
        self.connects_total = 0
        self.disconnects_total = 0

    # ------------------------------------------------------------ endpoint --
    async def handle(self, request: web.Request) -> web.WebSocketResponse:
        """The /ws route (reference socket_endpoint.py:26-52)."""
        ip = request.headers.get("x-real-ip") or (
            request.transport.get_extra_info("peername") or ("", 0))[0]
        if len(self.connections) >= self.cfg.max_connections:
            raise web.HTTPServiceUnavailable(text="Too many connections")
        if len(self.by_ip.get(ip, ())) >= self.cfg.max_per_user:
            raise web.HTTPForbidden(text="Too many connections from this IP")

        ws = web.WebSocketResponse(
            heartbeat=self.cfg.heartbeat_interval,
            max_msg_size=self.cfg.max_message_bytes)
        await ws.prepare(request)
        conn = WsConnection(ws, ip, self.cfg)
        self.connections[conn.id] = conn
        self.by_ip.setdefault(ip, set()).add(conn.id)
        self.connects_total += 1
        self._ensure_loops()
        log.info("ws connect %s from %s (%d total)", conn.id, ip,
                 len(self.connections))
        await conn.send({"type": "connection_established",
                         "connection_id": conn.id,
                         "channels": list(self.cfg.channels)})
        try:
            async for msg in ws:
                conn.last_activity = time.monotonic()
                if msg.type == WSMsgType.TEXT:
                    conn.messages_in += 1
                    conn.bytes_in += len(msg.data)
                    await self._on_message(conn, msg.data)
                elif msg.type in (WSMsgType.ERROR, WSMsgType.CLOSE):
                    break
        finally:
            self._drop(conn)
        return ws

    async def _on_message(self, conn: WsConnection, raw: str) -> None:
        if not conn.rate_ok():
            await conn.send_error("RATE_LIMIT_EXCEEDED", "Too many messages sent")
            return
        try:
            message = json.loads(raw)
        except json.JSONDecodeError:
            await conn.send_error("INVALID_JSON", "Message must be valid JSON")
            return
        mtype = message.get("type")
        if not mtype:
            await conn.send_error("INVALID_MESSAGE", "Message type is required")
            return
        if mtype == "ping":
            await conn.send({"type": "pong"})
            return
        if mtype == "pong":
            return
        if mtype in _SUBSCRIBE:
            channel, subscribe = _SUBSCRIBE[mtype]
            if channel not in self.channels:
                await conn.send_error("INVALID_CHANNEL",
                                      f"Unknown channel '{channel}'")
                return
            if subscribe:
                conn.channels.add(channel)
                self.channels[channel].add(conn.id)
                await conn.send_success(f"Subscribed to {channel}",
                                        {"channel": channel})
            else:
                if channel not in conn.channels:
                    await conn.send_error(
                        "NOT_SUBSCRIBED", f"Not subscribed to channel '{channel}'")
                    return
                conn.channels.discard(channel)
                self.channels[channel].discard(conn.id)
                await conn.send_success(f"Unsubscribed from {channel}",
                                        {"channel": channel})
            return
        await conn.send_error("INVALID_MESSAGE_TYPE",
                              f"Message type '{mtype}' not allowed")

    def _drop(self, conn: WsConnection) -> None:
        if self.connections.pop(conn.id, None) is not None:
            # count once even when the reap path and the handler's
            # finally both drop the same connection
            self.disconnects_total += 1
        self.by_ip.get(conn.ip, set()).discard(conn.id)
        if not self.by_ip.get(conn.ip):
            self.by_ip.pop(conn.ip, None)
        for members in self.channels.values():
            members.discard(conn.id)

    # ----------------------------------------------------------- broadcast --
    async def broadcast_to_channel(self, channel: str, message: dict) -> int:
        """Send to every subscriber; reap dead connections
        (reference socket_manager.py:201-231)."""
        sent = 0
        for conn_id in list(self.channels.get(channel, ())):
            conn = self.connections.get(conn_id)
            if conn is None:
                self.channels[channel].discard(conn_id)
                continue
            if await conn.send(message):
                sent += 1
            else:
                self._drop(conn)
        return sent

    async def broadcast_new_block(self, block_data: dict) -> int:
        return await self.broadcast_to_channel("block", {
            "type": "new_block", "data": block_data,
            "timestamp": datetime.now(timezone.utc).isoformat(),
        })

    async def broadcast_new_transaction(self, tx_data: dict) -> int:
        return await self.broadcast_to_channel("transaction", {
            "type": "new_transaction", "data": tx_data,
            "timestamp": datetime.now(timezone.utc).isoformat(),
        })

    # ----------------------------------------------------------- lifecycle --
    def _ensure_loops(self) -> None:
        if self._loops_started:
            return
        self._loops_started = True
        asyncio.ensure_future(self._cleanup_loop())
        asyncio.ensure_future(self._stats_loop())

    async def _cleanup_loop(self) -> None:
        """Expire idle connections (reference socket_manager.py:333-352)."""
        while True:
            await asyncio.sleep(self.cfg.cleanup_interval)
            now = time.monotonic()
            for conn in list(self.connections.values()):
                if now - conn.last_activity > self.cfg.connection_expiry:
                    log.info("ws expire %s", conn.id)
                    try:
                        await conn.ws.close()
                    except Exception as e:
                        # peer may already be gone; still worth a trace
                        log.debug("ws close %s failed: %s", conn.id, e)
                    self._drop(conn)

    async def _stats_loop(self) -> None:
        while True:
            await asyncio.sleep(300)
            log.info("ws stats: %s", self.get_stats())

    def get_stats(self) -> dict:
        return {
            "total_connections": len(self.connections),
            "unique_ips": len(self.by_ip),
            "channels": {c: len(m) for c, m in self.channels.items()},
            "messages_out": sum(c.messages_out for c in self.connections.values()),
            "messages_in": sum(c.messages_in for c in self.connections.values()),
            "connects_total": self.connects_total,
            "disconnects_total": self.disconnects_total,
        }

    def get_detailed_stats(self) -> dict:
        return {
            **self.get_stats(),
            "connections": [
                {
                    "id": c.id, "ip": c.ip,
                    "channels": sorted(c.channels),
                    "age_seconds": round(time.monotonic() - c.connected_at, 1),
                    "messages_in": c.messages_in,
                    "messages_out": c.messages_out,
                    "bytes_in": c.bytes_in,
                    "bytes_out": c.bytes_out,
                }
                for c in self.connections.values()
            ],
        }
