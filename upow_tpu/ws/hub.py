"""WebSocket push hub: channels, caps, heartbeats, typed broadcasts.

One class covers what the reference spreads over five modules
(websocket/socket_endpoint.py, socket_manager.py, socket_connection.py,
socket_handlers.py, socket_utils.py — ~1.1k LoC): per-channel subscriber
sets, per-IP connection caps, a token-bucket message rate limit, 64 KB
message cap, heartbeat pings with idle expiry, and the two typed
broadcasts ``new_block`` / ``new_transaction``.

Wire compatibility with the reference client protocol:
``{"type": "subscribe_block"|"unsubscribe_block"|"ping"|"pong"}`` in,
``{"type": "new_block"|"new_transaction", "data": ..., "timestamp": ...}``
out.  ``subscribe_transaction`` is ALSO accepted here: the reference
routes it (socket_handlers.py:23-31) but forgot it in
ALLOWED_MESSAGE_TYPES (socket_config.py:18-23), making it unreachable —
an evident bug, fixed rather than replicated since no working reference
client can depend on the broken behavior.

Delivery is decoupled from broadcast: every connection owns a bounded
send queue drained by a per-connection writer task, so one stalled
subscriber (full TCP window, hung middlebox) can NEVER block the
broadcast fan-out to everyone else.  Overflow sheds the OLDEST queued
message for that subscriber (drop-slowest: the laggard loses history,
live clients lose nothing) and counts it — exported as
``upow_ws_dropped_messages`` on /metrics.  A failed wire write reaps
the connection from the writer, exactly like the old inline reap.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from collections import deque
from datetime import datetime, timezone
from typing import Dict, Optional, Set

from aiohttp import WSMsgType, web

from ..config import WsConfig
from ..logger import get_logger

log = get_logger("ws")


def _retrieve(task: "asyncio.Task", what: str) -> None:
    """Done-callback for hub background tasks: retrieve and log a crash
    instead of leaving 'Task exception was never retrieved' to the GC
    (which surfaces minutes later, far from the cause, or never)."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.error("ws %s task died: %r", what, exc)

# broadcast encoder, module-level so tests can swap in a counting
# wrapper: broadcast_to_channel serializes each message through this
# exactly ONCE and fans the shared string out to every subscriber
# queue — per-subscriber dumps made a 10k-subscriber broadcast pay
# 10k identical encodes
_encode = json.dumps

_SUBSCRIBE = {
    "subscribe_block": ("block", True),
    "unsubscribe_block": ("block", False),
    "subscribe_transaction": ("transaction", True),
    "unsubscribe_transaction": ("transaction", False),
}


class WsConnection:
    """Per-connection state: socket, subscriptions, rate bucket, stats,
    and the bounded send queue its writer task drains."""

    def __init__(self, ws: web.WebSocketResponse, ip: str, cfg: WsConfig):
        self.id = uuid.uuid4().hex[:12]
        self.ws = ws
        self.ip = ip
        self.cfg = cfg
        self.channels: Set[str] = set()
        self.connected_at = time.monotonic()
        self.last_activity = time.monotonic()
        self.messages_in = 0
        self.messages_out = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.dropped = 0            # messages shed by queue overflow
        self.queue_hwm = 0          # deepest the send queue ever got
        self._bucket_times: list = []
        # 0 = unbounded (never shed); the deque IS the queue, the event
        # signals the writer — a plain asyncio.Queue cannot drop-oldest
        self._queue: deque = deque(
            maxlen=cfg.send_queue_max if cfg.send_queue_max > 0 else None)
        self._queue_event = asyncio.Event()
        self._closed = False

    def rate_ok(self) -> bool:
        now = time.monotonic()
        self._bucket_times = [t for t in self._bucket_times if now - t < 60.0]
        if len(self._bucket_times) >= self.cfg.rate_limit_per_minute:
            return False
        self._bucket_times.append(now)
        return True

    async def send(self, message) -> bool:
        """Enqueue for the writer task; never blocks on the socket.  A
        full queue sheds this subscriber's OLDEST pending message
        (drop-slowest).  Returns False once the connection is closed.
        ``message`` is a dict (per-connection replies, encoded at write
        time) or an already-encoded ``str`` shared by a broadcast."""
        if self._closed:
            return False
        if self._queue.maxlen and len(self._queue) == self._queue.maxlen:
            self._queue.popleft()  # deque would do this silently; count it
            self.dropped += 1
            try:
                from ..telemetry import event as _event

                # WHICH subscriber is shedding (and how badly) was
                # invisible on /metrics — the counter is hub-global
                _event("ws_queue_evict", subscriber=self.id, ip=self.ip,
                       dropped_total=self.dropped,
                       queue_len=len(self._queue) + 1)
            except Exception:  # telemetry must never break delivery
                log.debug("ws_queue_evict event failed", exc_info=True)
        self._queue.append(message)
        if len(self._queue) > self.queue_hwm:
            self.queue_hwm = len(self._queue)
        self._queue_event.set()
        return True

    async def _next_queued(self):
        while not self._queue:
            self._queue_event.clear()
            await self._queue_event.wait()
        return self._queue.popleft()

    async def _send_now(self, message) -> bool:
        """The actual wire write (writer task only)."""
        try:
            from ..resilience.faultinject import get_injector

            injector = get_injector()
            if injector is not None:
                # chaos hook: a hung/errored subscriber — the hub must
                # reap it and keep broadcasting to everyone else
                await injector.fire("ws.send", self.ip)
            payload = message if isinstance(message, str) \
                else _encode(message)
            await self.ws.send_str(payload)
            self.messages_out += 1
            self.bytes_out += len(payload)
            return True
        except (ConnectionError, RuntimeError):
            return False

    async def send_error(self, code: str, text: str) -> None:
        await self.send({"type": "error", "error_code": code, "message": text})

    async def send_success(self, text: str, data: Optional[dict] = None) -> None:
        await self.send({"type": "success", "message": text, "data": data or {}})


class WsHub:
    """Connection registry + channel broadcast + lifecycle loops."""

    def __init__(self, cfg: Optional[WsConfig] = None):
        self.cfg = cfg or WsConfig()
        self.connections: Dict[str, WsConnection] = {}
        self.by_ip: Dict[str, Set[str]] = {}
        self.channels: Dict[str, Set[str]] = {c: set() for c in self.cfg.channels}
        self._loops_started = False
        self._loop_tasks: Set[asyncio.Task] = set()
        self._writers: Dict[str, asyncio.Task] = {}
        # cumulative lifecycle counters: get_stats() sums over LIVE
        # connections only, so subscriber churn (the loadgen's ws
        # scenario) was invisible before these
        self.connects_total = 0
        self.disconnects_total = 0
        self.dropped_total = 0  # includes shed counts of reaped conns
        self.queue_hwm_total = 0  # deepest any queue got, ever (incl. reaped)

    # ------------------------------------------------------------ endpoint --
    async def handle(self, request: web.Request) -> web.WebSocketResponse:
        """The /ws route (reference socket_endpoint.py:26-52)."""
        ip = request.headers.get("x-real-ip") or (
            request.transport.get_extra_info("peername") or ("", 0))[0]
        if len(self.connections) >= self.cfg.max_connections:
            raise web.HTTPServiceUnavailable(text="Too many connections")
        if len(self.by_ip.get(ip, ())) >= self.cfg.max_per_user:
            raise web.HTTPForbidden(text="Too many connections from this IP")

        ws = web.WebSocketResponse(
            heartbeat=self.cfg.heartbeat_interval,
            max_msg_size=self.cfg.max_message_bytes)
        await ws.prepare(request)
        conn = WsConnection(ws, ip, self.cfg)
        self._register(conn)
        log.info("ws connect %s from %s (%d total)", conn.id, ip,
                 len(self.connections))
        await conn.send({"type": "connection_established",
                         "connection_id": conn.id,
                         "channels": list(self.cfg.channels)})
        try:
            async for msg in ws:
                conn.last_activity = time.monotonic()
                if msg.type == WSMsgType.TEXT:
                    conn.messages_in += 1
                    conn.bytes_in += len(msg.data)
                    await self._on_message(conn, msg.data)
                elif msg.type in (WSMsgType.ERROR, WSMsgType.CLOSE):
                    break
        finally:
            self._drop(conn)
        return ws

    async def _on_message(self, conn: WsConnection, raw: str) -> None:
        if not conn.rate_ok():
            await conn.send_error("RATE_LIMIT_EXCEEDED", "Too many messages sent")
            return
        try:
            message = json.loads(raw)
        except json.JSONDecodeError:
            await conn.send_error("INVALID_JSON", "Message must be valid JSON")
            return
        mtype = message.get("type")
        if not mtype:
            await conn.send_error("INVALID_MESSAGE", "Message type is required")
            return
        if mtype == "ping":
            await conn.send({"type": "pong"})
            return
        if mtype == "pong":
            return
        if mtype in _SUBSCRIBE:
            channel, subscribe = _SUBSCRIBE[mtype]
            if channel not in self.channels:
                await conn.send_error("INVALID_CHANNEL",
                                      f"Unknown channel '{channel}'")
                return
            if subscribe:
                conn.channels.add(channel)
                self.channels[channel].add(conn.id)
                await conn.send_success(f"Subscribed to {channel}",
                                        {"channel": channel})
            else:
                if channel not in conn.channels:
                    await conn.send_error(
                        "NOT_SUBSCRIBED", f"Not subscribed to channel '{channel}'")
                    return
                conn.channels.discard(channel)
                self.channels[channel].discard(conn.id)
                await conn.send_success(f"Unsubscribed from {channel}",
                                        {"channel": channel})
            return
        await conn.send_error("INVALID_MESSAGE_TYPE",
                              f"Message type '{mtype}' not allowed")

    def _register(self, conn: WsConnection) -> None:
        self.connections[conn.id] = conn
        self.by_ip.setdefault(conn.ip, set()).add(conn.id)
        self.connects_total += 1
        self._ensure_loops()
        writer = asyncio.ensure_future(self._writer(conn))
        writer.add_done_callback(lambda t: _retrieve(t, "writer"))
        self._writers[conn.id] = writer

    async def _writer(self, conn: WsConnection) -> None:
        """Drain one connection's send queue onto the wire.  A failed
        write means a dead subscriber: reap it here, exactly like the
        old inline broadcast reap, without ever stalling the hub."""
        while True:
            message = await conn._next_queued()
            if not await conn._send_now(message):
                self._writers.pop(conn.id, None)  # self-reap: don't
                self._drop(conn)                  # cancel ourselves
                return

    def connect_local(self, sink, ip: str = "local",
                      channels: tuple = ()) -> WsConnection:
        """Attach an in-process subscriber (swarm WS-churn scenarios,
        loadgen) — ``sink`` needs only ``async send_str(payload)``.
        Returns the registered connection; detach with ``drop()``."""
        conn = WsConnection(sink, ip, self.cfg)
        self._register(conn)
        for channel in channels:
            if channel in self.channels:
                conn.channels.add(channel)
                self.channels[channel].add(conn.id)
        return conn

    def drop(self, conn: WsConnection) -> None:
        """Public detach for connect_local subscribers."""
        self._drop(conn)

    def _drop(self, conn: WsConnection) -> None:
        if self.connections.pop(conn.id, None) is not None:
            # count once even when the reap path and the handler's
            # finally both drop the same connection
            self.disconnects_total += 1
            self.dropped_total += conn.dropped
            self.queue_hwm_total = max(self.queue_hwm_total, conn.queue_hwm)
        conn._closed = True
        writer = self._writers.pop(conn.id, None)
        if writer is not None:
            writer.cancel()
        self.by_ip.get(conn.ip, set()).discard(conn.id)
        if not self.by_ip.get(conn.ip):
            self.by_ip.pop(conn.ip, None)
        for members in self.channels.values():
            members.discard(conn.id)

    # ----------------------------------------------------------- broadcast --
    async def broadcast_to_channel(self, channel: str, message: dict) -> int:
        """Enqueue to every subscriber (reference
        socket_manager.py:201-231).  Returns the number of subscribers
        the message was queued for; wire delivery and dead-subscriber
        reaping happen in the per-connection writers, so a stalled
        client costs the broadcast nothing.  The payload is encoded
        ONCE here; every subscriber queue holds the same shared
        string."""
        sent = 0
        payload = _encode(message)
        for conn_id in list(self.channels.get(channel, ())):
            conn = self.connections.get(conn_id)
            if conn is None:
                self.channels[channel].discard(conn_id)
                continue
            if await conn.send(payload):
                sent += 1
            else:
                self._drop(conn)
        return sent

    async def broadcast_new_block(self, block_data: dict) -> int:
        return await self.broadcast_to_channel("block", {
            "type": "new_block", "data": block_data,
            "timestamp": datetime.now(timezone.utc).isoformat(),
        })

    async def broadcast_new_transaction(self, tx_data: dict) -> int:
        return await self.broadcast_to_channel("transaction", {
            "type": "new_transaction", "data": tx_data,
            "timestamp": datetime.now(timezone.utc).isoformat(),
        })

    # ----------------------------------------------------------- lifecycle --
    def _ensure_loops(self) -> None:
        if self._loops_started:
            return
        self._loops_started = True
        for name, coro in (("cleanup", self._cleanup_loop()),
                           ("stats", self._stats_loop())):
            task = asyncio.ensure_future(coro)
            task.add_done_callback(
                lambda t, n=name: _retrieve(t, n))
            self._loop_tasks.add(task)

    def close(self) -> None:
        """Drop every connection and cancel lifecycle/writer tasks
        (swarm teardown; a live server keeps the hub for its lifetime)."""
        for conn in list(self.connections.values()):
            self._drop(conn)
        for task in self._loop_tasks:
            task.cancel()
        self._loop_tasks.clear()
        self._loops_started = False

    async def _cleanup_loop(self) -> None:
        """Expire idle connections (reference socket_manager.py:333-352)."""
        while True:
            await asyncio.sleep(self.cfg.cleanup_interval)
            now = time.monotonic()
            for conn in list(self.connections.values()):
                if now - conn.last_activity > self.cfg.connection_expiry:
                    log.info("ws expire %s", conn.id)
                    try:
                        await conn.ws.close()
                    except Exception as e:
                        # peer may already be gone; still worth a trace
                        log.debug("ws close %s failed: %s", conn.id, e)
                    self._drop(conn)

    async def _stats_loop(self) -> None:
        while True:
            await asyncio.sleep(300)
            log.info("ws stats: %s", self.get_stats())

    def get_stats(self) -> dict:
        return {
            "total_connections": len(self.connections),
            "unique_ips": len(self.by_ip),
            "channels": {c: len(m) for c, m in self.channels.items()},
            "messages_out": sum(c.messages_out for c in self.connections.values()),
            "messages_in": sum(c.messages_in for c in self.connections.values()),
            "connects_total": self.connects_total,
            "disconnects_total": self.disconnects_total,
            "dropped_messages": self.dropped_total + sum(
                c.dropped for c in self.connections.values()),
            "send_queue_hwm": max(
                [self.queue_hwm_total]
                + [c.queue_hwm for c in self.connections.values()]),
        }

    def get_detailed_stats(self) -> dict:
        return {
            **self.get_stats(),
            "connections": [
                {
                    "id": c.id, "ip": c.ip,
                    "channels": sorted(c.channels),
                    "age_seconds": round(time.monotonic() - c.connected_at, 1),
                    "messages_in": c.messages_in,
                    "messages_out": c.messages_out,
                    "bytes_in": c.bytes_in,
                    "bytes_out": c.bytes_out,
                    "dropped": c.dropped,
                    "queue_hwm": c.queue_hwm,
                }
                for c in self.connections.values()
            ],
        }
