"""WebSocket push sidecar (reference ``websocket/`` ~1.1k LoC)."""

from .hub import WsHub  # noqa: F401
