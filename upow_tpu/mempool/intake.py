"""Coalesced transaction admission: micro-batched push_tx intake.

The continuous-batching idea from inference serving applied to tx
intake: concurrent ``push_tx`` requests are queued, drained in
micro-batches (``coalesce_window_ms`` / ``max_intake_batch``), each tx
runs its host-side rule checks individually, and every surviving
``SigCheck`` across the whole batch goes to P-256 verification in ONE
submission to the shared dispatch front (verify/dispatch.py) — N
concurrent requests cost ≪ N device round-trips, and an intake batch
landing while block verify is in flight shares ITS dispatch too.  The
degrade manager still decides the batch's
backend (``_resolve_backend`` inside run_sig_checks consults DEGRADE),
so a benched TPU transparently serves the batch on the host path.

Wire compatibility is the hard constraint: every waiter resolves with
a result dict byte-identical to the serial ``_verify_and_push_tx``
path — same strings, same order of precedence between rejection
reasons (coinbase/unsigned, dedup cache, banned address, already
pending, rule/signature failure).  The acceptance test in
tests/test_mempool.py pins this differentially against a serial node.

Fault injection: the ``mempool.intake`` site fires once per batch
before the signature dispatch — ``latency`` stalls the batch,
``error`` rejects it the same way a verifier exception would
(the serial path's behaviour for an exploding verify).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from .. import trace
from ..logger import get_logger
from ..resilience.faultinject import FaultInjected, get_injector
from ..verify import txverify  # noqa: F401  (re-exported: tests patch via this module)
from ..verify.dispatch import get_front
from .pool import MempoolEntry

log = get_logger("mempool")

# push_tx wire strings — must stay byte-identical to the reference
# (and to the serial path in node/app.py)
ERR_NOT_ADDED = "Transaction has not been added"
ERR_JUST_ADDED = "Transaction just added"
ERR_FORBIDDEN = "Access forbidden temporarily."
ERR_PRESENT = "Transaction already present"
MSG_ACCEPTED = "Transaction has been accepted"

_BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


def _reject(error: str) -> dict:
    return {"ok": False, "error": error}


def _log_drainer_exit(task: "asyncio.Task") -> None:
    """Done-callback on the drainer task: a crash outside _process's
    per-request catch (config access, queue bookkeeping) must be logged
    now, not surface as 'exception was never retrieved' at GC time —
    submitters whose futures it stranded respawn a fresh drainer on the
    next submit, so the crash would otherwise be completely silent."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        log.error("intake drainer died: %r", exc)


class _Req:
    __slots__ = ("tx", "sender", "fut", "t0", "tx_hash", "first_address",
                 "checks", "slice", "dup_of", "result", "span", "wait_span")

    def __init__(self, tx, sender, fut):
        self.tx = tx
        self.sender = sender
        self.fut = fut
        self.t0 = time.perf_counter()
        self.tx_hash: Optional[str] = None
        self.first_address: Optional[str] = None
        self.checks: Optional[list] = None
        self.slice = (0, 0)
        self.dup_of: Optional["_Req"] = None
        self.result: Optional[dict] = None
        # trace attribution across the submit -> drainer task hop: the
        # drainer records its per-request work against the submitting
        # request's span (telemetry/tracing.py cross-task API)
        self.span = trace.current_span()
        self.wait_span = trace.child_span(self.span, "intake.queue_wait")


class IntakeCoordinator:
    """Admission queue + drainer for one node.

    ``node`` is the owning Node instance (duck-typed: state, pool,
    tx_cache, config, make_tx_verifier(), accept_tx_effects(),
    _background).  The drainer task is lazily started by the first
    submit and re-registered with the node's background-task set so
    Node.close() reaps it.
    """

    def __init__(self, node, banned_addresses=frozenset()):
        self.node = node
        self.banned = banned_addresses
        self._queue: List[_Req] = []
        self._drainer: Optional[asyncio.Task] = None

    # ------------------------------------------------------------ entry ---

    QUEUE_DEPTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)

    async def submit(self, tx, sender: Optional[str]) -> dict:
        """Queue one tx and wait for its wire-compatible result dict."""
        fut = asyncio.get_event_loop().create_future()
        self._queue.append(_Req(tx, sender, fut))
        # admission-time backlog: how many requests each arrival found
        # ahead of it (incl. itself) — the burst-coalescing depth the
        # loadgen's push waves are designed to exercise
        trace.observe("mempool.intake_queue_depth", len(self._queue),
                      buckets=self.QUEUE_DEPTH_BUCKETS)
        self._ensure_drainer()
        return await fut

    def _ensure_drainer(self) -> None:
        if self._drainer is not None and not self._drainer.done():
            return
        self._drainer = asyncio.ensure_future(self._drain())
        self._drainer.add_done_callback(_log_drainer_exit)
        bg = getattr(self.node, "_background", None)
        if bg is not None:
            bg.add(self._drainer)
            self._drainer.add_done_callback(bg.discard)

    async def _drain(self) -> None:
        try:
            while self._queue:
                window = self.node.config.mempool.coalesce_window_ms / 1000.0
                if window > 0:
                    # hold the door: stragglers arriving inside the
                    # window join this batch instead of paying their
                    # own dispatch
                    await asyncio.sleep(window)
                batch = self._queue[:self.node.config.mempool.max_intake_batch]
                del self._queue[:len(batch)]
                if batch:
                    await self._process(batch)
        except asyncio.CancelledError:
            # node shutdown: nothing may hang on an unresolved future
            for req in self._queue:
                self._resolve(req, _reject(ERR_NOT_ADDED))
            self._queue.clear()
            raise

    def _resolve(self, req: _Req, result: dict) -> None:
        req.result = result
        if not req.fut.done():
            req.fut.set_result(result)
        trace.observe("mempool.admit_latency",
                      time.perf_counter() - req.t0)

    # ------------------------------------------------------------ batch ---

    async def _process(self, batch: List[_Req]) -> None:
        try:
            with trace.span("mempool.intake_batch", n=len(batch)):
                await self._process_inner(batch)
        except Exception as e:  # no waiter may hang; mirror the serial
            # path's catch-all around verify (reject, don't 500)
            log.error("intake batch failed: %s", e, exc_info=True)
        finally:
            # covers BaseException too: a drainer cancelled mid-batch
            # (Node.close) has already popped this batch off the queue,
            # so _drain's CancelledError handler cannot see it — settle
            # the in-flight waiters here before the cancellation
            # propagates, or their handler coroutines hang forever
            for req in batch:
                if not req.fut.done():
                    self._resolve(req, _reject(ERR_NOT_ADDED))

    async def _process_inner(self, batch: List[_Req]) -> None:
        node = self.node
        trace.inc("mempool.intake_batches")
        trace.inc("mempool.intake_txs", len(batch))
        trace.observe("mempool.intake_batch_size", len(batch),
                      buckets=_BATCH_SIZE_BUCKETS)

        inj = get_injector()
        if inj is not None:
            try:
                # attribute the batch-level fault to the first
                # submitter's trace so /debug/events can tie it back to
                # a request (the drainer itself has no ambient trace)
                with trace.attached(batch[0].span if batch else None):
                    await inj.fire("mempool.intake", key=str(len(batch)))
            except FaultInjected:
                trace.inc("mempool.intake_faults")
                for req in batch:
                    self._resolve(req, _reject(ERR_NOT_ADDED))
                return

        # pull in external journal writers (wallet CLI, block accept)
        # before membership checks — the pool is the intake authority.
        # stamp0 anchors the end-of-batch reconcile: the batch predicts
        # the stamp its own writes produce from here, and any deviation
        # means a foreign writer interleaved with the awaits below.
        await node.pool.sync(node.state)
        stamp0 = node.pool.journal_stamp

        # -- phase A: per-tx host-side checks, batch order -----------------
        seen: Dict[str, _Req] = {}
        survivors: List[_Req] = []
        for req in batch:
            trace.finish_child(req.wait_span, batch=len(batch))
            tx = req.tx
            if getattr(tx, "is_coinbase", False) or any(
                    i.signature is None for i in tx.inputs):
                self._resolve(req, _reject(ERR_NOT_ADDED))
                continue
            req.tx_hash = tx.hash()
            first = seen.get(req.tx_hash)
            if first is not None:
                req.dup_of = first  # settled after the first instance
                continue
            seen[req.tx_hash] = req
            if req.tx_hash in node.tx_cache:
                self._resolve(req, _reject(ERR_JUST_ADDED))
                continue
            if tx.inputs:
                req.first_address = await node.state.resolve_output_address(
                    tx.inputs[0].tx_hash, tx.inputs[0].index)
            if req.first_address in self.banned:
                self._resolve(req, _reject(ERR_FORBIDDEN))
                continue
            if req.tx_hash in node.pool:
                self._resolve(req, _reject(ERR_PRESENT))
                continue
            if await node.state.pending_transaction_exists(req.tx_hash):
                # journal row the pool does NOT hold (a conflict-skipped
                # loser from sync's reconcile): the serial path's
                # pending_transaction_exists check answers ERR_PRESENT
                # here, so the batched path must too — not the
                # double-spend/UNIQUE reject it would otherwise hit
                self._resolve(req, _reject(ERR_PRESENT))
                continue
            try:
                checks = await node.make_tx_verifier().prepare_pending(tx)
            except Exception as e:  # serial parity: verify errors reject
                log.info("tx verify error %s: %s", req.tx_hash, e)
                checks = None
            if checks is None:
                self._resolve(req, _reject(ERR_NOT_ADDED))
                continue
            req.checks = checks
            survivors.append(req)

        # -- phase B: ONE signature dispatch for the whole batch -----------
        flat: list = []
        for req in survivors:
            req.slice = (len(flat), len(flat) + len(req.checks))
            flat.extend(req.checks)
        verdicts: List[bool] = []
        if flat:
            dev = node.config.device
            t_dispatch = time.perf_counter()
            try:
                with trace.span("mempool.sig_dispatch", n=len(flat)):
                    # shared batched-dispatch front (verify/dispatch.py),
                    # now a thin client of the process-wide device
                    # runtime: an intake batch arriving while block
                    # verify (or the miner, or the device index) has
                    # work queued coalesces into ONE shared dispatch
                    # under weighted fair scheduling — verdict
                    # semantics unchanged
                    verdicts = await get_front().submit(
                        flat, backend=dev.sig_backend,
                        pad_block=dev.verify_pad_block,
                        device_timeout=dev.verify_device_timeout,
                        mesh_devices=dev.mesh_devices, source="mempool")
            except Exception as e:  # serial parity: verify errors reject
                log.warning("intake signature dispatch failed: %s", e)
                for req in survivors:
                    self._resolve(req, _reject(ERR_NOT_ADDED))
                survivors = []
            # the ONE coalesced dispatch appears in EVERY sharing
            # request's trace tree (same wall interval, n/coalesced
            # fields show the sharing)
            t_done = time.perf_counter()
            for req in survivors:
                trace.add_span(req.span, "intake.sig_dispatch",
                               t_dispatch, t_done, n=len(flat),
                               coalesced=len(survivors))

        # -- phase C: finalize in batch order ------------------------------
        claimed: Dict[tuple, str] = {}  # intra-batch outpoint claims
        added = 0           # successful journal inserts this batch
        last_seq = None     # journal sequence of the latest insert
        for req in survivors:
            lo, hi = req.slice
            if not all(verdicts[lo:hi]):
                self._resolve(req, _reject(ERR_NOT_ADDED))
                continue
            outpoints = tuple(i.outpoint for i in req.tx.inputs)
            if any(op in claimed for op in outpoints):
                # an earlier tx of this batch claimed the outpoint —
                # exactly the serial path's pending-double-spend reject
                self._resolve(req, _reject(ERR_NOT_ADDED))
                continue
            try:
                with trace.attached(req.span), \
                        trace.span("push_tx.journal_write"):
                    last_seq = await node.state.add_pending_transaction(
                        req.tx)
                added += 1
            except Exception as e:  # serial parity (journal reject)
                log.info("tx rejected %s: %s", req.tx_hash, e)
                self._resolve(req, _reject(ERR_NOT_ADDED))
                continue
            for op in outpoints:
                claimed[op] = req.tx_hash
            node.pool.add(MempoolEntry(
                tx_hash=req.tx_hash, tx_hex=req.tx.hex(),
                fees=await node.state.tx_fees(req.tx),
                outpoints=outpoints, tx=req.tx))
            # attached(): the ws broadcast / gossip tasks spawned inside
            # inherit THIS request's trace context, so the outbound
            # X-Upow-Trace header carries the submitter's ID (asserted
            # end-to-end by tests/test_telemetry.py)
            with trace.attached(req.span), trace.span("push_tx.effects"):
                await node.accept_tx_effects(req.tx, req.tx_hash,
                                             req.first_address, req.sender)
            self._resolve(req, {"ok": True, "result": MSG_ACCEPTED,
                                "tx_hash": req.tx_hash})

        # duplicates: the first instance's fate decides (serial parity:
        # an accepted first instance is in the dedup cache by the time
        # the second would run; a rejected one re-fails the same way)
        for req in batch:
            if req.dup_of is None or req.fut.done():
                continue
            first_result = req.dup_of.result or _reject(ERR_NOT_ADDED)
            if first_result.get("ok"):
                self._resolve(req, _reject(ERR_JUST_ADDED))
            else:
                self._resolve(req, dict(first_result))

        # the pool already contains this batch's writes — predict the
        # stamp those writes alone would have produced from stamp0 (K
        # inserts: count +K, max-seq = last insert's sequence, local
        # generation +K) and reconcile.  A match records the stamp
        # cheaply; ANY mismatch means a foreign journal writer (block
        # acceptance deleting mined txs, a wallet-CLI insert) landed
        # during one of this batch's awaits, and reconcile() falls back
        # to the full sync diff instead of stamping the change over —
        # a blind stamp write here would make every later sync() no-op
        # and leave already-mined txs in mining templates.
        expected = None
        if (stamp0 is not None and len(stamp0) == 3
                and (added == 0 or last_seq is not None)):
            expected = (stamp0[0] + added,
                        last_seq if added else stamp0[1],
                        stamp0[2] + added)
        await node.pool.reconcile(node.state, expected)
        # byte cap and TTL (write-through: evictions leave the journal)
        await node.pool.enforce_limits(node.state)
