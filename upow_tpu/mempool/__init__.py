"""Micro-batched mempool subsystem.

The in-process authority for pending transactions: a fee-rate priority
pool (:mod:`pool`), a coalescing admission pipeline that amortizes one
P-256 signature dispatch over a whole micro-batch of ``push_tx``
requests (:mod:`intake`), and block-template assembly with a
generation-keyed mining-info cache (:mod:`template`).  The SQL
``pending_transactions`` table stays on as a write-behind journal —
restart recovery plus the wallet CLI's direct-insert path — and the
pool reconciles against it by stamp (see :meth:`Mempool.sync`).

See docs/MEMPOOL.md for the architecture and config knobs.
"""

from .pool import Mempool, MempoolEntry, TTLSet
from .intake import IntakeCoordinator
from .template import MiningInfoCache, assemble_template, select_reference

__all__ = ["Mempool", "MempoolEntry", "TTLSet", "IntakeCoordinator",
           "MiningInfoCache", "assemble_template", "select_reference"]
