"""In-memory fee-priority transaction pool.

The pool is the in-process authority for pending transactions.  Its
ordering reproduces the reference query exactly (database.py:171-186,
mirrored by ``ChainState.get_pending_transactions_limit``)::

    ORDER BY CAST(fees AS REAL) / LENGTH(tx_hex) DESC, tx_hash

Python's ``int / int`` is the same IEEE-754 double division sqlite's
``CAST .. AS REAL`` performs, so the in-memory key ``(-fees/len(hex),
tx_hash)`` sorts bit-identically to the SQL — pinned by the
differential test in tests/test_mempool.py.

The SQL ``pending_transactions`` table remains as a write-behind
journal: accepted txs are written through to it (restart durability),
but reads on the hot path come from here.  :meth:`Mempool.sync`
reconciles pool against journal by stamp — cheap when nothing changed
(one COUNT/MAX query), incremental when another writer (the wallet
CLI's direct insert, block acceptance, reorg re-injection) moved it.
"""

from __future__ import annotations

import time
from bisect import bisect_left, insort
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from .. import trace
from ..core.tx import Tx, tx_from_hex
from ..logger import get_logger

log = get_logger("mempool")

Outpoint = Tuple[str, int]


@dataclass
class MempoolEntry:
    tx_hash: str
    tx_hex: str
    fees: int
    outpoints: Tuple[Outpoint, ...] = ()
    tx: Optional[Tx] = None          # parsed form when the caller has it
    added_mono: float = field(default_factory=time.monotonic)

    @property
    def fee_rate(self) -> float:
        return self.fees / len(self.tx_hex)

    @property
    def sort_key(self) -> tuple:
        # ascending sort == reference "rate DESC, tx_hash ASC"
        return (-self.fee_rate, self.tx_hash)

    @property
    def size_hex(self) -> int:
        return len(self.tx_hex)

    @classmethod
    def from_row(cls, tx_hash: str, tx_hex: str, fees: int) -> "MempoolEntry":
        """Entry from a journal row (recovery / external-writer sync)."""
        tx = tx_from_hex(tx_hex, check_signatures=False)
        outpoints = () if tx.is_coinbase else tuple(
            i.outpoint for i in tx.inputs)
        return cls(tx_hash=tx_hash, tx_hex=tx_hex, fees=fees,
                   outpoints=outpoints, tx=tx)


class Mempool:
    """Fee-rate priority pool + outpoint conflict map + byte cap + TTL.

    Pure data structure apart from :meth:`sync` (which reads the
    journal through a ChainState).  Every content mutation bumps
    :attr:`generation` — the mining-info cache key (template.py), so an
    idle miner polling an unchanged pool costs a dict lookup, not a
    re-sort/re-hash/re-merkle of the whole pending set.
    """

    def __init__(self, max_bytes_hex: int = 64 * 1024 * 1024,
                 tx_ttl: float = 0.0, allow_rbf: bool = False):
        self.max_bytes_hex = max_bytes_hex
        self.tx_ttl = tx_ttl
        self.allow_rbf = allow_rbf
        self.generation = 0
        self._entries: Dict[str, MempoolEntry] = {}
        self._order: List[tuple] = []           # sorted entry sort_keys
        self._spends: Dict[Outpoint, str] = {}  # outpoint -> tx_hash
        self._bytes = 0
        self._journal_stamp: Optional[tuple] = None

    # ------------------------------------------------------------ reads ---

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, tx_hash: str) -> bool:
        return tx_hash in self._entries

    def get(self, tx_hash: str) -> Optional[MempoolEntry]:
        return self._entries.get(tx_hash)

    def spender_of(self, outpoint: Outpoint) -> Optional[str]:
        """tx_hash of the pooled tx spending this outpoint, if any."""
        return self._spends.get(tuple(outpoint))

    @property
    def total_bytes_hex(self) -> int:
        return self._bytes

    @property
    def journal_stamp(self) -> Optional[tuple]:
        """Last reconciled journal stamp (None before the first sync)."""
        return self._journal_stamp

    def ordered(self) -> List[MempoolEntry]:
        """Entries in reference priority order (rate DESC, hash ASC)."""
        return [self._entries[key[1]] for key in self._order]

    def select_hex(self, limit_hex_chars: int) -> List[str]:
        """Reference-exact capped slice: walk priority order, stop at
        the FIRST tx that would overflow the byte budget (the reference
        breaks rather than skips, database.py:171-186)."""
        out, total = [], 0
        for key in self._order:
            tx_hex = self._entries[key[1]].tx_hex
            if total + len(tx_hex) > limit_hex_chars:
                break
            total += len(tx_hex)
            out.append(tx_hex)
        return out

    # ------------------------------------------------------- mutations ----

    def add(self, entry: MempoolEntry) -> str:
        """Insert; returns ``added`` | ``duplicate`` | ``conflict`` |
        ``replaced``.

        A conflict (an outpoint already claimed by a pooled tx) is
        rejected unless RBF is enabled AND the newcomer pays a strictly
        higher fee rate, in which case every conflicting tx is evicted
        first.  Intake keeps ``allow_rbf=False`` so the push_tx wire
        behaviour stays byte-identical to the reference reject.
        """
        if entry.tx_hash in self._entries:
            return "duplicate"
        losers = []
        for op in entry.outpoints:
            holder = self._spends.get(op)
            if holder is not None and holder != entry.tx_hash:
                losers.append(holder)
        if losers:
            if not self.allow_rbf:
                return "conflict"
            worst = min(self._entries[h].fee_rate for h in losers)
            if entry.fee_rate <= worst:
                return "conflict"
            for h in dict.fromkeys(losers):
                self._remove_one(h)
            trace.inc("mempool.rbf", len(set(losers)))
        self._entries[entry.tx_hash] = entry
        insort(self._order, entry.sort_key)
        for op in entry.outpoints:
            self._spends[op] = entry.tx_hash
        self._bytes += entry.size_hex
        self.generation += 1
        return "replaced" if losers else "added"

    def _remove_one(self, tx_hash: str) -> Optional[MempoolEntry]:
        entry = self._entries.pop(tx_hash, None)
        if entry is None:
            return None
        i = bisect_left(self._order, entry.sort_key)
        if i < len(self._order) and self._order[i] == entry.sort_key:
            del self._order[i]
        for op in entry.outpoints:
            if self._spends.get(op) == tx_hash:
                del self._spends[op]
        self._bytes -= entry.size_hex
        self.generation += 1
        return entry

    def remove(self, tx_hashes: Iterable[str]) -> List[MempoolEntry]:
        """Drop entries (block acceptance, GC); missing hashes ignored."""
        removed = []
        for h in tx_hashes:
            entry = self._remove_one(h)
            if entry is not None:
                removed.append(entry)
        return removed

    def clear(self) -> None:
        self._entries.clear()
        self._order.clear()
        self._spends.clear()
        self._bytes = 0
        self.generation += 1

    def expire(self, now_mono: Optional[float] = None) -> List[str]:
        """Evict entries older than ``tx_ttl`` (monotonic age — TTL is
        operational policy, not consensus time)."""
        if not self.tx_ttl:
            return []
        now = time.monotonic() if now_mono is None else now_mono
        stale = [h for h, e in self._entries.items()
                 if now - e.added_mono > self.tx_ttl]
        for h in stale:
            self._remove_one(h)
        if stale:
            trace.inc("mempool.expired", len(stale))
        return stale

    def evict_over_cap(self) -> List[str]:
        """Shed lowest-fee-rate entries until under the byte cap."""
        evicted = []
        while self._bytes > self.max_bytes_hex and self._order:
            victim = self._order[-1][1]
            self._remove_one(victim)
            evicted.append(victim)
        if evicted:
            trace.inc("mempool.evicted", len(evicted))
        return evicted

    # -------------------------------------------------- journal reconcile --

    async def sync(self, state, _stamp: Optional[tuple] = None) -> bool:
        """Reconcile pool content against the write-behind journal.

        Cheap no-op when the journal stamp is unchanged.  On change
        (wallet CLI insert, block acceptance removing txs, reorg
        re-injection, another process), the diff is applied: journal
        rows absent from the pool are parsed and added, pool entries
        gone from the journal are dropped.  Returns True when pool
        content changed (generation advanced)."""
        stamp = _stamp if _stamp is not None \
            else await state.pending_journal_stamp()
        if stamp == self._journal_stamp:
            return False
        gen0 = self.generation
        rows = await state.load_pending_journal()
        journal = {r["tx_hash"]: r for r in rows}
        for h in [h for h in self._entries if h not in journal]:
            self._remove_one(h)
        for h, r in journal.items():
            if h in self._entries:
                continue
            try:
                entry = MempoolEntry.from_row(h, r["tx_hex"], r["fees"])
            except (ValueError, KeyError, IndexError) as e:
                log.warning("journal row %s undecodable, skipped: %s", h, e)
                continue
            if self.add(entry) == "conflict":
                # two journal rows claim one outpoint (possible only via
                # external writers / reorg re-injection); priority order
                # decides nothing here — first reconciled row wins, the
                # loser stays journal-only until the mempool GC clears it
                trace.inc("mempool.sync_conflicts")
        self._journal_stamp = stamp
        return self.generation != gen0

    async def reconcile(self, state,
                        expected_stamp: Optional[tuple]) -> bool:
        """Post-write-through stamp update that cannot absorb a foreign
        journal mutation.  The caller predicts the stamp its OWN writes
        should have produced (``expected_stamp``); when the observed
        stamp matches exactly, it is recorded without reloading the
        journal.  On ANY deviation — or when the caller could not
        predict (``None``) — the full :meth:`sync` diff runs, so a
        concurrent external write (block acceptance deleting mined txs,
        a wallet-CLI insert) is diffed in rather than silently stamped
        over.  Returns True when pool content changed."""
        observed = await state.pending_journal_stamp()
        if expected_stamp is not None and observed == expected_stamp:
            self._journal_stamp = observed
            return False
        return await self.sync(state, _stamp=observed)

    async def enforce_limits(self, state) -> List[str]:
        """TTL + byte cap, with write-through to the journal so evicted
        txs do not resurrect on the next stamp reconcile.  The journal
        removal ends with a full :meth:`sync` rather than a blind stamp
        write: an external journal mutation landing between the DELETE
        and the stamp read must be diffed in, not absorbed.  Evictions
        only fire past the cap/TTL, so the reload stays off the common
        path."""
        dropped = self.expire()
        dropped += self.evict_over_cap()
        if dropped:
            await state.remove_pending_transactions_by_hash(dropped)
            await self.sync(state)
        return dropped


class TTLSet:
    """Bounded, TTL'd membership set for push_tx dedup.

    Replaces the reference's 100-entry deque (a few milliseconds of
    traffic at target load): capacity- and age-bounded, O(1) adds and
    lookups, expired entries purged from the insertion-ordered front.
    ``append`` is kept as an alias so call sites read like the deque
    they replaced.
    """

    def __init__(self, maxlen: int = 1 << 16, ttl: float = 600.0):
        self.maxlen = maxlen
        self.ttl = ttl
        self._items: Dict[str, float] = {}  # key -> monotonic deadline

    def _purge(self, now: float) -> None:
        # insertion order == ascending deadline (fixed ttl), so the
        # front of the dict is always the oldest entry
        while self._items:
            key = next(iter(self._items))
            if self.ttl and self._items[key] <= now:
                del self._items[key]
                continue
            break
        while len(self._items) > self.maxlen:
            del self._items[next(iter(self._items))]

    def add(self, key: str) -> None:
        now = time.monotonic()
        self._items.pop(key, None)  # re-add refreshes age and order
        self._items[key] = now + self.ttl
        self._purge(now)

    append = add

    def __contains__(self, key: str) -> bool:
        now = time.monotonic()
        self._purge(now)
        deadline = self._items.get(key)
        return deadline is not None and (not self.ttl or deadline > now)

    def __len__(self) -> int:
        self._purge(time.monotonic())
        return len(self._items)
