"""Block-template assembly + the mining-info cache.

``select_reference`` reproduces the reference mempool slice bit-for-bit
(fee-rate DESC, tx_hash tiebreak, running byte cap that BREAKS at the
first overflow — database.py:171-186).  ``assemble_template`` layers a
dependency guard on top: a tx spending another pooled tx's output is
only packed after its parent, and orphaned children (parent missed the
cut) are skipped instead of breaking the scan.  With no in-pool
dependencies — the common case, since intake's ``inputs_unspent`` rule
rejects spends of unconfirmed outputs — its output equals the
reference slice exactly, which is what the differential test pins.

:class:`MiningInfoCache` memoizes the expensive part of
``get_mining_info`` (sort + per-tx sha256 + merkle root over the whole
pending set) behind a key of (pool generation, chain tip, difficulty):
idle miner polling against an unchanged pool is a dict hit instead of
an O(mempool) rebuild per request.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .pool import MempoolEntry


def select_reference(entries: List[MempoolEntry],
                     limit_hex_chars: int) -> List[MempoolEntry]:
    """Reference-exact capped selection from priority-ordered entries."""
    out, total = [], 0
    for entry in entries:
        if total + entry.size_hex > limit_hex_chars:
            break
        total += entry.size_hex
        out.append(entry)
    return out


def assemble_template(entries: List[MempoolEntry],
                      limit_hex_chars: int) -> List[MempoolEntry]:
    """Greedy fee-rate packing under the byte cap, dependency-aware.

    ``entries`` must already be in priority order (Mempool.ordered()).
    A child is deferred until every in-pool parent has been packed; if
    a parent never makes the block, the child is dropped from this
    template rather than packed unspendable.  The byte cap keeps the
    reference break-at-first-overflow semantics.
    """
    in_pool = {e.tx_hash for e in entries}
    packed: List[MempoolEntry] = []
    packed_set: set = set()
    waiting: Dict[str, List[MempoolEntry]] = {}  # parent -> children
    total = 0
    capped = False

    def try_pack(entry: MempoolEntry) -> bool:
        nonlocal total, capped
        if capped:
            return False
        if total + entry.size_hex > limit_hex_chars:
            capped = True
            return False
        total += entry.size_hex
        packed.append(entry)
        packed_set.add(entry.tx_hash)
        # unblock children whose last missing parent was this tx, in
        # the priority order they were deferred in; a child with MORE
        # unpacked parents moves to its next missing parent's queue
        # (dropping it here would strand it even when every parent
        # eventually packs)
        for child in waiting.pop(entry.tx_hash, []):
            missing = [h for h, _ in child.outpoints
                       if h in in_pool and h not in packed_set]
            if missing:
                waiting.setdefault(missing[0], []).append(child)
            else:
                try_pack(child)
        return True

    for entry in entries:
        if capped:
            break
        if entry.tx_hash in packed_set:
            continue
        missing = [h for h, _ in entry.outpoints
                   if h in in_pool and h not in packed_set]
        if missing:
            waiting.setdefault(missing[0], []).append(entry)
            continue
        try_pack(entry)
    return packed


class MiningInfoCache:
    """Single-slot memo for the heavy half of get_mining_info.

    One slot suffices: every key component (pool generation, tip hash,
    difficulty) moves forward monotonically with chain/pool state, so a
    stale entry can never become valid again — and miner polling only
    ever asks for "now"."""

    def __init__(self):
        self._key: Optional[tuple] = None
        self._value: Optional[dict] = None
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Optional[dict]:
        if self._key == key:
            self.hits += 1
            return self._value
        self.misses += 1
        return None

    def put(self, key: tuple, value: dict) -> None:
        self._key = key
        self._value = value

    def invalidate(self) -> None:
        self._key = None
        self._value = None
