"""Snapshot builder: chain state -> published chunked generation.

The payload is backend-neutral JSON-lines, one object per row::

    {"t": "<table>", "r": [tx_hash, idx, address, amount(, is_stake)]}
    {"t": "tx",      "r": [block_hash, tx_hash, tx_hex, in_addrs,
                           out_addrs, out_amounts, fees]}
    {"t": "block",   "r": [id, hash, content, address, random,
                           difficulty, reward, timestamp]}

Tables stream in the fixed ``("unspent_outputs",) + _GOV_TABLES``
order with rows already canonically ordered by the state backends
(tx_hash, idx), then witness transactions ordered by tx_hash, then the
block tail ascending — so one chain state always serializes to one
byte stream, and the manifest (canonical JSON, no timestamps) is
byte-identical across rebuilds of the same state.  The byte stream is
cut into fixed ``chunk_bytes`` chunks, each sha256'd into the
manifest, which also commits to the anchor block (hash + height) and
the live ``get_unspent_outputs_hash`` / ``get_full_state_hash``
fingerprints the restore side must reproduce.

Crash safety: everything is written into a ``.staging-*`` dir first;
one ``os.replace`` publishes the generation and a second swings the
CURRENT pointer.  A crash anywhere leaves either the old generation or
the new one — never a torn mix — and the stale staging dir is swept by
:func:`..snapshot.layout.prune_generations` at the next build/boot.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os
import shutil
import tempfile
from typing import List, Optional

from .. import telemetry, trace
from ..logger import get_logger
from ..state.storage import _GOV_TABLES
from . import layout

log = get_logger("snapshot")

SNAPSHOT_TABLES = ("unspent_outputs",) + _GOV_TABLES


def _line(t: str, r: list) -> bytes:
    return (json.dumps({"t": t, "r": r}, sort_keys=True,
                       separators=(",", ":")) + "\n").encode()


async def serialize_payload(state, blocks_tail: int) -> tuple:
    """(payload bytes, per-section row counts) for the current state."""
    parts = []
    counts = {}
    for table in SNAPSHOT_TABLES:
        rows = await state.export_snapshot_rows(table)
        counts[table] = len(rows)
        parts.extend(_line(table, r) for r in rows)
    txs = await state.export_snapshot_txs(blocks_tail)
    counts["tx"] = len(txs)
    parts.extend(_line("tx", r) for r in txs)
    blocks = await state.export_snapshot_blocks(blocks_tail)
    counts["block"] = len(blocks)
    parts.extend(_line("block", r) for r in blocks)
    return b"".join(parts), counts


def _write_generation(staging: str, chunks: List[bytes], manifest: dict,
                      final: str) -> None:
    """Durable half of a build (runs in an executor): fsync'd chunk
    writes into staging, manifest, then the publishing rename."""
    for i, chunk in enumerate(chunks):
        with open(os.path.join(staging, layout.chunk_name(i)),
                  "wb") as fh:
            fh.write(chunk)
            fh.flush()
            os.fsync(fh.fileno())
    layout.write_manifest(os.path.join(staging, layout.MANIFEST_NAME),
                          manifest)
    if os.path.isdir(final):  # same anchor rebuilt: replace wholesale
        shutil.rmtree(final, ignore_errors=True)
    os.replace(staging, final)


async def build_snapshot(state, root: str, chunk_bytes: int = 1 << 20,
                         blocks_tail: int = 64,
                         keep: int = 2) -> Optional[dict]:
    """Build and publish one generation; returns its manifest (None on
    an empty chain — nothing to anchor to)."""
    anchor = await state.get_last_block()
    if anchor is None:
        return None
    os.makedirs(root, exist_ok=True)
    payload, counts = await serialize_payload(state, blocks_tail)
    chunks = [payload[off:off + chunk_bytes]
              for off in range(0, len(payload), chunk_bytes)] or [b""]
    manifest = {
        "version": layout.MANIFEST_VERSION,
        "anchor_height": anchor["id"],
        "anchor_hash": anchor["hash"],
        "utxo_fingerprint": await state.get_unspent_outputs_hash(),
        "full_state_fingerprint": await state.get_full_state_hash(),
        "chunk_bytes": chunk_bytes,
        "payload_bytes": len(payload),
        "payload_sha256": layout.sha256_hex(payload),
        "chunks": [{"i": i, "sha256": layout.sha256_hex(c), "size": len(c)}
                   for i, c in enumerate(chunks)],
        "counts": counts,
    }
    staging = tempfile.mkdtemp(prefix=".staging-", dir=root)
    final = os.path.join(
        root, layout.gen_name(anchor["id"], anchor["hash"]))
    loop = asyncio.get_running_loop()
    try:
        # chunk writes + fsync barriers + the publishing rename are the
        # slow durable half of a build; off the loop thread so a build
        # under load cannot stall gossip/WS for seconds
        await loop.run_in_executor(None, functools.partial(
            _write_generation, staging, chunks, manifest, final))
    except BaseException:
        await loop.run_in_executor(None, functools.partial(
            shutil.rmtree, staging, ignore_errors=True))
        raise
    layout.publish_current(root, os.path.basename(final))
    layout.prune_generations(root, keep=keep)
    trace.inc("snapshot.builds")
    telemetry.event("snapshot_build_complete", height=anchor["id"],
                    anchor=anchor["hash"], chunks=len(manifest["chunks"]),
                    bytes=len(payload))
    log.info("snapshot published: height=%d chunks=%d bytes=%d -> %s",
             anchor["id"], len(manifest["chunks"]), len(payload), final)
    return manifest
