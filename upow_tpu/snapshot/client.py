"""Resumable snapshot bootstrap (the restore half of docs/SNAPSHOT.md).

Trust model: the serving peer is NOT trusted.  The manifest itself is
validated first — strict 64-hex hashes (``payload_sha256`` names the
journal directory, so this is also the path-traversal gate), exact row
``i``/``size`` fields, and resource ceilings (``MAX_CHUNKS`` /
``MAX_CHUNK_BYTES`` / ``MAX_PAYLOAD_BYTES``) rejecting a manifest that
would have the client journal or assemble an unbounded payload.  Every
chunk is verified against the manifest's sha256 AND declared size
before it is journaled; the assembled
payload is verified against ``payload_sha256``; and the UTXO + full
state fingerprints are recomputed CLIENT-SIDE from the parsed rows and
compared to the manifest's anchors before a single database write —
the database only ever ingests a payload that already proved itself.
After the (single-transaction) restore the database's own fingerprints
are cross-checked once more against the manifest.

Crash model: the journal dir is keyed by the manifest's payload hash;
a chunk becomes durable only via write-to-``.part`` + fsync +
``os.replace`` onto ``chunk-NNNNNN.bin`` — the rename IS the commit.
kill -9 between chunks resumes from the last verified chunk with zero
re-downloads; kill -9 mid-chunk-write leaves a ``.part`` that is
simply overwritten.  Journaled chunks are re-verified from disk on
resume, so torn or tampered journal bytes are re-fetched, never
trusted.

Failure ladder: per-chunk integrity retries against one source are
capped (``SnapshotConfig.chunk_retries``), then the next health-ranked
source is tried (verified chunks carry over when it serves the same
payload); when sources or integrity run out, :class:`SnapshotError`
carries a structured reason and the caller (node/app.py) falls back to
full block replay — a bad snapshot peer must never break the join.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import json
import os
import re
import shutil
from typing import Dict, List, Optional

from .. import telemetry, trace
from ..logger import get_logger
from . import layout
from .builder import SNAPSHOT_TABLES

log = get_logger("snapshot")


async def _io(fn, *args):
    """Run blocking journal/file work off the event loop.

    A restore moves up to MAX_PAYLOAD_BYTES through open/fsync/replace
    and one giant assemble+hash; doing that on the loop thread stalls
    gossip, WS heartbeats, and every other handler for the duration.
    """
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, functools.partial(fn, *args))


class SnapshotError(Exception):
    """Restore could not complete; ``reason`` is the structured code
    surfaced in the ``snapshot_fallback`` telemetry event."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason
        self.detail = detail


# Resource ceilings on what a manifest may declare.  Without them a
# malicious peer could make a joining node download, journal and then
# assemble a multi-GB payload in memory — a disk/memory exhaustion DoS
# on the bootstrap path.  Overridable per call (SnapshotConfig wires
# them through Node.bootstrap_from_snapshot).
MAX_CHUNKS = 1 << 14            # 16384 manifest entries
MAX_CHUNK_BYTES = 16 << 20      # 16 MiB per chunk
MAX_PAYLOAD_BYTES = 1 << 30     # 1 GiB assembled payload

_HEX64 = re.compile(r"[0-9a-f]{64}")


def _manifest_error(m: dict, max_chunks: int = MAX_CHUNKS,
                    max_chunk_bytes: int = MAX_CHUNK_BYTES,
                    max_payload_bytes: int = MAX_PAYLOAD_BYTES
                    ) -> Optional[str]:
    """None when the manifest is well-formed and within the resource
    ceilings, else ``"malformed"`` / ``"oversize"``.  ``payload_sha256``
    names the journal directory, so the strict 64-hex check here is
    also the path-traversal gate — an attacker-chosen string must never
    become a path component."""
    try:
        if not (m["version"] == layout.MANIFEST_VERSION
                and isinstance(m["anchor_hash"], str)
                and isinstance(m["anchor_height"], int)
                and m["anchor_height"] > 0
                and isinstance(m["payload_sha256"], str)
                and _HEX64.fullmatch(m["payload_sha256"])
                and isinstance(m["payload_bytes"], int)
                and isinstance(m["utxo_fingerprint"], str)
                and isinstance(m["full_state_fingerprint"], str)
                and isinstance(m["chunks"], list) and m["chunks"]
                and all(isinstance(c["sha256"], str)
                        and _HEX64.fullmatch(c["sha256"])
                        and int(c["i"]) == i
                        and isinstance(c["size"], int) and c["size"] >= 0
                        for i, c in enumerate(m["chunks"]))):
            return "malformed"
        if m["payload_bytes"] != sum(c["size"] for c in m["chunks"]):
            return "malformed"
    except (KeyError, TypeError, ValueError):
        return "malformed"
    if (len(m["chunks"]) > max_chunks
            or m["payload_bytes"] > max_payload_bytes
            or any(c["size"] > max_chunk_bytes for c in m["chunks"])):
        return "oversize"
    return None


_ROW_ARITY = {"tx": 7, "block": 8, "unspent_outputs": 5}


def _row_ok(t: str, r) -> bool:
    """Shape check for one payload row: the exact arity the restore SQL
    binds, plus scalar types on the fields the client itself indexes
    (sort keys, anchor comparison) — so untrusted rows can never raise
    TypeError/IndexError past the SnapshotError ladder."""
    if not isinstance(r, list) or len(r) != _ROW_ARITY.get(t, 4):
        return False
    if t == "tx":
        return isinstance(r[1], str)
    if t == "block":
        return isinstance(r[0], int) and isinstance(r[1], str)
    return isinstance(r[0], str) and isinstance(r[1], int)


def parse_payload(payload: bytes) -> tuple:
    """payload bytes -> (tables dict, tx rows, block rows); raises
    SnapshotError on any malformed line."""
    tables: Dict[str, List[list]] = {t: [] for t in SNAPSHOT_TABLES}
    txs: List[list] = []
    blocks: List[list] = []
    for ln, raw in enumerate(payload.splitlines()):
        try:
            doc = json.loads(raw)
            t, r = doc["t"], doc["r"]
        except (ValueError, KeyError, TypeError):
            raise SnapshotError("payload_malformed", f"line {ln}")
        if t in tables:
            dest = tables[t]
        elif t == "tx":
            dest = txs
        elif t == "block":
            dest = blocks
        else:
            raise SnapshotError("payload_malformed",
                                f"line {ln}: unknown section {t!r}")
        if not _row_ok(t, r):
            raise SnapshotError("payload_malformed",
                                f"line {ln}: bad {t} row shape")
        dest.append(r)
    return tables, txs, blocks


def fingerprint_rows(rows: List[list]) -> str:
    """The table fingerprint recomputed from payload rows — must equal
    the backend's get_table_outpoints_hash (sha256 over the sorted
    outpoint concatenation)."""
    h = hashlib.sha256()
    for r in sorted(rows, key=lambda r: (r[0], r[1])):
        h.update(f"{r[0]}{r[1]}".encode())
    return h.hexdigest()


def full_fingerprint(tables: Dict[str, List[list]]) -> str:
    h = hashlib.sha256()
    for table in SNAPSHOT_TABLES:
        h.update(table.encode())
        h.update(fingerprint_rows(tables.get(table, [])).encode())
    return h.hexdigest()


class _Journal:
    """Verified-chunk journal for one payload identity."""

    def __init__(self, root: str, manifest: dict):
        self.manifest = manifest
        ident = manifest["payload_sha256"][:16]
        base = os.path.realpath(os.path.join(root, "restore"))
        self.dir = os.path.realpath(os.path.join(base, ident))
        # _manifest_error's 64-hex check is the real gate; this is the
        # belt-and-braces containment assert behind it
        if os.path.dirname(self.dir) != base:
            raise SnapshotError("journal_path_escape", ident)
        # prune journals of superseded payload identities (each failed
        # bootstrap against a different anchor would otherwise leak one
        # dir forever); only the identity being restored survives
        try:
            for name in os.listdir(base):
                if name != ident:
                    shutil.rmtree(os.path.join(base, name),
                                  ignore_errors=True)
        except OSError:
            pass
        os.makedirs(self.dir, exist_ok=True)
        layout.write_manifest(os.path.join(self.dir, layout.MANIFEST_NAME),
                              manifest)

    def chunk_path(self, i: int) -> str:
        return os.path.join(self.dir, layout.chunk_name(i))

    def have_verified(self, i: int) -> bool:
        """True when chunk i is journaled AND its bytes still match the
        manifest (re-verified from disk — a torn or tampered journal
        entry is treated as absent)."""
        try:
            with open(self.chunk_path(i), "rb") as fh:
                data = fh.read()
        except OSError:
            return False
        return layout.sha256_hex(data) == \
            self.manifest["chunks"][i]["sha256"]

    def commit_chunk(self, i: int, data: bytes) -> None:
        """Durable-then-rename: the ``os.replace`` is the commit point;
        a crash before it leaves only a ``.part`` the resume ignores."""
        part = self.chunk_path(i) + ".part"
        with open(part, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(part, self.chunk_path(i))

    def assemble(self) -> bytes:
        return b"".join(
            open(self.chunk_path(i), "rb").read()
            for i in range(len(self.manifest["chunks"])))

    def destroy(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)


async def bootstrap_from_snapshot(state, sources, root: str,
                                  chunk_retries: int = 2,
                                  progress: Optional[dict] = None,
                                  max_chunks: int = MAX_CHUNKS,
                                  max_chunk_bytes: int = MAX_CHUNK_BYTES,
                                  max_payload_bytes: int = MAX_PAYLOAD_BYTES
                                  ) -> dict:
    """Restore ``state`` from the first healthy source in ``sources``
    (NodeInterface instances, already health-ranked by the caller).

    Returns a result dict (method/height/anchor/chunks/chunks_reused/
    source/rpcs); raises :class:`SnapshotError` with a structured
    reason when every source is exhausted or integrity fails — the
    caller owns the replay fallback.
    """
    if not sources:
        raise SnapshotError("no_sources")
    progress = progress if progress is not None else {}
    progress.update(phase="manifest", verified=0, reused=0, total=0,
                    source="")
    rpcs = 0
    last_error = ""
    journal = None
    for iface in sources:
        src = iface.base_url
        try:
            rpcs += 1
            manifest = await iface.snapshot_manifest()
        except Exception as e:
            last_error = f"{src}: manifest: {e}"
            log.debug("snapshot source %s failed at manifest: %s", src, e)
            telemetry.event("snapshot_source_failed", source=src,
                            stage="manifest", error=str(e))
            continue
        err = "malformed" if not isinstance(manifest, dict) else \
            _manifest_error(manifest, max_chunks=max_chunks,
                            max_chunk_bytes=max_chunk_bytes,
                            max_payload_bytes=max_payload_bytes)
        if err is not None:
            last_error = f"{src}: manifest {err}"
            telemetry.event("snapshot_source_failed", source=src,
                            stage="manifest", error=err)
            continue
        if journal is None or \
                journal.manifest["payload_sha256"] != \
                manifest["payload_sha256"]:
            # new payload identity -> new journal; identical payload
            # from a failover source reuses every verified chunk
            # (construction prunes superseded journal dirs — executor)
            journal = await _io(_Journal, root, manifest)
        chunks = journal.manifest["chunks"]
        # per-pass counters: on failover, "reused" counts the verified
        # chunks the new pass inherited (i.e. not re-downloaded)
        progress.update(phase="chunks", total=len(chunks), source=src,
                        verified=0, reused=0,
                        height=journal.manifest["anchor_height"])
        telemetry.event("snapshot_restore_start", source=src,
                        height=journal.manifest["anchor_height"],
                        chunks=len(chunks))
        source_dead = False
        for i in range(len(chunks)):
            if await _io(journal.have_verified, i):
                progress["verified"] = progress.get("verified", 0) + 1
                progress["reused"] = progress.get("reused", 0) + 1
                trace.inc("snapshot.chunks_reused")
                continue
            ok = False
            for attempt in range(max(1, chunk_retries + 1)):
                try:
                    rpcs += 1
                    data = await iface.snapshot_chunk(i)
                except Exception as e:
                    last_error = f"{src}: chunk {i}: {e}"
                    log.debug("snapshot source %s failed at chunk %d: %s",
                              src, i, e)
                    telemetry.event("snapshot_source_failed", source=src,
                                    stage=f"chunk/{i}", error=str(e))
                    source_dead = True
                    break
                # the size check keeps the journal/assembly bounded by
                # what the (ceiling-checked) manifest declared — a hash
                # match alone would let the peer lie about sizes
                if len(data) == chunks[i]["size"] and \
                        layout.sha256_hex(data) == chunks[i]["sha256"]:
                    await _io(journal.commit_chunk, i, data)
                    ok = True
                    break
                trace.inc("snapshot.chunk_integrity_failures")
                last_error = f"{src}: chunk {i}: hash mismatch"
                telemetry.event("snapshot_chunk_corrupt", source=src,
                                chunk=i, attempt=attempt)
            if source_dead:
                break
            if not ok:
                source_dead = True  # integrity retries exhausted here
                break
            progress["verified"] = progress.get("verified", 0) + 1
            trace.inc("snapshot.chunks_fetched")
        if source_dead:
            continue  # next source; journaled chunks carry over
        return await _finish(state, journal, progress, src, rpcs)
    raise SnapshotError("sources_exhausted", last_error)


async def _finish(state, journal, progress: dict, src: str,
                  rpcs: int) -> dict:
    manifest = journal.manifest
    progress["phase"] = "verify"
    try:
        payload = await _io(journal.assemble)
        if await _io(layout.sha256_hex, payload) != \
                manifest["payload_sha256"]:
            # each chunk verified individually, so this means the
            # manifest itself is inconsistent — poison, not transport
            raise SnapshotError("payload_hash_mismatch", src)
        tables, txs, blocks = parse_payload(payload)
        if not blocks or blocks[-1][1] != manifest["anchor_hash"] or \
                blocks[-1][0] != manifest["anchor_height"]:
            raise SnapshotError("anchor_mismatch", src)
        # prove the payload against the manifest's fingerprints BEFORE
        # any database write — the db never ingests unproven rows
        if fingerprint_rows(tables["unspent_outputs"]) != \
                manifest["utxo_fingerprint"] or \
                full_fingerprint(tables) != \
                manifest["full_state_fingerprint"]:
            raise SnapshotError("fingerprint_mismatch", src)
    except SnapshotError:
        await _io(journal.destroy)
        raise
    except Exception as e:
        # untrusted bytes must never raise past the SnapshotError
        # ladder — the caller's replay fallback catches only that
        await _io(journal.destroy)
        raise SnapshotError("peer_malformed",
                            f"{src}: {type(e).__name__}: {e}")
    progress["phase"] = "restore"
    try:
        await state.restore_snapshot(tables, txs, blocks)
        # and cross-check what the database now reports (catches a
        # broken restore path, not a broken peer)
        mismatch = (await state.get_unspent_outputs_hash() !=
                    manifest["utxo_fingerprint"]
                    or await state.get_full_state_hash() !=
                    manifest["full_state_fingerprint"])
    except Exception as e:
        # atomic() rolled back: the pre-restore state is intact and the
        # replay fallback can proceed on it
        await _io(journal.destroy)
        raise SnapshotError("restore_failed",
                            f"{src}: {type(e).__name__}: {e}")
    if mismatch:
        # the unproven rows are already committed — wipe back to a
        # blank chain so the replay fallback syncs from genesis rather
        # than on top of state that failed its own cross-check
        await _io(journal.destroy)
        try:
            await state.restore_snapshot(
                {t: [] for t in SNAPSHOT_TABLES}, [], [])
        except Exception:
            log.exception("could not reset state after restored-state"
                          " mismatch; replay fallback starts dirty")
        raise SnapshotError("restored_state_mismatch", src)
    await _io(journal.destroy)
    progress["phase"] = "done"
    trace.inc("snapshot.restores")
    telemetry.event("snapshot_restore_complete", source=src,
                    height=manifest["anchor_height"],
                    anchor=manifest["anchor_hash"],
                    chunks=len(manifest["chunks"]),
                    reused=progress.get("reused", 0), rpcs=rpcs)
    return {
        "method": "snapshot",
        "height": manifest["anchor_height"],
        "anchor": manifest["anchor_hash"],
        "chunks": len(manifest["chunks"]),
        "chunks_reused": progress.get("reused", 0),
        "source": src,
        "rpcs": rpcs,
    }
