"""Resumable snapshot bootstrap (the restore half of docs/SNAPSHOT.md).

Trust model: the serving peer is NOT trusted.  Every chunk is verified
against the manifest's sha256 before it is journaled; the assembled
payload is verified against ``payload_sha256``; and the UTXO + full
state fingerprints are recomputed CLIENT-SIDE from the parsed rows and
compared to the manifest's anchors before a single database write —
the database only ever ingests a payload that already proved itself.
After the (single-transaction) restore the database's own fingerprints
are cross-checked once more against the manifest.

Crash model: the journal dir is keyed by the manifest's payload hash;
a chunk becomes durable only via write-to-``.part`` + fsync +
``os.replace`` onto ``chunk-NNNNNN.bin`` — the rename IS the commit.
kill -9 between chunks resumes from the last verified chunk with zero
re-downloads; kill -9 mid-chunk-write leaves a ``.part`` that is
simply overwritten.  Journaled chunks are re-verified from disk on
resume, so torn or tampered journal bytes are re-fetched, never
trusted.

Failure ladder: per-chunk integrity retries against one source are
capped (``SnapshotConfig.chunk_retries``), then the next health-ranked
source is tried (verified chunks carry over when it serves the same
payload); when sources or integrity run out, :class:`SnapshotError`
carries a structured reason and the caller (node/app.py) falls back to
full block replay — a bad snapshot peer must never break the join.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Dict, List, Optional

from .. import telemetry, trace
from ..logger import get_logger
from . import layout
from .builder import SNAPSHOT_TABLES

log = get_logger("snapshot")


class SnapshotError(Exception):
    """Restore could not complete; ``reason`` is the structured code
    surfaced in the ``snapshot_fallback`` telemetry event."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason
        self.detail = detail


def _manifest_ok(m: dict) -> bool:
    try:
        return (m["version"] == layout.MANIFEST_VERSION
                and isinstance(m["anchor_hash"], str)
                and int(m["anchor_height"]) > 0
                and isinstance(m["chunks"], list) and m["chunks"]
                and all(isinstance(c["sha256"], str)
                        and int(c["i"]) == i
                        for i, c in enumerate(m["chunks"])))
    except (KeyError, TypeError, ValueError):
        return False


def parse_payload(payload: bytes) -> tuple:
    """payload bytes -> (tables dict, tx rows, block rows); raises
    SnapshotError on any malformed line."""
    tables: Dict[str, List[list]] = {t: [] for t in SNAPSHOT_TABLES}
    txs: List[list] = []
    blocks: List[list] = []
    for ln, raw in enumerate(payload.splitlines()):
        try:
            doc = json.loads(raw)
            t, r = doc["t"], doc["r"]
        except (ValueError, KeyError, TypeError):
            raise SnapshotError("payload_malformed", f"line {ln}")
        if t in tables:
            tables[t].append(r)
        elif t == "tx":
            txs.append(r)
        elif t == "block":
            blocks.append(r)
        else:
            raise SnapshotError("payload_malformed",
                                f"line {ln}: unknown section {t!r}")
    return tables, txs, blocks


def fingerprint_rows(rows: List[list]) -> str:
    """The table fingerprint recomputed from payload rows — must equal
    the backend's get_table_outpoints_hash (sha256 over the sorted
    outpoint concatenation)."""
    h = hashlib.sha256()
    for r in sorted(rows, key=lambda r: (r[0], r[1])):
        h.update(f"{r[0]}{r[1]}".encode())
    return h.hexdigest()


def full_fingerprint(tables: Dict[str, List[list]]) -> str:
    h = hashlib.sha256()
    for table in SNAPSHOT_TABLES:
        h.update(table.encode())
        h.update(fingerprint_rows(tables.get(table, [])).encode())
    return h.hexdigest()


class _Journal:
    """Verified-chunk journal for one payload identity."""

    def __init__(self, root: str, manifest: dict):
        self.manifest = manifest
        self.dir = os.path.join(root, "restore",
                                manifest["payload_sha256"][:16])
        os.makedirs(self.dir, exist_ok=True)
        layout.write_manifest(os.path.join(self.dir, layout.MANIFEST_NAME),
                              manifest)

    def chunk_path(self, i: int) -> str:
        return os.path.join(self.dir, layout.chunk_name(i))

    def have_verified(self, i: int) -> bool:
        """True when chunk i is journaled AND its bytes still match the
        manifest (re-verified from disk — a torn or tampered journal
        entry is treated as absent)."""
        try:
            with open(self.chunk_path(i), "rb") as fh:
                data = fh.read()
        except OSError:
            return False
        return layout.sha256_hex(data) == \
            self.manifest["chunks"][i]["sha256"]

    def commit_chunk(self, i: int, data: bytes) -> None:
        """Durable-then-rename: the ``os.replace`` is the commit point;
        a crash before it leaves only a ``.part`` the resume ignores."""
        part = self.chunk_path(i) + ".part"
        with open(part, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(part, self.chunk_path(i))

    def assemble(self) -> bytes:
        return b"".join(
            open(self.chunk_path(i), "rb").read()
            for i in range(len(self.manifest["chunks"])))

    def destroy(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)


async def bootstrap_from_snapshot(state, sources, root: str,
                                  chunk_retries: int = 2,
                                  progress: Optional[dict] = None) -> dict:
    """Restore ``state`` from the first healthy source in ``sources``
    (NodeInterface instances, already health-ranked by the caller).

    Returns a result dict (method/height/anchor/chunks/chunks_reused/
    source/rpcs); raises :class:`SnapshotError` with a structured
    reason when every source is exhausted or integrity fails — the
    caller owns the replay fallback.
    """
    if not sources:
        raise SnapshotError("no_sources")
    progress = progress if progress is not None else {}
    progress.update(phase="manifest", verified=0, reused=0, total=0,
                    source="")
    rpcs = 0
    last_error = ""
    journal = None
    for iface in sources:
        src = iface.base_url
        try:
            rpcs += 1
            manifest = await iface.snapshot_manifest()
        except Exception as e:
            last_error = f"{src}: manifest: {e}"
            log.debug("snapshot source %s failed at manifest: %s", src, e)
            telemetry.event("snapshot_source_failed", source=src,
                            stage="manifest", error=str(e))
            continue
        if not isinstance(manifest, dict) or not _manifest_ok(manifest):
            last_error = f"{src}: manifest malformed"
            telemetry.event("snapshot_source_failed", source=src,
                            stage="manifest", error="malformed")
            continue
        if journal is None or \
                journal.manifest["payload_sha256"] != \
                manifest["payload_sha256"]:
            # new payload identity -> new journal; identical payload
            # from a failover source reuses every verified chunk
            journal = _Journal(root, manifest)
        chunks = journal.manifest["chunks"]
        # per-pass counters: on failover, "reused" counts the verified
        # chunks the new pass inherited (i.e. not re-downloaded)
        progress.update(phase="chunks", total=len(chunks), source=src,
                        verified=0, reused=0,
                        height=journal.manifest["anchor_height"])
        telemetry.event("snapshot_restore_start", source=src,
                        height=journal.manifest["anchor_height"],
                        chunks=len(chunks))
        source_dead = False
        for i in range(len(chunks)):
            if journal.have_verified(i):
                progress["verified"] = progress.get("verified", 0) + 1
                progress["reused"] = progress.get("reused", 0) + 1
                trace.inc("snapshot.chunks_reused")
                continue
            ok = False
            for attempt in range(max(1, chunk_retries + 1)):
                try:
                    rpcs += 1
                    data = await iface.snapshot_chunk(i)
                except Exception as e:
                    last_error = f"{src}: chunk {i}: {e}"
                    log.debug("snapshot source %s failed at chunk %d: %s",
                              src, i, e)
                    telemetry.event("snapshot_source_failed", source=src,
                                    stage=f"chunk/{i}", error=str(e))
                    source_dead = True
                    break
                if layout.sha256_hex(data) == chunks[i]["sha256"]:
                    journal.commit_chunk(i, data)
                    ok = True
                    break
                trace.inc("snapshot.chunk_integrity_failures")
                last_error = f"{src}: chunk {i}: hash mismatch"
                telemetry.event("snapshot_chunk_corrupt", source=src,
                                chunk=i, attempt=attempt)
            if source_dead:
                break
            if not ok:
                source_dead = True  # integrity retries exhausted here
                break
            progress["verified"] = progress.get("verified", 0) + 1
            trace.inc("snapshot.chunks_fetched")
        if source_dead:
            continue  # next source; journaled chunks carry over
        return await _finish(state, journal, progress, src, rpcs)
    raise SnapshotError("sources_exhausted", last_error)


async def _finish(state, journal, progress: dict, src: str,
                  rpcs: int) -> dict:
    manifest = journal.manifest
    progress["phase"] = "verify"
    payload = journal.assemble()
    if layout.sha256_hex(payload) != manifest["payload_sha256"]:
        # each chunk verified individually, so this means the manifest
        # itself is inconsistent — poison, not a transport problem
        journal.destroy()
        raise SnapshotError("payload_hash_mismatch", src)
    tables, txs, blocks = parse_payload(payload)
    if not blocks or blocks[-1][1] != manifest["anchor_hash"] or \
            blocks[-1][0] != manifest["anchor_height"]:
        journal.destroy()
        raise SnapshotError("anchor_mismatch", src)
    # prove the payload against the manifest's fingerprints BEFORE any
    # database write — the db never ingests unproven rows
    if fingerprint_rows(tables["unspent_outputs"]) != \
            manifest["utxo_fingerprint"] or \
            full_fingerprint(tables) != manifest["full_state_fingerprint"]:
        journal.destroy()
        raise SnapshotError("fingerprint_mismatch", src)
    progress["phase"] = "restore"
    await state.restore_snapshot(tables, txs, blocks)
    # and cross-check what the database now reports (catches a broken
    # restore path, not a broken peer)
    if await state.get_unspent_outputs_hash() != \
            manifest["utxo_fingerprint"] or \
            await state.get_full_state_hash() != \
            manifest["full_state_fingerprint"]:
        raise SnapshotError("restored_state_mismatch", src)
    journal.destroy()
    progress["phase"] = "done"
    trace.inc("snapshot.restores")
    telemetry.event("snapshot_restore_complete", source=src,
                    height=manifest["anchor_height"],
                    anchor=manifest["anchor_hash"],
                    chunks=len(manifest["chunks"]),
                    reused=progress.get("reused", 0), rpcs=rpcs)
    return {
        "method": "snapshot",
        "height": manifest["anchor_height"],
        "anchor": manifest["anchor_hash"],
        "chunks": len(manifest["chunks"]),
        "chunks_reused": progress.get("reused", 0),
        "source": src,
        "rpcs": rpcs,
    }
