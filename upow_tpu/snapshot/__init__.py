"""Crash-safe, block-hash-anchored UTXO snapshots (docs/SNAPSHOT.md).

Three parts share one on-disk layout (:mod:`.layout`):

* :mod:`.builder` — serialize the UTXO set + witness transactions + a
  block tail into fixed-size sha256'd chunks under a manifest that
  commits to the anchor block (hash, height) and the state
  fingerprints.  Built in a staging dir, published by one rename.
* the node's ``/snapshot/manifest`` + ``/snapshot/chunk/{i}`` handlers
  (node/app.py) — serve the published generation straight from disk.
* :mod:`.client` — resumable bootstrap: download chunks from
  health-ranked peers, verify every chunk hash before it is journaled,
  survive kill -9 at any byte, cross-check the restored fingerprint,
  and degrade to full block replay with a structured reason when
  integrity or sources run out.
"""

from .builder import build_snapshot
from .client import SnapshotError, bootstrap_from_snapshot
from .layout import (current_manifest, prune_generations, read_manifest,
                     snapshot_dir_ready)

__all__ = [
    "build_snapshot",
    "bootstrap_from_snapshot",
    "SnapshotError",
    "current_manifest",
    "prune_generations",
    "read_manifest",
    "snapshot_dir_ready",
]
