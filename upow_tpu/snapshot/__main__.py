"""CLI entry: snapshot smoke — round-trip plus the churn scenario.

    python -m upow_tpu.snapshot                     # round-trip + scenario
    python -m upow_tpu.snapshot --check-determinism # scenario twice, cmp fp
    python -m upow_tpu.snapshot --round-trip-only   # skip the swarm scenario

The round-trip boots a two-node loopback swarm, mines a short chain,
publishes a snapshot on node 0, onboards blank node 1 from it, and
requires byte-exact UTXO + full-state fingerprints on the restored
node plus generation rotation (two builds at different heights keep
only ``SnapshotConfig.keep`` generations on disk).  The scenario half
runs ``snapshot_churn`` (docs/SWARM.md): corruption, mid-transfer
partition, journaled resume, replay fallback.  Exit status is
non-zero when any check fails — CI's ``snapshot-smoke`` job gates on
the run directly.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import shutil
import sys
import tempfile

from ..swarm.harness import Swarm
from ..swarm.scenarios import (_wallet, core_ok, deterministic_world,
                               run_scenario)
from . import layout


async def _drive_round_trip(seed: int, tmp: str) -> list:
    failures = []
    swarm = await Swarm(2, seed=seed).start(topology="isolated")
    try:
        _, addr = _wallet(seed, "shared")
        for i in (0, 1):
            scfg = swarm.nodes[i].config.snapshot
            scfg.dir = os.path.join(tmp, f"n{i}")
            scfg.chunk_bytes = 1024
            scfg.blocks_tail = 4
        for _ in range(8):
            assert (await swarm.mine(0, addr, push_to=[0]))["ok"]
        manifest = await swarm.nodes[0].build_snapshot()
        if manifest is None:
            return ["build returned no manifest"]
        res = await swarm.nodes[1].bootstrap_from_snapshot(
            sources=[swarm.urls[0]])
        if not (res.get("ok") and res.get("method") == "snapshot"):
            failures.append(f"restore failed: {res}")
        fp0 = await swarm.nodes[0].state.get_unspent_outputs_hash()
        fp1 = await swarm.nodes[1].state.get_unspent_outputs_hash()
        full0 = await swarm.nodes[0].state.get_full_state_hash()
        full1 = await swarm.nodes[1].state.get_full_state_hash()
        if fp0 != fp1 or full0 != full1:
            failures.append("restored fingerprints diverge")
        if manifest["utxo_fingerprint"] != fp0:
            failures.append("manifest fingerprint != live state")
        # rotation: a second build at a later height must leave at most
        # SnapshotConfig.keep generations and zero staging dirs
        for _ in range(2):
            assert (await swarm.mine(0, addr, push_to=[0]))["ok"]
        second = await swarm.nodes[0].build_snapshot()
        if second is None or second["anchor_height"] <= \
                manifest["anchor_height"]:
            failures.append("second build did not advance the anchor")
        root = swarm.nodes[0].config.snapshot.dir
        gens = layout.list_generations(root)
        keep = swarm.nodes[0].config.snapshot.keep
        if len(gens) > keep:
            failures.append(f"rotation kept {len(gens)} > {keep} gens")
        if any(n.startswith(".staging-") for n in os.listdir(root)):
            failures.append("stale staging dir survived the build")
        if second is not None and \
                layout.current_manifest(root) != second:
            failures.append("CURRENT does not point at the newest build")
        print(f"ok   round-trip height={res.get('height')} "
              f"chunks={res.get('chunks')} rpcs={res.get('rpcs')} "
              f"gens={len(gens)}")
    finally:
        await swarm.close()
    return failures


def _round_trip(seed: int) -> list:
    tmp = tempfile.mkdtemp(prefix="snapshot-smoke-")
    try:
        with deterministic_world(seed):
            return asyncio.run(_drive_round_trip(seed, tmp))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _print_scenario(artifact: dict) -> bool:
    core = artifact["core"]
    good = core_ok(core)
    print(f"{'ok  ' if good else 'FAIL'} {artifact['scenario']:>16} "
          f"n={artifact['nodes']} seed={artifact['seed']} "
          f"{artifact['observed']['elapsed_s']:.2f}s "
          f"fp={artifact['fingerprint'][:16]}")
    if not good:
        for key, val in sorted(core.items()):
            if isinstance(val, bool) and not val:
                print(f"     core failed: {key}", file=sys.stderr)
    obs = artifact["observed"]
    print(f"     snapshot_rpcs={obs['snapshot_rpcs']} "
          f"replay_rpcs={obs['replay_rpcs']} "
          f"chunks={obs['manifest_chunks']} "
          f"corrupt_events={obs['corrupt_events']}")
    return good


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m upow_tpu.snapshot",
        description="snapshot smoke: build/serve/restore round-trip "
                    "plus the snapshot_churn swarm scenario")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--round-trip-only", action="store_true",
                        help="skip the swarm scenario")
    parser.add_argument("--check-determinism", action="store_true",
                        help="run the scenario twice with the same seed "
                             "and fail unless the core fingerprints are "
                             "identical")
    args = parser.parse_args(argv)

    ok = True
    failures = _round_trip(args.seed)
    for f in failures:
        print(f"FAIL round-trip: {f}", file=sys.stderr)
        ok = False

    if not args.round_trip_only:
        artifact = run_scenario("snapshot_churn", seed=args.seed)
        ok = _print_scenario(artifact) and ok
        if args.check_determinism:
            again = run_scenario("snapshot_churn", seed=args.seed)
            same = again["fingerprint"] == artifact["fingerprint"]
            print(f"{'ok  ' if same else 'FAIL'} determinism "
                  f"fp1={artifact['fingerprint'][:16]} "
                  f"fp2={again['fingerprint'][:16]}")
            ok = ok and same

    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
