"""On-disk snapshot layout shared by builder, server and client.

::

    <root>/
      CURRENT                     name of the published generation dir
      gen-000000024-6fe2a1b09c44/ one generation (anchor height + hash)
        manifest.json
        chunk-000000.bin ...
      .staging-*/                 builder scratch (rename publishes it)
      restore/                    client journal (see client.py)

Publishing is one ``os.replace`` of the staging dir onto the
generation name followed by one ``os.replace`` of the CURRENT pointer
file — readers either see the previous complete generation or the new
one, never a half-written mix.  Housekeeping (generation pruning,
stale staging sweep) follows the half-tail rotation stance from
tpu_watch.py: best-effort, OSError swallowed, never raises into the
caller — a full disk must degrade snapshot serving, not block accept.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import List, Optional

from ..logger import get_logger

log = get_logger("snapshot")

MANIFEST_NAME = "manifest.json"
CURRENT_NAME = "CURRENT"
MANIFEST_VERSION = 1


def gen_name(height: int, anchor_hash: str) -> str:
    """Generation dir name: sortable by height, disambiguated by the
    anchor hash prefix (two builds at one height after a reorg must not
    collide)."""
    return f"gen-{int(height):09d}-{anchor_hash[:12]}"


def chunk_name(i: int) -> str:
    return f"chunk-{int(i):06d}.bin"


def canonical_json(doc: dict) -> bytes:
    """The byte form every hash commits to — identical state must
    yield identical manifest bytes (no timestamps in the document)."""
    return (json.dumps(doc, sort_keys=True, separators=(",", ":"))
            + "\n").encode()


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def write_manifest(path: str, manifest: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(canonical_json(manifest))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def read_manifest(path: str) -> Optional[dict]:
    try:
        with open(path, "rb") as fh:
            return json.loads(fh.read())
    except (OSError, ValueError):
        return None


def publish_current(root: str, name: str) -> None:
    """Point CURRENT at a generation dir (atomic pointer swap)."""
    tmp = os.path.join(root, CURRENT_NAME + ".tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(name + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, os.path.join(root, CURRENT_NAME))


def current_gen_dir(root: str) -> Optional[str]:
    """The published generation dir, or None when nothing is live."""
    try:
        with open(os.path.join(root, CURRENT_NAME), encoding="utf-8") as fh:
            name = fh.read().strip()
    except OSError:
        return None
    if not name or "/" in name or name.startswith("."):
        return None
    path = os.path.join(root, name)
    return path if os.path.isdir(path) else None


def current_manifest(root: str) -> Optional[dict]:
    gen = current_gen_dir(root)
    if gen is None:
        return None
    return read_manifest(os.path.join(gen, MANIFEST_NAME))


def snapshot_dir_ready(root: str) -> bool:
    return bool(root) and current_manifest(root) is not None


def list_generations(root: str) -> List[str]:
    """Generation dir names, oldest first (name order == height order)."""
    try:
        names = sorted(n for n in os.listdir(root)
                       if n.startswith("gen-")
                       and os.path.isdir(os.path.join(root, n)))
    except OSError:
        return []
    return names


def prune_generations(root: str, keep: int = 2) -> int:
    """Bound disk use to the newest ``keep`` generations and sweep any
    abandoned ``.staging-*`` scratch dirs (a builder crash between
    mkdtemp and publish leaks one).  Never raises; the published
    CURRENT generation is always retained.  Returns dirs removed."""
    removed = 0
    try:
        current = current_gen_dir(root)
        names = list_generations(root)
        doomed = names[:-keep] if keep > 0 else names
        for name in doomed:
            path = os.path.join(root, name)
            if current is not None and os.path.abspath(path) == \
                    os.path.abspath(current):
                continue
            shutil.rmtree(path, ignore_errors=True)
            removed += 1
        for name in os.listdir(root):
            if name.startswith(".staging-"):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
                removed += 1
    except OSError:
        pass
    if removed:
        log.info("snapshot prune: removed %d dirs under %s", removed, root)
    return removed
