"""Typed configuration for every process in the framework.

The reference scatters its knobs across ``config.py:1`` (the CORE_URL
seed), env vars (``upow/node/main.py:249-254``), ``ip_config.json``
(hot-reloaded, ``ip_manager.py:19-40``), WebSocket constants
(``websocket/socket_config.py:6-43``) and hardcoded consensus constants.
Here one dataclass tree feeds the node, miner, wallet and bench; every
field can come from (in order of precedence) explicit kwargs, a JSON
config file, or ``UPOW_``-prefixed environment variables.

Device selection (the ``device: cpu|tpu`` switch from BASELINE.json) maps
to the mining/verify backend choices; mesh shape covers multi-chip.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_SEED_URL = "https://api.upow.ai/"


@dataclass
class DeviceConfig:
    """Compute-backend selection (BASELINE.json `device` flag)."""

    device: str = "auto"            # auto | tpu | cpu
    search_backend: str = "auto"    # auto | pallas | jnp | native | python
    sig_backend: str = "auto"       # auto | tpu | host
    search_batch: int = 1 << 24     # nonces per device dispatch
    verify_pad_block: int = 128     # lane padding for the P-256 kernel
    verify_device_timeout: float = 240.0  # seconds before a hung device
                                    # dispatch falls back to the host path
    mesh_devices: int = 0           # 0 = all visible devices
    utxo_index: bool = False        # device-resident UTXO membership
                                    # prefilter on block accept (worth it
                                    # with a real accelerator; on a CPU
                                    # node sqlite is already fast)
    verify_kernel: str = ""         # "" = default | jac | complete
    verify_window: int = 0          # 0 = default | 4 | 5  (jac ladder w)
    txid_backend: str = "auto"      # auto | device | host — batch txid
                                    # hashing for sync pages / block
                                    # accept (crypto/sha256.txid_batch);
                                    # auto resolves by measuring both
                                    # once per process
    txid_min_batch: int = 256       # below this, always hashlib
    verify_microbatch: int = 1024   # txs per check_block micro-batch:
                                    # digest prep of batch N overlaps the
                                    # in-flight sig verify of batch N-1
                                    # (verify/block.py); 0 = whole block

    def resolve_search_backend(self, platform: str) -> str:
        if self.search_backend != "auto":
            return self.search_backend
        return "pallas" if platform == "tpu" else "jnp"

    def apply_kernel_overrides(self) -> None:
        """Push the A/B-able kernel knobs into crypto.p256 (module-level
        so every dispatch path — node, bench, tests — sees one value).
        No-op at defaults: importing p256 pulls in jax, which a host-path
        node must not pay at startup."""
        if not (self.verify_kernel or self.verify_window):
            return
        if self.verify_kernel and self.verify_kernel not in ("jac",
                                                             "complete"):
            raise ValueError(
                f"device.verify_kernel must be 'jac' or 'complete', "
                f"not {self.verify_kernel!r}")
        window = self.verify_window
        if window and (not isinstance(window, int) or isinstance(window, bool)
                       or not 2 <= window <= 13):
            raise ValueError(
                f"device.verify_window must be an int in [2, 13], "
                f"not {window!r}")
        from .crypto import p256

        if self.verify_kernel:
            p256.PALLAS_KERNEL = self.verify_kernel
        if window:
            p256.PALLAS_JAC_WINDOW = window


@dataclass
class DeviceRuntimeConfig:
    """Per-process device-runtime service (upow_tpu/device/runtime.py,
    docs/DEVICE_RUNTIME.md).  Operational only — the runtime changes who
    shares a dispatch, never what is computed, so nodes with different
    runtime settings stay bit-identical on chain state.  All fields
    overridable as ``UPOW_DEVICE_RUNTIME_<FIELD>``."""

    arm_timeout: float = 90.0       # backend probe/arm deadline; a hung
                                    # tunnel costs the process ONE such
                                    # timeout, then every source runs on
                                    # the host paths
    aot_warm: bool = True           # compile the kernel set at arm time
                                    # (real accelerators only; the CPU
                                    # XLA fallbacks are never warmed)
    compile_cache_dir: str = ""     # persistent compile cache root fed
                                    # to compile_cache.enable() at arm
                                    # ('' = caller manages it, as
                                    # bench.py does)
    weights: str = ("block=4,index=3,mempool=2,verify=2,"
                    "mine=1,bench=1,other=1")
                                    # fair-share weights per source; a
                                    # served item charges cost/weight to
                                    # its source's virtual pass, so
                                    # block verify outruns a saturating
                                    # miner stream 4:1
    queue_max: int = 8192           # per-source pending-item cap;
                                    # overflow raises (backpressure)
    max_coalesce: int = 64          # sig submissions merged into one
                                    # shared dispatch

    def parsed_weights(self) -> dict:
        weights = {}
        for part in self.weights.split(","):
            name, _, raw = part.strip().partition("=")
            name, raw = name.strip(), raw.strip()
            if name and raw:
                try:
                    weights[name] = max(1, int(raw))
                except ValueError:
                    raise ValueError(
                        f"device_runtime.weights entry {part!r}: weight "
                        f"must be an integer") from None
        return weights

    @classmethod
    def from_env(cls) -> "DeviceRuntimeConfig":
        """Defaults + ``UPOW_DEVICE_RUNTIME_*`` env overrides — the
        runtime singleton arms before any Config object exists, so it
        reads the same env surface directly."""
        cfg = cls()
        _apply_env_fields(cfg, "device_runtime")
        return cfg


@dataclass
class ResilienceConfig:
    """Retry / circuit-breaker / degradation / fault-injection knobs.

    Everything here is operational policy, not consensus: two nodes with
    different resilience settings stay bit-identical on chain state.
    Fault injection is OFF unless ``faults`` is non-empty, so production
    code paths run unmodified by default.
    """

    # retry with jittered exponential backoff for outbound RPC
    rpc_attempts: int = 3           # total tries per logical call
    rpc_backoff_base: float = 0.25  # first retry delay (seconds)
    rpc_backoff_max: float = 2.0    # per-retry delay ceiling
    rpc_backoff_multiplier: float = 2.0
    rpc_jitter: float = 0.5         # +/- fraction of each delay
    rpc_deadline: float = 45.0      # total budget per logical call
                                    # (attempts + backoffs); 0 = none
    propagate_deadline: float = 10.0  # per-peer bound on gossip sends
    # per-peer circuit breakers (PeerBook health scores)
    breaker_failure_threshold: int = 5   # consecutive failures -> open
    breaker_open_secs: float = 30.0      # open -> half-open probe delay
    breaker_half_open_max: int = 1       # trial calls while half-open
    # TPU -> CPU graceful degradation for the verify hot path
    device_failure_limit: int = 3   # consecutive errors -> degraded
    device_cooldown: float = 60.0   # degraded -> re-probe interval
    # deterministic fault injection (resilience/faultinject.py); empty
    # spec = disabled, hooks are inert.  Example:
    #   "rpc:error:p=0.5;device.verify:error:times=3"
    faults: str = ""
    faults_seed: int = 0


@dataclass
class MempoolConfig:
    """Micro-batched mempool subsystem (upow_tpu/mempool/).

    All operational policy: nodes with different mempool settings stay
    bit-identical on chain state, and push_tx keeps the reference wire
    shape (error strings / status codes) regardless of these knobs.
    """

    enabled: bool = True            # False = per-request serial intake
                                    # (the reference-shaped path, kept
                                    # as the differential baseline)
    coalesce_window_ms: float = 2.0  # admission-queue drain window: how
                                    # long the first waiter of a batch
                                    # holds the door for stragglers
    max_intake_batch: int = 128     # txs per micro-batch (one P-256
                                    # device dispatch per batch)
    max_pool_bytes_hex: int = 64 * 1024 * 1024  # pool byte cap (hex
                                    # chars, 16 reference blocks);
                                    # overflow evicts lowest fee-rate
    tx_ttl: float = 7200.0          # seconds before an un-mined pooled
                                    # tx expires (0 = never)
    tx_cache_size: int = 1 << 16    # push_tx dedup set capacity
                                    # (replaces the 100-entry deque)
    tx_cache_ttl: float = 600.0     # seconds a dedup entry stays live
    allow_rbf: bool = False         # replace-by-fee on outpoint
                                    # conflict (pool API only; intake
                                    # keeps the reference reject)
    reinject_on_reorg: bool = True  # re-queue txs from rolled-back
                                    # blocks into the journal/pool


@dataclass
class CacheConfig:
    """Generation-anchored hot-state read cache (state/hotcache.py,
    docs/CACHING.md).  Operational only: the cache serves byte-identical
    responses, so nodes with different cache settings stay bit-identical
    on the wire.  All overridable as ``UPOW_CACHE_<FIELD>``."""

    enabled: bool = True
    class_cap_bytes: int = 8 * 1024 * 1024  # default LRU byte cap per
                                    # entry class (address/blocks/tx/...)
    class_caps: str = ""            # per-class overrides, e.g.
                                    # "address=16777216,blocks=4194304"
    max_entry_bytes: int = 1 * 1024 * 1024  # bodies above this are
                                    # served but never stored (one giant
                                    # page must not flush a whole class)
    revalidate_interval: float = 0.25  # seconds between re-anchoring the
                                    # generation against the shared DB
                                    # (tip hash + journal stamp) to catch
                                    # OTHER workers' writes; 0 = every
                                    # read, negative = never (sole-writer
                                    # process)

    def parsed_class_caps(self) -> dict:
        caps = {}
        for part in self.class_caps.split(","):
            name, _, raw = part.strip().partition("=")
            if name and raw:
                try:
                    caps[name] = int(raw)
                except ValueError:
                    raise ValueError(
                        f"cache.class_caps entry {part!r}: cap must be an"
                        f" integer byte count") from None
        return caps


@dataclass
class SnapshotConfig:
    """Block-hash-anchored UTXO snapshot subsystem (upow_tpu/snapshot/,
    docs/SNAPSHOT.md).  Operational only: a snapshot-restored node and a
    full-replay node end on byte-identical UTXO fingerprints, so none of
    these knobs touch consensus.  All overridable as
    ``UPOW_SNAPSHOT_<FIELD>``."""

    dir: str = ""                   # snapshot root directory; '' disables
                                    # both building and serving
    chunk_bytes: int = 1 << 20      # fixed chunk size the payload is
                                    # split into (each chunk sha256'd
                                    # into the manifest)
    blocks_tail: int = 64           # recent block rows carried in the
                                    # payload so a restored node has a
                                    # tip + fork-detection history
                                    # (should be >= sync_reorg_window in
                                    # production; swarm uses a tiny
                                    # window so the default covers it)
    keep: int = 2                   # on-disk generations retained; older
                                    # ones and stale staging dirs are
                                    # pruned (never raising)
    chunk_retries: int = 2          # per-chunk integrity retries against
                                    # ONE source before failing over
    max_chunks: int = 1 << 14       # restore-side ceilings on what a
    max_chunk_bytes: int = 16 << 20  # peer manifest may declare; an
    max_payload_bytes: int = 1 << 30  # oversize manifest is rejected
                                    # before any chunk is fetched
                                    # (anti-DoS on the bootstrap path)
    rebuild_interval_blocks: int = 0  # rebuild the snapshot generation
                                    # every N accepted blocks (0 =
                                    # operator-driven only); arms the
                                    # archive compactor without an
                                    # operator
    rebuild_jitter_blocks: int = 0  # per-node deterministic offset
                                    # (seeded from the node identity,
                                    # 0..jitter) added to the cadence so
                                    # a fleet doesn't rebuild in
                                    # lockstep


@dataclass
class ArchiveConfig:
    """Cold-block archival tier (upow_tpu/archive/, docs/ARCHIVE.md).
    Operational only: pruned and unpruned nodes answer every read
    byte-identically, so none of these knobs touch consensus.  All
    overridable as ``UPOW_ARCHIVE_<FIELD>``."""

    dir: str = ""                   # archive root directory; '' disables
                                    # the whole tier (no reader attach,
                                    # no compactor, /archive/* serve 404)
    segment_blocks: int = 256       # fixed height range per segment;
                                    # a pure function of chain content,
                                    # so every node on the same chain
                                    # with the same setting produces
                                    # byte-identical segments
    safety_window: int = 64         # blocks below the snapshot anchor
                                    # kept hot regardless (must exceed
                                    # any plausible reorg depth; pair
                                    # with node.sync_reorg_window)
    reader_cache_segments: int = 4  # parsed segments kept in memory for
                                    # fallthrough reads (LRU)
    max_segment_bytes: int = 256 << 20  # fetch-side ceiling on what a
    max_segments: int = 1 << 12         # peer manifest may declare
                                        # (anti-DoS, mirrors snapshot
                                        # restore caps)


@dataclass
class NodeConfig:
    host: str = "0.0.0.0"
    port: int = 3006                # reference run_node.py port
    db_backend: str = "sqlite"      # sqlite | postgres
    db_path: str = "upow_tpu.db"    # sqlite file ('' -> in-memory)
    pg_dsn: str = ""                # postgres DSN (db_backend=postgres);
                                    # reference ecosystem interop — point
                                    # at an existing uPow database
                                    # (db_setup.sh / schema.sql)
    seed_url: str = DEFAULT_SEED_URL
    peers_file: str = "nodes.json"
    ip_config_file: str = "ip_config.json"
    self_url: str = ""              # discovered from first request if empty
    trust_proxy_headers: bool = False  # honour X-Forwarded-For/X-Real-IP
    max_peers: int = 100            # nodes_manager.py:26
    active_within: int = 7 * 86400  # peer considered active (nodes_manager.py:24)
    prune_after: int = 90 * 86400   # forget peers silent this long (:25)
    propagate_sample: int = 10      # sample size per class (:144-149)
    response_cap: int = 20 * 1024 * 1024  # streaming response cap (:79-86)
    http_timeout: float = 30.0      # outbound RPC session total timeout
                                    # (both session-creation sites: the
                                    # node's shared pool and the lazy
                                    # NodeInterface fallback)
    sync_reorg_window: int = 500    # main.py:167-185
    sync_page: int = 1000           # block download page (main.py:188-192)
    sync_fetch_interval: float = 1.7  # min seconds between get_blocks
                                    # fetches — the peer's limit is
                                    # 40/min (one per 1.5 s); 1.7 s keeps
                                    # headroom for clock jitter and the
                                    # limiter's window alignment even
                                    # with the pipelined next-page
                                    # prefetch
    mempool_clean_interval: int = 600  # main.py:678-683
    rate_limits_enabled: bool = True   # slowapi parity (main.py:55)


@dataclass
class WsConfig:
    """WebSocket push sidecar limits (websocket/socket_config.py:6-43)."""

    enabled: bool = True
    max_connections: int = 1000
    max_per_user: int = 5
    max_message_bytes: int = 64 * 1024
    rate_limit_per_minute: int = 60
    heartbeat_interval: float = 30.0
    connection_expiry: float = 300.0
    cleanup_interval: float = 60.0  # idle-expiry sweep period
    send_queue_max: int = 256       # bounded per-subscriber send queue;
                                    # overflow sheds that subscriber's
                                    # oldest pending message
                                    # (drop-slowest) and counts it as
                                    # upow_ws_dropped_messages; 0 =
                                    # unbounded (never shed)
    channels: tuple = ("block", "transaction")


@dataclass
class MinerConfig:
    address: str = ""
    node_url: str = DEFAULT_SEED_URL
    workers: int = 1                # device shards, not processes
    ttl: float = 90.0               # per-template budget (miner.py:96-98)
    refresh: float = 100.0          # outer watchdog (miner.py:149-156)


@dataclass
class LogConfig:
    path: str = "logs/app.log"
    level: str = "INFO"
    max_bytes: int = 5 * 1024 * 1024   # my_logger.py rotation size
    backups: int = 100
    console: bool = True
    json_format: bool = False       # JSONL records carrying trace_id


@dataclass
class TelemetryConfig:
    """Observability knobs (upow_tpu/telemetry/) — operational only,
    never consensus.  All overridable as ``UPOW_TELEMETRY_<FIELD>``."""

    trace_requests: bool = True     # root span per inbound HTTP request
    trace_recent: int = 32          # completed traces kept, recency ring
    trace_slowest: int = 16         # completed traces kept, slowest top-N
    max_trace_spans: int = 512      # span budget per trace tree
    events_buffer: int = 256        # /debug/events ring size
    max_metric_names: int = 1024    # cardinality cap per registry kind
    debug_endpoints: bool = True    # serve /debug/traces, /debug/events
    instance_scope: bool = False    # per-node registries (swarm fleets);
                                    # default keeps the process globals


@dataclass
class WatchtowerConfig:
    """Streaming alerting engine (upow_tpu/watchtower/) — operational
    only, never consensus.  Overridable as ``UPOW_WATCHTOWER_<FIELD>``.

    Defaults describe the standing rule pack (docs/ALERTING.md):
    verify-throughput collapse, mempool depth spike, sync lag, breaker
    flip storm, ws drop rate, device arm flaps, stuck block height,
    and per-route SLO burn rates.  Thresholds are deliberately
    conservative — the clean seeded geo-soak must fire zero alerts."""

    enabled: bool = False           # run the evaluation task on this node
    interval: float = 5.0           # evaluation cadence, seconds
    # SLO burn-rate (burnrate.py): canonical 5m/1h + 30m/6h pairs,
    # compressible for scenarios via window_scale.
    slo_target: float = 0.999
    fast_burn: float = 14.4
    slow_burn: float = 6.0
    window_scale: float = 1.0
    # for-durations: fast rules page quickly, slow rules must sustain.
    for_fast: float = 15.0
    for_slow: float = 60.0
    # rule thresholds
    verify_min_rate: float = 1.0    # submissions/s EWMA floor before the
                                    # collapse rule may judge a drop
    verify_z: float = 6.0           # z-score magnitude for rate anomalies
    mempool_spike_ratio: float = 8.0
    mempool_spike_floor: float = 1000.0
    sync_lag_limit: float = 600.0   # seconds behind tip timestamp
    breaker_storm_window: float = 60.0
    breaker_storm_opens: int = 6    # breaker open transitions in window
    ws_drop_limit: float = 50.0     # dropped ws messages per second
    arm_flap_window: float = 600.0
    arm_flaps: int = 3              # degrade/arm-failure events in window
    stuck_height_deadline: float = 300.0
    history: int = 64               # firing/resolved transition ring
    bench_events: str = ""          # append alert_fired JSONL records to
                                    # this path (bench harnesses point it
                                    # at .bench_events.jsonl)


@dataclass
class ProfilingConfig:
    """Opt-in performance capture (upow_tpu/profiling/) — all off by
    default; overridable as ``UPOW_PROFILE_<FIELD>``."""

    enabled: bool = False           # serve /debug/profile (also requires
                                    # telemetry.debug_endpoints)
    trace_dir: str = "logs/jax_traces"  # xprof capture output directory
    max_capture_seconds: float = 120.0  # auto-stop: a capture left
                                    # running past this is closed on the
                                    # next /debug/profile touch
    cost_analysis: bool = False     # record compiled.cost_analysis()
                                    # FLOPs/bytes next to the
                                    # compile-cache counters


@dataclass
class Config:
    device: DeviceConfig = field(default_factory=DeviceConfig)
    device_runtime: DeviceRuntimeConfig = field(
        default_factory=DeviceRuntimeConfig)
    node: NodeConfig = field(default_factory=NodeConfig)
    ws: WsConfig = field(default_factory=WsConfig)
    miner: MinerConfig = field(default_factory=MinerConfig)
    log: LogConfig = field(default_factory=LogConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    snapshot: SnapshotConfig = field(default_factory=SnapshotConfig)
    archive: ArchiveConfig = field(default_factory=ArchiveConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    watchtower: WatchtowerConfig = field(default_factory=WatchtowerConfig)
    profile: ProfilingConfig = field(default_factory=ProfilingConfig)

    @classmethod
    def load(cls, path: Optional[str] = None, **overrides) -> "Config":
        """File -> env -> kwargs, later wins.

        Env vars: ``UPOW_<SECTION>_<FIELD>`` (e.g. ``UPOW_NODE_PORT=3007``,
        ``UPOW_DEVICE_DEVICE=tpu``).  ``overrides`` are dotted
        (``node__port=3007``).
        """
        cfg = cls()
        if path and os.path.exists(path):
            # RC001: config is a one-time startup read, before the
            # event loop serves any traffic
            with open(path) as f:  # upowlint: disable=RC001
                cfg = _merge_dict(cfg, json.load(f))
        cfg = _merge_env(cfg)
        for key, value in overrides.items():
            section, _, fname = key.partition("__")
            sub = getattr(cfg, section)
            if not hasattr(sub, fname):
                raise KeyError(f"unknown config field {key}")
            setattr(sub, fname, value)
        return cfg

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _merge_dict(cfg: Config, data: dict) -> Config:
    for section, values in data.items():
        if not hasattr(cfg, section):
            raise KeyError(f"unknown config section {section}")
        sub = getattr(cfg, section)
        for fname, value in values.items():
            if not hasattr(sub, fname):
                raise KeyError(f"unknown config field {section}.{fname}")
            setattr(sub, fname, value)
    return cfg


def _merge_env(cfg: Config) -> Config:
    for section in ("device", "device_runtime", "node", "ws", "miner",
                    "log", "resilience", "mempool", "cache", "snapshot",
                    "archive", "telemetry", "watchtower", "profile"):
        _apply_env_fields(getattr(cfg, section), section)
    return cfg


def _apply_env_fields(sub, section: str) -> None:
    """Apply ``UPOW_<SECTION>_<FIELD>`` env overrides onto one config
    dataclass instance (shared by _merge_env and the sections that must
    self-load before a Config exists, e.g. DeviceRuntimeConfig)."""
    for f in dataclasses.fields(sub):
        env = f"UPOW_{section.upper()}_{f.name.upper()}"
        if env in os.environ:
            raw = os.environ[env]
            if f.type in ("int", int):
                value = int(raw)
            elif f.type in ("float", float):
                value = float(raw)
            elif f.type in ("bool", bool):
                value = raw.lower() in ("1", "true", "yes")
            else:
                value = raw
            setattr(sub, f.name, value)
