"""Telemetry subsystem: spans → trace trees, metrics, events, /metrics.

Grown out of the original single-module ``trace.py`` (which remains as
a thin compatibility shim re-exporting this package).  Layers:

* :mod:`.metrics` — flat process-wide aggregates (span stats, counters,
  fixed-bucket histograms) with a cardinality cap.
* :mod:`.tracing` — contextvar-based request-scoped trace trees with a
  bounded ring buffer (recent + slowest) and cross-node trace-ID
  propagation via the ``X-Upow-Trace`` header.
* :mod:`.events` — structured event ring buffer (reorgs, breaker
  trips, degrade transitions, fault injections) for ``/debug/events``.
* :mod:`.device` — TPU/kernel telemetry: batch occupancy, dispatch
  latency, jit compile-cache hit/miss, device memory gauges.
* :mod:`.exposition` — Prometheus 0.0.4 text rendering + the format
  validator used by tests and ``make metrics-check``.

The module-level functions below are the stable API every other
subsystem imports (usually via the ``trace`` shim)."""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

from ..logger import get_logger
from . import device, events, exposition, metrics, scope, slo, tracing
from .events import emit as event
from .metrics import (counters, ensure_counter, ensure_histogram,  # noqa: F401
                      histograms, inc, observe, stats)
from .scope import TelemetryScope  # noqa: F401
from .tracing import (add_span, attached, child_span, current_span,  # noqa: F401
                      current_trace_id, finish_child, new_trace_id,
                      open_traces, request_trace, span, traces,
                      valid_trace_id)

log = get_logger("telemetry")

#: HTTP header carrying the trace ID across gossip hops.
TRACE_HEADER = "X-Upow-Trace"

__all__ = [
    "TRACE_HEADER", "TelemetryScope", "add_span", "attached",
    "child_span", "configure", "counters", "current_span",
    "current_trace_id", "device", "ensure_counter", "ensure_histogram",
    "event", "events", "exposition", "finish_child", "histograms",
    "inc", "metrics", "new_trace_id", "observe", "open_traces",
    "profile", "request_trace", "reset", "scope", "slo", "span",
    "stats", "traces", "tracing", "valid_trace_id",
]


def configure(cfg=None) -> None:
    """Apply a TelemetryConfig (config.py) and pre-register the metric
    families the acceptance criteria require to exist from scrape #1
    (occupancy / compile-cache series for the batch kernels)."""
    if cfg is not None:
        metrics.set_max_names(cfg.max_metric_names)
        tracing.configure(recent=cfg.trace_recent,
                          slowest=cfg.trace_slowest,
                          max_spans=cfg.max_trace_spans)
        events.configure(cfg.events_buffer)
    # incremental event-cursor loss counter (events.since): exported
    # all-zero from scrape #1 so metrics-check can pin the name
    metrics.ensure_counter(events.ROTATED_UNSEEN)
    device.preregister("p256_verify")
    device.preregister("sha256_txid")
    device.preregister_runtime()
    device.preregister_index()
    device.preregister_mine()
    for stage in ("block_decode", "block_sig_wait", "accept_probe"):
        device.preregister_stage(stage)
    # shared sig dispatch front (verify/dispatch.py) — deferred import:
    # telemetry must stay importable without the verify package
    try:
        from ..verify.dispatch import preregister as _front_preregister

        _front_preregister()
    except Exception as e:  # pragma: no cover - import-cycle guard
        log.debug("dispatch front preregister skipped: %s", e)


def reset() -> None:
    """Clear every registry and buffer (tests)."""
    metrics.reset()
    tracing.reset()
    events.reset()
    device.reset()


@contextmanager
def profile(trace_dir: Optional[str] = None):
    """Capture a JAX profiler trace into ``trace_dir`` (xprof format).

    No-op when trace_dir is falsy or the profiler is unavailable.  Only
    profiler SETUP/TEARDOWN failures are swallowed — exceptions raised
    by the caller's body must propagate untouched (a yield inside a
    try/except would eat them and then crash contextlib)."""
    if not trace_dir:
        yield
        return
    ctx = None
    try:
        import jax

        ctx = jax.profiler.trace(trace_dir)
        ctx.__enter__()
    except Exception as e:  # profiling must never break the caller
        log.warning("jax profiler unavailable: %s", e)
        ctx = None
    try:
        yield
    finally:
        if ctx is not None:
            try:
                ctx.__exit__(None, None, None)
            except Exception as e:
                log.warning("jax profiler teardown failed: %s", e)
