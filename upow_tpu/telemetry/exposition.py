"""Prometheus text exposition (format 0.0.4): render and validate.

The render side replaces the string-building previously inlined in
``node/app.py h_metrics``: every name passes :func:`sanitize` (the
dotted registry names — ``resilience.propagate_timeouts`` — are
illegal as-is), histograms are accumulated into cumulative
``le``-labelled buckets, and the correct content type is exported as
:data:`CONTENT_TYPE`.

The validate side is a mini-parser of the same format used by the
exposition test and ``make metrics-check``: it checks every sample
name against the legal-name grammar, ``le`` label ordering, cumulative
bucket monotonicity, and the ``_count`` == +Inf-bucket invariant for
every exported histogram."""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_BAD_CHAR_RE = re.compile(r"[^a-zA-Z0-9_:]")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*|\S+)"      # name (validated separately)
    r"(?:\{([^}]*)\})?"                       # optional label set
    r"\s+(\S+)"                               # value
    r"(?:\s+\S+)?$")                          # optional timestamp
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
# OpenMetrics-style exemplar suffix: ` # {label="..."} value [ts]`.
# Strictly an extension of the 0.0.4 grammar — rendered only on bucket
# lines that carry an attached exemplar; validate() accepts and checks
# it (label grammar, float value, value within the bucket's le bound).
_EXEMPLAR_RE = re.compile(
    r"^\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\"(?:,\s*)?)*)\}"
    r"\s+(\S+)(?:\s+\S+)?$")


def sanitize(name: str) -> str:
    """Map an internal dotted metric name onto the legal grammar."""
    safe = _BAD_CHAR_RE.sub("_", name)
    if not safe or not _NAME_RE.match(safe):
        safe = "_" + safe
    return safe


def _escape_label(value: str) -> str:
    """Escape a label *value* (backslash, quote, newline).  Label values
    take the full escaped grammar — running them through :func:`sanitize`
    would corrupt digit-leading trace ids with a ``_`` prefix."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    if isinstance(value, float) and not value.is_integer():
        return repr(value)
    return str(int(value))


class Exposition:
    """Line builder for one /metrics response."""

    def __init__(self, prefix: str = "upow"):
        self.prefix = prefix
        self.lines: List[str] = []

    def _name(self, name: str) -> str:
        return sanitize(f"{self.prefix}_{name}")

    def gauge(self, name: str, value, help_text: str = "") -> None:
        full = self._name(name)
        if help_text:
            self.lines.append(f"# HELP {full} {help_text}")
        self.lines.append(f"# TYPE {full} gauge")
        self.lines.append(f"{full} {_fmt(value)}")

    def counter(self, name: str, value, help_text: str = "") -> None:
        full = self._name(name)
        if not full.endswith("_total"):
            full += "_total"
        if help_text:
            self.lines.append(f"# HELP {full} {help_text}")
        self.lines.append(f"# TYPE {full} counter")
        self.lines.append(f"{full} {_fmt(value)}")

    def histogram(self, name: str, bounds: Sequence[float],
                  counts: Sequence[int], total: float, summed: float,
                  help_text: str = "",
                  exemplars: Optional[Dict[int, dict]] = None) -> None:
        """``counts`` per-bucket with +Inf overflow last (registry shape).

        ``exemplars`` maps bucket index (0..len(bounds), +Inf last) to
        ``{"trace_id", "value"}``; a bucket with one gets the
        OpenMetrics exemplar suffix ``# {trace_id="..."} value``."""
        full = self._name(name)
        if help_text:
            self.lines.append(f"# HELP {full} {help_text}")
        self.lines.append(f"# TYPE {full} histogram")
        ex = exemplars or {}

        def _suffix(idx: int) -> str:
            e = ex.get(idx)
            if not e or not e.get("trace_id"):
                return ""
            return (f' # {{trace_id="{_escape_label(str(e["trace_id"]))}"}}'
                    f' {e["value"]:.6f}')

        cum = 0
        for i, (bound, count) in enumerate(zip(bounds, counts)):
            cum += count
            self.lines.append(
                f'{full}_bucket{{le="{bound}"}} {cum}{_suffix(i)}')
        cum += counts[-1]
        self.lines.append(
            f'{full}_bucket{{le="+Inf"}} {cum}{_suffix(len(bounds))}')
        self.lines.append(f"{full}_sum {summed:.6f}")
        self.lines.append(f"{full}_count {int(total)}")

    def span_stats(self, name: str, agg: dict) -> None:
        full = sanitize(f"{self.prefix}_span_{name}")
        self.lines.append(f"# TYPE {full}_count counter")
        self.lines.append(f"{full}_count {agg['count']}")
        self.lines.append(f"# TYPE {full}_seconds_total counter")
        self.lines.append(f"{full}_seconds_total {agg['total_s']:.6f}")
        self.lines.append(f"# TYPE {full}_seconds_max gauge")
        self.lines.append(f"{full}_seconds_max {agg['max_s']:.6f}")

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


# ---------------------------------------------------------- validator ---

def _parse_le(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    return float(raw)


def _split_exemplar(line: str) -> Tuple[str, Optional[str]]:
    """Split a sample line into (sample, exemplar_raw or None).

    The ``' # {'`` separator only counts *outside* the label set: a
    quoted label value may legitimately contain it (only backslash,
    quote and newline are escaped), so scanning starts after the label
    set's closing ``}`` — found by walking the braces quote- and
    escape-aware, not by ``find``."""
    space = line.find(" ")
    brace = line.find("{")
    start = 0
    if brace != -1 and (space == -1 or brace < space):
        # a label set opens directly after the name (no space before
        # it); any later '{' belongs to an exemplar or a label value
        i, in_str, esc = brace + 1, False, False
        while i < len(line):
            ch = line[i]
            if in_str:
                if esc:
                    esc = False
                elif ch == "\\":
                    esc = True
                elif ch == '"':
                    in_str = False
            elif ch == '"':
                in_str = True
            elif ch == "}":
                start = i + 1
                break
            i += 1
    cut = line.find(" # {", start)
    if cut == -1:
        return line, None
    return line[:cut], line[cut + 3:]


def validate(text: str) -> List[str]:
    """Return a list of format violations ([] == clean)."""
    errors: List[str] = []
    # histogram name -> [(le, cumulative_count)]; plain name -> value
    buckets: Dict[str, List[Tuple[float, float]]] = {}
    values: Dict[str, float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                errors.append(f"line {lineno}: malformed comment {line!r}")
            elif not _NAME_RE.match(parts[2]):
                errors.append(
                    f"line {lineno}: illegal metric name {parts[2]!r}")
            continue
        line, exemplar_raw = _split_exemplar(line)
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name, labels_raw, value_raw = m.group(1), m.group(2), m.group(3)
        exemplar_value = None
        if exemplar_raw is not None:
            em = _EXEMPLAR_RE.match(exemplar_raw)
            if em is None:
                errors.append(
                    f"line {lineno}: malformed exemplar {exemplar_raw!r}")
            elif not (name.endswith("_bucket") or name.endswith("_total")):
                errors.append(
                    f"line {lineno}: exemplar on non-bucket/counter "
                    f"sample {name!r}")
            else:
                try:
                    exemplar_value = float(em.group(2))
                except ValueError:
                    errors.append(
                        f"line {lineno}: bad exemplar value "
                        f"{em.group(2)!r}")
        if not _NAME_RE.match(name):
            errors.append(f"line {lineno}: illegal metric name {name!r}")
            continue
        try:
            value = float(value_raw)
        except ValueError:
            errors.append(f"line {lineno}: bad value {value_raw!r}")
            continue
        labels = dict(_LABEL_RE.findall(labels_raw)) if labels_raw else {}
        if name.endswith("_bucket") and "le" in labels:
            try:
                le = _parse_le(labels["le"])
            except ValueError:
                errors.append(
                    f"line {lineno}: bad le value {labels['le']!r}")
                continue
            if (exemplar_value is not None and le != math.inf
                    and exemplar_value > le):
                errors.append(
                    f"line {lineno}: exemplar value {exemplar_value} "
                    f"exceeds bucket le={labels['le']}")
            buckets.setdefault(name[:-len("_bucket")], []).append(
                (le, value))
        else:
            values[name] = value
    for hist, series in buckets.items():
        les = [le for le, _ in series]
        if les != sorted(les):
            errors.append(f"{hist}: le labels not in ascending order")
        if len(set(les)) != len(les):
            errors.append(f"{hist}: duplicate le label")
        if not les or les[-1] != math.inf:
            errors.append(f"{hist}: missing le=\"+Inf\" bucket")
        counts = [c for _, c in series]
        if any(b > a for b, a in zip(counts, counts[1:])):
            errors.append(f"{hist}: cumulative bucket counts not monotone")
        count_name = hist + "_count"
        if count_name not in values:
            errors.append(f"{hist}: missing {count_name}")
        elif les and les[-1] == math.inf and counts[-1] != values[count_name]:
            errors.append(
                f"{hist}: _count {values[count_name]} != +Inf bucket "
                f"{counts[-1]}")
        if hist + "_sum" not in values:
            errors.append(f"{hist}: missing {hist}_sum")
    return errors
