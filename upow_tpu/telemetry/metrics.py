"""Flat process-wide metric registries: spans, counters, histograms.

This is the aggregation layer the old ``trace.py`` module provided,
extracted so the tracing layer (trace trees) and the exposition layer
(/metrics rendering) can grow around it without every consumer
changing its import.  All registries are name -> aggregate dicts and
are safe to update from executor threads (a single lock guards every
mutation; reads snapshot under the same lock).

Cardinality is bounded: at most ``max_names`` *distinct* names may
exist per registry kind (span / counter / histogram).  A name beyond
the cap is dropped with one warning per kind — a bug that derives
metric names from request data cannot grow the registries without
limit under heavy traffic.  ``telemetry.dropped_names`` counts the
drops (that counter itself is exempt from the cap).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from ..logger import get_logger

log = get_logger("telemetry")

# Default buckets suit sub-second latencies; size-like metrics (batch
# sizes, queue depths) pass their own buckets on first observe.
_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: counter tracking names dropped by the cardinality cap; exempt from
#: the cap itself so the signal survives the overflow it reports.
DROPPED = "telemetry.dropped_names"

_lock = threading.Lock()
_stats: Dict[str, dict] = {}
_counters: Dict[str, int] = {}
_hists: Dict[str, dict] = {}
_max_names = 1024
_warned: set = set()


def set_max_names(n: int) -> None:
    global _max_names
    _max_names = max(1, int(n))


def _admit(registry: dict, name: str, kind: str) -> bool:
    """True if ``name`` may create a new entry in ``registry``."""
    if name in registry or name == DROPPED:
        return True
    if len(registry) < _max_names:
        return True
    _counters[DROPPED] = _counters.get(DROPPED, 0) + 1
    if kind not in _warned:
        _warned.add(kind)
        log.warning(
            "metric cardinality cap (%d) reached for %s registry; "
            "dropping new name %r (and any further new names)",
            _max_names, kind, name)
    return False


# ------------------------------------------------------------- spans ---

def record_span(name: str, seconds: float) -> None:
    with _lock:
        if not _admit(_stats, name, "span"):
            return
        agg = _stats.setdefault(name, {"count": 0, "total_s": 0.0,
                                       "max_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += seconds
        agg["max_s"] = max(agg["max_s"], seconds)


def stats() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _stats.items()}


# ---------------------------------------------------------- counters ---

def inc(name: str, n: int = 1) -> None:
    with _lock:
        if not _admit(_counters, name, "counter"):
            return
        _counters[name] = _counters.get(name, 0) + n


def counters() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


# -------------------------------------------------------- histograms ---

def observe(name: str, value: float,
            buckets: Optional[Sequence[float]] = None) -> None:
    """Record ``value`` into histogram ``name``.

    Bucket bounds are fixed by the first observe (or an earlier
    ``ensure_histogram``); later ``buckets=`` arguments are ignored.
    ``counts`` is per-bucket with the +Inf overflow LAST — not
    cumulative; the exposition layer accumulates into Prometheus
    ``le`` semantics.
    """
    with _lock:
        h = _hists.get(name)
        if h is None:
            if not _admit(_hists, name, "histogram"):
                return
            h = _new_hist(name, buckets)
        h["count"] += 1
        h["sum"] += value
        for i, bound in enumerate(h["bounds"]):
            if value <= bound:
                h["counts"][i] += 1
                break
        else:
            h["counts"][-1] += 1  # +Inf overflow bucket


def ensure_histogram(name: str,
                     buckets: Optional[Sequence[float]] = None) -> None:
    """Register an empty histogram so it is exported before first use."""
    with _lock:
        if name not in _hists and _admit(_hists, name, "histogram"):
            _new_hist(name, buckets)


def ensure_counter(name: str) -> None:
    with _lock:
        if name not in _counters and _admit(_counters, name, "counter"):
            _counters[name] = 0


def _new_hist(name: str, buckets: Optional[Sequence[float]]) -> dict:
    bounds = tuple(sorted(buckets)) if buckets else _DEFAULT_BUCKETS
    h = {"bounds": bounds, "counts": [0] * (len(bounds) + 1),
         "count": 0, "sum": 0.0}
    _hists[name] = h
    return h


def histograms() -> Dict[str, dict]:
    """Snapshot: {name: {bounds, counts (per-bucket, +Inf last), sum,
    count}} — the shape the original trace.py exported."""
    with _lock:
        return {k: {"bounds": v["bounds"], "counts": list(v["counts"]),
                    "count": v["count"], "sum": v["sum"]}
                for k, v in _hists.items()}


# ------------------------------------------------------------- reset ---

def reset() -> None:
    """Clear every registry (tests)."""
    with _lock:
        _stats.clear()
        _counters.clear()
        _hists.clear()
        _warned.clear()
