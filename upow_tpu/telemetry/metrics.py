"""Flat metric registries: spans, counters, histograms.

This is the aggregation layer the old ``trace.py`` module provided,
extracted so the tracing layer (trace trees) and the exposition layer
(/metrics rendering) can grow around it without every consumer
changing its import.  All registries are name -> aggregate dicts and
are safe to update from executor threads (a single lock guards every
mutation; reads snapshot under the same lock).

Registries live in a ``MetricsRegistry`` instance.  The module-level
functions keep the historical flat API but resolve the target
registry per call: the one bound to the current telemetry scope
(``scope.current()`` — one registry per swarm node) or the process
global when no scope is active (the single-node path, unchanged).

Cardinality is bounded: at most ``max_names`` *distinct* names may
exist per registry kind (span / counter / histogram).  A name beyond
the cap is dropped with one warning per kind — a bug that derives
metric names from request data cannot grow the registries without
limit under heavy traffic.  ``telemetry.dropped_names`` counts the
drops (that counter itself is exempt from the cap).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from ..logger import get_logger
from . import scope

log = get_logger("telemetry")

# Default buckets suit sub-second latencies; size-like metrics (batch
# sizes, queue depths) pass their own buckets on first observe.
_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: counter tracking names dropped by the cardinality cap; exempt from
#: the cap itself so the signal survives the overflow it reports.
DROPPED = "telemetry.dropped_names"


class MetricsRegistry:
    """One instance's span/counter/histogram aggregates."""

    def __init__(self, max_names: int = 1024):
        self._lock = threading.Lock()
        self._stats: Dict[str, dict] = {}
        self._counters: Dict[str, int] = {}
        self._hists: Dict[str, dict] = {}
        self._max_names = max(1, int(max_names))
        self._warned: set = set()

    def set_max_names(self, n: int) -> None:
        self._max_names = max(1, int(n))

    def _admit(self, registry: dict, name: str, kind: str) -> bool:
        """True if ``name`` may create a new entry in ``registry``."""
        if name in registry or name == DROPPED:
            return True
        if len(registry) < self._max_names:
            return True
        self._counters[DROPPED] = self._counters.get(DROPPED, 0) + 1
        if kind not in self._warned:
            self._warned.add(kind)
            log.warning(
                "metric cardinality cap (%d) reached for %s registry; "
                "dropping new name %r (and any further new names)",
                self._max_names, kind, name)
        return False

    # --------------------------------------------------------- spans ---

    def record_span(self, name: str, seconds: float) -> None:
        with self._lock:
            if not self._admit(self._stats, name, "span"):
                return
            agg = self._stats.setdefault(
                name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += seconds
            agg["max_s"] = max(agg["max_s"], seconds)

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}

    # ------------------------------------------------------ counters ---

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            if not self._admit(self._counters, name, "counter"):
                return
            self._counters[name] = self._counters.get(name, 0) + n

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # ---------------------------------------------------- histograms ---

    def observe(self, name: str, value: float,
                buckets: Optional[Sequence[float]] = None) -> None:
        """Record ``value`` into histogram ``name``.

        Bucket bounds are fixed by the first observe (or an earlier
        ``ensure_histogram``); later ``buckets=`` arguments are
        ignored.  ``counts`` is per-bucket with the +Inf overflow LAST
        — not cumulative; the exposition layer accumulates into
        Prometheus ``le`` semantics.
        """
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                if not self._admit(self._hists, name, "histogram"):
                    return
                h = self._new_hist(name, buckets)
            h["count"] += 1
            h["sum"] += value
            for i, bound in enumerate(h["bounds"]):
                if value <= bound:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1  # +Inf overflow bucket

    def observe_exemplar(self, name: str, value: float,
                         trace_id: str) -> None:
        """Attach an exemplar trace id to the bucket ``value`` lands in.

        Exemplars link a histogram bucket to a concrete trace
        (OpenMetrics ``# {trace_id="..."} value``).  Storage is bounded
        by construction — at most one exemplar per bucket, newest wins
        with a preference for slower samples within the bucket so the
        worst representative survives.  No-op for unknown histograms
        (exemplars never create series).
        """
        if not trace_id:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return
            for i, bound in enumerate(h["bounds"]):
                if value <= bound:
                    idx = i
                    break
            else:
                idx = len(h["bounds"])  # +Inf overflow bucket
            ex = h.setdefault("exemplars", {})
            prev = ex.get(idx)
            if prev is None or value >= prev["value"]:
                ex[idx] = {"trace_id": str(trace_id), "value": float(value)}

    def ensure_histogram(self, name: str,
                         buckets: Optional[Sequence[float]] = None) -> None:
        """Register an empty histogram so it exports before first use."""
        with self._lock:
            if name not in self._hists and \
                    self._admit(self._hists, name, "histogram"):
                self._new_hist(name, buckets)

    def ensure_counter(self, name: str) -> None:
        with self._lock:
            if name not in self._counters and \
                    self._admit(self._counters, name, "counter"):
                self._counters[name] = 0

    def _new_hist(self, name: str,
                  buckets: Optional[Sequence[float]]) -> dict:
        bounds = tuple(sorted(buckets)) if buckets else _DEFAULT_BUCKETS
        h = {"bounds": bounds, "counts": [0] * (len(bounds) + 1),
             "count": 0, "sum": 0.0}
        self._hists[name] = h
        return h

    def histograms(self) -> Dict[str, dict]:
        """Snapshot: {name: {bounds, counts (per-bucket, +Inf last),
        sum, count[, exemplars]}} — the shape the original trace.py
        exported, plus per-bucket exemplars when any were attached."""
        with self._lock:
            out = {}
            for k, v in self._hists.items():
                row = {"bounds": v["bounds"], "counts": list(v["counts"]),
                       "count": v["count"], "sum": v["sum"]}
                if v.get("exemplars"):
                    row["exemplars"] = {i: dict(e)
                                        for i, e in v["exemplars"].items()}
                out[k] = row
            return out

    # --------------------------------------------------------- reset ---

    def reset(self) -> None:
        """Clear every registry (tests)."""
        with self._lock:
            self._stats.clear()
            self._counters.clear()
            self._hists.clear()
            self._warned.clear()


_global = MetricsRegistry()


def _reg() -> MetricsRegistry:
    sc = scope.current()
    return sc.metrics if sc is not None else _global


def set_max_names(n: int) -> None:
    _reg().set_max_names(n)


def record_span(name: str, seconds: float) -> None:
    _reg().record_span(name, seconds)


def stats() -> Dict[str, dict]:
    return _reg().stats()


def inc(name: str, n: int = 1) -> None:
    _reg().inc(name, n)


def counters() -> Dict[str, int]:
    return _reg().counters()


def observe(name: str, value: float,
            buckets: Optional[Sequence[float]] = None) -> None:
    _reg().observe(name, value, buckets)


def observe_exemplar(name: str, value: float, trace_id: str) -> None:
    _reg().observe_exemplar(name, value, trace_id)


def ensure_histogram(name: str,
                     buckets: Optional[Sequence[float]] = None) -> None:
    _reg().ensure_histogram(name, buckets)


def ensure_counter(name: str) -> None:
    _reg().ensure_counter(name)


def histograms() -> Dict[str, dict]:
    return _reg().histograms()


def reset() -> None:
    _reg().reset()
