"""TPU/kernel telemetry: dispatch latency, compile hit/miss, occupancy.

The batch kernels (P-256 verify, sha256 txid) pad every batch up to a
block multiple before dispatch; how much of each dispatched batch is
*real* work was invisible until now.  ``record_batch`` feeds, per
kernel:

- ``kernel.<name>.dispatch_seconds``   latency histogram
- ``kernel.<name>.occupancy``          real/padded-lane ratio histogram
- ``kernel.<name>.lanes_real``         counters (padding waste =
  ``kernel.<name>.lanes_padded``       padded - real)
- ``kernel.<name>.compile_cache_hits`` jit in-process cache proxy:
  ``kernel.<name>.compile_cache_misses``  the first dispatch of a
  given compile key (padded shape / static args) compiles, later
  ones reuse the traced program.

Device memory gauges are best-effort: ``memory_stats()`` is populated
on TPU/GPU backends and typically absent on CPU; we never import jax
here — if the caller hasn't, there is nothing to report."""

from __future__ import annotations

import sys
import threading
from typing import Dict, Hashable, Optional, Set

from ..logger import get_logger
from . import metrics

log = get_logger("telemetry")

OCCUPANCY_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
DISPATCH_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)

_lock = threading.Lock()
_seen_keys: Dict[str, Set[Hashable]] = {}
_costs: Dict[str, Dict[str, float]] = {}
_MAX_KEYS_PER_KERNEL = 4096
_MAX_COST_KERNELS = 64
_MAX_COST_KEYS = 16


def preregister(kernel: str) -> None:
    """Create the kernel's metric families so /metrics exports them
    (all-zero) before the first dispatch."""
    metrics.ensure_histogram("kernel.%s.occupancy" % kernel,
                             OCCUPANCY_BUCKETS)
    metrics.ensure_histogram("kernel.%s.dispatch_seconds" % kernel,
                             DISPATCH_BUCKETS)
    for c in ("lanes_real", "lanes_padded",
              "compile_cache_hits", "compile_cache_misses"):
        metrics.ensure_counter("kernel.%s.%s" % (kernel, c))


def record_batch(kernel: str, real: int, padded: int,
                 seconds: Optional[float] = None,
                 compile_key: Optional[Hashable] = None) -> None:
    """Record one batch dispatch. ``real`` lanes of ``padded`` total."""
    padded = max(int(padded), 1)
    real = min(max(int(real), 0), padded)
    metrics.inc("kernel.%s.lanes_real" % kernel, real)
    metrics.inc("kernel.%s.lanes_padded" % kernel, padded)
    metrics.observe("kernel.%s.occupancy" % kernel, real / padded,
                    buckets=OCCUPANCY_BUCKETS)
    if seconds is not None:
        metrics.observe("kernel.%s.dispatch_seconds" % kernel, seconds,
                        buckets=DISPATCH_BUCKETS)
    if compile_key is not None:
        with _lock:
            seen = _seen_keys.setdefault(kernel, set())
            hit = compile_key in seen
            if not hit and len(seen) < _MAX_KEYS_PER_KERNEL:
                seen.add(compile_key)
        metrics.inc("kernel.%s.compile_cache_%s"
                    % (kernel, "hits" if hit else "misses"))


def preregister_stage(stage: str) -> None:
    """Create a pipeline stage's metric families (all-zero) so /metrics
    exports them before the first block flows through the pipeline."""
    metrics.ensure_histogram("pipeline.%s.seconds" % stage,
                             DISPATCH_BUCKETS)
    metrics.ensure_histogram("pipeline.%s.occupancy" % stage,
                             OCCUPANCY_BUCKETS)
    metrics.ensure_counter("pipeline.%s.items" % stage)


def record_stage(stage: str, seconds: float, items: Optional[int] = None,
                 wall: Optional[float] = None) -> None:
    """Record one pipeline stage pass (ISSUE 7: pipelined block verify).

    ``seconds`` is the stage's busy time; ``wall`` (when given) is the
    whole pipeline's wall time for the same pass, making
    ``pipeline.<stage>.occupancy`` the fraction of the pipeline the
    stage kept busy — overlap shows up as stage occupancies summing
    past 1.0, a serialized pipeline as fractions that add to ~1.0.
    """
    metrics.observe("pipeline.%s.seconds" % stage, max(seconds, 0.0),
                    buckets=DISPATCH_BUCKETS)
    if items:
        metrics.inc("pipeline.%s.items" % stage, items)
    if wall is not None and wall > 0:
        metrics.observe("pipeline.%s.occupancy" % stage,
                        min(max(seconds, 0.0) / wall, 1.0),
                        buckets=OCCUPANCY_BUCKETS)


RUNTIME_SOURCES = ("block", "mempool", "mine", "index", "verify",
                   "bench", "other")
RUNTIME_QUEUE_DEPTH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 1024,
                               4096)
RUNTIME_COALESCE_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 64)


def preregister_runtime(sources=RUNTIME_SOURCES) -> None:
    """Create the device-runtime queue families (device/runtime.py) so
    /metrics exports them before the first submission: per-source
    queue-wait histograms and submission counters, the queue-depth and
    submissions-per-dispatch histograms, and a ``device_runtime``
    kernel occupancy series for the shared dispatches."""
    metrics.ensure_histogram("runtime.queue_depth",
                             RUNTIME_QUEUE_DEPTH_BUCKETS)
    metrics.ensure_histogram("runtime.coalesced", RUNTIME_COALESCE_BUCKETS)
    for c in ("submissions", "dispatches", "faults"):
        metrics.ensure_counter("runtime.%s" % c)
    for s in sources:
        metrics.ensure_histogram("runtime.queue_wait.%s" % s,
                                 DISPATCH_BUCKETS)
        metrics.ensure_counter("runtime.source.%s" % s)
    preregister("device_runtime")


def record_runtime_dispatch(n_submissions: int,
                            waits_by_source: Dict[str, float],
                            depth: int, real: int, padded: int,
                            seconds: float) -> None:
    """Record one device-runtime drain: how many submissions shared the
    dispatch, how long each source's items queued, the queue depth seen
    at pop time, and the occupancy of the padded batch."""
    metrics.inc("runtime.dispatches")
    metrics.observe("runtime.coalesced", n_submissions,
                    buckets=RUNTIME_COALESCE_BUCKETS)
    metrics.observe("runtime.queue_depth", max(depth, 1),
                    buckets=RUNTIME_QUEUE_DEPTH_BUCKETS)
    for source, wait in waits_by_source.items():
        metrics.observe("runtime.queue_wait.%s" % source,
                        max(wait, 0.0), buckets=DISPATCH_BUCKETS)
    record_batch("device_runtime", real=real, padded=padded,
                 seconds=seconds)


INDEX_KERNELS = ("utxo_probe", "utxo_apply", "accept_fused")


def preregister_index() -> None:
    """Create the HBM-resident UTXO index families (state/device_index.py)
    so /metrics exports them before the first probe: the probe/apply/
    fused kernel series plus the probe counters — ``shadow_consults``
    is the accept path's zero-host-round-trip acceptance signal (it
    stays 0 on collision-free blocks)."""
    for kernel in INDEX_KERNELS:
        preregister(kernel)
    for c in ("probes", "probe_outpoints", "shadow_consults",
              "ambiguous_probes"):
        metrics.ensure_counter("index.%s" % c)


def record_index_probe(outpoints: int, shadow_consults: int,
                       ambiguous: int = 0) -> None:
    """Record one resident-index probe batch: how many outpoints it
    answered, and how many needed the host shadow map (fingerprint
    ambiguity — the steady-state target is zero)."""
    metrics.inc("index.probes")
    metrics.inc("index.probe_outpoints", max(int(outpoints), 0))
    if shadow_consults:
        metrics.inc("index.shadow_consults", int(shadow_consults))
    if ambiguous:
        metrics.inc("index.ambiguous_probes", int(ambiguous))


HIT_LATENCY_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 90.0)


def preregister_mine() -> None:
    """Create the mesh mining families (mine/mesh_engine.py) so /metrics
    exports them before the first round: the ``mine_mesh`` kernel series
    (occupancy = real nonces vs shard capacity; compile-cache counters
    are the no-recompile-job-swap signal) plus the per-shard range
    occupancy and time-to-hit histograms."""
    preregister("mine_mesh")
    metrics.ensure_histogram("mine.shard_occupancy", OCCUPANCY_BUCKETS)
    metrics.ensure_histogram("mine.hit_latency", HIT_LATENCY_BUCKETS)


def record_mine_round(shard_spans, batch_per_device: int,
                      seconds: Optional[float] = None,
                      compile_key: Optional[Hashable] = None) -> None:
    """Record one mesh search round: ``shard_spans`` is the per-shard
    planned nonce count; capacity per shard is ``batch_per_device``.
    The compile key is (batch, n_shards, nonce_spec) — job fields are
    deliberately absent, so a chain-tip change that recompiles would
    surface as a new key = a ``compile_cache_misses`` increment."""
    spans = [max(int(s), 0) for s in shard_spans]
    cap = max(int(batch_per_device), 1)
    record_batch("mine_mesh", real=sum(spans), padded=cap * len(spans),
                 seconds=seconds, compile_key=compile_key)
    for span in spans:
        metrics.observe("mine.shard_occupancy", min(span / cap, 1.0),
                        buckets=OCCUPANCY_BUCKETS)


def record_mine_hit(latency_seconds: float) -> None:
    """Record time from job load to winning nonce (mine.hit_latency)."""
    metrics.observe("mine.hit_latency", max(float(latency_seconds), 0.0),
                    buckets=HIT_LATENCY_BUCKETS)


def record_cost(kernel: str, analysis: dict) -> None:
    """Store an XLA ``compiled.cost_analysis()`` estimate for ``kernel``
    (``upow_tpu/profiling``): numeric entries only, keys sanitized to
    metric-name charset, bounded per kernel and overall so a pathological
    analysis dict cannot grow /metrics without limit."""
    clean: Dict[str, float] = {}
    for key in sorted(analysis):
        value = analysis[key]
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        clean[key.replace(" ", "_").replace("-", "_")] = float(value)
        if len(clean) >= _MAX_COST_KEYS:
            break
    if not clean:
        return
    with _lock:
        if kernel not in _costs and len(_costs) >= _MAX_COST_KERNELS:
            return
        _costs[kernel] = clean


def cost_estimates() -> Dict[str, Dict[str, float]]:
    """Snapshot of recorded per-compile cost analyses, keyed by kernel."""
    with _lock:
        return {k: dict(v) for k, v in _costs.items()}


def device_memory() -> Dict[str, dict]:
    """Best-effort per-device memory stats; {} when jax isn't loaded
    or the backend doesn't expose memory_stats (CPU)."""
    if "jax" not in sys.modules:
        return {}
    out: Dict[str, dict] = {}
    try:
        import jax
        # HBM stat sampling from already-initialized devices (called
        # post-arm from the telemetry exporter; never first-touch)
        for dev in jax.local_devices():  # upowlint: disable=DR001
            try:
                stats = dev.memory_stats()
            except Exception as e:
                log.debug("memory_stats failed for %s: %s", dev, e)
                stats = None
            if not stats:
                continue
            label = "%s_%d" % (dev.platform, dev.id)
            out[label] = {k: v for k, v in stats.items()
                          if isinstance(v, (int, float))}
    except Exception as e:
        log.debug("device memory stats unavailable: %s", e)
    return out


def reset() -> None:
    with _lock:
        _seen_keys.clear()
        _costs.clear()
