"""``make metrics-check``: boot the node app in-process, scrape
``/metrics``, and run the exposition-format validator.

This is the CI gate for the observability surface: it fails when any
exported name is illegal, any histogram's cumulative buckets regress,
the content type drifts from 0.0.4, a required metric family
disappears, or a /debug endpoint stops returning well-formed JSON.
Runs against an in-memory sqlite chain with networking disabled — no
sockets, no peers, exactly like the test-suite clusters.
"""

from __future__ import annotations

import asyncio
import sys
import tempfile

from . import exposition

#: families the acceptance criteria pin: kernel occupancy + compile
#: cache, chain height, mempool depth (substring match on /metrics)
REQUIRED = (
    "upow_kernel_p256_verify_occupancy_bucket",
    "upow_kernel_sha256_txid_occupancy_bucket",
    "upow_kernel_p256_verify_compile_cache_hits_total",
    "upow_kernel_p256_verify_compile_cache_misses_total",
    "upow_block_height",
    "upow_mempool_transactions",
)


async def _run() -> int:
    from aiohttp.test_utils import TestClient, TestServer

    from ..config import Config
    from ..node.app import Node

    scratch = tempfile.mkdtemp(prefix="upow-metrics-check-")
    cfg = Config.load(
        node__db_path="",                 # in-memory chain
        node__seed_url="",                # no external seed
        node__peers_file=f"{scratch}/nodes.json",
        node__ip_config_file="",
        ws__enabled=False,
        device__sig_backend="host",
        log__console=False, log__path="")
    node = Node(cfg)
    server = TestServer(node.app)
    client = TestClient(server)
    await client.start_server()
    failures = []
    try:
        resp = await client.get("/metrics")
        body = await resp.text()
        ctype = resp.headers.get("Content-Type", "")
        if ctype != exposition.CONTENT_TYPE:
            failures.append(
                f"content type {ctype!r} != {exposition.CONTENT_TYPE!r}")
        failures.extend(exposition.validate(body))
        for name in REQUIRED:
            if name not in body:
                failures.append(f"required metric missing: {name}")
        for path in ("/debug/traces", "/debug/events"):
            dresp = await client.get(path)
            payload = await dresp.json()
            if dresp.status != 200 or not payload.get("ok"):
                failures.append(f"{path} unhealthy: {payload}")
    finally:
        await client.close()
        await node.close()
    if failures:
        for f in failures:
            print(f"metrics-check: FAIL {f}")
        return 1
    print(f"metrics-check: OK ({len(body.splitlines())} exposition lines,"
          f" {len(REQUIRED)} required families present)")
    return 0


def main() -> int:
    return asyncio.run(_run())


if __name__ == "__main__":
    sys.exit(main())
