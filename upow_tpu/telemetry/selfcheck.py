"""``make metrics-check``: boot the node app in-process, scrape
``/metrics``, and run the exposition-format validator — then boot a
3-node swarm, merge its per-node scrapes into the ``upow_fleet_*``
families and validate those too.

This is the CI gate for the observability surface: it fails when any
exported name is illegal, any histogram's cumulative buckets regress,
the content type drifts from 0.0.4, a required metric family
disappears (single-node or fleet), or a /debug endpoint stops
returning well-formed JSON.  Runs against in-memory sqlite chains with
networking disabled — no sockets, no peers, exactly like the
test-suite clusters.
"""

from __future__ import annotations

import asyncio
import sys
import tempfile

from . import exposition

#: families the acceptance criteria pin: kernel occupancy + compile
#: cache, chain height, mempool depth (substring match on /metrics)
REQUIRED = (
    "upow_kernel_p256_verify_occupancy_bucket",
    "upow_kernel_sha256_txid_occupancy_bucket",
    "upow_kernel_p256_verify_compile_cache_hits_total",
    "upow_kernel_p256_verify_compile_cache_misses_total",
    "upow_block_height",
    "upow_mempool_transactions",
    # archive tier families (docs/ARCHIVE.md) — emitted as zeros even
    # when ArchiveConfig.dir is unset, so a bare node still carries them
    "upow_archive_segments",
    "upow_archive_archived_blocks",
    "upow_archive_archived_txs",
    "upow_archive_hot_rows_pruned",
    "upow_archive_fallthrough_reads",
    # watchtower alert families (docs/ALERTING.md) — emitted as zeros
    # even when WatchtowerConfig.enabled is off, so a bare node still
    # carries them and dashboards never see a family appear from nowhere
    "upow_alert_firing",
    "upow_alert_pending",
    "upow_alert_silenced",
    "upow_alert_exemplars_firing",
    "upow_alert_eval_lag_seconds",
    "upow_alert_evaluations_total",
    "upow_alert_fired_total",
    "upow_alert_resolved_total",
    # incremental /debug/events cursor-loss counter (telemetry/events.py)
    "upow_telemetry_events_rotated_unseen_total",
)

#: families the merged fleet rendering must always carry
#: (substring match on the render_fleet output)
REQUIRED_FLEET = (
    "upow_fleet_nodes",
    "upow_fleet_height_spread",
    "upow_fleet_events_total",
    "upow_fleet_traces_total",
    "upow_fleet_block_propagation_p95_ms",
    "upow_fleet_block_propagation_seconds_bucket",
    "upow_fleet_tx_propagation_seconds_bucket",
)


async def _run() -> int:
    from aiohttp.test_utils import TestClient, TestServer

    from ..config import Config
    from ..node.app import Node

    scratch = tempfile.mkdtemp(prefix="upow-metrics-check-")
    cfg = Config.load(
        node__db_path="",                 # in-memory chain
        node__seed_url="",                # no external seed
        node__peers_file=f"{scratch}/nodes.json",
        node__ip_config_file="",
        ws__enabled=False,
        device__sig_backend="host",
        log__console=False, log__path="")
    node = Node(cfg)
    server = TestServer(node.app)
    client = TestClient(server)
    await client.start_server()
    failures = []
    try:
        resp = await client.get("/metrics")
        body = await resp.text()
        ctype = resp.headers.get("Content-Type", "")
        if ctype != exposition.CONTENT_TYPE:
            failures.append(
                f"content type {ctype!r} != {exposition.CONTENT_TYPE!r}")
        failures.extend(exposition.validate(body))
        for name in REQUIRED:
            if name not in body:
                failures.append(f"required metric missing: {name}")
        for path in ("/debug/traces", "/debug/events"):
            dresp = await client.get(path)
            payload = await dresp.json()
            if dresp.status != 200 or not payload.get("ok"):
                failures.append(f"{path} unhealthy: {payload}")
    finally:
        await client.close()
        await node.close()
    if failures:
        for f in failures:
            print(f"metrics-check: FAIL {f}")
        return 1
    print(f"metrics-check: OK ({len(body.splitlines())} exposition lines,"
          f" {len(REQUIRED)} required families present)")
    return 0


async def _run_fleet() -> int:
    """Fleet half of the gate: 3 scoped nodes, one gossiped block, the
    merged ``upow_fleet_*`` rendering through the same validator."""
    from ..fleet import scrape
    from ..swarm.harness import Swarm
    from ..swarm.scenarios import _wallet, deterministic_world

    failures = []
    with deterministic_world(0):
        async def drive():
            swarm = await Swarm(3, seed=0).start()
            try:
                _, addr = _wallet(0, "metrics_check")
                res = await swarm.mine(0, addr)
                if not res.get("ok"):
                    failures.append(f"fleet bootstrap mine failed: {res}")
                await swarm.wait_converged()
                await swarm.settle()
                return await scrape.scrape(swarm)
            finally:
                await swarm.close()

        snapshot = await drive()
    for label, rec in snapshot["nodes"].items():
        if rec["metrics_status"] != 200:
            failures.append(f"{label} /metrics -> {rec['metrics_status']}")
        failures.extend(f"{label}: {v}"
                        for v in exposition.validate(rec["metrics_text"]))
    text = scrape.render_fleet(snapshot)
    failures.extend(f"fleet: {v}" for v in exposition.validate(text))
    for name in REQUIRED_FLEET:
        if name not in text:
            failures.append(f"required fleet metric missing: {name}")
    if failures:
        for f in failures:
            print(f"metrics-check: FAIL {f}")
        return 1
    print(f"metrics-check: OK fleet ({len(snapshot['nodes'])} nodes "
          f"merged, {len(text.splitlines())} exposition lines, "
          f"{len(REQUIRED_FLEET)} required fleet families present)")
    return 0


def main() -> int:
    rc = asyncio.run(_run())
    return rc or asyncio.run(_run_fleet())


if __name__ == "__main__":
    sys.exit(main())
