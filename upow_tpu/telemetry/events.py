"""Structured event ring buffer for the /debug/events surface.

Rare-but-important state changes — reorgs, breaker trips, degrade
transitions, fault injections — are worth keeping verbatim rather
than only as counters: when a node misbehaves, the sequence and the
trace IDs matter.  ``emit()`` stamps each record with wall-clock time
and the current trace ID (None when emitted outside a traced
context, e.g. from an executor thread).

The ring lives in an ``EventRing`` instance; the module functions
resolve the target per call — the ring of the active telemetry scope
(one per swarm node) or the process-global ring when no scope is
bound (single-node path, unchanged)."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

from . import scope, tracing


class EventRing:
    """Bounded oldest-evicting ring of structured event records."""

    def __init__(self, maxlen: int = 256):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(maxlen)))

    def configure(self, maxlen: int = 256) -> None:
        with self._lock:
            self._events = deque(self._events, maxlen=max(1, int(maxlen)))

    def emit(self, kind: str, **fields: Any) -> None:
        rec = {"ts": round(time.time(), 6), "kind": kind,
               "trace_id": tracing.current_trace_id()}
        for k, v in fields.items():
            rec[k] = v if isinstance(v, (str, int, float, bool)) \
                or v is None else str(v)
        with self._lock:
            self._events.append(rec)

    def snapshot(self, limit: Optional[int] = None,
                 kind: Optional[str] = None) -> List[dict]:
        """Events oldest-first; optionally last ``limit`` of one kind."""
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if limit is not None:
            out = out[-max(0, int(limit)):]
        return out

    def reset(self) -> None:
        with self._lock:
            self._events.clear()


_global = EventRing()


def _ring() -> EventRing:
    sc = scope.current()
    return sc.events if sc is not None else _global


def configure(maxlen: int = 256) -> None:
    _ring().configure(maxlen)


def emit(kind: str, **fields: Any) -> None:
    _ring().emit(kind, **fields)


def snapshot(limit: Optional[int] = None,
             kind: Optional[str] = None) -> List[dict]:
    return _ring().snapshot(limit, kind)


def reset() -> None:
    _ring().reset()
