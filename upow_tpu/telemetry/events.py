"""Structured event ring buffer for the /debug/events surface.

Rare-but-important state changes — reorgs, breaker trips, degrade
transitions, fault injections, firing alerts — are worth keeping
verbatim rather than only as counters: when a node misbehaves, the
sequence and the trace IDs matter.  ``emit()`` stamps each record
with wall-clock time, the current trace ID (None when emitted outside
a traced context, e.g. from an executor thread), and a monotonic
per-ring sequence number so consumers (the watchtower engine,
``/debug/events?since=<seq>`` pollers) can read the ring
incrementally: records with ``seq`` beyond the cursor are new, and a
cursor older than the oldest retained record means the gap rotated
away unseen — counted in ``telemetry.events.rotated_unseen``.

The ring lives in an ``EventRing`` instance; the module functions
resolve the target per call — the ring of the active telemetry scope
(one per swarm node) or the process-global ring when no scope is
bound (single-node path, unchanged)."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

from . import scope, tracing

ROTATED_UNSEEN = "telemetry.events.rotated_unseen"


class EventRing:
    """Bounded oldest-evicting ring of structured event records."""

    def __init__(self, maxlen: int = 256):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(maxlen)))
        self._seq = 0               # seq of the newest emitted record

    def configure(self, maxlen: int = 256) -> None:
        with self._lock:
            self._events = deque(self._events, maxlen=max(1, int(maxlen)))

    def emit(self, kind: str, **fields: Any) -> None:
        rec = {"ts": round(time.time(), 6), "kind": kind,
               "trace_id": tracing.current_trace_id()}
        for k, v in fields.items():
            rec[k] = v if isinstance(v, (str, int, float, bool)) \
                or v is None else str(v)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._events.append(rec)

    def snapshot(self, limit: Optional[int] = None,
                 kind: Optional[str] = None) -> List[dict]:
        """Events oldest-first; optionally last ``limit`` of one kind."""
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if limit is not None:
            out = out[-max(0, int(limit)):]
        return out

    def since(self, seq: int, limit: Optional[int] = None,
              kind: Optional[str] = None) -> dict:
        """Incremental read: records with ``seq`` strictly beyond the
        cursor, oldest-first.

        Returns ``{"events", "next_seq", "missed"}`` — pass ``next_seq``
        back as the cursor of the following poll.  ``missed`` counts
        records that rotated out of the ring before this cursor saw
        them (0 when the cursor kept up)."""
        seq = max(0, int(seq))
        with self._lock:
            out = list(self._events)
            last = self._seq
        oldest = last - len(out) + 1 if out else last + 1
        missed = max(0, min(oldest - 1, last) - seq)
        out = [e for e in out if e["seq"] > seq]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if limit is not None:
            out = out[-max(0, int(limit)):]
        return {"events": out, "next_seq": last, "missed": missed}

    def reset(self) -> None:
        """Drop retained records; ``_seq`` stays monotonic.  Zeroing it
        would strand consumers holding a cursor (the watchtower engine,
        ``?since=`` pollers): post-reset events re-use already-consumed
        sequence numbers, so ``since()`` filters them out silently until
        the cursor happens to catch up again."""
        with self._lock:
            self._events.clear()


_global = EventRing()


def _ring() -> EventRing:
    sc = scope.current()
    return sc.events if sc is not None else _global


def configure(maxlen: int = 256) -> None:
    _ring().configure(maxlen)


def emit(kind: str, **fields: Any) -> None:
    _ring().emit(kind, **fields)


def snapshot(limit: Optional[int] = None,
             kind: Optional[str] = None) -> List[dict]:
    return _ring().snapshot(limit, kind)


def since(seq: int, limit: Optional[int] = None,
          kind: Optional[str] = None) -> dict:
    """Incremental scoped read; rotated-away records the cursor never
    saw are counted into the scope's ``telemetry.events.rotated_unseen``
    counter (silent loss becomes a visible metric)."""
    out = _ring().since(seq, limit, kind)
    if out["missed"]:
        from . import metrics
        metrics.inc(ROTATED_UNSEEN, out["missed"])
    return out


def reset() -> None:
    _ring().reset()
