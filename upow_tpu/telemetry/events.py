"""Structured event ring buffer for the /debug/events surface.

Rare-but-important state changes — reorgs, breaker trips, degrade
transitions, fault injections — are worth keeping verbatim rather
than only as counters: when a node misbehaves, the sequence and the
trace IDs matter.  ``emit()`` stamps each record with wall-clock time
and the current trace ID (None when emitted outside a traced
context, e.g. from an executor thread)."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

from . import tracing

_lock = threading.Lock()
_events: deque = deque(maxlen=256)


def configure(maxlen: int = 256) -> None:
    global _events
    with _lock:
        _events = deque(_events, maxlen=max(1, int(maxlen)))


def emit(kind: str, **fields: Any) -> None:
    rec = {"ts": round(time.time(), 6), "kind": kind,
           "trace_id": tracing.current_trace_id()}
    for k, v in fields.items():
        rec[k] = v if isinstance(v, (str, int, float, bool)) or v is None \
            else str(v)
    with _lock:
        _events.append(rec)


def snapshot(limit: Optional[int] = None,
             kind: Optional[str] = None) -> List[dict]:
    """Events oldest-first; optionally the last ``limit`` of one kind."""
    with _lock:
        out = list(_events)
    if kind is not None:
        out = [e for e in out if e["kind"] == kind]
    if limit is not None:
        out = out[-max(0, int(limit)):]
    return out


def reset() -> None:
    with _lock:
        _events.clear()
