"""SLO latency capture: per-endpoint request histograms + quantiles.

The load generator (``upow_tpu/loadgen``) and the node's HTTP
middleware both record request latencies here, into the flat
:mod:`.metrics` registries — so the new series ride the existing
``/metrics`` exposition loop for free:

- ``slo.http.<endpoint>.latency_seconds``  fixed-bucket histogram
- ``slo.http.<endpoint>.requests``         counter
- ``slo.http.<endpoint>.errors``           counter (status >= 500)

Endpoint names come from the node's *registered route table* (never
from raw request paths), so the cardinality cap can't be consumed by
request-derived garbage.

Quantiles are estimated from the histogram by linear interpolation
within the bucket that crosses the target rank — the standard
Prometheus ``histogram_quantile`` estimate.  The +Inf overflow bucket
clamps to the top finite bound (there is nothing to interpolate
toward), which is also what Prometheus does.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from . import metrics, tracing

#: HTTP buckets: finer than the span default at the fast end (an
#: in-process cached read answers in tens of microseconds) while still
#: covering multi-second tail stalls.
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

_PREFIX = "slo.http."
_SUFFIX = ".latency_seconds"


def _safe(endpoint: str) -> str:
    return endpoint.strip("/").replace("/", "_") or "root"


def preregister(endpoints: Iterable[str]) -> None:
    """Create the SLO families for a fixed endpoint set so /metrics
    exports them (all-zero) from scrape #1."""
    for ep in endpoints:
        ep = _safe(ep)
        metrics.ensure_histogram(_PREFIX + ep + _SUFFIX, LATENCY_BUCKETS)
        metrics.ensure_counter(_PREFIX + ep + ".requests")
        metrics.ensure_counter(_PREFIX + ep + ".errors")


def observe_request(endpoint: str, seconds: float, status: int = 200,
                    trace_id: Optional[str] = None) -> None:
    """Record one served request against ``endpoint``'s SLO series.

    When the request ran under a trace, the trace id is attached as a
    bucket exemplar — /metrics then links the latency bucket to the
    concrete (possibly cross-node) trace that produced it, and firing
    alerts pick the same ids up as incident exemplars.  Callers that
    measure *after* their trace context closed (the node middleware
    times the full handler) pass the id explicitly; inside a live
    trace the ambient id is picked up automatically."""
    ep = _safe(endpoint)
    name = _PREFIX + ep + _SUFFIX
    metrics.observe(name, seconds, LATENCY_BUCKETS)
    tid = trace_id or tracing.current_trace_id()
    if tid:
        metrics.observe_exemplar(name, seconds, tid)
    metrics.inc(_PREFIX + ep + ".requests")
    if status >= 500:
        metrics.inc(_PREFIX + ep + ".errors")


def quantile(hist: dict, q: float) -> Optional[float]:
    """Estimate the ``q``-quantile (0 < q < 1) of a snapshot histogram
    ``{bounds, counts (per-bucket, +Inf last), count, sum}``."""
    total = hist.get("count", 0)
    if total <= 0:
        return None
    bounds = list(hist["bounds"])
    counts = list(hist["counts"])
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev_cum = cum
        cum += c
        if cum < rank or c == 0:
            continue
        if i >= len(bounds):          # +Inf bucket: clamp to top bound
            return float(bounds[-1]) if bounds else None
        lo = float(bounds[i - 1]) if i > 0 else 0.0
        hi = float(bounds[i])
        return lo + (hi - lo) * (rank - prev_cum) / c
    return float(bounds[-1]) if bounds else None


def summary() -> Dict[str, dict]:
    """Per-endpoint snapshot: requests/errors plus histogram-estimated
    p50/p95/p99 in milliseconds (None until the first observation)."""
    counters = metrics.counters()
    out: Dict[str, dict] = {}
    for name, hist in metrics.histograms().items():
        if not (name.startswith(_PREFIX) and name.endswith(_SUFFIX)):
            continue
        ep = name[len(_PREFIX):-len(_SUFFIX)]
        row = {"requests": counters.get(_PREFIX + ep + ".requests", 0),
               "errors": counters.get(_PREFIX + ep + ".errors", 0)}
        for label, q in (("p50_ms", 0.5), ("p95_ms", 0.95),
                         ("p99_ms", 0.99)):
            est = quantile(hist, q)
            row[label] = round(est * 1000.0, 4) if est is not None else None
        out[ep] = row
    return out
