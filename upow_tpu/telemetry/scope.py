"""Instance scoping for the telemetry registries.

Historically every registry in this package was module-global: one
process, one node, one set of metrics/events/traces.  The swarm
simulator breaks that assumption — 10..50 real node apps share one
interpreter, and their gauges/histograms clobber each other (see the
old comment in swarm/transport.py).  A ``TelemetryScope`` bundles one
node's private registries; ``activate()`` binds it to the current
async context so the module-level functions in ``metrics`` /
``events`` / ``tracing`` transparently write to the scoped registries
instead of the process globals.

Design constraints:

- This module is a LEAF: no sibling imports at module level, so
  ``metrics``/``events``/``tracing`` may import it without cycles.
  ``TelemetryScope.__init__`` defers its sibling imports.
- The default path (no scope active) is unchanged — single-node
  processes keep the module globals and pay one contextvar read.
- Scope is carried by a contextvar, so tasks spawned inside an active
  scope (``ensure_future`` copies contextvars) inherit it — a node's
  gossip/ws/sync tasks report into that node's registries.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Iterator, Optional

_active: contextvars.ContextVar[Optional["TelemetryScope"]] = \
    contextvars.ContextVar("upow_telemetry_scope", default=None)


def current() -> Optional["TelemetryScope"]:
    """The scope bound to the current context, or None (globals)."""
    return _active.get()


@contextlib.contextmanager
def activate(sc: Optional["TelemetryScope"]) -> Iterator[
        Optional["TelemetryScope"]]:
    """Bind ``sc`` for the duration of the block (None rebinds globals)."""
    token = _active.set(sc)
    try:
        yield sc
    finally:
        _active.reset(token)


class TelemetryScope:
    """One instance's private metrics + events + trace registries."""

    def __init__(self, name: str = "", *, max_metric_names: int = 1024,
                 events_buffer: int = 256, trace_recent: int = 32,
                 trace_slowest: int = 16, max_trace_spans: int = 512):
        from .events import EventRing
        from .metrics import MetricsRegistry
        from .tracing import TraceBuffer
        self.name = name
        self.metrics = MetricsRegistry(max_names=max_metric_names)
        self.events = EventRing(maxlen=events_buffer)
        self.traces = TraceBuffer(recent=trace_recent, slowest=trace_slowest)
        self.max_trace_spans = max(1, int(max_trace_spans))

    @classmethod
    def from_config(cls, cfg, name: str = "") -> "TelemetryScope":
        """Build from a ``TelemetryConfig`` (same knobs as the globals)."""
        return cls(name,
                   max_metric_names=cfg.max_metric_names,
                   events_buffer=cfg.events_buffer,
                   trace_recent=cfg.trace_recent,
                   trace_slowest=cfg.trace_slowest,
                   max_trace_spans=cfg.max_trace_spans)

    def activate(self):
        return activate(self)

    def reset(self) -> None:
        self.metrics.reset()
        self.events.reset()
        self.traces.reset()
