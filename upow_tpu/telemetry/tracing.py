"""Request-scoped trace trees over a contextvar trace context.

``request_trace()`` opens a root span bound to the current async
context; every ``span()`` entered underneath (same task, or any task
spawned while the context is active — ``ensure_future`` copies
contextvars) nests into a tree.  Completed roots land in a bounded
ring buffer retaining the most recent N and the N slowest traces,
served as JSON by ``/debug/traces``.

Trace IDs are 32-hex-char strings.  Inbound HTTP requests adopt a
well-formed ``X-Upow-Trace`` header; outbound gossip RPCs attach the
current ID (see node/peers.py), so one push_tx or block propagation
can be followed across nodes.

Cross-task spans: the intake drainer processes requests submitted
from *other* tasks, so ambient context alone cannot attribute its
per-request work.  ``current_span()`` captured at submit time plus
``child_span()`` / ``add_span()`` / ``attached()`` let the drainer
record against each submitter's tree explicitly.

Every span also feeds the flat ``metrics`` aggregates, so the
pre-existing ``trace.stats()`` consumers see identical numbers.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from ..logger import get_logger
from . import metrics, scope as _scope

log = get_logger("telemetry")

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,64}$")

_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("upow_trace_span", default=None)

_LEVELS = {"debug": 10, "info": 20}


def new_trace_id() -> str:
    return uuid.uuid4().hex


def valid_trace_id(value: Optional[str]) -> bool:
    """True for IDs we are willing to adopt from a peer's header."""
    return bool(value) and _TRACE_ID_RE.match(value) is not None


class Span:
    """One node of a trace tree.

    ``start_ts`` is wall-clock (operator display); durations come from
    ``perf_counter``.  Children are appended at creation; a per-root
    span budget (``max_spans``) stops a pathological request from
    growing its tree without bound — excess spans still feed the flat
    aggregates, they just don't attach.
    """

    __slots__ = ("name", "trace_id", "fields", "start_ts", "_t0",
                 "duration_s", "children", "root", "done", "error")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 root: Optional["Span"] = None, **fields: Any):
        self.name = name
        self.trace_id = trace_id
        self.fields = fields
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        self.duration_s: Optional[float] = None
        self.children: List[Span] = []
        self.root = root if root is not None else self
        self.done = False
        self.error: Optional[str] = None

    def finish(self, **fields: Any) -> float:
        if fields:
            self.fields.update(fields)
        if self.duration_s is None:
            self.duration_s = time.perf_counter() - self._t0
        self.done = True
        return self.duration_s

    def to_dict(self) -> dict:
        d: Dict[str, Any] = {
            "name": self.name,
            "start_ts": round(self.start_ts, 6),
            "duration_ms": round((self.duration_s or 0.0) * 1000.0, 3),
        }
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.fields:
            d["fields"] = {k: _jsonable(v) for k, v in self.fields.items()}
        if self.error:
            d["error"] = self.error
        if self.children:
            d["spans"] = [c.to_dict() for c in self.children]
        return d


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class TraceBuffer:
    """Bounded retention of completed traces: recent ring + slowest top-N.

    Also tracks the roots currently *in flight* (opened by
    ``request_trace`` but not yet recorded), so a flight recorder can
    snapshot what a node was doing at the moment of a failure."""

    _OPEN_CAP = 256

    def __init__(self, recent: int = 32, slowest: int = 16):
        self._lock = threading.Lock()
        self._open: Dict[int, Span] = {}
        self.configure(recent, slowest)

    def configure(self, recent: int, slowest: int) -> None:
        with self._lock:
            self._recent: deque = deque(maxlen=max(1, int(recent)))
            self._slowest: List[dict] = []
            self._slow_cap = max(1, int(slowest))

    def record_open(self, root: Span) -> None:
        with self._lock:
            if len(self._open) < self._OPEN_CAP:
                self._open[id(root)] = root

    def discard_open(self, root: Span) -> None:
        with self._lock:
            self._open.pop(id(root), None)

    def open_snapshot(self) -> List[dict]:
        """In-flight (not yet recorded) trace roots, oldest first."""
        with self._lock:
            roots = list(self._open.values())
        out = [r.to_dict() for r in roots if not r.done]
        out.sort(key=lambda d: d["start_ts"])
        return out

    def record(self, root: Span) -> None:
        snap = root.to_dict()
        with self._lock:
            self._open.pop(id(root), None)
            self._recent.append(snap)
            self._slowest.append(snap)
            self._slowest.sort(key=lambda t: t["duration_ms"], reverse=True)
            del self._slowest[self._slow_cap:]

    def snapshot(self) -> dict:
        with self._lock:
            return {"recent": list(self._recent),
                    "slowest": list(self._slowest)}

    def reset(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slowest.clear()
            self._open.clear()


_buffer = TraceBuffer()
_max_spans = 512


def _buf() -> TraceBuffer:
    sc = _scope.current()
    return sc.traces if sc is not None else _buffer


def _span_budget() -> int:
    sc = _scope.current()
    return sc.max_trace_spans if sc is not None else _max_spans


def configure(recent: int = 32, slowest: int = 16,
              max_spans: int = 512) -> None:
    global _max_spans
    _max_spans = max(1, int(max_spans))
    _buffer.configure(recent, slowest)


def traces() -> dict:
    return _buf().snapshot()


def open_traces() -> List[dict]:
    """In-flight trace roots of the active scope (or the globals)."""
    return _buf().open_snapshot()


def current_span() -> Optional[Span]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    sp = _current.get()
    return sp.root.trace_id if sp is not None else None


def _attach(parent: Span, child: Span) -> bool:
    root = parent.root
    if root.done:
        return False  # late child of an already-recorded trace
    # per-root span budget lives in the root's field dict (kept out of
    # Span.__slots__; stripped before the tree is recorded)
    used = root.fields.get("_spans", 0)
    if used >= _span_budget():
        return False
    root.fields["_spans"] = used + 1
    parent.children.append(child)
    return True


@contextlib.contextmanager
def request_trace(name: str, trace_id: Optional[str] = None,
                  **fields: Any):
    """Open a root span; on exit record the tree into the ring buffer."""
    tid = trace_id if valid_trace_id(trace_id) else new_trace_id()
    root = Span(name, trace_id=tid, **fields)
    buf = _buf()  # pin the buffer so open/record hit the same scope
    buf.record_open(root)
    token = _current.set(root)
    try:
        yield root
    except BaseException as e:
        root.error = type(e).__name__
        raise
    finally:
        _current.reset(token)
        root.finish()
        root.fields.pop("_spans", None)
        metrics.record_span(name, root.duration_s)
        buf.record(root)


@contextlib.contextmanager
def span(name: str, level: str = "debug", **fields: Any):
    """Time a section: flat aggregate always, tree node when traced."""
    parent = _current.get()
    node: Optional[Span] = None
    token = None
    if parent is not None and not parent.root.done:
        node = Span(name, root=parent.root, **fields)
        if _attach(parent, node):
            token = _current.set(node)
        else:
            node = None
    t0 = time.perf_counter()
    try:
        yield node
    except BaseException as e:
        if node is not None:
            node.error = type(e).__name__
        raise
    finally:
        dt = time.perf_counter() - t0
        if token is not None:
            _current.reset(token)
        if node is not None:
            node.duration_s = dt
            node.done = True
        metrics.record_span(name, dt)
        lvl = _LEVELS.get(level, 10)
        if log.isEnabledFor(lvl):
            extra = "".join(f" {k}={v}" for k, v in fields.items())
            log.log(lvl, "%s took %.3fs%s", name, dt, extra)


def child_span(parent: Optional[Span], name: str,
               **fields: Any) -> Optional[Span]:
    """Explicitly start a span under ``parent`` (cross-task attribution).

    Returns the started Span (caller must ``finish()`` it) or None when
    there is no parent / the trace is already recorded.  The flat
    aggregate is fed by ``finish_child``.
    """
    if parent is None:
        return None
    node = Span(name, root=parent.root, **fields)
    if not _attach(parent, node):
        return None
    return node


def finish_child(node: Optional[Span], name: Optional[str] = None,
                 **fields: Any) -> None:
    if node is None:
        return
    dt = node.finish(**fields)
    metrics.record_span(name or node.name, dt)


def add_span(parent: Optional[Span], name: str, t0: float, t1: float,
             **fields: Any) -> None:
    """Attach an already-timed section (perf_counter endpoints) under
    ``parent``.  Used for work shared by many requests (one coalesced
    sig dispatch) that must appear in each requester's tree."""
    if parent is None:
        return
    node = Span(name, root=parent.root, **fields)
    node.start_ts = time.time() - (time.perf_counter() - t0)
    node.duration_s = max(0.0, t1 - t0)
    node.done = True
    _attach(parent, node)


@contextlib.contextmanager
def attached(sp: Optional[Span]):
    """Make ``sp`` the ambient span for the duration of the block.

    The intake drainer runs in its own context; entering a submitter's
    captured span here routes nested ``span()`` calls — and contextvars
    copied into tasks spawned inside (ws publish, gossip propagate) —
    to that submitter's trace.  A None span is a no-op."""
    if sp is None or sp.root.done:
        yield
        return
    token = _current.set(sp)
    try:
        yield
    finally:
        _current.reset(token)


def reset() -> None:
    _buf().reset()
