// Native CPU backends for upow_tpu: sha256 PoW search + P-256 ECDSA verify.
//
// These play the roles the reference delegates to native dependencies:
// hashlib/OpenSSL's C sha256 in the miner hot loop (miner.py:83-98) and
// fastecdsa's C/GMP extension for signature verification
// (upow/upow_transactions/transaction_input.py:100-109).  Python binds via
// ctypes (upow_tpu/native/__init__.py); no pybind11 in the image.
//
// The P-256 implementation mirrors the TPU kernel's production path —
// Montgomery field arithmetic + the same Jacobian formula set
// (dbl-2001-b, add/madd-2007-bl) in a 4-bit-window Strauss walk — but
// where the kernel handles formula degeneracies with lane flags (no
// branches on device), the CPU handles them with explicit branches:
// verify-only code with nothing secret to leak.  The two fast paths
// cross-check each other in tests.

#include <cstdint>
#include <cstring>
#include <cstddef>
#if defined(__x86_64__) && defined(__GNUC__)
#include <immintrin.h>  // SHA-NI intrinsics (guarded per-function below)
#include <cpuid.h>      // runtime SHA/SSE4.1 detection (shani_available)
#endif

// ---------------------------------------------------------------- sha256 --

namespace sha256 {

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

static inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

static void compress(uint32_t state[8], const uint8_t block[64]) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; i++) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + K[i] + w[i];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

#if defined(__x86_64__) && defined(__GNUC__)
// SHA-NI compression (x86 SHA extensions): ~10x the portable loop on one
// core.  Compiled with a per-function target attribute so the rest of the
// library needs no -m flags; selected at runtime via cpuid.
static bool shani_available() {
  // raw cpuid, not __builtin_cpu_supports("sha"): gcc only learned the
  // "sha" feature name in 11.x, and the distro toolchain here is older
  unsigned eax, ebx, ecx, edx;
  if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx) ||
      !(ebx & (1u << 29)))  // CPUID.7.0:EBX.SHA
    return false;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx) ||
      !(ecx & (1u << 19)))  // CPUID.1:ECX.SSE4.1
    return false;
  return true;
}

// LANES independent single-block compressions interleaved: sha256rnds2
// has multi-cycle latency but ~1/cycle throughput, so a single hash
// chain leaves the unit mostly idle.  Interleaving fills the pipeline —
// the nonce search has unlimited independent work.  LANES = 4 measured
// fastest here (measured 21.0 vs 20.4 at 2 and 20.2 at 8 lanes; ~1.3x one
// stream on this virtualized core — bare-metal SHA-NI has more
// pipeline headroom).
template <int LANES>
__attribute__((target("sha,sse4.1")))
static void compress_shani_multi(uint32_t state[][8],
                                 const uint8_t* const blocks[]) {
  const __m128i MASK = _mm_set_epi64x(0x0c0d0e0f08090a0bULL,
                                      0x0405060700010203ULL);
  __m128i S0[LANES], S1[LANES], S0v[LANES], S1v[LANES], M[LANES][4];
  for (int l = 0; l < LANES; l++) {
    __m128i TMP = _mm_loadu_si128((const __m128i*)&state[l][0]);
    __m128i ST1 = _mm_loadu_si128((const __m128i*)&state[l][4]);
    TMP = _mm_shuffle_epi32(TMP, 0xB1);
    ST1 = _mm_shuffle_epi32(ST1, 0x1B);
    S0[l] = _mm_alignr_epi8(TMP, ST1, 8);
    S1[l] = _mm_blend_epi16(ST1, TMP, 0xF0);
    S0v[l] = S0[l]; S1v[l] = S1[l];
    for (int i = 0; i < 4; i++)
      M[l][i] = _mm_shuffle_epi8(
          _mm_loadu_si128((const __m128i*)(blocks[l] + 16 * i)), MASK);
  }
  for (int i = 0; i < 16; i++) {
    const __m128i k = _mm_loadu_si128((const __m128i*)&K[4 * i]);
    for (int l = 0; l < LANES; l++) {
      __m128i wk = _mm_add_epi32(M[l][i & 3], k);
      S1[l] = _mm_sha256rnds2_epu32(S1[l], S0[l], wk);
      wk = _mm_shuffle_epi32(wk, 0x0E);
      S0[l] = _mm_sha256rnds2_epu32(S0[l], S1[l], wk);
      if (i < 12) {
        __m128i tmp = _mm_alignr_epi8(M[l][(i + 3) & 3], M[l][(i + 2) & 3], 4);
        M[l][i & 3] = _mm_sha256msg2_epu32(
            _mm_add_epi32(_mm_sha256msg1_epu32(M[l][i & 3], M[l][(i + 1) & 3]),
                          tmp),
            M[l][(i + 3) & 3]);
      }
    }
  }
  for (int l = 0; l < LANES; l++) {
    S0[l] = _mm_add_epi32(S0[l], S0v[l]);
    S1[l] = _mm_add_epi32(S1[l], S1v[l]);
    __m128i TMP = _mm_shuffle_epi32(S0[l], 0x1B);
    S1[l] = _mm_shuffle_epi32(S1[l], 0xB1);
    S0[l] = _mm_blend_epi16(TMP, S1[l], 0xF0);
    S1[l] = _mm_alignr_epi8(S1[l], TMP, 8);
    _mm_storeu_si128((__m128i*)&state[l][0], S0[l]);
    _mm_storeu_si128((__m128i*)&state[l][4], S1[l]);
  }
}

// single-stream form (digest(), sequential callers): the 1-lane
// instantiation of the same transcription — one copy to keep correct
__attribute__((target("sha,sse4.1")))
static void compress_shani(uint32_t state[8], const uint8_t block[64]) {
  compress_shani_multi<1>((uint32_t(*)[8])state, &block);
}
#else
template <int LANES>
static void compress_shani_multi(uint32_t state[][8],
                                 const uint8_t* const blocks[]) {
  for (int l = 0; l < LANES; l++) compress(state[l], blocks[l]);
}
#endif
#if !(defined(__x86_64__) && defined(__GNUC__))
static void compress_shani(uint32_t state[8], const uint8_t block[64]) {
  compress(state, block);
}
static bool shani_available() { return false; }
#endif

typedef void (*compress_fn)(uint32_t[8], const uint8_t[64]);

static compress_fn pick_compress() {
  // cpuid runs once; per-message callers (upow_sha256 on short inputs)
  // would otherwise pay serializing cpuid leaves per call
  static const compress_fn picked =
      shani_available() ? compress_shani : compress;
  return picked;
}

static void digest(const uint8_t* msg, size_t len, uint8_t out[32]) {
  const compress_fn compress = pick_compress();
  uint32_t state[8];
  memcpy(state, H0, sizeof(H0));
  size_t off = 0;
  for (; off + 64 <= len; off += 64) compress(state, msg + off);
  uint8_t tail[128] = {0};
  size_t rem = len - off;
  memcpy(tail, msg + off, rem);
  tail[rem] = 0x80;
  size_t tlen = (rem + 9 <= 64) ? 64 : 128;
  uint64_t bits = uint64_t(len) * 8;
  for (int i = 0; i < 8; i++) tail[tlen - 1 - i] = uint8_t(bits >> (8 * i));
  compress(state, tail);
  if (tlen == 128) compress(state, tail + 64);
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 4; j++) out[4 * i + j] = uint8_t(state[i] >> (24 - 8 * j));
}

}  // namespace sha256

extern "C" void upow_sha256(const uint8_t* msg, size_t len, uint8_t out[32]) {
  sha256::digest(msg, len, out);
}

// PoW nonce search over [start, start+count): header = prefix || nonce_le4.
// target_nibbles: required leading hex chars of the digest; charset < 16
// additionally bounds the next nibble (manager.py:130-151).  Returns the
// first satisfying nonce, or 0xFFFFFFFF.  Midstate-split like the TPU
// kernel: full prefix blocks folded once, one-or-two compressions per nonce.
extern "C" uint32_t upow_pow_search(const uint8_t* prefix, size_t prefix_len,
                                    const uint8_t* target_nibbles,
                                    size_t n_target, uint32_t charset,
                                    uint32_t start, uint32_t count) {
  const sha256::compress_fn compress = sha256::pick_compress();
  uint32_t mid[8];
  memcpy(mid, sha256::H0, sizeof(mid));
  size_t n_full = prefix_len / 64;
  for (size_t i = 0; i < n_full; i++) compress(mid, prefix + 64 * i);
  size_t rem = prefix_len - 64 * n_full;
  size_t total = prefix_len + 4;
  // same bound as make_template: rem + nonce(4) + 0x80 must fit before the
  // 8-byte length field (rem + 4 <= 55), else the tail spans two blocks
  if (rem + 4 > 55) return 0xFFFFFFFFu;

  uint8_t tail[64] = {0};
  memcpy(tail, prefix + 64 * n_full, rem);
  tail[rem + 4] = 0x80;
  uint64_t bits = uint64_t(total) * 8;
  for (int i = 0; i < 8; i++) tail[63 - i] = uint8_t(bits >> (8 * i));

  auto hit = [&](const uint32_t state[8]) -> bool {
    bool ok = true;
    for (size_t i = 0; i < n_target && ok; i++) {
      uint32_t nib = (state[i / 8] >> (28 - 4 * (i % 8))) & 0xF;
      ok = nib == target_nibbles[i];
    }
    if (ok && charset < 16) {
      uint32_t nib = (state[n_target / 8] >> (28 - 4 * (n_target % 8))) & 0xF;
      ok = nib < charset;
    }
    return ok;
  };

  const uint64_t end = uint64_t(start) + count;
  uint64_t n = start;

  if (sha256::shani_available()) {
    // 4-way interleaved SHA-NI: ~1.3x one stream here (pipeline-bound, not
    // throughput-bound).  Returns the LOWEST hit in the quad — same
    // first-hit semantics as the scalar loop.
    constexpr int LANES = 4;
    uint8_t blks[LANES][64];
    uint32_t states[LANES][8];
    const uint8_t* blk_ptrs[LANES];
    for (int l = 0; l < LANES; l++) {
      memcpy(blks[l], tail, 64);
      blk_ptrs[l] = blks[l];
    }
    for (; n + LANES <= end; n += LANES) {
      for (int l = 0; l < LANES; l++) {
        uint64_t nl = n + l;
        memcpy(states[l], mid, sizeof(mid));
        blks[l][rem] = uint8_t(nl);
        blks[l][rem + 1] = uint8_t(nl >> 8);
        blks[l][rem + 2] = uint8_t(nl >> 16);
        blks[l][rem + 3] = uint8_t(nl >> 24);
      }
      sha256::compress_shani_multi<LANES>(states, blk_ptrs);
      for (int l = 0; l < LANES; l++)
        if (hit(states[l])) return uint32_t(n + l);
    }
  }

  uint8_t blk[64];
  memcpy(blk, tail, 64);  // only the 4 nonce bytes change per iteration
  for (; n < end; n++) {
    uint32_t state[8];
    memcpy(state, mid, sizeof(mid));
    blk[rem] = uint8_t(n);
    blk[rem + 1] = uint8_t(n >> 8);
    blk[rem + 2] = uint8_t(n >> 16);
    blk[rem + 3] = uint8_t(n >> 24);
    compress(state, blk);
    if (hit(state)) return uint32_t(n);
  }
  return 0xFFFFFFFFu;
}

// ----------------------------------------------------------------- P-256 --

namespace p256 {

typedef unsigned __int128 u128;

// little-endian 4x64 limbs
struct Fe { uint64_t v[4]; };

static const Fe P = {{0xffffffffffffffffULL, 0x00000000ffffffffULL,
                      0x0000000000000000ULL, 0xffffffff00000001ULL}};
static const Fe N = {{0xf3b9cac2fc632551ULL, 0xbce6faada7179e84ULL,
                      0xffffffffffffffffULL, 0xffffffff00000000ULL}};
// -p^-1 mod 2^64 and -n^-1 mod 2^64
static const uint64_t P_INV = 0x0000000000000001ULL;
static const uint64_t N_INV = 0xccd1c8aaee00bc4fULL;
// R^2 mod p / mod n  (R = 2^256)
static const Fe P_R2 = {{0x0000000000000003ULL, 0xfffffffbffffffffULL,
                         0xfffffffffffffffeULL, 0x00000004fffffffdULL}};
static const Fe N_R2 = {{0x83244c95be79eea2ULL, 0x4699799c49bd6fa6ULL,
                         0x2845b2392b6bec59ULL, 0x66e12d94f3d95620ULL}};
// curve b, Montgomery form (b*R mod p)
static const Fe B_M = {{0xd89cdf6229c4bddfULL, 0xacf005cd78843090ULL,
                        0xe5a220abf7212ed6ULL, 0xdc30061d04874834ULL}};
// generator, Montgomery form
static const Fe GX_M = {{0x79e730d418a9143cULL, 0x75ba95fc5fedb601ULL,
                         0x79fb732b77622510ULL, 0x18905f76a53755c6ULL}};
static const Fe GY_M = {{0xddf25357ce95560aULL, 0x8b4ab8e4ba19e45cULL,
                         0xd2e88688dd21f325ULL, 0x8571ff1825885d85ULL}};
// 1 in Montgomery form mod p (R mod p)
static const Fe ONE_M = {{0x0000000000000001ULL, 0xffffffff00000000ULL,
                          0xffffffffffffffffULL, 0x00000000fffffffeULL}};

static inline bool geq(const Fe& a, const Fe& b) {
  for (int i = 3; i >= 0; i--) {
    if (a.v[i] > b.v[i]) return true;
    if (a.v[i] < b.v[i]) return false;
  }
  return true;  // equal
}

static inline void sub_raw(Fe& r, const Fe& a, const Fe& b) {
  u128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 d = u128(a.v[i]) - b.v[i] - uint64_t(borrow);
    r.v[i] = uint64_t(d);
    borrow = (d >> 64) ? 1 : 0;
  }
}

static inline void add_mod(Fe& r, const Fe& a, const Fe& b, const Fe& mod) {
  u128 carry = 0;
  uint64_t t[4];
  for (int i = 0; i < 4; i++) {
    u128 s = u128(a.v[i]) + b.v[i] + uint64_t(carry);
    t[i] = uint64_t(s);
    carry = s >> 64;
  }
  Fe tt = {{t[0], t[1], t[2], t[3]}};
  if (carry || geq(tt, mod)) sub_raw(tt, tt, mod);
  r = tt;
}

static inline void sub_mod(Fe& r, const Fe& a, const Fe& b, const Fe& mod) {
  Fe d;
  if (geq(a, b)) { sub_raw(d, a, b); }
  else { Fe t; sub_raw(t, b, a); sub_raw(d, mod, t); }
  r = d;
}

// Montgomery CIOS multiplication, 64-bit limbs, u128 accumulators.
static void mont_mul(Fe& r, const Fe& a, const Fe& b, const Fe& mod,
                     uint64_t inv) {
  uint64_t t[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 4; i++) {
    u128 carry = 0;
    for (int j = 0; j < 4; j++) {
      u128 s = u128(a.v[i]) * b.v[j] + t[j] + uint64_t(carry);
      t[j] = uint64_t(s);
      carry = s >> 64;
    }
    u128 s = u128(t[4]) + uint64_t(carry);
    t[4] = uint64_t(s);
    t[5] = uint64_t(s >> 64);

    uint64_t m = t[0] * inv;
    carry = 0;
    u128 s0 = u128(m) * mod.v[0] + t[0];
    carry = s0 >> 64;
    for (int j = 1; j < 4; j++) {
      u128 sj = u128(m) * mod.v[j] + t[j] + uint64_t(carry);
      t[j - 1] = uint64_t(sj);
      carry = sj >> 64;
    }
    u128 s4 = u128(t[4]) + uint64_t(carry);
    t[3] = uint64_t(s4);
    t[4] = t[5] + uint64_t(s4 >> 64);
    t[5] = 0;
  }
  Fe out = {{t[0], t[1], t[2], t[3]}};
  if (t[4] || geq(out, mod)) sub_raw(out, out, mod);
  r = out;
}

static inline bool is_zero(const Fe& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static inline bool eq(const Fe& a, const Fe& b) {
  return ((a.v[0] ^ b.v[0]) | (a.v[1] ^ b.v[1]) | (a.v[2] ^ b.v[2]) |
          (a.v[3] ^ b.v[3])) == 0;
}

static void from_be32(Fe& r, const uint8_t* be) {
  for (int i = 0; i < 4; i++) {
    uint64_t w = 0;
    for (int j = 0; j < 8; j++) w = (w << 8) | be[8 * (3 - i) + j];
    r.v[i] = w;
  }
}

// modular inverse via Fermat (mod is prime): a^(mod-2) in Montgomery domain
static void mont_pow(Fe& r, const Fe& a_m, const Fe& e, const Fe& mod,
                     uint64_t inv, const Fe& one_m) {
  Fe acc = one_m;
  for (int i = 255; i >= 0; i--) {
    mont_mul(acc, acc, acc, mod, inv);
    if ((e.v[i / 64] >> (i % 64)) & 1) mont_mul(acc, acc, a_m, mod, inv);
  }
  r = acc;
}

// ---- Jacobian arithmetic (verify-only: data-dependent branches are
// fine, there is no secret to leak).  Same formula choices as the TPU
// kernel (dbl-2001-b a=-3, add-2007-bl, madd-2007-bl) but with the
// exceptional cases handled by explicit branches instead of lane flags.

struct Jac { Fe X, Y, Z; };  // Z == 0 encodes infinity

#define PMUL(r, a, b) mont_mul(r, a, b, P, P_INV)
#define PADD(r, a, b) add_mod(r, a, b, P)
#define PSUB(r, a, b) sub_mod(r, a, b, P)

static void jac_dbl(Jac& R, const Jac& Pp) {
  // dbl-2001-b (a = -3): 3M + 5S
  if (is_zero(Pp.Z)) { R = Pp; return; }
  Fe delta, gamma, beta, alpha, t0, t1, t2;
  PMUL(delta, Pp.Z, Pp.Z);
  PMUL(gamma, Pp.Y, Pp.Y);
  PMUL(beta, Pp.X, gamma);
  PSUB(t0, Pp.X, delta); PADD(t1, Pp.X, delta);
  PMUL(alpha, t0, t1);
  PADD(t0, alpha, alpha); PADD(alpha, t0, alpha);  // alpha *= 3
  Fe X3, Y3, Z3;
  PMUL(X3, alpha, alpha);
  PADD(t0, beta, beta); PADD(t0, t0, t0); PADD(t0, t0, t0);  // 8*beta
  PSUB(X3, X3, t0);
  PADD(Z3, Pp.Y, Pp.Z); PMUL(Z3, Z3, Z3);
  PSUB(Z3, Z3, gamma); PSUB(Z3, Z3, delta);
  PADD(t0, beta, beta); PADD(t0, t0, t0);  // 4*beta
  PSUB(t0, t0, X3); PMUL(Y3, alpha, t0);
  PMUL(t1, gamma, gamma);
  PADD(t2, t1, t1); PADD(t2, t2, t2); PADD(t2, t2, t2);  // 8*gamma^2
  PSUB(Y3, Y3, t2);
  R.X = X3; R.Y = Y3; R.Z = Z3;
}

static void jac_add(Jac& R, const Jac& Pp, const Jac& Q) {
  // add-2007-bl: 11M + 5S, with branch handling for the degeneracies
  if (is_zero(Pp.Z)) { R = Q; return; }
  if (is_zero(Q.Z)) { R = Pp; return; }
  Fe Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  PMUL(Z1Z1, Pp.Z, Pp.Z); PMUL(Z2Z2, Q.Z, Q.Z);
  PMUL(U1, Pp.X, Z2Z2); PMUL(U2, Q.X, Z1Z1);
  PMUL(t, Q.Z, Z2Z2); PMUL(S1, Pp.Y, t);
  PMUL(t, Pp.Z, Z1Z1); PMUL(S2, Q.Y, t);
  Fe H, Rr;
  PSUB(H, U2, U1); PSUB(Rr, S2, S1);
  if (is_zero(H)) {
    if (is_zero(Rr)) { jac_dbl(R, Pp); return; }  // P == Q
    R.X = ONE_M; R.Y = ONE_M;                     // P == -Q: infinity
    R.Z = Fe{{0, 0, 0, 0}};
    return;
  }
  PADD(Rr, Rr, Rr);  // r = 2*(S2-S1)
  Fe I, J, V;
  PADD(t, H, H); PMUL(I, t, t);       // I = (2H)^2
  PMUL(J, H, I);                       // J = H*I
  PMUL(V, U1, I);                      // V = U1*I
  Fe X3, Y3, Z3;
  PMUL(X3, Rr, Rr); PSUB(X3, X3, J);
  PSUB(X3, X3, V); PSUB(X3, X3, V);
  PSUB(t, V, X3); PMUL(Y3, Rr, t);
  PMUL(t, S1, J); PADD(t, t, t);
  PSUB(Y3, Y3, t);
  PADD(Z3, Pp.Z, Q.Z); PMUL(Z3, Z3, Z3);
  PSUB(Z3, Z3, Z1Z1); PSUB(Z3, Z3, Z2Z2); PMUL(Z3, Z3, H);
  R.X = X3; R.Y = Y3; R.Z = Z3;
}

static void jac_madd(Jac& R, const Jac& Pp, const Fe& qx_m, const Fe& qy_m) {
  // madd-2007-bl (Q affine, Z2 = 1): 7M + 4S
  if (is_zero(Pp.Z)) { R.X = qx_m; R.Y = qy_m; R.Z = ONE_M; return; }
  Fe Z1Z1, U2, S2, t;
  PMUL(Z1Z1, Pp.Z, Pp.Z);
  PMUL(U2, qx_m, Z1Z1);
  PMUL(t, Pp.Z, Z1Z1); PMUL(S2, qy_m, t);
  Fe H, Rr;
  PSUB(H, U2, Pp.X); PSUB(Rr, S2, Pp.Y);
  if (is_zero(H)) {
    if (is_zero(Rr)) { jac_dbl(R, Pp); return; }
    R.X = ONE_M; R.Y = ONE_M; R.Z = Fe{{0, 0, 0, 0}};
    return;
  }
  Fe HH, I, J, V;
  PMUL(HH, H, H);
  PADD(I, HH, HH); PADD(I, I, I);  // I = 4*HH
  PMUL(J, H, I);
  PMUL(V, Pp.X, I);
  PADD(Rr, Rr, Rr);  // r = 2*(S2-Y1)
  Fe X3, Y3, Z3;
  PMUL(X3, Rr, Rr); PSUB(X3, X3, J);
  PSUB(X3, X3, V); PSUB(X3, X3, V);
  PSUB(t, V, X3); PMUL(Y3, Rr, t);
  PMUL(t, Pp.Y, J); PADD(t, t, t);
  PSUB(Y3, Y3, t);
  PADD(Z3, Pp.Z, H); PMUL(Z3, Z3, Z3);
  PSUB(Z3, Z3, Z1Z1); PSUB(Z3, Z3, HH);
  R.X = X3; R.Y = Y3; R.Z = Z3;
}

// Fixed 4-bit-window affine G table (Montgomery domain), built once per
// process: GT[k] = (k+1)*G for k = 0..14.  Batch-normalized to affine
// with ONE Fermat inversion (Montgomery's trick).
static Fe GT_X[15], GT_Y[15];

static void build_g_table() {
  Jac pts[15];
  pts[0] = {GX_M, GY_M, ONE_M};
  for (int k = 1; k < 15; k++) jac_madd(pts[k], pts[k - 1], GX_M, GY_M);
  // batch-invert the Z's
  Fe prefix[15], acc = ONE_M;
  for (int k = 0; k < 15; k++) { prefix[k] = acc; PMUL(acc, acc, pts[k].Z); }
  Fe inv_acc, pm2, two = {{2, 0, 0, 0}};
  sub_raw(pm2, P, two);
  mont_pow(inv_acc, acc, pm2, P, P_INV, ONE_M);
  for (int k = 14; k >= 0; k--) {
    Fe zinv, z2, z3;
    PMUL(zinv, inv_acc, prefix[k]);
    PMUL(inv_acc, inv_acc, pts[k].Z);
    PMUL(z2, zinv, zinv); PMUL(z3, z2, zinv);
    PMUL(GT_X[k], pts[k].X, z2);
    PMUL(GT_Y[k], pts[k].Y, z3);
  }
}

#undef PMUL
#undef PADD
#undef PSUB

}  // namespace p256

// Verify one ECDSA signature over a precomputed sha256 digest.
// All inputs big-endian 32-byte: digest z, r, s, pubkey (qx, qy).
// Returns 1 accept / 0 reject.  Matches fastecdsa.ecdsa.verify semantics.
extern "C" int upow_p256_verify(const uint8_t* z_be, const uint8_t* r_be,
                                const uint8_t* s_be, const uint8_t* qx_be,
                                const uint8_t* qy_be) {
  using namespace p256;
  Fe z, r, s, qx, qy;
  from_be32(z, z_be); from_be32(r, r_be); from_be32(s, s_be);
  from_be32(qx, qx_be); from_be32(qy, qy_be);

  // range checks: 0 < r,s < n
  if (is_zero(r) || is_zero(s) || geq(r, N) || geq(s, N)) return 0;
  // on-curve check: qy^2 == qx^3 - 3*qx + b (Montgomery domain)
  Fe qx_m, qy_m, lhs, rhs, t;
  mont_mul(qx_m, qx, P_R2, P, P_INV);
  mont_mul(qy_m, qy, P_R2, P, P_INV);
  mont_mul(lhs, qy_m, qy_m, P, P_INV);
  mont_mul(rhs, qx_m, qx_m, P, P_INV);
  mont_mul(rhs, rhs, qx_m, P, P_INV);
  sub_mod(rhs, rhs, qx_m, P); sub_mod(rhs, rhs, qx_m, P);
  sub_mod(rhs, rhs, qx_m, P);
  add_mod(rhs, rhs, B_M, P);
  if (!eq(lhs, rhs)) return 0;
  if (is_zero(qx) && is_zero(qy)) return 0;

  // scalars mod n (Montgomery domain mod n)
  static const Fe N_ONE_M = {{0x0c46353d039cdaafULL, 0x4319055258e8617bULL,
                              0x0000000000000000ULL, 0x00000000ffffffffULL}};
  Fe s_m, w_m, z_m, r_m, u1, u2, nm2;
  mont_mul(s_m, s, N_R2, N, N_INV);
  // n - 2 for Fermat inverse
  Fe two = {{2, 0, 0, 0}};
  sub_raw(nm2, N, two);
  mont_pow(w_m, s_m, nm2, N, N_INV, N_ONE_M);
  // z reduced mod n implicitly by mont ops? No: reduce first if z >= n.
  Fe z_red = z;
  if (geq(z_red, N)) sub_raw(z_red, z_red, N);
  mont_mul(z_m, z_red, N_R2, N, N_INV);
  mont_mul(r_m, r, N_R2, N, N_INV);
  mont_mul(u1, z_m, w_m, N, N_INV);   // still Montgomery
  mont_mul(u2, r_m, w_m, N, N_INV);
  // strip Montgomery: multiply by 1
  Fe one = {{1, 0, 0, 0}};
  mont_mul(u1, u1, one, N, N_INV);
  mont_mul(u2, u2, one, N, N_INV);

  // Strauss double-scalar walk R = u1*G + u2*Q, 4-bit windows, MSB
  // first: 252 doublings + at most 2 table adds per window (skipped on
  // zero digits).  G adds are mixed (static affine table); the Q table
  // is built per call.  ~3x fewer Montgomery muls than the earlier
  // 256-step always-add complete ladder — verify-only code, so the
  // data-dependent branches are fine.
  {
    static const bool g_ready = []() { build_g_table(); return true; }();
    (void)g_ready;
  }
  Jac QT[15];
  QT[0] = {qx_m, qy_m, ONE_M};
  for (int k = 1; k < 15; k++) jac_madd(QT[k], QT[k - 1], qx_m, qy_m);

  Jac R = {ONE_M, ONE_M, {{0, 0, 0, 0}}};  // infinity
  for (int wi = 63; wi >= 0; wi--) {
    if (wi != 63) {
      jac_dbl(R, R); jac_dbl(R, R); jac_dbl(R, R); jac_dbl(R, R);
    }
    unsigned d1 = unsigned(u1.v[wi / 16] >> (4 * (wi % 16))) & 15u;
    if (d1) jac_madd(R, R, GT_X[d1 - 1], GT_Y[d1 - 1]);
    unsigned d2 = unsigned(u2.v[wi / 16] >> (4 * (wi % 16))) & 15u;
    if (d2) jac_add(R, R, QT[d2 - 1]);
  }
  if (is_zero(R.Z)) return 0;

  // accept iff X == r*Z^2 or X == (r+n)*Z^2 in the field (x mod n == r)
  Fe z2, r_pm, rz;
  mont_mul(z2, R.Z, R.Z, P, P_INV);
  mont_mul(r_pm, r, P_R2, P, P_INV);
  mont_mul(rz, r_pm, z2, P, P_INV);
  if (eq(R.X, rz)) return 1;
  // r + n < p case
  Fe rn;
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 sum = u128(r.v[i]) + N.v[i] + uint64_t(carry);
    rn.v[i] = uint64_t(sum);
    carry = sum >> 64;
  }
  if (!carry && geq(P, rn) && !eq(P, rn)) {
    Fe rn_m;
    mont_mul(rn_m, rn, P_R2, P, P_INV);
    mont_mul(rz, rn_m, z2, P, P_INV);
    if (eq(R.X, rz)) return 1;
  }
  return 0;
}

// Batch wrapper: arrays of 32-byte big-endian fields; out[i] in {0,1}.
extern "C" void upow_p256_verify_batch(const uint8_t* z, const uint8_t* r,
                                       const uint8_t* s, const uint8_t* qx,
                                       const uint8_t* qy, size_t n,
                                       uint8_t* out) {
  // embarrassingly parallel — one core per signature when OpenMP is
  // available (the build adds -fopenmp when g++ supports it)
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
  for (size_t i = 0; i < n; i++)
    out[i] = uint8_t(upow_p256_verify(z + 32 * i, r + 32 * i, s + 32 * i,
                                      qx + 32 * i, qy + 32 * i));
}
