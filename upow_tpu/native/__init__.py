"""ctypes bindings for the native C++ backends, built lazily with g++.

The shared library compiles on first use into ``_build/`` next to this
file (no pybind11 in the image; plain C ABI + ctypes).  If no compiler is
available the module degrades gracefully: :func:`load` returns ``None``
and callers fall back to hashlib / pure Python — the same layering the
reference gets from hashlib/fastecdsa being optional C accelerators.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "upow_native.cpp")
_LIB = os.path.join(_DIR, "_build", "libupow_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    gxx = shutil.which("g++")
    if gxx is None:
        return False
    os.makedirs(os.path.dirname(_LIB), exist_ok=True)
    base = [gxx, "-O3", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", _LIB + ".tmp"]
    for cmd in (base + ["-fopenmp"], base):  # OpenMP if the toolchain has it
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(_LIB + ".tmp", _LIB)
            return True
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
            continue
    return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _compile():
                return None
        lib = ctypes.CDLL(_LIB)
        lib.upow_sha256.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p]
        lib.upow_sha256.restype = None
        lib.upow_pow_search.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        ]
        lib.upow_pow_search.restype = ctypes.c_uint32
        lib.upow_p256_verify.argtypes = [ctypes.c_char_p] * 5
        lib.upow_p256_verify.restype = ctypes.c_int
        lib.upow_p256_verify_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.upow_p256_verify_batch.restype = None
        _lib = lib
        return _lib


def sha256(message: bytes) -> Optional[bytes]:
    lib = load()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(32)
    lib.upow_sha256(message, len(message), out)
    return out.raw


def pow_search(prefix: bytes, target_prefix_hex: str, charset: int,
               start: int, count: int) -> Optional[int]:
    """First nonce in [start, start+count) passing the PoW rule, else None.

    Mirrors the reference miner's hot loop (miner.py:83-98) at C speed.
    Returns None also when the native library is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    nibbles = bytes(int(c, 16) for c in target_prefix_hex)
    hit = lib.upow_pow_search(prefix, len(prefix), nibbles, len(nibbles),
                              charset, start, count)
    return None if hit == 0xFFFFFFFF else hit


def p256_verify(msg_digest: bytes, r: int, s: int, qx: int, qy: int) -> Optional[bool]:
    lib = load()
    if lib is None:
        return None
    be = lambda x: x.to_bytes(32, "big")
    return bool(lib.upow_p256_verify(msg_digest, be(r), be(s), be(qx), be(qy)))


def p256_verify_batch(digests, sigs, pubs) -> Optional[list]:
    lib = load()
    if lib is None:
        return None
    n = len(digests)
    cat = lambda xs: b"".join(xs)
    be = lambda x: x.to_bytes(32, "big")
    out = ctypes.create_string_buffer(n)
    lib.upow_p256_verify_batch(
        cat(digests), cat(be(r) for r, _ in sigs), cat(be(s) for _, s in sigs),
        cat(be(x) for x, _ in pubs), cat(be(y) for _, y in pubs), n, out,
    )
    return [bool(b) for b in out.raw]
