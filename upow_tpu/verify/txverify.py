"""Transaction verification: DPoS rules on host, signatures batched on TPU.

The reference validates each transaction serially — ~10 rule checks with
live DB reads, then one fastecdsa verify per input (transaction.py:185-238,
transaction_input.py:100-109).  Here the rule checks stay host-side (they
are state lookups, not compute) but signature verification is *collected*
per transaction or per block and dispatched to the batched P-256 kernel in
one device call (crypto/p256.py) — the design SURVEY.md §2.3 calls for.

Signature semantics replicated exactly, including the reference's quirk of
accepting a signature over EITHER the raw signing bytes OR their ASCII-hex
string (transaction_input.py:100-109 tries both), and the per-tx
(pubkey, signature) dedup (transaction.py:148-163).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.codecs import OutputType, TransactionType, string_to_point
from ..core.constants import MAX_INODES, SMALLEST
from ..core.tx import Tx
from ..state.storage import ChainState, _INPUT_TABLE

# The one grandfathered unstake tx exempt from the release-votes rule
# (reference transaction.py:471-472).
_UNSTAKE_EXCEPTION_HASHES = {
    "8befeb253bc6eddd8501f5b27a02b195f5c06a51ccf788213cbedafe7cc49c53",
}


class SigCheck(Tuple):
    """(digest_bytes, digest_hexform, (r, s), pubkey_point) — one deferred
    signature check."""


def _dedup_sig_checks(tx: Tx, voter: bool, address_of,
                      digests: Optional[tuple] = None) -> Optional[List[tuple]]:
    """Collect per-input signature checks with the reference's dedup.

    Returns None if any input is unsigned or its key can't resolve.
    ``address_of(tx_input)`` -> spending (or voter) address string.
    ``digests`` optionally carries the (digest, digest_hexform) pair a
    fused batch prep (verify/block.py:_fused_digest_prep) already
    computed, skipping the two per-tx hashlib passes here.
    """
    if digests is not None:
        digest, digest_hexform = digests
    else:
        signing_bytes = bytes.fromhex(tx.hex(False))
        digest = hashlib.sha256(signing_bytes).digest()
        digest_hexform = hashlib.sha256(tx.hex(False).encode()).digest()
    checks, seen = [], set()
    for tx_input in tx.inputs:
        if tx_input.signature is None:
            return None
        address = address_of(tx_input)
        if address is None:
            return None
        try:
            pub = string_to_point(address)
        except (ValueError, NotImplementedError):
            return None
        # Consensus-exact dedup: the reference keys on
        # (tx_input.public_key, signature) but from_hex never sets
        # public_key (transaction.py:148-163, 520-592), so its runtime key
        # degenerates to the signature value ALONE — a later input reusing
        # an earlier input's (r, s) is skipped even under a different
        # address.  Replicate that exactly; hardening here would fork.
        key = tx_input.signature
        if key in seen:
            continue
        seen.add(key)
        checks.append((digest, digest_hexform, tx_input.signature, pub))
    return checks


# Device-path health for the verify hot path: one process-wide state
# machine (ok / degraded-with-cooldown / poisoned) replacing the old
# one-way _DEVICE_POISONED flag.  Errors now degrade with periodic
# re-probes; hangs still poison permanently (the stuck daemon thread
# cannot be reclaimed).  The node pushes its configured failure_limit /
# cooldown in via DEGRADE.configure() at startup.
from ..resilience.degrade import DegradeManager, POISONED as _POISONED

DEGRADE = DegradeManager()


def _device_usable() -> bool:
    """True iff a device backend initialized within the probe budget.

    ``jax.default_backend()`` itself HANGS (not raises) when the
    tunneled-TPU PJRT client cannot reach the chip — observed live:
    ``jax.devices()`` blocked >500 s.  A validating node must never
    wedge block accept on that, so backend detection goes through the
    process-wide thread-boxed probe (benchutil), and a hang poisons the
    device path for the life of the process (the stuck thread cannot be
    recovered)."""
    if DEGRADE.state == _POISONED:
        return False
    from ..benchutil import probed_platform_cached

    platform = probed_platform_cached(timeout=90.0)  # probe timeout, not consensus  # upowlint: disable=CP001
    if platform is None:
        DEGRADE.poison("jax backend init hung/failed")
        import logging

        logging.getLogger("upow_tpu.verify").warning(
            "jax backend init hung/failed; signature verification "
            "pinned to the host path for this process")
    return platform not in (None, "cpu")


def device_verify_allowed() -> bool:
    """Public gate for other verify-path device dispatches (fused accept
    path, txid batching): a device backend is up AND the degrade state
    machine currently allows dispatching to it.  Mirrors exactly the
    check ``_resolve_backend`` applies before routing signature batches
    to the device."""
    return _device_usable() and DEGRADE.allow()


async def run_sig_checks_async(checks: Sequence[tuple],
                               backend: str = "auto",
                               pad_block: int = 128,
                               device_timeout: float = 240.0,  # operational timeout  # upowlint: disable=CP001
                               precomputed=None,
                               mesh_devices: int = 1) -> List[bool]:
    """Executor-wrapped :func:`run_sig_checks`: the device dispatch (and
    its hang time-box) must not block the node's event loop — the C++
    host batch and ctypes both release the GIL, so this also overlaps
    verification with peer I/O."""
    import asyncio
    import functools

    return await asyncio.get_event_loop().run_in_executor(
        None, functools.partial(run_sig_checks, checks, backend=backend,
                                pad_block=pad_block,
                                device_timeout=device_timeout,
                                precomputed=precomputed,
                                mesh_devices=mesh_devices))


_VERIFY_MESH = {}  # mesh_devices -> Mesh | None, built once per process
_VERIFY_MESH_LOCK = threading.Lock()  # intake + block verify race this
# cache from different executor threads


def _verify_mesh(mesh_devices: int):
    """DP mesh for the device verify dispatch (SURVEY §2.3): 0 = all
    visible devices, 1 = single device (no mesh), N = first N.  On a
    one-chip host this is always None — the batch stays resident on the
    single device with no partitioning overhead.  The 'complete' kernel
    variant has no mesh wiring (p256 partitions the jac ladder only);
    it keeps the unsharded dispatch rather than poisoning the device
    path."""
    if mesh_devices == 1:
        return None
    from ..crypto import p256

    if p256.PALLAS_KERNEL == "complete":
        return None
    with _VERIFY_MESH_LOCK:
        if mesh_devices not in _VERIFY_MESH:
            from ..device.runtime import get_runtime

            devices = get_runtime().devices()
            n = len(devices) if mesh_devices == 0 else min(
                mesh_devices, len(devices))
            if n <= 1:
                _VERIFY_MESH[mesh_devices] = None
            else:
                from ..parallel.mesh import make_mesh

                _VERIFY_MESH[mesh_devices] = make_mesh(devices[:n])
        return _VERIFY_MESH[mesh_devices]


_SIG_VERDICTS: "OrderedDict[tuple, bool]" = OrderedDict()
_SIG_VERDICTS_MAX = 1 << 16
_SIG_VERDICTS_LOCK = threading.Lock()  # intake + block verify run on
# different executor threads; OrderedDict mutation is not atomic
_SIG_VERDICT_STATS = {"hits": 0, "misses": 0}


def sig_verdict_stats() -> dict:
    """Cache size + hit/miss counters (observability: node /metrics)."""
    with _SIG_VERDICTS_LOCK:
        return {"size": len(_SIG_VERDICTS), **_SIG_VERDICT_STATS}


def clear_sig_verdicts() -> None:
    """Drop the process-level signature-verdict cache (tests)."""
    with _SIG_VERDICTS_LOCK:
        _SIG_VERDICTS.clear()
        _SIG_VERDICT_STATS["hits"] = _SIG_VERDICT_STATS["misses"] = 0


_CANARY_LOCK = threading.Lock()
_CANARY: Optional[Tuple[tuple, tuple]] = None
_CANARY_EXPECTED = (True, False)


def _canary_checks() -> Tuple[tuple, tuple]:
    """Deterministic (known-good, known-bad) signature checks.

    Appended to every device-path cache-miss dispatch; the device's
    verdicts are admitted into the process-wide cache only when the
    canaries come back exactly ``(True, False)``.  A device batch that
    silently miscomputes (stale AOT cache entry, sick tunnel) then
    taints at most the one dispatch it belongs to instead of being
    replayed from the cache on every re-accept forever.  The key pair
    is fixed and public BY DESIGN — it signs nothing but this
    self-check message and guards no value.
    """
    global _CANARY
    with _CANARY_LOCK:
        if _CANARY is None:
            from ..core import curve
            from ..core.constants import CURVE_N

            priv = 0x7E57AB1E_0000C0DE_7E57AB1E_0000C0DE % CURVE_N
            k = 0x9E3779B97F4A7C15_F39CC060_5CEDC834 % CURVE_N
            pub = curve.point_mul(priv, curve.G)
            digest = hashlib.sha256(b"upow-tpu verify canary").digest()
            hexform = hashlib.sha256(b"upow-tpu verify canary hex").digest()
            z = int.from_bytes(digest, "big")  # upowlint: disable=CE001
            r = curve.point_mul(k, curve.G)[0] % CURVE_N
            s = (pow(k, -1, CURVE_N) * (z + r * priv)) % CURVE_N
            good = (digest, hexform, (r, s), pub)
            bad = (digest, hexform, (r, s - 1 if s > 1 else s + 1), pub)
            _CANARY = (good, bad)
        return _CANARY


def _resolve_backend(backend: str, n_checks: int) -> str:
    """Apply the ``auto`` policy and the device-health override (single
    source for the cached and uncached layers)."""
    if backend == "auto":
        if n_checks < 8:
            return "host"
        return "device" if (_device_usable() and DEGRADE.allow()) \
            else "host"
    if backend != "host" and not DEGRADE.allow():
        # an explicitly configured device backend must also honor the
        # health state: re-paying device_timeout (and leaking another
        # stuck daemon thread) on every block would stall the node 4 min
        # per block after one hang; a degraded device is only retried
        # after its cooldown
        return "host"
    return backend


def run_sig_checks(checks: Sequence[tuple], backend: str = "auto",
                   pad_block: int = 128,
                   device_timeout: float = 240.0,  # operational timeout  # upowlint: disable=CP001
                   use_cache: bool = True,
                   precomputed=None,
                   mesh_devices: int = 1) -> List[bool]:
    """Verify deferred checks in one (or two) batched device calls.

    Pass 1 verifies against the raw-bytes digest; only failures re-try the
    hex-string digest (the reference's or-fallback).  ``backend='host'``
    uses the C++/pure-Python path.

    ``auto`` policy: the device batch only pays off on a real
    accelerator — on a CPU-only host the XLA ladder costs minutes of
    compile for throughput the OpenMP C++ batch beats anyway, so auto
    means device iff a device backend probes healthy (see
    :func:`_device_usable` — the probe survives a hung TPU tunnel), and
    the host batch otherwise (small batches always stay host-side:
    dispatch overhead dominates under ~8 signatures).

    Verdicts are memoized process-wide (bounded LRU) keyed on the full
    (digest, hexdigest, signature, pubkey) tuple: ECDSA verification is
    pure, so a tx verified at mempool intake is NOT re-verified when its
    block is accepted — the reference pays that double verification
    (push_tx intake then check_block, transaction.py:185-238) on every
    gossiped tx.  Reorgs and sync re-accepts hit the same cache.

    Host-path verdicts are always cached.  Device-path verdicts are
    cached only when the batch's canary pair (:func:`_canary_checks`,
    one known-good and one known-bad signature riding in the same
    dispatch) comes back exactly (True, False): a device batch that
    silently miscomputes (stale AOT cache entry, sick tunnel) would
    otherwise turn one wrong verdict into a permanent one — replayed on
    every re-accept even after the device path is poisoned off.  With
    the canary gate, a sick batch taints at most itself.
    """
    if not checks:
        return []
    if precomputed:
        # page-level batch verdicts (chain-sync prefill): one device
        # dispatch per sync page instead of one per block.  Transient —
        # lives only for that page's accept loop, so it carries exactly
        # the per-batch device trust the per-block dispatch would.
        out_pre: List[Optional[bool]] = [precomputed.get(c) for c in checks]
        rest_idx = [i for i, v in enumerate(out_pre) if v is None]
        if rest_idx:
            rest = run_sig_checks(
                [checks[i] for i in rest_idx], backend=backend,
                pad_block=pad_block, device_timeout=device_timeout,
                use_cache=use_cache, mesh_devices=mesh_devices)
            for i, v in zip(rest_idx, rest):
                out_pre[i] = v
        return out_pre  # type: ignore[return-value]
    if use_cache:
        out: List[Optional[bool]] = [None] * len(checks)
        misses = []
        with _SIG_VERDICTS_LOCK:
            for i, c in enumerate(checks):
                v = _SIG_VERDICTS.get(c)
                if v is None:
                    misses.append(i)
                else:
                    _SIG_VERDICTS.move_to_end(c)
                    out[i] = v
            _SIG_VERDICT_STATS["hits"] += len(checks) - len(misses)
            _SIG_VERDICT_STATS["misses"] += len(misses)
        if misses:
            miss_checks = [checks[i] for i in misses]
            resolved = _resolve_backend(backend, len(miss_checks))
            dispatch_checks = miss_checks
            canaries = 0
            if resolved != "host":
                # ride the canary pair along in the same device batch;
                # their verdicts gate whether this batch may be cached
                canary = _canary_checks()
                dispatch_checks = miss_checks + list(canary)
                canaries = len(canary)
            fresh = run_sig_checks(
                dispatch_checks, backend=resolved,
                pad_block=pad_block, device_timeout=device_timeout,
                use_cache=False, mesh_devices=mesh_devices)
            cacheable = resolved == "host"
            if canaries:
                canary_ok = tuple(fresh[-canaries:]) == _CANARY_EXPECTED
                fresh = fresh[: len(miss_checks)]
                from .. import trace

                trace.inc("verify.canary_%s"
                          % ("pass" if canary_ok else "fail"))
                if canary_ok:
                    cacheable = True
                else:
                    import logging

                    logging.getLogger("upow_tpu.verify").warning(
                        "device verify canary failed; %d verdicts NOT "
                        "cached", len(miss_checks))
            for i, v in zip(misses, fresh):
                out[i] = v
            if cacheable:
                with _SIG_VERDICTS_LOCK:
                    for i, v in zip(misses, fresh):
                        _SIG_VERDICTS[checks[i]] = v
                    while len(_SIG_VERDICTS) > _SIG_VERDICTS_MAX:
                        _SIG_VERDICTS.popitem(last=False)
        return out  # type: ignore[return-value]
    backend = _resolve_backend(backend, len(checks))
    if backend == "host":
        from .. import native

        batch = native.p256_verify_batch(
            [c[0] for c in checks], [c[2] for c in checks],
            [c[3] for c in checks])
        if batch is None:
            batch = [_host_verify_digest(c[0], c[2], c[3]) for c in checks]
        out = list(map(bool, batch))
        retry = [i for i, ok in enumerate(out) if not ok]
        if retry:
            second = native.p256_verify_batch(
                [checks[i][1] for i in retry],
                [checks[i][2] for i in retry],
                [checks[i][3] for i in retry])
            if second is None:
                second = [_host_verify_digest(checks[i][1], checks[i][2],
                                              checks[i][3]) for i in retry]
            for i, ok in zip(retry, second):
                out[i] = bool(ok)
        return out

    from ..crypto import p256

    def device_batch(digests, sigs, pubs):
        """Time-boxed device dispatch: a tunnel that dies AFTER the
        startup probe makes the call hang, not raise.  A hang poisons
        the device path immediately; raised exceptions are logged and
        degrade it (CPU fallback + cooldown re-probe) after a few
        consecutive failures — either way the caller re-runs on the
        host, and the node survives."""
        import logging

        from ..device.runtime import get_runtime
        from ..resilience.faultinject import get_injector

        def dispatch():
            # chaos hook: an injected hang lands INSIDE the boxed
            # worker thread, exercising the same time-box a real stuck
            # PJRT call would
            injector = get_injector()
            if injector is not None:
                injector.fire_sync("device.verify")
            return p256.verify_batch_prehashed(
                digests, sigs, pubs, pad_block=pad_block,
                mesh=_verify_mesh(mesh_devices))

        import time as _time

        from .. import trace as _trace

        t0 = _time.perf_counter()
        # through the device-runtime queue (executes inline when this
        # already runs on the drainer thread — a coalesced front group)
        status, value = get_runtime().run_boxed(
            dispatch, device_timeout,  # generous: covers first compile
            kernel="p256_verify", source="verify")
        from ..telemetry.device import DISPATCH_BUCKETS as _DISPATCH_BUCKETS

        _trace.observe("kernel.p256_verify.dispatch_seconds",
                       _time.perf_counter() - t0,
                       buckets=_DISPATCH_BUCKETS)
        log = logging.getLogger("upow_tpu.verify")
        if status == "ok":
            DEGRADE.record_success()
            return value
        if status == "err":
            DEGRADE.record_failure(value)
            log.warning(
                "device verify dispatch failed (state=%s): %s",
                DEGRADE.state, value, exc_info=value)
            raise value
        DEGRADE.poison("device verify hung")
        log.warning(
            "device verify dispatch hung; falling back to host path "
            "(device poisoned for this process)")
        raise TimeoutError("device verify hung")

    import logging

    log = logging.getLogger("upow_tpu.verify")
    try:
        first = device_batch(
            [c[0] for c in checks], [c[2] for c in checks],
            [c[3] for c in checks])
    except Exception as e:
        from .. import trace

        trace.inc("resilience.device_fallback")
        log.warning("device verify pass-1 unusable (%s); host fallback for "
                    "%d checks", e, len(checks))
        return run_sig_checks(checks, backend="host", pad_block=pad_block,
                              device_timeout=device_timeout, use_cache=False)
    out = list(map(bool, first))
    retry = [i for i, ok in enumerate(out) if not ok]
    if retry:
        try:
            second = device_batch(
                [checks[i][1] for i in retry],
                [checks[i][2] for i in retry],
                [checks[i][3] for i in retry])
        except Exception as e:
            # pass-1 verdicts are already in hand (same math on device);
            # only the hex-digest retries need the host
            log.debug("device verify pass-2 unusable (%s); host retry for "
                      "%d checks", e, len(retry))
            second = [_host_verify_digest(checks[i][1], checks[i][2],
                                          checks[i][3]) for i in retry]
        for i, ok in zip(retry, second):
            out[i] = bool(ok)
    return out


def _host_verify_digest(digest: bytes, sig, pub) -> bool:
    from ..core import curve
    from ..core.constants import CURVE_N

    r, s = sig
    if not (0 < r < CURVE_N and 0 < s < CURVE_N):
        return False
    # ECDSA bits2int (SEC 1 / RFC 6979): the digest is a big-endian
    # integer by the signature algorithm's definition, not wire format.
    z = int.from_bytes(digest, "big")  # upowlint: disable=CE001
    w = pow(s, -1, CURVE_N)
    p1 = curve.point_mul(z * w % CURVE_N, curve.G)
    p2 = curve.point_mul(r * w % CURVE_N, pub)
    p = curve.point_add(p1, p2)
    return p is not None and p[0] % CURVE_N == r % CURVE_N


class TxVerifier:
    """All rule checks for one transaction against a ChainState.

    Mirrors Transaction.verify's chain (transaction.py:185-238); each rule
    method cites its reference lines.
    """

    def __init__(self, state: ChainState, is_syncing: bool = False,
                 verify_pad_block: int = 128,
                 verify_device_timeout: float = 240.0,  # operational timeout  # upowlint: disable=CP001
                 tx_overlay: Optional[Dict[str, Tx]] = None,
                 verify_mesh_devices: int = 1):
        self.state = state
        self.is_syncing = is_syncing
        self.verify_pad_block = verify_pad_block
        self.verify_device_timeout = verify_device_timeout
        self.verify_mesh_devices = verify_mesh_devices
        # not-yet-accepted source txs (chain-sync page prefill): input
        # resolution consults these before the chain state, so signature
        # checks for a whole sync page can be collected up front even
        # when a tx spends an output created earlier in the same page
        self.tx_overlay = tx_overlay or {}

    # -- address resolution ------------------------------------------------

    async def input_address(self, tx_input) -> Optional[str]:
        src = self.tx_overlay.get(tx_input.tx_hash)
        if src is not None:
            # coinbase sources included: spending a same-page miner
            # reward is the common case two blocks into a sync page
            if 0 <= tx_input.index < len(src.outputs):
                return src.outputs[tx_input.index].address
            return None
        return await self.state.resolve_output_address(tx_input.tx_hash, tx_input.index)

    async def voter_address(self, tx_input) -> Optional[str]:
        """For revoke inputs: the vote tx's FIRST input address
        (transaction_input.py:56-58, 79-82)."""
        src = self.tx_overlay.get(tx_input.tx_hash)
        if src is not None:
            if src.is_coinbase or not src.inputs:
                return None
            return await self.input_address(src.inputs[0])
        info = await self.state.get_transaction_info(tx_input.tx_hash)
        if info is None or not info["inputs_addresses"]:
            tx = await self.state.get_transaction(tx_input.tx_hash, include_pending=True)
            if tx is None or tx.is_coinbase or not tx.inputs:
                return None
            return await self.input_address(tx.inputs[0])
        return info["inputs_addresses"][0]

    # -- double spends -----------------------------------------------------

    @staticmethod
    def no_internal_double_spend(tx: Tx) -> bool:
        """No outpoint used twice within the tx (transaction.py:90-97)."""
        outpoints = [i.outpoint for i in tx.inputs]
        return len(set(outpoints)) == len(outpoints)

    async def inputs_unspent(self, tx: Tx) -> bool:
        """Every input exists in the UTXO-class table its tx type spends
        (transaction.py:99-124)."""
        table = _INPUT_TABLE.get(tx.transaction_type, "unspent_outputs")
        present = await self.state.outpoints_exist(
            [i.outpoint for i in tx.inputs], table)
        return all(present)

    async def no_pending_double_spend(self, tx: Tx) -> bool:
        """Inputs absent from the pending-spent overlay
        (transaction.py:126-133; like the reference, only this tx's
        outpoints are fetched — not the whole overlay)."""
        pending = await self.state.get_pending_spent_outpoints(
            [i.outpoint for i in tx.inputs])
        return all(i.outpoint not in pending for i in tx.inputs)

    # -- DPoS rules (each returns True when the rule does not apply) -------

    async def check_stake(self, tx: Tx) -> bool:
        """transaction.py:434-465."""
        if not any(o.output_type == OutputType.STAKE for o in tx.outputs):
            return True
        address = await self.input_address(tx.inputs[0])
        stakes = await self.state.get_stake_outputs(address)
        if stakes and not self.is_syncing:
            return False
        pending = [
            t for t in await self.state.get_pending_stake_transactions(address)
            if t.hash() != tx.hash()
        ]
        if pending:
            return False
        delegate_power = sum(
            o.amount for o in tx.outputs
            if o.output_type == OutputType.DELEGATE_VOTING_POWER)
        if delegate_power > 0:
            if delegate_power != 10 * SMALLEST:  # 10 "coins" of voting power
                return False
            if await self.state.get_delegates_all_power(address):
                return False
        else:
            if not await self.state.get_delegates_all_power(address):
                return False
        return True

    async def check_unstake(self, tx: Tx) -> bool:
        """transaction.py:467-479."""
        if not any(o.output_type == OutputType.UN_STAKE for o in tx.outputs):
            return True
        address = await self.input_address(tx.inputs[0])
        if await self.state.get_delegates_spent_votes(address) \
                and tx.hash() not in _UNSTAKE_EXCEPTION_HASHES:
            return False
        if await self.state.get_pending_vote_as_delegate_transactions(address):
            return False
        return True

    async def check_inode_register(self, tx: Tx) -> bool:
        """transaction.py:325-362."""
        if not any(o.output_type == OutputType.INODE_REGISTRATION for o in tx.outputs):
            return True
        address = await self.input_address(tx.inputs[0])
        amount = sum(o.amount for o in tx.outputs
                     if o.output_type == OutputType.INODE_REGISTRATION)
        if amount != 1000 * SMALLEST:
            return False
        if not await self.state.get_stake_outputs(address):
            return False
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            return False
        if await self.state.is_validator_registered(address, check_pending_txs=True):
            return False
        if len(await self.state.get_active_inodes(check_pending_txs=True)) >= MAX_INODES:
            return False
        active = await self.state.get_active_inodes()
        if any(e["wallet"] == address for e in active):
            return False
        return True

    async def check_inode_deregister(self, tx: Tx) -> bool:
        """transaction.py:240-254."""
        if tx.transaction_type != TransactionType.INODE_DE_REGISTRATION:
            return True
        address = await self.input_address(tx.inputs[0])
        if not await self.state.get_inode_registration_outputs(address):
            return False
        active = await self.state.get_active_inodes()
        if any(e["wallet"] == address for e in active):
            return False
        return True

    async def check_validator_register(self, tx: Tx) -> bool:
        """transaction.py:364-396."""
        if tx.transaction_type != TransactionType.VALIDATOR_REGISTRATION:
            return True
        address = await self.input_address(tx.inputs[0])
        if not await self.state.get_stake_outputs(address):
            return False
        if await self.state.is_validator_registered(address, check_pending_txs=True):
            return False
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            return False
        reg_amount = sum(o.amount for o in tx.outputs
                         if o.output_type == OutputType.VALIDATOR_REGISTRATION)
        if reg_amount != 100 * SMALLEST:
            return False
        power = [o for o in tx.outputs
                 if o.output_type == OutputType.VALIDATOR_VOTING_POWER]
        if len(power) != 1 or power[0].amount != 10 * SMALLEST:
            return False
        return True

    async def check_vote_as_validator(self, tx: Tx) -> bool:
        """transaction.py:256-288."""
        if tx.transaction_type != TransactionType.VOTE_AS_VALIDATOR:
            return True
        vote_range = sum(o.amount for o in tx.outputs
                         if o.output_type == OutputType.VOTE_AS_VALIDATOR)
        if vote_range > 10 * SMALLEST or vote_range <= 0:
            return False
        address = await self.input_address(tx.inputs[0])
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            return False
        if not await self.state.is_validator_registered(address, check_pending_txs=True):
            return False
        recipient = ""
        for o in tx.outputs:
            if o.output_type == OutputType.VOTE_AS_VALIDATOR:
                recipient = o.address
        if not await self.state.is_inode_registered(recipient, check_pending_txs=True):
            return False
        return True

    async def check_vote_as_delegate(self, tx: Tx,
                                     verifying_add_pending: bool = False) -> bool:
        """transaction.py:290-323."""
        if tx.transaction_type != TransactionType.VOTE_AS_DELEGATE:
            return True
        vote_range = sum(o.amount for o in tx.outputs
                         if o.output_type == OutputType.VOTE_AS_DELEGATE)
        if vote_range > 10 * SMALLEST or vote_range <= 0:
            return False
        address = await self.input_address(tx.inputs[0])
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            return False
        if not await self.state.get_stake_outputs(
                address, check_pending_txs=verifying_add_pending):
            return False
        recipient = ""
        for o in tx.outputs:
            if o.output_type == OutputType.VOTE_AS_DELEGATE:
                recipient = o.address
        if not await self.state.is_validator_registered(recipient, check_pending_txs=True):
            return False
        return True

    async def check_revoke_as_validator(self, tx: Tx) -> bool:
        """transaction.py:399-417."""
        if tx.transaction_type != TransactionType.REVOKE_AS_VALIDATOR:
            return True
        address = await self.voter_address(tx.inputs[0])
        if not await self.state.is_validator_registered(address, check_pending_txs=True):
            return False
        if not await self.state.get_stake_outputs(address):
            return False
        valid = [await self.state.is_revoke_valid(i.tx_hash) for i in tx.inputs]
        return any(valid)

    async def check_revoke_as_delegate(self, tx: Tx) -> bool:
        """transaction.py:419-432."""
        if tx.transaction_type != TransactionType.REVOKE_AS_DELEGATE:
            return True
        address = await self.voter_address(tx.inputs[0])
        if not await self.state.get_stake_outputs(address):
            return False
        valid = [await self.state.is_revoke_valid(i.tx_hash) for i in tx.inputs]
        return any(valid)

    # -- outputs & fees ----------------------------------------------------

    @staticmethod
    def check_outputs(tx: Tx) -> bool:
        """Non-empty, every output verifies (transaction.py:181-183)."""
        return bool(tx.outputs) and all(o.verify() for o in tx.outputs)

    async def check_fees(self, tx: Tx) -> bool:
        """fee >= 0 (transaction.py:234-236, 499-518)."""
        return await self.state.tx_fees(tx) >= 0

    # -- the full chain ----------------------------------------------------

    async def rules_ok(self, tx: Tx, check_double_spend: bool = True,
                       verifying_add_pending: bool = False) -> bool:
        """Everything except signatures, in reference order."""
        if check_double_spend and not self.no_internal_double_spend(tx):
            return False
        if check_double_spend and not await self.inputs_unspent(tx):
            return False
        for rule in (
            self.check_stake,
            self.check_unstake,
            self.check_validator_register,
            self.check_revoke_as_validator,
            self.check_revoke_as_delegate,
            self.check_inode_deregister,
            self.check_inode_register,
            self.check_vote_as_validator,
        ):
            if not await rule(tx):
                return False
        if not await self.check_vote_as_delegate(
                tx, verifying_add_pending=verifying_add_pending):
            return False
        if not self.check_outputs(tx):
            return False
        if not await self.check_fees(tx):
            return False
        return True

    async def collect_sig_checks(self, tx: Tx,
                                 digests: Optional[tuple] = None
                                 ) -> Optional[List[tuple]]:
        """Deferred signature tuples for this tx (None -> invalid).
        ``digests`` forwards a fused-prep (digest, digest_hexform) pair
        so the per-tx sha256 passes are skipped (verify/block.py)."""
        is_revoke = tx.transaction_type in (
            TransactionType.REVOKE_AS_VALIDATOR, TransactionType.REVOKE_AS_DELEGATE)
        addresses = {}
        for tx_input in tx.inputs:
            addr = (await self.voter_address(tx_input) if is_revoke
                    else await self.input_address(tx_input))
            addresses[tx_input.outpoint] = addr
        return _dedup_sig_checks(
            tx, is_revoke, lambda i: addresses.get(i.outpoint),
            digests=digests)

    async def verify(self, tx: Tx, check_double_spend: bool = True,
                     verifying_add_pending: bool = False,
                     sig_backend: str = "auto") -> bool:
        """Full single-tx verification (rules + signatures)."""
        if not await self.rules_ok(tx, check_double_spend, verifying_add_pending):
            return False
        checks = await self.collect_sig_checks(tx)
        if checks is None:
            return False
        return all(await run_sig_checks_async(
            checks, backend=sig_backend, pad_block=self.verify_pad_block,
            device_timeout=self.verify_device_timeout,
            mesh_devices=self.verify_mesh_devices))

    async def prepare_pending(self, tx: Tx) -> Optional[List[tuple]]:
        """Host-side half of add-pending verification: every rule check
        plus the pending-double-spend overlay, with the signature work
        COLLECTED but not dispatched.  The mempool intake flattens the
        returned check tuples across a whole micro-batch into one
        ``run_sig_checks_async`` call; ``None`` means the tx failed a
        host-side rule and never reaches the device."""
        if not await self.rules_ok(tx, verifying_add_pending=True):
            return None
        if not await self.no_pending_double_spend(tx):
            return None
        return await self.collect_sig_checks(tx)

    async def verify_pending(self, tx: Tx, sig_backend: str = "auto") -> bool:
        """add-pending intake check (transaction.py:481-482)."""
        checks = await self.prepare_pending(tx)
        if checks is None:
            return False
        return all(await run_sig_checks_async(
            checks, backend=sig_backend, pad_block=self.verify_pad_block,
            device_timeout=self.verify_device_timeout,
            mesh_devices=self.verify_mesh_devices))
