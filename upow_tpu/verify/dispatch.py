"""Shared batched-dispatch front for P-256 signature verification.

Now a thin client of the process-wide device runtime
(device/runtime.py): the front still coalesces per event loop (and
keeps its counters, metrics, and dispatch_fn injection seams), but a
group headed for the default dispatch target is forwarded to the
runtime's queue, where it can share one device dispatch with batches
from OTHER loops and subsystems (mempool intake + block verify + the
device UTXO index on one chip).

First slice of ROADMAP item 3 (the co-resident kernel server): every
subsystem that needs signature verdicts — block verify's micro-batches
(verify/block.py), mempool intake's coalesced admission batches
(mempool/intake.py), benches — submits its checks HERE instead of
calling :func:`txverify.run_sig_checks_async` directly.  Submissions
queue; a per-event-loop drainer flattens every queued submission with
compatible dispatch parameters into ONE ``run_sig_checks_async`` call
and scatters the verdicts back.  While one dispatch is in flight on the
executor thread, new submissions pile up and form the next coalesced
batch — the natural double-buffering that keeps the device (or the
OpenMP host batch) fed while callers decode/hash the next micro-batch.

Verdict semantics are exactly :func:`txverify.run_sig_checks`'s — the
front only changes WHO shares a dispatch, never what is computed — so
wire behaviour stays byte-identical to the serial paths (pinned by the
differential tests in tests/test_verify_pipeline.py).

Telemetry (telemetry/device.py): each coalesced dispatch records a
``sig_front`` kernel batch (occupancy = submitted lanes / pad-block
rounded lanes), plus ``pipeline.front.*`` counters and a coalesced
submissions-per-dispatch histogram — the cross-subsystem sharing is
directly observable on /metrics.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..logger import get_logger
from ..telemetry import device as ktel
from ..telemetry import metrics
from . import txverify

log = get_logger("verify.dispatch")

COALESCE_BUCKETS = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32)

# The default dispatch target at import time.  A coalesced group whose
# effective target is still this pristine default is forwarded to the
# process-wide device runtime (device/runtime.py) where it can share a
# dispatch with OTHER event loops and subsystems; a group whose target
# was monkeypatched or explicitly injected dispatches locally so those
# seams observe exactly the calls they always did.
_ORIG_ASYNC = txverify.run_sig_checks_async


class _Submission:
    __slots__ = ("checks", "key", "precomputed", "fut", "source",
                 "dispatch_fn", "t0")

    def __init__(self, checks, key, precomputed, fut, source, dispatch_fn):
        self.checks = checks
        self.key = key
        self.precomputed = precomputed
        self.fut = fut
        self.source = source
        self.dispatch_fn = dispatch_fn
        self.t0 = time.perf_counter()


class SigDispatchFront:
    """Per-event-loop coalescing queue in front of run_sig_checks."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._queue: List[_Submission] = []
        self._drainer: Optional[asyncio.Task] = None
        # introspection for tests/benches: dispatches actually issued
        # and total submissions coalesced into them
        self.dispatches = 0
        self.submissions = 0

    async def submit(self, checks: Sequence[tuple], *,
                     backend: str = "auto",
                     pad_block: int = 128,
                     device_timeout: float = 240.0,  # operational timeout  # upowlint: disable=CP001
                     mesh_devices: int = 1,
                     precomputed: Optional[dict] = None,
                     source: str = "other",
                     dispatch_fn=None) -> List[bool]:
        """Queue one batch of sig checks; resolves to its verdict list.

        Submissions sharing (backend, pad_block, device_timeout,
        mesh_devices, precomputed identity, dispatch_fn identity)
        coalesce into one dispatch; incompatible ones dispatch
        separately in arrival order.  ``dispatch_fn`` lets a caller
        inject the underlying verify callable (callers resolve it from
        their own module globals, so established monkeypatch seams keep
        intercepting their path); the default — and anything identical
        to it — is :func:`txverify.run_sig_checks_async`.
        """
        if not checks:
            return []
        if dispatch_fn is txverify.run_sig_checks_async:
            dispatch_fn = None  # default fn must not split coalescing keys
        key = (backend, pad_block, device_timeout, mesh_devices,
               id(precomputed) if precomputed is not None else None,
               id(dispatch_fn) if dispatch_fn is not None else None)
        fut: asyncio.Future = self._loop.create_future()
        self._queue.append(
            _Submission(list(checks), key, precomputed, fut, source,
                        dispatch_fn))
        self.submissions += 1
        metrics.inc("pipeline.front.submissions")
        metrics.inc("pipeline.front.source.%s" % source)
        self._ensure_drainer()
        return await fut

    def _ensure_drainer(self) -> None:
        if self._drainer is not None and not self._drainer.done():
            return
        self._drainer = self._loop.create_task(self._drain())

    async def _drain(self) -> None:
        while self._queue:
            head_key = self._queue[0].key
            group = [s for s in self._queue if s.key == head_key]
            self._queue = [s for s in self._queue if s.key != head_key]
            await self._dispatch_group(group)

    async def _dispatch_group(self, group: List[_Submission]) -> None:
        flat: List[tuple] = []
        slices: List[Tuple[int, int]] = []
        for s in group:
            slices.append((len(flat), len(flat) + len(s.checks)))
            flat.extend(s.checks)
        backend, pad_block, device_timeout, mesh_devices, _, _ = group[0].key
        self.dispatches += 1
        metrics.inc("pipeline.front.dispatches")
        metrics.observe("pipeline.front.coalesced", len(group),
                        buckets=COALESCE_BUCKETS)
        t0 = time.perf_counter()
        fn = group[0].dispatch_fn or txverify.run_sig_checks_async
        try:
            if group[0].dispatch_fn is None \
                    and txverify.run_sig_checks_async is _ORIG_ASYNC:
                # thin-client path: hand the whole coalesced group to
                # the device runtime, which owns arming/scheduling and
                # may merge it with compatible batches from other
                # sources into one shared dispatch
                from ..device.runtime import get_runtime

                verdicts = await asyncio.wrap_future(
                    get_runtime().submit_sig_checks(
                        flat, backend=backend, pad_block=pad_block,
                        device_timeout=device_timeout,
                        mesh_devices=mesh_devices,
                        precomputed=group[0].precomputed,
                        source=group[0].source))
            else:
                verdicts = await fn(
                    flat, backend=backend, pad_block=pad_block,
                    device_timeout=device_timeout,
                    precomputed=group[0].precomputed,
                    mesh_devices=mesh_devices)
        except Exception as e:
            # not swallowed: every submitter in the group re-raises it
            log.debug("coalesced sig dispatch failed (%d submissions): %s",
                      len(group), e)
            for s in group:
                if not s.fut.done():
                    s.fut.set_exception(e)
            return
        finally:
            padded = max(pad_block, 1) * (
                (len(flat) + max(pad_block, 1) - 1) // max(pad_block, 1))
            ktel.record_batch("sig_front", real=len(flat), padded=padded,
                              seconds=time.perf_counter() - t0)
        for s, (lo, hi) in zip(group, slices):
            if not s.fut.done():
                s.fut.set_result(verdicts[lo:hi])


_FRONTS: Dict[int, SigDispatchFront] = {}
_MAX_FRONTS = 32  # dead test loops accumulate; keep the map bounded


def get_front() -> SigDispatchFront:
    """The calling event loop's dispatch front (one per loop: futures
    and the drainer task are loop-bound; tests spin up fresh loops)."""
    loop = asyncio.get_event_loop()
    front = _FRONTS.get(id(loop))
    if front is None or front._loop is not loop or loop.is_closed():
        if len(_FRONTS) >= _MAX_FRONTS:
            for key in [k for k, f in _FRONTS.items()
                        if f._loop.is_closed()]:
                del _FRONTS[key]
            if len(_FRONTS) >= _MAX_FRONTS:
                _FRONTS.clear()
        front = SigDispatchFront(loop)
        _FRONTS[id(loop)] = front
    return front


def preregister() -> None:
    """Export the front's metric families before the first dispatch."""
    ktel.preregister("sig_front")
    metrics.ensure_histogram("pipeline.front.coalesced", COALESCE_BUCKETS)
    for c in ("submissions", "dispatches"):
        metrics.ensure_counter("pipeline.front.%s" % c)
