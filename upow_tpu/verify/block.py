"""Block validation and acceptance — the consensus manager (manager.py).

Departures from the reference, by design (SURVEY.md §7):

* **Batched signature verify** — instead of the serial per-input fastecdsa
  call inside the per-tx loop (manager.py:628-632), ALL signature checks
  in the block are collected and dispatched to the TPU P-256 kernel in one
  call (verify/txverify.py), with the host/native path for small blocks.
* **Pure difficulty/PoW math** — imported from the stateless core
  (core/difficulty.py) and wired to storage here, not entangled with it.
* **One transaction per block accept** — storage mutations run inside a
  single sqlite transaction instead of the reference's serializable-retry
  loops (database.py:640-672).

Rules and quirks are otherwise replicated exactly; citations inline.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import time
from decimal import Decimal
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.clock import timestamp as now_ts
from ..core.constants import MAX_BLOCK_SIZE_HEX, SMALLEST
from ..core import difficulty as difficulty_rules
from ..core.difficulty import BLOCKS_COUNT, LAST_BLOCK_FOR_GENESIS_KEY, check_pow
from ..core.header import split_block_content
from ..core.merkle import merkle_root
from ..core.rewards import get_block_reward, get_inode_rewards
from ..core.tx import CoinbaseTx, Tx, TxOutput
from ..state.storage import ChainState, _INPUT_TABLE
from ..telemetry import device as ktel
from ..trace import event, span
from .dispatch import get_front
from .txverify import TxVerifier, run_sig_checks_async  # noqa: F401  (re-exported for tests)

# Historical chain patches: grandfathered double-spends by height and the
# one merkle exception (consensus DATA for mainnet compatibility;
# manager.py:837-867, 639-645).
DOUBLE_SPEND_WHITELIST = {
    286523: [
        ("16c519171bfa7ee7d42af0d84fe731433048a1aedfd5df692b8beaa755ef6eb9", 0),
        ("747d753fcfecdce5d3a080666ff139ca9123d72d2eb529386f2c3f9f4a55f983", 1),
        ("856b36ecd55a3a427cc988550457435ee9dd7580a423bc3177c1d173b50ff101", 1),
        ("af33808f839698734d801e907f1eb1c24c3547d4cdd984ed0f2e41c58c6d1d9a", 1),
        ("db843078e1fd5f1bbf1c2f550f87548df6fe714ccd12a0ba4a1e25e10fea3ae0", 1),
        ("eb10fd11319aeee7a21766b85c89580f6c3f509a6afaf743df717ca91d33e0da", 1),
    ],
    347027: [
        ("4fd22d5ca99eaa044288de9f850385cbf758efdc4967a92623138e986ce4316e", 2),
        ("b88e9beef7559d48d99ea82e71f7c0601981d6972021feb929c04bc7b52368c2", 1),
        ("ed0f9e07d97ab8a5dc7b8e68ad631a5e78f3cfb6ee6f2aa013854caa64a7b1ae", 1),
    ],
    347034: [
        ("047f5c343dcd15a16c44b3f05fe98bc467002405490ecfb517652207e5425858", 2),
    ],
    349122: [
        ("691695269d8baa441b8e1638a17b3b8497295ec8322c750e8b5312768d4b9ce5", 1),
        ("f7894d0cab92445bd1bb7681106d8fb18d9b4af2465db8a73efbdb97431f855f", 1),
    ],
    395735: [
        ("461c359b956773ff97af6d2189ae84bcc52740e077224efc80b8b5826b51cb92", 1),
        ("ef573f3543ef22b087387fd81493cc7bc977adcc1ff4198483a98a67a6d10e6b", 1),
        ("9efcb290e4c24843bab40dc50591680ac897e52a28db62c7594e4a2b07702291", 1),
    ],
    395736: [
        ("d8421370cef17939c4a2b17c21c7674059c0c24766e80d6129c666f11e886e08", 1),
        ("af2422540ef2f4570b998b262c242b37f7f0e44fbadabcb0f52684dd0ce1ace5", 1),
    ],
}
MERKLE_EXCEPTION = (
    340510, "54e7e3fbfe5c3c7b2a74d14efd22a61c231d157b2c5c2476fca67736736b9ac8")


def _fused_digest_prep(transactions: Sequence[Tx],
                       txid_backend: str = "host",
                       txid_min_batch: int = 256,
                       probe: Optional[Sequence[tuple]] = None):
    """Fused sha256 preparation for one verify micro-batch.

    Per tx, THREE digests feed the hot path: the raw signing-bytes
    digest and its hex-form twin (both consumed by the signature
    checks) and the txid (consumed by merkle_root and storage).  The
    serial path hashed each separately per tx; here all of them go
    through ONE ``txid_batch`` call — shapes allow it, sha256 is
    length-bucketed inside — and txids seed ``Tx._hash`` so the later
    ``merkle_root`` is memo reads.  The txid seed is definitionally
    safe: the payload IS ``bytes.fromhex(tx.hex())``, exactly what
    ``Tx.hash()`` would digest.

    The batch dispatch is gated exactly like the node's sync txid
    prefill (node/app.py): a host backend, or a micro-batch below
    ``txid_min_batch``, hashes inline with hashlib — fusing only pays
    where a device dispatch is amortized.

    ``probe`` (HBM-resident accept path) is a list of
    ``(DeviceUtxoIndex, outpoints)`` parts: the micro-batch's outpoint
    membership probes ride the SAME runtime dispatch as the device
    txid batch via :func:`state.device_index.fused_probe` — one
    scheduler slot for digest prep + membership instead of two queue
    round-trips.  With a probe, the device txid batch additionally
    requires the degrade gate (``txverify.device_verify_allowed``) so
    a degraded device path falls back to hashlib without abandoning
    the probe dispatch.

    Returns ``{id(tx): (digest, digest_hexform)}`` for
    ``collect_sig_checks``; with ``probe``, returns
    ``(that dict, [(present, amounts, shadow_consults), ...])``.
    """
    payloads: List[bytes] = []
    need_txid: List[bool] = []
    for tx in transactions:
        signing_hex = tx.hex(False)
        payloads.append(bytes.fromhex(signing_hex))
        payloads.append(signing_hex.encode())
        need = getattr(tx, "_hash", "x") is None
        need_txid.append(need)
        if need:
            payloads.append(bytes.fromhex(tx.hex()))
    probe_results = None
    if probe is not None:
        from ..state.device_index import fused_probe
        from ..crypto.sha256 import txid_batch
        from .txverify import device_verify_allowed

        extra = None
        if (txid_backend != "host" and len(payloads) >= txid_min_batch
                and device_verify_allowed()):
            extra = functools.partial(txid_batch, payloads,
                                      backend=txid_backend)
        probe_results, digests = fused_probe(probe, extra_fn=extra,
                                             source="block")
        if digests is None:
            digests = [hashlib.sha256(p).hexdigest() for p in payloads]
    elif txid_backend == "host" or len(payloads) < txid_min_batch:
        digests = [hashlib.sha256(p).hexdigest() for p in payloads]
    else:
        from ..crypto.sha256 import txid_batch

        digests = txid_batch(payloads, backend=txid_backend)
    out: Dict[int, tuple] = {}
    pos = 0
    for tx, need in zip(transactions, need_txid):
        pair = (bytes.fromhex(digests[pos]),
                bytes.fromhex(digests[pos + 1]))
        pos += 2
        if need:
            tx._hash = digests[pos]
            pos += 1
        out[id(tx)] = pair
    if probe is not None:
        return out, probe_results
    return out


class BlockManager:
    """Difficulty, check_block, create_block over one ChainState."""

    def __init__(self, state: ChainState, sig_backend: str = "auto",
                 verify_pad_block: int = 128,
                 # operational timeout, not consensus data
                 verify_device_timeout: float = 240.0,  # upowlint: disable=CP001
                 verify_mesh_devices: int = 1,
                 verify_microbatch: int = 1024,
                 txid_backend: str = "host",
                 txid_min_batch: int = 256,
                 fused_accept: bool = True):
        self.state = state
        self.sig_backend = sig_backend
        self.verify_pad_block = verify_pad_block
        self.verify_device_timeout = verify_device_timeout
        # DP-shard the device verify batch over a mesh (SURVEY §2.3):
        # 0 = all visible devices, 1 = single device, N = first N
        self.verify_mesh_devices = verify_mesh_devices
        # pipelined check_block: txs per micro-batch (0 = whole block in
        # one batch, i.e. no overlap) and the backend for the fused
        # digest prep (config.device.txid_backend; "host" is hashlib)
        self.verify_microbatch = verify_microbatch
        self.txid_backend = txid_backend
        self.txid_min_batch = txid_min_batch
        # HBM-resident accept path: when the state exposes armed
        # DeviceUtxoIndex tables (state.resident_indexes()), fuse the
        # per-micro-batch membership probe into the digest-prep dispatch
        # and skip the per-table SQL round-trips entirely
        self.fused_accept = fused_accept
        self._difficulty_cache: Optional[Tuple[Decimal, dict]] = None
        self._inode_cache: Optional[List[dict]] = None
        self._inode_cache_time = 0.0  # monotonic epoch, not consensus  # upowlint: disable=CP001
        self.is_syncing = False
        # transient page-level signature verdicts (chain-sync prefill):
        # set by the node's create_blocks around a page's accept loop
        self.page_sig_verdicts: Optional[dict] = None
        # mempool notification: called with the tx hashes of every
        # journal removal this manager performs (mined txs on block
        # acceptance, GC evictions), AFTER the removal committed.  The
        # node points this at Mempool.remove so the in-memory pool
        # drops mined txs immediately instead of waiting for the next
        # stamp reconcile to notice the journal moved.
        self.on_pending_removed = None
        # hot-state cache notification (state/hotcache.py): called with
        # no arguments after ANY committed chain mutation this manager
        # performs (block accept on either path).  The node points this
        # at HotStateCache.bump so the read cache's generation advances
        # the moment the new tip is visible — reorgs are covered by the
        # storage-level ChainState.on_blocks_removed hook instead, since
        # sync calls remove_blocks directly on state.
        self.on_state_committed = None
        # one acceptance at a time: check_block suspends (sql, executor
        # dispatch), so two concurrent push_block handlers could both
        # validate against tip N and race the same block id into the
        # insert — the loser must instead re-validate against the new
        # tip and reject cleanly ("Previous hash is not matched")
        self._accept_lock = asyncio.Lock()

    def invalidate_difficulty(self):
        self._difficulty_cache = None

    def _notify_pending_removed(self, hashes: List[str]) -> None:
        if self.on_pending_removed is not None and hashes:
            self.on_pending_removed(hashes)

    def _notify_committed(self) -> None:
        if self.on_state_committed is not None:
            self.on_state_committed()

    @staticmethod
    def device_health() -> dict:
        """Snapshot of the verify device path's degradation state
        (txverify.DEGRADE) — the node's /metrics reads it through the
        manager so the HTTP layer never imports verify internals."""
        from .txverify import DEGRADE

        return {**DEGRADE.snapshot(), "gauge": DEGRADE.state_gauge()}

    # -------------------------------------------------------- difficulty --

    async def calculate_difficulty(self) -> Tuple[Decimal, dict]:
        """(difficulty for next block, last block dict) — manager.py:83-121
        via the pure retarget in core/difficulty.py."""
        last_block = await self.state.get_last_block()
        if last_block is None:
            return difficulty_rules.START_DIFFICULTY, {}
        last = {
            "id": last_block["id"],
            "timestamp": last_block["timestamp"],
            "difficulty": last_block["difficulty"],
            "hash": last_block["hash"],
        }
        window_start = None
        if last["id"] >= int(BLOCKS_COUNT) and last["id"] % int(BLOCKS_COUNT) == 0:
            first = await self.state.get_block_by_id(
                last["id"] - int(BLOCKS_COUNT) + 1)
            window_start = first["timestamp"] if first else last["timestamp"]
        return difficulty_rules.next_difficulty(last, window_start), last

    async def get_difficulty(self) -> Tuple[Decimal, dict]:
        if self._difficulty_cache is None:
            self._difficulty_cache = await self.calculate_difficulty()
        return self._difficulty_cache

    # ------------------------------------------------------ inode cache ---

    async def get_active_inodes_cached(self, max_age: float = 300.0) -> List[dict]:  # cache TTL, not consensus  # upowlint: disable=CP001
        """5-minute active-inode cache (manager.py:30-32, 870-900)."""
        if self._inode_cache is not None and \
                time.monotonic() - self._inode_cache_time < max_age:
            return self._inode_cache
        inodes = await self.state.get_active_inodes()
        self._inode_cache = inodes
        self._inode_cache_time = time.monotonic()
        return inodes

    # ------------------------------------------------------- check_block --

    async def check_block(self, block_content: str, transactions: Sequence[Tx],
                          mining_info: Optional[Tuple[Decimal, dict]] = None,
                          errors: Optional[list] = None) -> bool:
        """Full block validation (manager.py:422-647)."""
        errors = errors if errors is not None else []
        if mining_info is None:
            mining_info = await self.calculate_difficulty()
        difficulty, last_block = mining_info
        block_no = (last_block["id"] + 1) if last_block else 1
        with span("block.header_check"):
            try:
                (previous_hash, address, merkle_tree, content_time,
                 content_difficulty, nonce) = split_block_content(
                     block_content)
            except (AssertionError, ValueError, NotImplementedError) as e:
                errors.append(f"malformed block content: {e}")
                return False

            # PoW vs the previous hash at current difficulty
            # (manager.py:130-151)
            if not check_pow(block_content,
                             last_block.get("hash") if last_block else None,
                             difficulty):
                errors.append("block not valid")
                return False
            if last_block and previous_hash != last_block["hash"]:
                errors.append("Previous hash is not matched")
                return False
            prev_ts = last_block.get("timestamp", 0) if last_block else 0
            if prev_ts >= content_time:
                errors.append("timestamp younger than previous block")
                return False
            if content_time > now_ts():
                errors.append("timestamp in the future")
                return False

        transactions = [tx for tx in transactions if not tx.is_coinbase]
        if sum(len(tx.hex()) for tx in transactions) > MAX_BLOCK_SIZE_HEX:
            errors.append("block is too big")
            return False

        # double-spend scan: the fused resident path answers membership
        # from the HBM-resident UTXO index inside the SAME dispatch as
        # the digest prep (zero per-tx host round-trips in steady state)
        # and hands the prepared digests forward; otherwise the serial
        # per-table SQL scan runs first, exactly as before.  Both paths
        # feed the identical verdict (whitelist, dup detect, error
        # strings), so acceptance is byte-identical.
        prep_cache: Optional[Dict[int, tuple]] = None
        if transactions:
            resident = None
            if self.fused_accept and hasattr(self.state, "resident_indexes"):
                resident = self.state.resident_indexes()
            if resident:
                prep_cache, by_table, presence = \
                    await self._fused_accept_scan(transactions, resident)
                if not self._double_spend_verdict(
                        by_table, presence, block_no, errors):
                    return False
            elif not await self._check_block_double_spends(
                    transactions, block_no, errors):
                return False

        # pipelined verify (ISSUE 7 tentpole b/c): the block is split into
        # micro-batches; the fused digest prep (tx decode + txid/digest
        # sha256) of batch N runs on the executor and OVERLAPS the batched
        # P-256 verify of batch N-1, which is already in flight through the
        # shared dispatch front.  Verdicts are only inspected after the
        # full rules loop, so error ordering is byte-identical to the old
        # serial path: a rules failure always surfaces before a signature
        # verdict, and the error strings are unchanged.
        verifier = TxVerifier(
            self.state, is_syncing=self.is_syncing,
            verify_pad_block=self.verify_pad_block,
            verify_device_timeout=self.verify_device_timeout,
            verify_mesh_devices=self.verify_mesh_devices)
        front = get_front()
        loop = asyncio.get_event_loop()
        mb = self.verify_microbatch or len(transactions) or 1
        dispatches: List[asyncio.Future] = []
        n_checks = 0
        decode_busy = 0.0  # telemetry accumulator  # upowlint: disable=CP001
        t_wall = time.perf_counter()
        failed_tx: Optional[Tx] = None
        for start in range(0, len(transactions), mb):
            chunk = transactions[start:start + mb]
            t0 = time.perf_counter()
            if prep_cache is not None:
                # fused accept path already hashed the whole block during
                # the membership scan — phase 2 is pure rules + sig
                prep = prep_cache
            else:
                prep = await loop.run_in_executor(None, functools.partial(
                    _fused_digest_prep, chunk, self.txid_backend,
                    self.txid_min_batch))
            chunk_checks: List[tuple] = []
            for tx in chunk:
                if not await verifier.rules_ok(tx, check_double_spend=False):
                    failed_tx = tx
                    break
                checks = await verifier.collect_sig_checks(
                    tx, digests=prep.get(id(tx)))
                if checks is None:
                    failed_tx = tx
                    break
                chunk_checks.extend(checks)
            decode_busy += time.perf_counter() - t0
            if failed_tx is not None:
                break
            n_checks += len(chunk_checks)
            if chunk_checks:
                # dispatch_fn resolves run_sig_checks_async through THIS
                # module's globals so the long-standing patch seam
                # (tests monkeypatch block.run_sig_checks_async) keeps
                # intercepting the block path behind the shared front;
                # when the seam is pristine the front forwards the
                # group to the device runtime (source="block", weight 4
                # — a saturating miner stream cannot starve this)
                dispatches.append(asyncio.ensure_future(front.submit(
                    chunk_checks, backend=self.sig_backend,
                    pad_block=self.verify_pad_block,
                    device_timeout=self.verify_device_timeout,
                    mesh_devices=self.verify_mesh_devices,
                    precomputed=self.page_sig_verdicts, source="block",
                    dispatch_fn=run_sig_checks_async)))
        if failed_tx is not None:
            for d in dispatches:
                d.cancel()
            await asyncio.gather(*dispatches, return_exceptions=True)
            errors.append(
                f"transaction {failed_tx.hash()} has been not verified")
            return False
        t_tail = time.perf_counter()
        with span("block.sig_verify", n=n_checks,
                  micro_batches=len(dispatches)):
            results = await asyncio.gather(*dispatches)
        wall = time.perf_counter() - t_wall
        ktel.record_stage("block_decode", decode_busy,
                          items=len(transactions), wall=wall)
        ktel.record_stage("block_sig_wait", time.perf_counter() - t_tail,
                          items=n_checks, wall=wall)
        if not all(all(r) for r in results):
            errors.append("signature verification failed")
            return False

        computed_merkle = merkle_root(transactions)
        if merkle_tree != computed_merkle:
            if (block_no, merkle_tree) == MERKLE_EXCEPTION:
                return True
            errors.append("merkle tree does not match")
            return False
        return True

    @staticmethod
    def _inputs_by_table(transactions: Sequence[Tx]) -> dict:
        """Group every input outpoint by the UTXO-class table it spends
        from, in tx order (reference database.py:589-622 partitioning)."""
        by_table: dict = {}
        for tx in transactions:
            table = _INPUT_TABLE.get(tx.transaction_type, "unspent_outputs")
            by_table.setdefault(table, []).extend(i.outpoint for i in tx.inputs)
        return by_table

    @staticmethod
    def _double_spend_verdict(by_table: dict, presence: dict,
                              block_no: int, errors: list) -> bool:
        """Shared verdict over per-table membership flags: missing set,
        in-block duplicate detect, and the historical whitelist — error
        strings identical on the SQL and fused resident paths
        (manager.py:469-615)."""
        for table, outpoints in by_table.items():
            present = presence[table]
            missing = {o for o, ok in zip(outpoints, present) if not ok}
            has_dup = len(set(outpoints)) != len(outpoints)
            if not missing and not has_dup:
                continue
            if table == "unspent_outputs" and block_no in DOUBLE_SPEND_WHITELIST:
                allowed = set(map(tuple, DOUBLE_SPEND_WHITELIST[block_no]))
                if missing - allowed == set():
                    continue
            errors.append(f"double spend in block: {block_no} ({table})")
            return False
        return True

    async def _check_block_double_spends(self, transactions: Sequence[Tx],
                                         block_no: int, errors: list) -> bool:
        """Per-class outpoint set-diff vs the six UTXO tables
        (manager.py:469-615), with the historical whitelist."""
        by_table = self._inputs_by_table(transactions)
        presence = {
            table: await self.state.outpoints_exist(outpoints, table)
            for table, outpoints in by_table.items()}
        return self._double_spend_verdict(by_table, presence, block_no, errors)

    async def _fused_accept_scan(self, transactions: Sequence[Tx],
                                 resident: dict) -> tuple:
        """Phase 1 of the HBM-resident accept path: walk the block in
        verify micro-batches and, per batch, run ONE fused runtime
        dispatch doing sha256 digest prep + resident outpoint membership
        (:func:`_fused_digest_prep` with ``probe=``).  Membership for a
        table without a resident index (never the case after
        ``enable_device_index``, but cheap to keep correct) falls back
        to the SQL scan.

        Returns ``(prep_cache, by_table, presence)``: the whole block's
        digest dict for phase 2, plus per-table outpoints and presence
        flags in the same grouping/order the serial scan produces."""
        loop = asyncio.get_event_loop()
        mb = self.verify_microbatch or len(transactions) or 1
        prep_cache: Dict[int, tuple] = {}
        by_table: dict = {}
        presence: dict = {}
        host_tables: dict = {}
        n_probed = 0
        t0 = time.perf_counter()
        for start in range(0, len(transactions), mb):
            chunk = transactions[start:start + mb]
            chunk_tables = self._inputs_by_table(chunk)
            parts = [(table, ops) for table, ops in chunk_tables.items()
                     if ops and table in resident]
            prep, probe_results = await loop.run_in_executor(
                None, functools.partial(
                    _fused_digest_prep, chunk, self.txid_backend,
                    self.txid_min_batch,
                    probe=[(resident[t], ops) for t, ops in parts]))
            prep_cache.update(prep)
            for (table, ops), (present, _amounts, _consults) in zip(
                    parts, probe_results):
                by_table.setdefault(table, []).extend(ops)
                presence.setdefault(table, []).extend(
                    bool(p) for p in present)
                n_probed += len(ops)
            for table, ops in chunk_tables.items():
                if ops and table not in resident:
                    host_tables.setdefault(table, []).extend(ops)
        for table, ops in host_tables.items():
            by_table.setdefault(table, []).extend(ops)
            presence.setdefault(table, []).extend(
                await self.state.outpoints_exist(ops, table))
        ktel.record_stage("accept_probe", time.perf_counter() - t0,
                          items=n_probed)
        return prep_cache, by_table, presence

    # ------------------------------------------------------ create_block --

    async def create_block(self, block_content: str, transactions: List[Tx],
                           last_block: Optional[dict] = None,
                           errors: Optional[list] = None) -> bool:
        """Validate + apply one mined block (manager.py:650-757)."""
        errors = errors if errors is not None else []
        async with self._accept_lock:
            with span("block_accept", level="info", txs=len(transactions)):
                return await self._create_block_timed(
                    block_content, transactions, last_block, errors)

    async def _create_block_timed(self, block_content, transactions,
                                  last_block, errors) -> bool:
        self.invalidate_difficulty()
        difficulty, last_block = await self.calculate_difficulty()
        block_no = (last_block["id"] + 1) if last_block else 1
        if not await self.check_block(block_content, transactions,
                                      (difficulty, last_block), errors):
            return False

        block_hash = hashlib.sha256(bytes.fromhex(block_content)).hexdigest()
        (previous_hash, address, merkle_tree, content_time,
         content_difficulty, nonce) = split_block_content(block_content)

        active_inodes = await self.state.get_active_inodes()
        self._inode_cache = active_inodes
        self._inode_cache_time = time.monotonic()

        block_reward = get_block_reward(block_no)  # int smallest units
        miner_reward_dec, inode_rewards_dec = get_inode_rewards(
            Decimal(block_reward) / SMALLEST, active_inodes, block_no=block_no)

        # genesis-key / emission gate (manager.py:679-689)
        genesis = await self.state.get_block_by_id(1)
        if genesis is not None:
            _, genesis_address, _, _, _, _ = split_block_content(genesis["content"])
            if address == genesis_address and block_no <= LAST_BLOCK_FOR_GENESIS_KEY:
                pass
            elif inode_rewards_dec:
                pass
            else:
                errors.append("Emission detail is not formed. "
                              "Hence you cannot mine currently.")
                return False

        fees = 0
        for tx in transactions:
            fees += await self.state.tx_fees(tx)

        miner_amount = int(miner_reward_dec * SMALLEST) + fees
        coinbase = CoinbaseTx(block_hash, address, miner_amount)
        for inode_address, reward_dec in inode_rewards_dec.items():
            coinbase.outputs.append(
                TxOutput(inode_address, int(reward_dec * SMALLEST)))
        if not all(o.verify() for o in coinbase.outputs):
            errors.append("invalid coinbase outputs")
            return False

        with span("block.utxo_apply", txs=len(transactions)):
            async with self.state.atomic():
                await self.state.add_block(
                    block_no, block_hash, block_content, address, nonce,
                    difficulty, block_reward + fees, content_time)
                await self.state.add_transaction(coinbase, block_hash)
                await self.state.add_transactions(transactions, block_hash)
                await self.state.add_transaction_outputs(
                    list(transactions) + [coinbase])
                if transactions:
                    await self.state.remove_pending_transactions_by_hash(
                        [tx.hash() for tx in transactions])
                    await self.state.remove_outputs(transactions)
        # outside the atomic block: the pool must only drop entries for
        # a COMMITTED acceptance
        with span("block.mempool_remove"):
            self._notify_pending_removed(
                [tx.hash() for tx in transactions])
        self._notify_committed()
        # first-seen stamp for the fleet propagation tracker: emitted
        # once per node per committed block (timed accept path)
        event("block_seen", hash=block_hash, height=block_no)

        if block_no % 10 == 0:
            fingerprint = await self.state.get_unspent_outputs_hash()
            import logging

            logging.getLogger("upow_tpu").info(
                "unspent_outputs_hash on block no. %s: %s", block_no, fingerprint)
        self.invalidate_difficulty()

        # emission audit sidecar (manager.py:741-753)
        self.state.record_emission(block_no, [
            {
                "power": str(i["power"]),
                "emission": str(i["emission"]),
                "wallet": i["wallet"],
                "inode_reward": str(inode_rewards_dec.get(i["wallet"], "")),
            }
            for i in active_inodes
        ])
        return True

    async def create_block_syncing(self, block_content: str,
                                   transactions: List[Tx],
                                   coinbase: CoinbaseTx,
                                   errors: Optional[list] = None) -> bool:
        """Sync-time accept: trusts the embedded coinbase, skips the
        emission gate, still runs full check_block (manager.py:760-835)."""
        errors = errors if errors is not None else []
        async with self._accept_lock:
            return await self._create_block_syncing_locked(
                block_content, transactions, coinbase, errors)

    async def _create_block_syncing_locked(self, block_content, transactions,
                                           coinbase, errors) -> bool:
        self.invalidate_difficulty()
        difficulty, last_block = await self.calculate_difficulty()
        block_no = (last_block["id"] + 1) if last_block else 1
        was_syncing = self.is_syncing
        self.is_syncing = True
        try:
            if not await self.check_block(block_content, transactions,
                                          (difficulty, last_block), errors):
                return False
        finally:
            self.is_syncing = was_syncing

        block_hash = hashlib.sha256(bytes.fromhex(block_content)).hexdigest()
        (previous_hash, address, merkle_tree, content_time,
         content_difficulty, nonce) = split_block_content(block_content)
        block_reward = get_block_reward(block_no)
        fees = 0
        for tx in transactions:
            fees += await self.state.tx_fees(tx)
        if not all(o.verify() for o in coinbase.outputs):
            errors.append("invalid coinbase outputs")
            return False

        with span("block.utxo_apply", txs=len(transactions)):
            async with self.state.atomic():
                await self.state.add_block(
                    block_no, block_hash, block_content, address, nonce,
                    difficulty, block_reward + fees, content_time)
                await self.state.add_transaction(coinbase, block_hash)
                await self.state.add_transactions(transactions, block_hash)
                await self.state.add_transaction_outputs(
                    list(transactions) + [coinbase])
                if transactions:
                    await self.state.remove_pending_transactions_by_hash(
                        [tx.hash() for tx in transactions])
                    await self.state.remove_outputs(transactions)
        with span("block.mempool_remove"):
            self._notify_pending_removed(
                [tx.hash() for tx in transactions])
        self._notify_committed()
        # first-seen stamp, sync-accept path (same semantics as timed)
        event("block_seen", hash=block_hash, height=block_no)
        self.invalidate_difficulty()
        return True

    # --------------------------------------------------------- mempool GC --

    async def clear_pending_transactions(self) -> None:
        """Evict mempool entries whose inputs are gone or double-used
        (manager.py:253-349).  Deliberate divergences — the mempool is
        node-local, not consensus, so eviction SELECTION may differ:

        * no unbounded recursion (the reference re-enters itself after
          every single eviction);
        * when EVERY checked input of a class is missing, the reference
          wipes the ENTIRE mempool (verify_outputs' unfiltered
          remove_pending_transactions, manager.py:336-338) — we evict
          only the affected transactions;
        * the reference removes "by contains" — a hex-substring match of
          outpoint bytes against whole tx hexes (manager.py:343-348),
          which can false-positive on an unrelated tx whose serialized
          bytes happen to contain the pattern — we match exact tx
          hashes."""
        while True:
            txs = await self.state.get_pending_transactions_limit(hex_only=False)
            used: set = set()
            evicted = False
            by_table: dict = {}
            for tx in txs:
                outpoints = [i.outpoint for i in tx.inputs]
                if any(o in used for o in outpoints):
                    await self.state.remove_pending_transactions_by_hash([tx.hash()])
                    self._notify_pending_removed([tx.hash()])
                    evicted = True
                    break
                used.update(outpoints)
                table = _INPUT_TABLE.get(tx.transaction_type, "unspent_outputs")
                by_table.setdefault(table, {})[tx.hash()] = outpoints
            if evicted:
                continue
            for table, tx_map in by_table.items():
                all_outpoints = [o for ops in tx_map.values() for o in ops]
                present = await self.state.outpoints_exist(all_outpoints, table)
                missing = {o for o, ok in zip(all_outpoints, present) if not ok}
                if not missing:
                    continue
                doomed = [h for h, ops in tx_map.items()
                          if any(o in missing for o in ops)]
                await self.state.remove_pending_transactions_by_hash(doomed)
                self._notify_pending_removed(doomed)
            return
