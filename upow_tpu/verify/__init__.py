"""Block + transaction validation with TPU-batched signature verify."""

from .block import BlockManager, DOUBLE_SPEND_WHITELIST, MERKLE_EXCEPTION
from .txverify import (TxVerifier, run_sig_checks,
                       run_sig_checks_async)
