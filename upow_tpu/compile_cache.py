"""Persistent XLA compile cache, keyed per host fingerprint.

JAX's persistent cache stores XLA:CPU AOT executables whose code is
specialised to the *compiling* machine's CPU features.  When the cache
directory is shared between machines (this repo's ``.jax_cache`` travels
with the checkout), loading an entry produced by a host with a different
feature set logs ``cpu_aot_loader`` feature-mismatch errors and can run
miscompiled code (observed: an execution that never completes).  Keying
the directory by a host fingerprint keeps reruns on the same machine
instant while making foreign entries invisible.

Known cosmetic residue: this XLA build's AOT loader compares the
compile-time LLVM feature string — which includes derived *tuning*
preferences (``+prefer-no-gather``/``+prefer-no-scatter``) — against a
host probe that never reports tuning prefs, so reloading an entry
compiled BY THIS SAME HOST still logs a two-feature mismatch warning
(verified 2026-08-01: cold-compile then warm-reload in one session,
same dir, warnings present, results correct).  Genuine cross-host
divergence is what the fingerprint prevents; the warning text alone is
not evidence of it.
"""

from __future__ import annotations

import hashlib
import logging
import os
import platform


_FP_CACHE = None


def _gcc_native_march() -> str:
    """GCC's CPUID-based microarch detection (``-march=native``
    expansion).  Virtualized /proc/cpuinfo is often generic and
    identical across different physical hosts, while the LLVM tuning
    features XLA:CPU AOT code is specialised to (e.g.
    ``prefer-no-gather``) come from raw CPUID — two hosts with the same
    cpuinfo can still produce incompatible AOT entries (observed: a VM
    migration flagged feature mismatches under an unchanged cpuinfo
    fingerprint).  GCC reads the same CPUID, so its expansion
    distinguishes those hosts."""
    import subprocess

    try:
        out = subprocess.run(
            ["gcc", "-march=native", "-E", "-v", "-"],
            stdin=subprocess.DEVNULL, capture_output=True, text=True,
            timeout=15)
        for line in (out.stderr + out.stdout).splitlines():
            if "-march=" in line:
                return line[line.index("-march="):].strip()
    except Exception as e:
        logging.getLogger("upow_tpu.compile_cache").debug(
            "gcc -march=native probe failed: %s", e)
    return "gcc-unavailable"


def host_fingerprint() -> str:
    """Stable per-machine tag: arch + CPU flags + microarch identity
    (family/model/stepping/microcode) + GCC's CPUID-detected feature
    expansion.  'fpv2' orphans pre-round-4 dirs whose entries may have
    been produced by a cpuinfo-identical but tuning-different host."""
    global _FP_CACHE
    if _FP_CACHE is not None:
        return _FP_CACHE
    bits = ["fpv2", platform.machine()]
    try:
        seen = set()
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                # one of each: the FLAGS are what the AOT cache entries
                # are specialised to; family/model/stepping/microcode
                # pin the microarch even when the model name is generic
                if key in ("flags", "Features", "model name", "vendor_id",
                           "cpu family", "model", "stepping",
                           "microcode") and key not in seen:
                    seen.add(key)
                    bits.append(line.strip())
    except OSError:
        bits.append(platform.processor() or "unknown")
    bits.append(_gcc_native_march())
    _FP_CACHE = hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]
    return _FP_CACHE


def evict_host_dir(cache_root: str) -> None:
    """Delete this host's cache subdir (the layout twin of
    :func:`enable`) — for recovery when a cached AOT entry miscomputes
    or hangs (e.g. CPU features changed under the same fingerprint
    after a VM migration)."""
    import shutil

    shutil.rmtree(os.path.join(cache_root, host_fingerprint()),
                  ignore_errors=True)


_enabled_dir = ""  # set by enable(); read by entry_count() for /metrics


def enable(cache_root: str) -> str:
    """Point JAX's persistent compile cache at a per-host subdir of
    ``cache_root``.  Never raises; returns the directory used ('' on
    failure)."""
    import jax

    global _enabled_dir
    path = os.path.join(cache_root, host_fingerprint())
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
        _enabled_dir = path
        return path
    except Exception as e:
        logging.getLogger("upow_tpu.compile_cache").warning(
            "could not enable persistent compile cache at %s: %s", path, e)
        return ""


def entry_count() -> int:
    """Entries in the enabled persistent cache dir (-1 when disabled).

    Operational gauge only — complements the in-process jit hit/miss
    counters in telemetry.device, which cover the (far hotter) traced-
    program reuse inside one process lifetime."""
    if not _enabled_dir:
        return -1
    try:
        return len(os.listdir(_enabled_dir))
    except OSError:
        return 0


# --- cpu_aot_loader warning triage ---------------------------------------

# The tuning-pref residue documented at the top of this module: reloads
# of entries compiled BY THIS HOST still mismatch on exactly these two
# derived preferences, because the host probe never reports them.
COSMETIC_TUNING_PREFS = frozenset(
    {"+prefer-no-gather", "+prefer-no-scatter"})

_AOT_MISMATCH = None  # compiled lazily (re import at module top is avoided)


def aot_mismatch_features(stderr_text: str) -> set:
    """Features named by ``cpu_aot_loader`` 'Target machine feature X is
    not supported on the host machine' lines in ``stderr_text``."""
    global _AOT_MISMATCH
    if _AOT_MISMATCH is None:
        import re

        _AOT_MISMATCH = re.compile(
            r"Target machine feature\s+(\S+)\s+is\s+not\s+supported")
    return set(_AOT_MISMATCH.findall(stderr_text))


def foreign_aot_mismatches(stderr_text: str) -> set:
    """Mismatched features BEYOND the documented cosmetic pair — a
    non-empty result means the loaded AOT entry really was compiled for
    a different machine (the thing the host fingerprint exists to
    prevent) and the host cache dir should be evicted, even if the run
    happened to exit 0."""
    return aot_mismatch_features(stderr_text) - COSMETIC_TUNING_PREFS
