"""Persistent XLA compile cache, keyed per host fingerprint.

JAX's persistent cache stores XLA:CPU AOT executables whose code is
specialised to the *compiling* machine's CPU features.  When the cache
directory is shared between machines (this repo's ``.jax_cache`` travels
with the checkout), loading an entry produced by a host with a different
feature set logs ``cpu_aot_loader`` feature-mismatch errors and can run
miscompiled code (observed: an execution that never completes).  Keying
the directory by a host fingerprint keeps reruns on the same machine
instant while making foreign entries invisible.
"""

from __future__ import annotations

import hashlib
import os
import platform


def host_fingerprint() -> str:
    """Stable per-machine tag: arch + CPU flag set (+ model name)."""
    bits = [platform.machine()]
    try:
        seen = set()
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip()
                # one of each: the FLAGS are what the AOT cache entries
                # are specialised to; model name disambiguates further
                if key in ("flags", "Features", "model name") and key not in seen:
                    seen.add(key)
                    bits.append(line.strip())
    except OSError:
        bits.append(platform.processor() or "unknown")
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def evict_host_dir(cache_root: str) -> None:
    """Delete this host's cache subdir (the layout twin of
    :func:`enable`) — for recovery when a cached AOT entry miscomputes
    or hangs (e.g. CPU features changed under the same fingerprint
    after a VM migration)."""
    import shutil

    shutil.rmtree(os.path.join(cache_root, host_fingerprint()),
                  ignore_errors=True)


def enable(cache_root: str) -> str:
    """Point JAX's persistent compile cache at a per-host subdir of
    ``cache_root``.  Never raises; returns the directory used ('' on
    failure)."""
    import jax

    path = os.path.join(cache_root, host_fingerprint())
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
        return path
    except Exception:
        return ""
