"""TPU-first mining: template → batched device nonce search → push_block."""

from .engine import MiningJob, MineResult, mine, NONCE_SPACE
