"""Persistent mesh-sharded nonce search: one resident SPMD program.

The single-device dispatcher in :mod:`.engine` recompiles nothing per
round but holds no mesh: on a v5e-8 seven chips idle while one scans.
This module owns the multi-device path:

* **One compiled program** — ``parallel.mesh._pow_search_mesh_resident``
  is jitted once per (batch_per_device, nonce_spec, mesh) at arm time
  (AOT-warmed by the device runtime alongside the probe kernels).  Every
  job-specific field — midstate, tail words, per-shard ranges, packed
  target — rides as runtime data, so a new job or chain-tip change is a
  pure dispatch: zero recompilation, asserted by the ``mine_mesh``
  compile-cache counters.
* **Disjoint shard ranges** — each round's [start, start+count) window
  is split across the mesh with :func:`parallel.mesh.shard_bounds`; the
  per-round plan is retained in the dispatch accounting so tests (and
  operators) can prove disjoint, exact coverage.  A ``pmin`` collective
  reduces per-shard hits to the global winner on device.
* **Single dispatch owner** — every dispatch goes through
  ``device/runtime.py`` ``submit_call`` under the weighted-fair source
  "mine", so mining rounds co-reside with block verify and mempool
  coalescing instead of racing them for the chip.
* **Structured arm ladder** — :meth:`MeshEngine.arm` walks runtime →
  scrubbed-env re-arm → child probe, capturing each attempt's actual
  exception text and traceback fingerprint (no more opaque
  "hung/failed"); the ladder lands in ``stats()`` and, via bench.py /
  tpu_watch.py, in ``.bench_events.jsonl``.

Multi-host runs split the nonce space first via
``parallel.multihost.plan_nonce_ranges`` (each process mines its own
planned range through this engine), then shard within the process's
mesh — DCN never sees the hot loop.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..crypto import sha256 as sha_kernel
from ..telemetry import device as _ktel

log = logging.getLogger("upow.mine.mesh")

#: rounds of per-shard range accounting retained (oldest dropped);
#: totals keep counting past the window
ACCOUNTING_WINDOW = 4096

#: wall-clock budget for one child-probe arm attempt
_CHILD_PROBE_TIMEOUT = 60.0


def _arm_attempt(name: str, ok: bool, seconds: float,
                 error: Optional[BaseException] = None,
                 detail: Optional[str] = None) -> dict:
    """One rung of the arm ladder, with the real failure text captured."""
    from ..benchutil import traceback_fingerprint

    rec = {"attempt": name, "ok": bool(ok), "seconds": round(seconds, 3)}
    if error is not None:
        rec["error"] = repr(error)
        rec["traceback_fingerprint"] = traceback_fingerprint(error)
    elif detail is not None and not ok:
        rec["error"] = detail
    elif detail is not None:
        rec["detail"] = detail
    return rec


def _child_probe(timeout: float = _CHILD_PROBE_TIMEOUT) -> dict:
    """Out-of-process backend probe for the last arm-ladder rung.

    Runs ``jax.devices()`` in a child with the parent's env and captures
    the child's stderr — when the in-process attempts died without a
    Python exception (native hang, SIGKILL by the backend), the child's
    stderr text is the only diagnostic there is.
    """
    import subprocess
    import sys

    from ..benchutil import text_fingerprint

    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM=' + d[0].platform + ' COUNT=' + str(len(d)))")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired:
        return _arm_attempt(
            "child-probe", False, time.perf_counter() - t0,
            detail=f"child probe hung past {timeout:.0f}s (backend init "
                   "never returned in a fresh process either)")
    dt = time.perf_counter() - t0
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return _arm_attempt(
                "child-probe", True, dt,
                detail=line.strip() + " (child sees the backend; parent "
                "process state is the blocker)")
    tail = (proc.stderr or "").strip().splitlines()[-6:]
    detail = (f"child probe rc={proc.returncode}; stderr tail: "
              + (" | ".join(tail) if tail else "<empty>"))
    rec = _arm_attempt("child-probe", False, dt, detail=detail)
    if tail:
        rec["traceback_fingerprint"] = text_fingerprint("\n".join(tail))
    return rec


class MeshEngine:
    """A resident, mesh-sharded nonce-search service.

    Lifecycle: construct (cheap) → :meth:`arm` (compiles the resident
    program once) → :meth:`set_job` / :meth:`dispatch` per round (pure
    dispatches).  :func:`get_mesh_engine` keeps one engine per process so
    the compiled program survives across jobs and block templates.
    """

    def __init__(self, mesh_devices: int = 0,
                 batch_per_device: Optional[int] = None,
                 round_hint: Optional[int] = None):
        self._mesh_devices = int(mesh_devices)
        self._batch_per_device = batch_per_device
        self._round_hint = round_hint
        self._mesh = None
        self._n_dev = 0
        self._armed = False
        self.arm_ladder: List[dict] = []
        self.arm_failure_reason: Optional[str] = None
        self._job_key: Optional[tuple] = None
        self._job_arrays = None
        self._nonce_spec = None
        self._job_t0 = 0.0
        self._rounds: List[dict] = []
        self._dispatches = 0
        self._nonces_planned = 0

    # ------------------------------------------------------------- arm ---

    @property
    def armed(self) -> bool:
        return self._armed

    @property
    def n_devices(self) -> int:
        return self._n_dev

    @property
    def batch_per_device(self) -> int:
        return int(self._batch_per_device or 0)

    @property
    def capacity(self) -> int:
        """Max nonces a single dispatch can cover (n_dev * batch)."""
        return self._n_dev * self.batch_per_device

    def arm(self, timeout: Optional[float] = None) -> dict:
        """Arm the runtime and compile the resident program, walking the
        structured retry ladder: runtime → scrubbed-env re-arm → child
        probe.  Each rung records its actual exception text; the ladder
        is kept on the engine (and returned) so callers can log or emit
        it verbatim instead of a generic "hung/failed"."""
        if self._armed:
            return {"armed": True, "ladder": self.arm_ladder,
                    "devices": self._n_dev}
        from ..config import DeviceRuntimeConfig
        from ..device.runtime import get_runtime

        runtime = get_runtime()
        timeout = timeout if timeout is not None else \
            DeviceRuntimeConfig.from_env().arm_timeout
        ladder: List[dict] = []
        for name, kwargs in (
                ("runtime", {}),
                ("runtime-scrubbed-env", {"scrub_env": True, "force": True})):
            t0 = time.perf_counter()
            try:
                runtime.arm(deadline=timeout, attempt=name, **kwargs)
                if runtime.platform() is None:
                    info = runtime.stats().get("arm", {})
                    ladder.append(_arm_attempt(
                        name, False, time.perf_counter() - t0,
                        detail=info.get("arm_failure_reason")
                        or "backend probe returned no platform"))
                    continue
                self._build_mesh_and_warm(via_runtime=True)
                ladder.append(_arm_attempt(
                    name, True, time.perf_counter() - t0,
                    detail=f"{runtime.platform()} x{self._n_dev}"))
                self._armed = True
                break
            except Exception as e:
                log.debug("mesh arm attempt %s failed", name, exc_info=True)
                ladder.append(_arm_attempt(
                    name, False, time.perf_counter() - t0, error=e))
        if not self._armed:
            ladder.append(_child_probe())
        self.arm_ladder = ladder
        if not self._armed:
            self.arm_failure_reason = "; ".join(
                f"{r['attempt']}: {r.get('error') or r.get('detail', '?')}"
                for r in ladder)
        else:
            self.arm_failure_reason = None
        return {"armed": self._armed, "ladder": ladder,
                "devices": self._n_dev,
                "arm_failure_reason": self.arm_failure_reason}

    def _build_mesh_and_warm(self, via_runtime: bool) -> None:
        """Build the dp mesh from the armed runtime's device view and
        compile the resident program with an all-invalid dummy dispatch.

        ``via_runtime=False`` is for the runtime's own AOT-warm hook,
        which runs adjacent to the drainer — a nested submit_call there
        would deadlock the single drainer thread."""
        from ..config import DeviceConfig, _apply_env_fields
        from ..device.runtime import get_runtime
        from ..parallel.mesh import make_mesh, pow_search_resident

        runtime = get_runtime()
        devices = runtime.devices()
        if not devices:
            raise RuntimeError("runtime armed but exposes no devices")
        if self._mesh_devices:
            devices = devices[: self._mesh_devices]
        self._n_dev = len(devices)
        self._mesh = make_mesh(devices)
        if self._batch_per_device is None:
            if self._round_hint:
                # ceil: one round of round_hint nonces must fit capacity
                self._batch_per_device = max(
                    1, (int(self._round_hint) + self._n_dev - 1)
                    // self._n_dev)
            else:
                cfg = DeviceConfig()
                _apply_env_fields(cfg, "device")
                self._batch_per_device = max(
                    1, cfg.search_batch // self._n_dev)
        # dummy template: zero midstate/tail/target, every shard empty
        # (base == limit == 0) — compiles the exact program real jobs
        # dispatch, costs one masked-out round of hashing
        import jax.numpy as jnp

        spec = sha_kernel.make_template(bytes(104)).nonce_spec
        zeros8 = jnp.zeros(8, jnp.uint32)
        zeros16 = jnp.zeros(16, jnp.uint32)
        zn = jnp.zeros(self._n_dev, jnp.uint32)
        zt = jnp.zeros(7, jnp.uint32)

        def warm():
            return int(pow_search_resident(
                zeros8, zeros16, zn, zn, zt,
                self._batch_per_device, spec, self._mesh))

        if via_runtime:
            runtime.submit_call(
                warm, kernel="sha256_search_mesh", source="mine").result()
        else:
            warm()

    # ------------------------------------------------------------- job ---

    def set_job(self, job) -> None:
        """Load a :class:`..mine.engine.MiningJob`: host-side midstate +
        packed target become device arrays; the resident program is NOT
        recompiled (all job fields are traced arguments)."""
        import jax.numpy as jnp

        key = (job.prefix, job.previous_hash, str(job.difficulty))
        if self._job_key == key:
            return
        template = sha_kernel.make_template(job.prefix)
        spec = sha_kernel.target_spec(job.previous_hash, job.difficulty)
        self._job_arrays = (
            jnp.asarray(template.midstate),
            jnp.asarray(template.tail_words),
            jnp.asarray(sha_kernel.pack_target(spec)),
        )
        self._nonce_spec = template.nonce_spec
        self._job_key = key
        self._job_t0 = time.perf_counter()

    # -------------------------------------------------------- dispatch ---

    def plan_round(self, start: int, count: int) -> List[Tuple[int, int]]:
        """Disjoint per-shard [lo, hi) plan for one round via
        :func:`parallel.mesh.shard_bounds` — also what the accounting
        records, so the test oracle and the dispatch share one source."""
        from ..parallel.mesh import shard_bounds

        return [shard_bounds(start, start + count, i, self._n_dev)
                for i in range(self._n_dev)]

    def dispatch(self, start: int, count: int):
        """Scan [start, start+count) across the mesh; returns the async
        device handle (``int()`` blocks and yields min hit or SENTINEL).

        ``count`` must fit one round (<= :attr:`capacity`); the caller's
        loop (engine.mine) sizes rounds accordingly."""
        if not self._armed:
            raise RuntimeError("MeshEngine.dispatch before arm()")
        if self._job_arrays is None:
            raise RuntimeError("MeshEngine.dispatch before set_job()")
        if count <= 0 or count > self.capacity:
            raise ValueError(
                f"round of {count} nonces does not fit capacity "
                f"{self.capacity} ({self._n_dev} shards x "
                f"{self.batch_per_device})")
        from ..device.runtime import get_runtime
        from ..parallel.mesh import pow_search_resident

        shards = self.plan_round(start, count)
        bases = np.array([lo for lo, _ in shards], dtype=np.uint32)
        limits = np.array([hi for _, hi in shards], dtype=np.uint32)
        self._dispatches += 1
        self._nonces_planned += count
        self._rounds.append(
            {"round": self._dispatches, "lo": start, "hi": start + count,
             "shards": shards})
        if len(self._rounds) > ACCOUNTING_WINDOW:
            del self._rounds[0]
        mid, tail, target = self._job_arrays
        nonce_spec, batch, mesh = self._nonce_spec, self._batch_per_device, self._mesh
        _ktel.record_mine_round(
            [hi - lo for lo, hi in shards], batch,
            compile_key=(batch, self._n_dev, nonce_spec))
        runtime = get_runtime()
        return runtime.submit_call(
            lambda: pow_search_resident(
                mid, tail, bases, limits, target,
                batch, nonce_spec, mesh),
            kernel="sha256_search_mesh", source="mine").result()

    def dispatcher(self, job) -> Callable:
        """dispatch(start, count) closure for :func:`engine.mine`'s
        pipelined round loop — arms lazily, loads the job, and routes
        every round through the runtime."""
        if not self._armed:
            info = self.arm()
            if not info["armed"]:
                raise RuntimeError(
                    "mesh engine failed to arm: "
                    + (self.arm_failure_reason or "unknown"))
        self.set_job(job)
        return self.dispatch

    def note_hit(self) -> None:
        """Record time-to-hit for the current job (mine.hit_latency)."""
        if self._job_t0:
            _ktel.record_mine_hit(time.perf_counter() - self._job_t0)

    # ----------------------------------------------------------- stats ---

    def stats(self) -> dict:
        return {
            "armed": self._armed,
            "devices": self._n_dev,
            "batch_per_device": self.batch_per_device,
            "capacity": self.capacity,
            "dispatches": self._dispatches,
            "nonces_planned": self._nonces_planned,
            "rounds": list(self._rounds),
            "arm_ladder": list(self.arm_ladder),
            "arm_failure_reason": self.arm_failure_reason,
        }


# one resident engine per process: the whole point is that the compiled
# program outlives jobs, so callers share it rather than re-instantiating
_ENGINE: Optional[MeshEngine] = None


def get_mesh_engine(mesh_devices: int = 0,
                    batch_per_device: Optional[int] = None,
                    round_hint: Optional[int] = None) -> MeshEngine:
    """Process-wide resident engine.

    A mesh-size or per-shard-batch change replaces the engine (those are
    compile keys); everything else — jobs, targets, chain tips — reuses
    the resident program.  ``round_hint`` is the total nonces per round
    the caller intends to dispatch: before arm it sizes the per-shard
    batch; after arm an engine whose capacity no longer fits is replaced
    (one recompile), a smaller hint reuses the resident program.
    """
    global _ENGINE
    eng = _ENGINE
    if eng is not None and eng._mesh_devices == int(mesh_devices):
        if not eng._armed:
            if batch_per_device is not None:
                eng._batch_per_device = int(batch_per_device)
            if round_hint is not None and eng._batch_per_device is None:
                eng._round_hint = max(int(round_hint), eng._round_hint or 0)
            return eng
        fits_batch = (batch_per_device is None
                      or int(batch_per_device) == eng._batch_per_device)
        fits_round = round_hint is None or int(round_hint) <= eng.capacity
        if fits_batch and fits_round:
            return eng
    _ENGINE = MeshEngine(mesh_devices=mesh_devices,
                         batch_per_device=batch_per_device,
                         round_hint=round_hint)
    return _ENGINE


def reset_mesh_engine() -> None:
    """Drop the resident engine (tests)."""
    global _ENGINE
    _ENGINE = None


def engine_stats() -> Optional[dict]:
    """Stats of the resident engine, or None before first use — the
    node's /metrics gauges read this without forcing an arm."""
    return _ENGINE.stats() if _ENGINE is not None else None


def warm_resident_search() -> None:
    """Arm-time AOT hook (device runtime): compile the resident mesh
    program for the default engine when more than one device is visible.
    Called adjacent to the runtime drainer — must NOT submit_call."""
    from ..device.runtime import get_runtime

    if len(get_runtime().devices()) < 2:
        return  # single device: engine.mine's per-device path owns it
    eng = get_mesh_engine()
    if eng._armed:
        return
    eng._build_mesh_and_warm(via_runtime=False)
    eng._armed = True
    eng.arm_ladder = [
        {"attempt": "runtime-aot-warm", "ok": True, "seconds": 0.0,
         "detail": f"warmed at arm x{eng._n_dev}"}]


def planned_range(lo: int = 0, hi: Optional[int] = None) -> Tuple[int, int]:
    """This process's nonce range under the deterministic multi-host
    plan (``multihost.plan_nonce_ranges``) — the mesh shards within it."""
    from ..mine.engine import NONCE_SPACE
    from ..parallel import multihost

    return multihost.my_nonce_range(lo, NONCE_SPACE if hi is None else hi)
