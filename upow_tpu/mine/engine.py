"""Mining engine: backend-abstracted nonce search over a block template.

The reference mines with N Python processes striding the nonce space and
hashing one candidate at a time (miner.py:83-98, ~0.1-1 Mh/s per core).
Here a template compiles once into a device program that tests a whole
batch per dispatch — fixed-size rounds (XLA wants static shapes; the 90 s
template TTL maps to a wall-clock budget checked between rounds), with the
host polling the round result for an early exit.

Backends:
    pallas  — Pallas TPU kernel (production path on TPU)
    jnp     — pure jax.numpy/XLA (any device; also the CPU-mesh test path)
    native  — C++ midstate loop via ctypes (fast host fallback)
    python  — hashlib loop (reference-shaped, last resort / oracle)
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from decimal import Decimal
from typing import Callable, Optional

from ..core.difficulty import check_pow_hash, pow_target
from ..core.header import BlockHeader
from ..crypto import sha256 as sha_kernel

NONCE_SPACE = 1 << 32
# Device searchers reserve 0xFFFFFFFF as the no-hit sentinel of their
# min-reduction (crypto/sha256.py SENTINEL), so that one nonce is never
# searched: a hit there would be reported as a miss.  Excluding a single
# candidate out of 2^32 costs ~nothing and keeps every backend's contract
# identical.
MAX_SEARCH_END = NONCE_SPACE - 1


@dataclass
class MiningJob:
    """One immutable search job: a fully-built header prefix + PoW target."""

    prefix: bytes           # header minus the 4-byte nonce
    previous_hash: str
    difficulty: Decimal

    @classmethod
    def from_header_fields(cls, previous_hash: str, address: str,
                           merkle_root: str, timestamp: int,
                           difficulty) -> "MiningJob":
        difficulty = Decimal(str(difficulty))
        header = BlockHeader(
            previous_hash=previous_hash,
            address=address,
            merkle_root=merkle_root,
            timestamp=timestamp,
            difficulty_x10=int(difficulty * 10),
            nonce=0,
        )
        return cls(header.prefix_bytes(), previous_hash, difficulty)

    def block_content(self, nonce: int) -> str:
        return (self.prefix + nonce.to_bytes(4, "little")).hex()

    def check(self, nonce: int) -> bool:
        digest = hashlib.sha256(self.prefix + nonce.to_bytes(4, "little")).hexdigest()
        return check_pow_hash(digest, self.previous_hash, self.difficulty)


def _make_dispatcher(job: MiningJob, backend: str,
                     mesh_devices: int = 0,
                     batch: Optional[int] = None) -> Optional[Callable]:
    """For device backends: dispatch(start, count) -> async device handle.

    The handle resolves via ``int()``; keeping several dispatches in
    flight hides the host↔device round-trip (which otherwise caps the
    hash rate — measured ~2x on a tunneled v5e chip).

    ``backend='mesh'`` routes rounds through the resident mesh engine
    (mesh_engine.py): one compiled SPMD program per process whose
    template/target ride as runtime data, each round split across the
    "dp" mesh by shard_bounds with a pmin hit reduction (config
    device.mesh_devices caps the mesh size, 0 = all visible devices).
    ``batch`` is the round size mine() will dispatch — the engine sizes
    its per-shard capacity from it once, at first use."""
    if backend not in ("pallas", "jnp", "mesh"):
        return None
    from ..device.runtime import get_runtime

    runtime = get_runtime()

    def _through_runtime(inner, kernel: str):
        # dispatch ISSUANCE goes through the device owner (so miner
        # rounds interleave fairly with verify/index batches); XLA's
        # async dispatch returns the device handle immediately, and the
        # caller still blocks on int(handle) — the pipelining depth in
        # mine() keeps its overlap
        def dispatch(start: int, count: int):
            return runtime.submit_call(
                lambda: inner(start, count), kernel=kernel,
                source="mine").result()

        return dispatch

    if backend == "mesh":
        from .mesh_engine import get_mesh_engine

        # the engine submits every round through the runtime itself
        # (kernel "sha256_search_mesh", source "mine") and keeps the
        # per-round shard accounting
        engine = get_mesh_engine(mesh_devices=mesh_devices, round_hint=batch)
        return engine.dispatcher(job)
    template = sha_kernel.make_template(job.prefix)
    spec = sha_kernel.target_spec(job.previous_hash, job.difficulty)
    fn = sha_kernel.pow_search_pallas if backend == "pallas" else sha_kernel.pow_search_jnp

    def dispatch(start: int, count: int):
        return fn(template, spec, nonce_base=start, batch=count)

    return _through_runtime(dispatch, "sha256_search")


def _make_searcher(job: MiningJob, backend: str) -> Callable[[int, int], Optional[int]]:
    """Return search(start, count) -> first hit nonce or None."""
    dispatch = _make_dispatcher(job, backend)
    if dispatch is not None:

        def search(start: int, count: int) -> Optional[int]:
            hit = int(dispatch(start, count))
            return None if hit == int(sha_kernel.SENTINEL) else hit

        return search

    if backend == "native":
        from .. import native

        if native.load() is None:
            raise RuntimeError("native backend requested but no C++ toolchain")
        prefix_hex, _, charset = pow_target(job.previous_hash, job.difficulty)

        def search(start: int, count: int) -> Optional[int]:
            return native.pow_search(job.prefix, prefix_hex, charset, start, count)

        return search

    if backend == "python":

        def search(start: int, count: int) -> Optional[int]:
            for n in range(start, start + count):
                if job.check(n):
                    return n
            return None

        return search

    raise ValueError(f"unknown backend {backend!r}")


@dataclass
class MineResult:
    nonce: Optional[int]          # None -> TTL expired
    hashes_tried: int
    elapsed: float

    @property
    def hashrate(self) -> float:
        return self.hashes_tried / self.elapsed if self.elapsed > 0 else 0.0


def mine(job: MiningJob, backend: str = "jnp", *, start: int = 0,
         stride_end: int = NONCE_SPACE, batch: int = 1 << 22,
         ttl: float = 90.0, progress: Optional[Callable] = None,
         mesh_devices: int = 0) -> MineResult:
    """Search [start, stride_end) in fixed rounds until hit or TTL.

    ``start``/``stride_end`` let a coordinator hand disjoint nonce ranges to
    multiple chips/hosts (the reference's worker striding, miner.py:140-148,
    without the per-nonce interleave that would defeat batching).
    """
    stride_end = min(stride_end, MAX_SEARCH_END)
    t0 = time.time()
    tried = 0
    cursor = start

    dispatch = _make_dispatcher(job, backend, mesh_devices=mesh_devices,
                                batch=batch)
    if dispatch is not None:
        # Pipelined device rounds: keep `depth` dispatches in flight so the
        # chip never idles while the host blocks on a result.  A hit wastes
        # at most the in-flight rounds (already dispatched) — negligible
        # against the ~2x throughput the overlap buys on a tunneled chip.
        depth = 2
        inflight = []  # (handle, base, count)
        while cursor < stride_end or inflight:
            while len(inflight) < depth and cursor < stride_end:
                count = min(batch, stride_end - cursor)
                inflight.append((dispatch(cursor, count), cursor, count))
                cursor += count
            handle, _, count = inflight.pop(0)
            hit = int(handle)
            tried += count
            if hit != int(sha_kernel.SENTINEL):
                if job.check(hit):
                    if backend == "mesh":
                        from .mesh_engine import get_mesh_engine

                        get_mesh_engine(mesh_devices=mesh_devices).note_hit()
                    return MineResult(hit, tried, time.time() - t0)
                raise AssertionError(
                    f"backend {backend} returned nonce {hit} failing host check")
            elapsed = time.time() - t0
            if progress is not None:
                progress(tried, elapsed)
            if elapsed > ttl:
                break
        return MineResult(None, tried, time.time() - t0)

    search = _make_searcher(job, backend)
    while cursor < stride_end:
        count = min(batch, stride_end - cursor)
        hit = search(cursor, count)
        tried += count
        if hit is not None:
            # device says hit; host double-checks before shipping (cheap)
            if job.check(hit):
                return MineResult(hit, tried, time.time() - t0)
            raise AssertionError(
                f"backend {backend} returned nonce {hit} failing host check")
        elapsed = time.time() - t0
        if progress is not None:
            progress(tried, elapsed)
        if elapsed > ttl:
            break
        cursor += count
    return MineResult(None, tried, time.time() - t0)
