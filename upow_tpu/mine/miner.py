"""Mining client CLI — wire-compatible with the reference miner.

Usage:
    python -m upow_tpu.mine.miner <address> [--device tpu|cpu|...]
                                  [--node URL] [--batch N] [--ttl S]
                                  [--shard i/k]

Protocol (miner.py:126-156): GET {node}/get_mining_info → build a template
(merkle over ALL pending tx hashes, miner.py:15-18,68), search nonces, POST
{node}/push_block {block_content, txs, block_no}.  The ``--shard i/k`` flag
assigns this process the i-th of k disjoint nonce ranges — the multi-chip /
multi-host scale-out story (each shard is one device or one host; no
communication needed until a hit).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Optional

from ..core.clock import timestamp
from ..core.merkle import miner_merkle_root
from .engine import MiningJob, mine

GENESIS_PREV_HASH = (18_884_643).to_bytes(32, "little").hex()  # miner.py:37-40


def _http_json(url: str, payload: Optional[dict] = None, timeout: float = 20.0) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode() if payload is not None else None,
        headers={"Content-Type": "application/json"} if payload is not None else {},
        method="POST" if payload is not None else "GET",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def fetch_mining_info(node: str) -> dict:
    res = _http_json(node + "get_mining_info")
    if "result" not in res:  # readable node error, not KeyError
        raise RuntimeError(f"node error: {res.get('error', res)!s:.200}")
    return res["result"]


def build_job(info: dict, address: str) -> tuple:
    last_block = dict(info["last_block"])
    last_block.setdefault("hash", GENESIS_PREV_HASH)
    last_block.setdefault("id", 0)
    pending_hashes = info["pending_transactions_hashes"]
    job = MiningJob.from_header_fields(
        previous_hash=last_block["hash"],
        address=address,
        merkle_root=miner_merkle_root(pending_hashes),
        timestamp=timestamp(),
        difficulty=info["difficulty"],
    )
    return job, pending_hashes, last_block["id"] + 1


def push_block(node: str, block_content: str, txs: list, block_no: int) -> dict:
    return _http_json(
        node + "push_block",
        {"block_content": block_content, "txs": txs, "block_no": block_no},
        timeout=20 + len(txs) // 3,
    )


def select_backend(device: str) -> str:
    if device in ("pallas", "jnp", "native", "python", "mesh"):
        return device
    if device == "tpu":
        return "pallas"
    if device == "cpu":
        from .. import native

        return "native" if native.load() is not None else "jnp"
    raise SystemExit(f"unknown device {device!r}")


def _runtime_arm_reason() -> Optional[str]:
    """The device runtime's structured ``arm_failure_reason``, read
    WITHOUT blocking — the watchdog fires precisely when dispatches are
    stuck, so it must not wait on the armed-platform event.  None when
    the runtime never started, armed cleanly, or can't be inspected."""
    try:
        from ..device import runtime as _dr

        rt = _dr._RUNTIME
        if rt is None:
            return None
        return rt._arm_info.get("arm_failure_reason")
    except (ImportError, AttributeError, TypeError):
        return None


#: watchdog exit codes the supervisor decodes in its respawn log
RC_HANG = 3        # stale heartbeat, backend had armed (device hang)
RC_ARM_FAILED = 4  # stale heartbeat AND the runtime recorded an arm failure


def _start_hang_watchdog(heartbeat: dict, limit: float, _exit=None):
    """A device dispatch on a dropped TPU tunnel HANGS (never raises), so
    the in-loop TTL check can never fire.  This thread hard-exits the
    process when the heartbeat goes stale; the supervisor (reference
    miner.py:149-156's outer watchdog) respawns a fresh process — the
    only reliable recovery once a thread is stuck inside the PJRT client.

    When the device runtime recorded a structured arm failure, the exit
    message carries that actual reason (and the exit status becomes
    ``RC_ARM_FAILED``) instead of the generic device-hang guess.

    ``heartbeat['limit']`` (optional) overrides ``limit`` — the caller
    raises it for the first round (cold compile can exceed the steady-
    state budget) and drops it once progress ticks.
    """
    import os
    import threading

    _exit = _exit or os._exit

    def watch():
        while True:
            time.sleep(min(5.0, limit / 4))
            lim = heartbeat.get("limit", limit)
            if time.monotonic() - heartbeat["t"] > lim:
                reason = _runtime_arm_reason()
                if reason:
                    print(f"no mining progress for {lim:.0f}s — backend "
                          f"arm failure: {reason}; exiting for respawn",
                          file=sys.stderr, flush=True)
                    _exit(RC_ARM_FAILED)
                else:
                    print(f"no mining progress for {lim:.0f}s — device "
                          "hang? exiting for respawn",
                          file=sys.stderr, flush=True)
                    _exit(RC_HANG)
                # os._exit never returns; a test's substitute does — stop
                # so the thread doesn't keep printing for the rest of the
                # process lifetime
                return

    t = threading.Thread(target=watch, daemon=True, name="miner-watchdog")
    t.start()
    return t


def run(address: str, node: str, device: str, batch: int, ttl: float,
        shard: tuple = (0, 1), once: bool = False,
        mesh_devices: int = 0, hang_grace: float = 90.0,
        first_round_grace: float = 240.0) -> int:
    backend = select_backend(device)
    i, k = shard
    from ..parallel.multihost import plan_nonce_ranges

    lo, hi = plan_nonce_ranges(k)[i]
    print(f"upow_tpu miner: backend={backend} shard={i}/{k} "
          f"nonces=[{lo}, {hi}) node={node}")
    # first round gets extra headroom: a cold-cache pallas compile can
    # legitimately exceed the steady-state ttl+grace budget
    heartbeat = {"t": time.monotonic(),
                 "limit": ttl + hang_grace + first_round_grace}
    if backend in ("pallas", "jnp", "mesh") and not once:
        _start_hang_watchdog(heartbeat, ttl + hang_grace)
    while True:
        heartbeat["t"] = time.monotonic()
        try:
            info = fetch_mining_info(node)
        except (urllib.error.URLError, OSError, ValueError,
                RuntimeError) as e:
            # RuntimeError carries a node error envelope (syncing,
            # rate-limited) — transient, retry like unreachable
            print(f"node unreachable: {e}; retrying", file=sys.stderr)
            time.sleep(1)
            continue
        job, pending_hashes, block_no = build_job(info, address)
        print(f"difficulty: {info['difficulty']}  block: {block_no}  "
              f"confirming {len(pending_hashes)} transactions")

        def progress(tried, elapsed):
            heartbeat["t"] = time.monotonic()
            heartbeat["limit"] = ttl + hang_grace  # compiled: steady budget
            print(f"{tried / elapsed / 1e6:.2f} MH/s ({tried} hashes)")

        result = mine(job, backend, start=lo, stride_end=hi, batch=batch,
                      ttl=ttl, progress=progress, mesh_devices=mesh_devices)
        if result.nonce is None:
            print(f"template expired after {result.hashes_tried} hashes; refreshing")
            if once:
                return 1
            continue
        content = job.block_content(result.nonce)
        print(f"found nonce {result.nonce} at {result.hashrate / 1e6:.2f} MH/s")
        try:
            reply = push_block(node, content, pending_hashes, block_no)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"push_block failed: {e}", file=sys.stderr)
            reply = {"ok": False}
        print(reply)
        if reply.get("ok"):
            print("BLOCK MINED\n")
        if once:
            return 0 if reply.get("ok") else 1


def _reap(procs, timeout: float = 5.0) -> None:
    import subprocess

    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def _child_cmd(args) -> list:
    """Base child command shared by the supervisor and the worker fan-out
    (one home so new flags cannot silently diverge)."""
    return [sys.executable, "-m", "upow_tpu.mine.miner", args.address,
            "--node", args.node, "--device", args.device,
            "--batch", str(args.batch), "--ttl", str(args.ttl)]


def _supervise(args) -> int:
    """Respawn loop for device-backend miners (reference miner.py:149-156):
    restart the mining child whenever it exits — watchdog hang-exit (rc 3),
    crash, or backend failure — with a short backoff."""
    import os
    import subprocess

    env = dict(os.environ, UPOW_MINER_CHILD="1")
    cmd = _child_cmd(args) + ["--shard", args.shard]
    child = None
    rc_meaning = {
        RC_HANG: "watchdog: device hang (backend had armed)",
        RC_ARM_FAILED: "watchdog: backend arm failure — the child's "
                       "stderr above has the structured reason",
    }
    try:
        while True:
            child = subprocess.Popen(cmd, env=env)
            rc = child.wait()
            if rc == 0:
                return 0
            detail = rc_meaning.get(rc, "crash or backend failure")
            print(f"miner child exited rc={rc} ({detail}); "
                  "respawning in 5s", file=sys.stderr, flush=True)
            child = None
            time.sleep(5)
    except KeyboardInterrupt:
        if child is not None:
            child.terminate()
            try:
                child.wait(timeout=5)
            except subprocess.TimeoutExpired:
                child.kill()
        return 130


def _run_workers(args) -> int:
    """Reference-style multi-process fan-out (miner.py:126-156): worker i
    takes contiguous shard i/N.  CPU-parity path — one process drives a
    whole TPU, so fanning out there would just contend for the chip."""
    import subprocess

    if args.device in ("tpu", "pallas", "mesh"):
        print("workers>1 with --device tpu would have every process fight "
              "over the one chip (libtpu is single-client); use --device "
              "cpu, or shard across hosts with --shard/UPOW_COORDINATOR_"
              "ADDRESS", file=sys.stderr)
        return 2
    import os

    procs = []
    base = _child_cmd(args)
    if args.once:
        base.append("--once")
    # workers are leaf miners: the child marker stops each one becoming a
    # nested supervisor (which would mask failures and orphan grandchildren)
    env = dict(os.environ, UPOW_MINER_CHILD="1")
    for i in range(args.workers):
        procs.append(subprocess.Popen(
            base + ["--shard", f"{i}/{args.workers}"], env=env))
    try:
        while True:
            codes = [p.poll() for p in procs]
            if any(c == 0 for c in codes):
                _reap(procs)  # first finder wins; stop the losers
                return 0
            if all(c is not None for c in codes):
                return max(codes)
            time.sleep(0.2)
    except KeyboardInterrupt:
        _reap(procs)
        return 130


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="uPow TPU miner")
    ap.add_argument("address")
    ap.add_argument("workers", nargs="?", type=int, default=0,
                    help="reference-compatible positional: spawn N host "
                         "processes on disjoint nonce shards "
                         "(miner.py:126-156); 0 = single process")
    ap.add_argument("node_pos", nargs="?", default=None,
                    help="reference-compatible positional node URL")
    ap.add_argument("--node", default="http://localhost:3006/")
    ap.add_argument("--device", default="tpu",
                    help="tpu|cpu or explicit backend "
                         "pallas|jnp|mesh|native|python")
    ap.add_argument("--batch", type=int, default=0,
                    help="nonces per dispatch (0 = config device.search_batch)")
    ap.add_argument("--ttl", type=float, default=90.0)
    ap.add_argument("--shard", default="0/1", help="i/k disjoint nonce-range shard")
    ap.add_argument("--once", action="store_true", help="mine a single template and exit")
    args = ap.parse_args(argv)
    from ..config import Config

    cfg = Config.load()
    if args.batch <= 0:
        args.batch = cfg.device.search_batch
    if args.node_pos:
        args.node = args.node_pos
    if args.workers > 1:
        return _run_workers(args)
    import os

    if (not args.once and select_backend(args.device) in ("pallas", "jnp",
                                                          "mesh")
            and not os.environ.get("UPOW_MINER_CHILD")):
        # device backends run supervised: the hang watchdog hard-exits a
        # child stuck in a dead-tunnel dispatch, and this loop respawns it
        # (the reference's outer watchdog, miner.py:149-156)
        return _supervise(args)
    i, k = (int(x) for x in args.shard.split("/"))
    assert 0 <= i < k, "--shard must be i/k with 0 <= i < k"
    if (i, k) == (0, 1):
        # multi-host run (UPOW_COORDINATOR_ADDRESS set): each process
        # takes its slot in the deterministic nonce plan automatically
        from ..parallel import multihost

        if multihost.initialize():
            import jax

            i, k = jax.process_index(), jax.process_count()
            print(f"distributed mining: process {i}/{k}")
    node = args.node.rstrip("/") + "/"
    return run(args.address, node, args.device, args.batch, args.ttl,
               shard=(i, k), once=args.once,
               mesh_devices=cfg.device.mesh_devices)


if __name__ == "__main__":
    raise SystemExit(main())
