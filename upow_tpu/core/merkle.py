"""Transaction "merkle tree" — actually a flat hash (manager.py:352-378).

root = sha256( concat( sha256(raw_tx) for raw_tx sorted by raw bytes ) )

The ordered variant skips the sort (used by the miner over the hash list
the node hands it, and historically for blocks < 22500).
"""

from __future__ import annotations

import hashlib
from typing import Iterable, List, Union

TxLike = Union[str, "object"]  # hex string or object with .hex()


def _raw(tx: TxLike) -> bytes:
    return bytes.fromhex(tx if isinstance(tx, str) else tx.hex())


def merkle_root(transactions: Iterable[TxLike]) -> str:
    """Sorted-by-raw-bytes flat hash (manager.py:365-378).

    Each leaf is the txid (sha256 of the raw tx), so for tx OBJECTS the
    memoized ``tx.hash()`` is used instead of re-hashing: identical by
    construction (hash() digests the same re-serialized bytes ``_raw``
    yields), it halves host hashing on the sync path, and — critically —
    it makes the header comparison in check_block validate
    device-batched txid seeds against the honest peer's root.  A
    corrupted device digest that slips past the integrity sample then
    surfaces as a merkle mismatch (page rejected, host-hash retry)
    instead of silently keying storage with a wrong txid."""
    pairs = []
    for tx in transactions:
        if isinstance(tx, str):
            # lowercase so the hex-string sort key stays byte-order
            # equivalent (nibble -> hex char is monotonic, so sorting
            # the hex text equals sorting the raw bytes — no fromhex
            # per tx just for the sort key)
            key = tx.lower()
            digest = hashlib.sha256(bytes.fromhex(key)).digest()
        else:
            key = tx.hex()  # memoized, lowercase by construction
            digest = bytes.fromhex(tx.hash())
        pairs.append((key, digest))
    pairs.sort(key=lambda p: p[0])
    return hashlib.sha256(b"".join(d for _, d in pairs)).hexdigest()


def merkle_root_ordered(transactions: Iterable[TxLike]) -> str:
    """Order-preserving variant (manager.py:352-362)."""
    acc = b""
    for tx in transactions:
        acc += hashlib.sha256(_raw(tx)).digest()
    return hashlib.sha256(acc).hexdigest()


def miner_merkle_root(tx_hashes: List[str]) -> str:
    """The miner-side merkle over pending tx *hashes* (miner.py:15-18).

    The node's get_mining_info hands the miner 64-char tx hashes
    (node/main.py:630, and the reference miner asserts len == 64); joining
    their raw digests and hashing equals the node's merkle_root only
    because the node pre-sorts/pre-hashes — do NOT pass full tx hexes.
    """
    assert all(len(tx) == 64 for tx in tx_hashes), "expects 64-char tx hashes"
    return hashlib.sha256(b"".join(bytes.fromhex(tx) for tx in tx_hashes)).hexdigest()
