"""UTC epoch timestamp, matching the reference's helpers.py:37-38."""

from __future__ import annotations

import time


def timestamp() -> int:
    """Whole seconds since the epoch, UTC."""
    return int(time.time())
