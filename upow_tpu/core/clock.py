"""UTC epoch timestamp, matching the reference's helpers.py:37-38.

A process-wide injectable offset supports tests that must cross protocol
time windows (the 48-hour revoke rule, peer pruning) without sleeping —
every consensus-path caller imports :func:`timestamp` from here, so the
whole node moves through time together.
"""

from __future__ import annotations

import time

_offset = 0


def timestamp() -> int:
    """Whole seconds since the epoch, UTC (+ any injected test offset)."""
    return int(time.time()) + _offset


def advance(seconds: int) -> None:
    """Shift the process clock forward (tests only)."""
    global _offset
    _offset += int(seconds)


def reset() -> None:
    global _offset
    _offset = 0
