"""UTC epoch timestamp, matching the reference's helpers.py:37-38.

A process-wide injectable offset supports tests that must cross protocol
time windows (the 48-hour revoke rule, peer pruning) without sleeping —
every consensus-path caller imports :func:`timestamp` from here, so the
whole node moves through time together.
"""

from __future__ import annotations

import time

_offset = 0
_frozen = None


def timestamp() -> int:
    """Whole seconds since the epoch, UTC (+ any injected test offset)."""
    base = _frozen if _frozen is not None else int(time.time())
    return base + _offset


def advance(seconds: int) -> None:
    """Shift the process clock forward (tests only)."""
    global _offset
    _offset += int(seconds)


def freeze(epoch: int) -> None:
    """Pin the base clock to a fixed epoch (tests only): long soaks must
    advance chain time ONLY via :func:`advance` — with a live base, real
    runtime inflates block spacing, and a sustained ~1 s/block of extra
    wall time walks the retarget below zero, where the difficulty target
    becomes unsatisfiable (a reference-faithful wedge: the
    START_DIFFICULTY floor only applies from block 590600,
    manager.py:116-118).  Clears any accumulated offset so the clock is
    genuinely pinned to ``epoch``."""
    global _frozen, _offset
    _frozen = int(epoch)
    _offset = 0


def reset() -> None:
    global _offset, _frozen
    _offset = 0
    _frozen = None
