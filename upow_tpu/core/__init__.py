"""Pure protocol kernel: constants, codecs, wire formats, consensus math.

Everything here is deterministic, I/O-free and byte-compatible with the
reference implementation (citations are ``file:line`` into /root/reference).
"""

from .constants import (
    ENDIAN,
    SMALLEST,
    MAX_SUPPLY,
    VERSION,
    MAX_BLOCK_SIZE_HEX,
    MAX_INODES,
    CURVE_P,
    CURVE_N,
)
from .codecs import (
    sha256_hex,
    b58encode,
    b58decode,
    AddressFormat,
    TransactionType,
    OutputType,
    InputType,
    point_to_bytes,
    bytes_to_point,
    point_to_string,
    string_to_point,
    string_to_bytes,
    bytes_to_string,
    transaction_type_from_message,
)
from .tx import Tx, TxInput, TxOutput, CoinbaseTx, tx_from_hex
from .header import BlockHeader, split_block_content, block_to_bytes
from .difficulty import (
    difficulty_to_hashrate,
    hashrate_to_difficulty,
    charset_count,
    check_pow,
    next_difficulty,
    START_DIFFICULTY,
    BLOCK_TIME,
    BLOCKS_COUNT,
)
from .rewards import get_block_reward, get_inode_rewards, get_circulating_supply
from .merkle import merkle_root, merkle_root_ordered
