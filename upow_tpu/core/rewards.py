"""Emission schedule and reward splitting — consensus-exact.

The inode reward split is the one place the framework keeps Decimal
arithmetic: the reference's behavior (9-digit precision context after block
39000, quantization quirks, and redistribution folded into the per-address
loop — manager.py:171-212) is consensus-critical, so it is replicated
exactly, warts and all.  Everything else is int smallest-units.
"""

from __future__ import annotations

import decimal
from decimal import Decimal
from typing import Dict, List, Tuple

from .constants import MAX_SUPPLY, SMALLEST

HALVING_INTERVAL = 1_576_800  # blocks ≈ 3 years of minutes (manager.py:156)
NINE_HALVINGS = 14_191_200  # manager.py:158
COINS_PER_BLOCK = 6
DECIMAL_SWITCH_BLOCK = 39_000  # round_up behavior switch (manager.py:181-188)


def round_up_decimal(value: Decimal, round_up_length: str = "0.00000001") -> Decimal:
    """Quantize only when sub-smallest dust exists (helpers.py:147-151)."""
    quantum = Decimal(round_up_length)
    if (value * SMALLEST) % 1 != 0:
        value = value.quantize(quantum)
    return value


def round_up_decimal_new(value: Decimal, round_up_length: str = "0.00000001") -> Decimal:
    """Unconditional quantize (helpers.py:154-157), used after block 39000."""
    return value.quantize(Decimal(round_up_length))


def get_block_reward(block_no: int) -> int:
    """Reward in smallest units: 6 coins halving every 1,576,800 blocks,
    zero after 9 halvings (manager.py:154-168).

    6e8 is divisible by 2^9 so the int math is exact at every halving.
    """
    assert block_no > 0
    if block_no > NINE_HALVINGS:
        return 0
    num_halvings = block_no // HALVING_INTERVAL
    if block_no % HALVING_INTERVAL == 0:
        num_halvings -= 1
    return (COINS_PER_BLOCK * SMALLEST) >> num_halvings


def get_block_reward_decimal(block_no: int) -> Decimal:
    return Decimal(get_block_reward(block_no)) / SMALLEST


def get_inode_rewards(
    reward: Decimal, inode_address_details: List[dict], block_no: int = 1
) -> Tuple[Decimal, Dict[str, Decimal]]:
    """Split the block reward 50/50 miner/inodes (manager.py:171-212).

    Inodes receive pro-rata by emission percent; shares of inodes below 1%
    are redistributed among those at >= 1%.  Faithful to the reference,
    including the quirk that redistribution happens *inside* the loop (so
    eligible wallets accrue a redistribution increment per iteration once
    any sub-1% share has been seen) and the precision-9 local context after
    block 39000.
    """
    total_percent = sum(entry["emission"] for entry in inode_address_details)
    if not inode_address_details or total_percent <= 0:
        return reward, {}
    # Decimal("0.5") == Decimal(0.5) exactly (0.5 is a power of two), so
    # this stays bit-identical to the reference while keeping the module
    # free of float literals.
    miner_reward = reward * Decimal("0.5")
    distribution_reward = reward * Decimal("0.5")
    distributed_rewards: Dict[str, Decimal] = {}
    redistribution_reward = Decimal(0)

    with decimal.localcontext() as ctx:
        ctx.prec = 9 if block_no > DECIMAL_SWITCH_BLOCK else ctx.prec
        for address_detail in inode_address_details:
            percent = address_detail["emission"]
            address_reward = distribution_reward * Decimal(percent) / Decimal(total_percent)
            if block_no > DECIMAL_SWITCH_BLOCK:
                address_reward = round_up_decimal_new(address_reward)
            else:
                address_reward = round_up_decimal(address_reward)
            if percent >= 1:
                distributed_rewards[address_detail["wallet"]] = address_reward
            else:
                redistribution_reward += (
                    distribution_reward * Decimal(percent) / Decimal(total_percent)
                )

            if redistribution_reward > 0:
                num_eligible = sum(1 for e in inode_address_details if e["emission"] >= 1)
                redistribution_amount = redistribution_reward / num_eligible
                if block_no > DECIMAL_SWITCH_BLOCK:
                    redistribution_amount = round_up_decimal_new(redistribution_amount)
                else:
                    redistribution_amount = round_up_decimal(redistribution_amount)
                for entry in inode_address_details:
                    if entry["emission"] >= 1:
                        distributed_rewards[entry["wallet"]] += redistribution_amount

    return miner_reward, distributed_rewards


def get_circulating_supply(block_no: int) -> Decimal:
    """Supply after ``block_no`` blocks (manager.py:215-234)."""
    halving_interval = 3 * 365 * 24 * 60
    initial = COINS_PER_BLOCK
    if block_no > halving_interval * 9:
        return Decimal(MAX_SUPPLY)
    supply = 0
    num_halvings = block_no // halving_interval
    remaining = block_no % halving_interval
    if remaining == 0:
        num_halvings -= 1
    for i in range(num_halvings + 1):
        current = initial / (2 ** i)
        if i == num_halvings and remaining > 0:
            supply += current * remaining
        else:
            supply += current * halving_interval
    return supply
