"""Primitive codecs: sha256 (hex-aware), base58, address <-> point, enums.

Byte-compatible with /root/reference/upow/helpers.py.  Clean-room
implementations — no base58/fastecdsa dependency.
"""

from __future__ import annotations

import hashlib
from enum import Enum, IntEnum
from functools import lru_cache
from typing import Tuple, Union

from .constants import CURVE_A, CURVE_B, CURVE_P, ENDIAN


def sha256_hex(message: Union[str, bytes]) -> str:
    """sha256 hexdigest; a str argument is interpreted as HEX, not text.

    Matches helpers.py:41-44 — the whole chain hashes raw bytes, and every
    hex string is decoded before hashing.
    """
    if isinstance(message, str):
        message = bytes.fromhex(message)
    return hashlib.sha256(message).hexdigest()


def sha256_bytes(message: Union[str, bytes]) -> bytes:
    if isinstance(message, str):
        message = bytes.fromhex(message)
    return hashlib.sha256(message).digest()


def byte_length(i: int) -> int:
    """Minimum bytes to hold ``i`` (helpers.py:47-48).

    Pure-int ceil-div; identical to the reference's ceil(bits / 8.0) for
    every non-negative int (float division is exact up to 2**52 bits).
    """
    return (i.bit_length() + 7) // 8


# --- base58 (Bitcoin alphabet) ------------------------------------------

_B58_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_B58_INDEX = {c: i for i, c in enumerate(_B58_ALPHABET)}


def b58encode(data: bytes) -> str:
    # base58 treats the payload as one big-endian bigint by convention
    # (Bitcoin's encoding); this is not uPow wire-format serialization.
    n = int.from_bytes(data, "big")  # upowlint: disable=CE001
    out = []
    while n:
        n, r = divmod(n, 58)
        out.append(_B58_ALPHABET[r])
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def b58decode(s: str) -> bytes:
    n = 0
    for c in s:
        try:
            n = n * 58 + _B58_INDEX[c]
        except KeyError:
            raise ValueError(f"invalid base58 character {c!r}")
    # Inverse of b58encode's big-endian bigint convention (see above).
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")  # upowlint: disable=CE001
    pad = 0
    for c in s:
        if c == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + body


# --- enums (helpers.py:65-95) -------------------------------------------


class AddressFormat(Enum):
    FULL_HEX = "hex"
    COMPRESSED = "compressed"


class TransactionType(IntEnum):
    REGULAR = 0
    INODE_DE_REGISTRATION = 4
    VALIDATOR_REGISTRATION = 5
    VOTE_AS_VALIDATOR = 6
    VOTE_AS_DELEGATE = 7
    REVOKE_AS_VALIDATOR = 8
    REVOKE_AS_DELEGATE = 9


class OutputType(IntEnum):
    REGULAR = 0
    STAKE = 1
    UN_STAKE = 2
    INODE_REGISTRATION = 3
    VALIDATOR_REGISTRATION = 5
    VOTE_AS_VALIDATOR = 6
    VOTE_AS_DELEGATE = 7
    VALIDATOR_VOTING_POWER = 8
    DELEGATE_VOTING_POWER = 9


class InputType(IntEnum):
    REGULAR = 0
    FEES = 10


def transaction_type_from_message(message: bytes | None) -> TransactionType:
    """Tx type is encoded in the free-form message bytes (helpers.py:97-112).

    The message decodes (utf-8, falling back to its hex form) to the decimal
    value of a TransactionType; anything unparseable is REGULAR.
    """
    if message is None:
        return TransactionType.REGULAR
    try:
        try:
            text = message.decode("utf-8")
        except UnicodeDecodeError:
            text = message.hex()
        value = int(text)
        return TransactionType(value) if value in TransactionType._value2member_map_ else TransactionType.REGULAR
    except (ValueError, TypeError):
        return TransactionType.REGULAR


# --- curve point <-> address codecs (helpers.py:58-62, 126-192) ----------
#
# Addresses come in two formats:
#   FULL_HEX   — 64 bytes: x||y, each 32-byte little-endian, hex-encoded.
#   COMPRESSED — 33 bytes: 0x2A (y even) or 0x2B (y odd) || x 32-byte LE,
#                base58-encoded.
# A "point" here is a plain (x, y) int tuple on P-256.

Point = Tuple[int, int]


def is_on_curve(point: Point) -> bool:
    x, y = point
    return (y * y - (x * x * x + CURVE_A * x + CURVE_B)) % CURVE_P == 0


def x_to_y(x: int, is_odd: bool = False) -> int:
    """Decompress: recover y from x and its parity (helpers.py:58-62).

    p ≡ 3 (mod 4) so sqrt is a single exponentiation.
    """
    y2 = (x * x * x + CURVE_A * x + CURVE_B) % CURVE_P
    y = pow(y2, (CURVE_P + 1) // 4, CURVE_P)
    if y * y % CURVE_P != y2:
        raise ValueError("x is not on the curve")
    return y if y % 2 == is_odd else CURVE_P - y


def point_to_bytes(point: Point, address_format: AddressFormat = AddressFormat.FULL_HEX) -> bytes:
    x, y = point
    if address_format is AddressFormat.FULL_HEX:
        return x.to_bytes(32, ENDIAN) + y.to_bytes(32, ENDIAN)
    elif address_format is AddressFormat.COMPRESSED:
        return (42 if y % 2 == 0 else 43).to_bytes(1, ENDIAN) + x.to_bytes(32, ENDIAN)
    raise NotImplementedError()


@lru_cache(maxsize=65536)
def bytes_to_point(point_bytes: bytes) -> Point:
    """Decode (and for 33-byte form decompress) an address to its curve
    point.  Cached: block verification decodes the same addresses over
    and over (a few decompressions per tx, ~130 µs each in sqrt-mod-p),
    and real chains reuse addresses heavily.  Invalid inputs raise and
    are NOT cached (lru_cache does not memoize exceptions)."""
    if len(point_bytes) == 64:
        x = int.from_bytes(point_bytes[:32], ENDIAN)
        y = int.from_bytes(point_bytes[32:], ENDIAN)
        # The reference's fastecdsa Point constructor validates on-curve
        # and raises; decode-acceptance must match (consensus surface).
        if not is_on_curve((x, y)):
            raise ValueError("64-byte address is not a point on P-256")
        return (x, y)
    elif len(point_bytes) == 33:
        specifier = point_bytes[0]
        x = int.from_bytes(point_bytes[1:], ENDIAN)
        return (x, x_to_y(x, specifier == 43))
    raise NotImplementedError()


def point_to_string(point: Point, address_format: AddressFormat = AddressFormat.COMPRESSED) -> str:
    if address_format is AddressFormat.FULL_HEX:
        return point_to_bytes(point).hex()
    elif address_format is AddressFormat.COMPRESSED:
        return b58encode(point_to_bytes(point, AddressFormat.COMPRESSED))
    raise NotImplementedError()


@lru_cache(maxsize=65536)
def string_to_bytes(string: str) -> bytes:
    """Address string to bytes: hex first, base58 fallback (helpers.py:183-188).
    Cached alongside :func:`bytes_to_point` — the pure-python base58
    decode is a per-address cost the verify path pays repeatedly."""
    try:
        return bytes.fromhex(string)
    except ValueError:
        return b58decode(string)


def bytes_to_string(point_bytes: bytes) -> str:
    point = bytes_to_point(point_bytes)
    if len(point_bytes) == 64:
        return point_to_string(point, AddressFormat.FULL_HEX)
    elif len(point_bytes) == 33:
        return point_to_string(point, AddressFormat.COMPRESSED)
    raise NotImplementedError()


def string_to_point(string: str) -> Point:
    return bytes_to_point(string_to_bytes(string))
