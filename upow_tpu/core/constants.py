"""Protocol constants, byte-compatible with the reference.

Reference: /root/reference/upow/constants.py:1-9.  The signature curve is
NIST P-256 (``constants.py:4`` — ``CURVE = curve.P256``); its domain
parameters are spelled out here so the framework has no external ECC
dependency.
"""

# All integer serialization is little-endian (constants.py:3).
ENDIAN = "little"

# 8 decimal places: amounts are integers in "smallest" units on the wire
# (constants.py:5).  The framework keeps amounts as int smallest-units
# everywhere except the Decimal-sensitive inode reward split.
SMALLEST = 100_000_000

# Float literal is reference-faithful (constants.py:6); .75 is exactly
# representable, and every consumer goes through Decimal/str first.
MAX_SUPPLY = 18_884_643.75  # upowlint: disable=CP001
VERSION = 2  # tx version (constants.py:7)
MAX_BLOCK_SIZE_HEX = 4096 * 1024  # 4 MB hex == 2 MB raw (constants.py:8)
MAX_INODES = 12  # constants.py:9

# --- NIST P-256 (secp256r1) domain parameters ---------------------------
# y^2 = x^3 + ax + b over GF(p);  base point G of prime order n.
CURVE_P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
CURVE_A = CURVE_P - 3
CURVE_B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
CURVE_N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
CURVE_GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
CURVE_GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
