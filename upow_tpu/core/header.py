"""Block header codec: 138-byte v1 / 108-byte v2 (manager.py:385-419).

Layout (all integers little-endian):

    [version(1) only if v2] | prev_hash(32) | address(64 v1 / 33 v2)
    | merkle_root(32) | timestamp(4) | difficulty*10(2) | nonce(4)

v1 is exactly 138 bytes and has no version byte; anything else starts with
a version byte > 1 (v2 == 108 bytes).  The nonce is the final 4 bytes —
the property the TPU midstate-split sha256 kernel exploits.
"""

from __future__ import annotations

from dataclasses import dataclass
from decimal import Decimal
from io import BytesIO
from typing import Tuple

from .codecs import bytes_to_string, string_to_bytes
from .constants import ENDIAN

HEADER_SIZE_V1 = 138
HEADER_SIZE_V2 = 108
NONCE_OFFSET_V1 = 134
NONCE_OFFSET_V2 = 104


@dataclass
class BlockHeader:
    previous_hash: str
    address: str
    merkle_root: str
    timestamp: int
    difficulty_x10: int  # difficulty * 10, as stored on the wire
    nonce: int

    @property
    def difficulty(self) -> Decimal:
        # Decimal, not float: 63/10 must compare equal to Decimal("6.3")
        # the way the reference's split_block_content result does.
        return self.difficulty_x10 / Decimal(10)

    @property
    def version(self) -> int:
        return 1 if len(string_to_bytes(self.address)) == 64 else 2

    def prefix_bytes(self) -> bytes:
        """Everything up to (not including) the 4-byte nonce — the miner's
        per-template constant (miner.py:74-82)."""
        address_bytes = string_to_bytes(self.address)
        version = b"" if len(address_bytes) == 64 else bytes([2])
        return (
            version
            + bytes.fromhex(self.previous_hash)
            + address_bytes
            + bytes.fromhex(self.merkle_root)
            + self.timestamp.to_bytes(4, ENDIAN)
            + self.difficulty_x10.to_bytes(2, ENDIAN)
        )

    def tobytes(self) -> bytes:
        return self.prefix_bytes() + self.nonce.to_bytes(4, ENDIAN)

    def hex(self) -> str:
        return self.tobytes().hex()


def block_to_bytes(last_block_hash: str, block: dict) -> bytes:
    """Reference-shaped dict -> header bytes (manager.py:385-398)."""
    return BlockHeader(
        previous_hash=last_block_hash,
        address=block["address"],
        merkle_root=block["merkle_tree"],
        timestamp=int(block["timestamp"]),
        # Exact Decimal path; agrees with the reference's
        # int(float(d) * 10) for every representable difficulty (the wire
        # field is x10 in [0, 65535], all round-trip exact — verified by
        # tests/test_lint.py::test_difficulty_x10_decimal_matches_float).
        difficulty_x10=int(Decimal(str(block["difficulty"])) * 10),
        nonce=block["random"],
    ).tobytes()


def split_block_content(block_content: str) -> Tuple[str, str, str, int, Decimal, int]:
    """header hex -> (prev_hash, address, merkle, timestamp, difficulty, nonce)

    Mirrors manager.py:401-419 including its strictness: v1 is length-138
    exactly, v2 must be length-108, others unsupported.
    """
    header = parse_header(block_content)
    return (
        header.previous_hash,
        header.address,
        header.merkle_root,
        header.timestamp,
        header.difficulty,
        header.nonce,
    )


def parse_header(block_content: str) -> BlockHeader:
    raw = bytes.fromhex(block_content)
    stream = BytesIO(raw)
    if len(raw) == HEADER_SIZE_V1:
        version = 1
    else:
        version = int.from_bytes(stream.read(1), ENDIAN)
        assert version > 1, "not a v1 (138-byte) header and no version byte"
        if version == 2:
            assert len(raw) == HEADER_SIZE_V2, f"v2 header must be 108 bytes, got {len(raw)}"
        else:
            raise NotImplementedError(f"unknown header version {version}")
    previous_hash = stream.read(32).hex()
    address = bytes_to_string(stream.read(64 if version == 1 else 33))
    merkle_root = stream.read(32).hex()
    timestamp = int.from_bytes(stream.read(4), ENDIAN)
    difficulty_x10 = int.from_bytes(stream.read(2), ENDIAN)
    nonce = int.from_bytes(stream.read(4), ENDIAN)
    return BlockHeader(previous_hash, address, merkle_root, timestamp, difficulty_x10, nonce)
