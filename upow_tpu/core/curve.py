"""Clean-room NIST P-256 ECDSA: keygen, RFC6979 sign, verify.

This is the host-side reference implementation (the role fastecdsa's C
extension plays in the reference — upow/transaction_input.py:84-86,100-109).
The batched TPU verify kernel in ``upow_tpu.crypto`` is differential-tested
against it; the fast CPU path uses OpenSSL via ``cryptography`` when
available.

Signatures are (r, s) int pairs over sha256 of the message bytes, matching
``fastecdsa.ecdsa.sign(msg, d)`` / ``verify`` defaults.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
from typing import Optional, Tuple

from .constants import CURVE_A, CURVE_GX, CURVE_GY, CURVE_N, CURVE_P

Point = Optional[Tuple[int, int]]  # None is the point at infinity
G: Point = (CURVE_GX, CURVE_GY)


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def point_add(p1: Point, p2: Point) -> Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % CURVE_P == 0:
            return None
        lam = (3 * x1 * x1 + CURVE_A) * _inv(2 * y1, CURVE_P) % CURVE_P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, CURVE_P) % CURVE_P
    x3 = (lam * lam - x1 - x2) % CURVE_P
    y3 = (lam * (x1 - x3) - y1) % CURVE_P
    return (x3, y3)


def _point_mul_affine_ladder(k: int, p: Point) -> Point:
    """The original affine double-and-add — kept as the differential
    oracle for the Jacobian ladder below (tests compare them)."""
    result: Point = None
    addend = p
    while k:
        if k & 1:
            result = point_add(result, addend)
        addend = point_add(addend, addend)
        k >>= 1
    return result


def _jac_to_affine(acc) -> Point:
    X, Y, Z = acc
    if Z == 0:
        return None
    z_inv = _inv(Z, CURVE_P)
    z2 = z_inv * z_inv % CURVE_P
    return (X * z2 % CURVE_P, Y * z2 * z_inv % CURVE_P)


def point_mul(k: int, p: Point) -> Point:
    """k * p via an MSB-first Jacobian ladder — one modular inversion
    total instead of one per group op (~5x the affine ladder; this is
    the pure-python verify oracle's inner loop).

    Verify scalars are adversary-influenced (u2 = r·s⁻¹ mod n), so
    unlike the fixed-base walk the identity cases ARE reachable here:
    the accumulator can land on ±p mid-ladder.  ``_jac_madd`` resolves
    them exactly (doubling / infinity), and an infinite accumulator
    restarts cleanly at the next set bit."""
    if p is None or k == 0:
        return None
    acc = None  # Jacobian accumulator
    for bit in bin(k)[2:]:
        if acc is not None:
            acc = _jac_double(acc)
        if bit == "1":
            if acc is None:
                acc = (p[0], p[1], 1)
            else:
                acc = _jac_madd(acc, p)
    if acc is None:
        return None
    return _jac_to_affine(acc)


_G_WINDOW = 8  # fixed-base table: 32 windows x 256 entries, built lazily
_G_TABLE: Optional[list] = None


def _g_table() -> list:
    """T[i][j] = j * 2^(8i) * G for j in 1..255 (index j-1).  One-time
    ~0.2 s build; every subsequent k*G costs <=31 point adds instead of
    the ~384 add/double ops of the generic ladder — signing is the
    wallet's per-tx hot loop (reference delegates it to fastecdsa's C)."""
    global _G_TABLE
    if _G_TABLE is None:
        table = []
        base: Point = G
        for _ in range(256 // _G_WINDOW):
            row = [base]
            for _ in range(254):
                row.append(point_add(row[-1], base))
            table.append(row)
            nxt = row[-1]  # 255 * 2^(8i) * G
            base = point_add(nxt, base)  # 2^(8(i+1)) * G
        _G_TABLE = table
    return _G_TABLE


def _jac_madd(p1, p2):
    """Jacobian (X1,Y1,Z1) + affine (x2,y2) mixed addition — the table
    walk's inner op, no modular inverse (one inverse total at the end
    instead of one per add; signing is the wallet's per-tx hot loop)."""
    X1, Y1, Z1 = p1
    x2, y2 = p2
    Z1Z1 = Z1 * Z1 % CURVE_P
    A = (x2 * Z1Z1 - X1) % CURVE_P
    B = (y2 * Z1 * Z1Z1 - Y1) % CURVE_P
    if A == 0:
        if B == 0:
            return _jac_double(p1)
        return None  # P + (-P) = infinity
    AA = A * A % CURVE_P
    AAA = AA * A % CURVE_P
    X1AA = X1 * AA % CURVE_P
    X3 = (B * B - AAA - 2 * X1AA) % CURVE_P
    Y3 = (B * (X1AA - X3) - Y1 * AAA) % CURVE_P
    Z3 = Z1 * A % CURVE_P
    return (X3, Y3, Z3)


def _jac_double(p):
    """Jacobian doubling for a = -3 (P-256)."""
    X1, Y1, Z1 = p
    delta = Z1 * Z1 % CURVE_P
    gamma = Y1 * Y1 % CURVE_P
    beta = X1 * gamma % CURVE_P
    alpha = 3 * (X1 - delta) * (X1 + delta) % CURVE_P
    X3 = (alpha * alpha - 8 * beta) % CURVE_P
    Z3 = ((Y1 + Z1) * (Y1 + Z1) - gamma - delta) % CURVE_P
    Y3 = (alpha * (4 * beta - X3) - 8 * gamma * gamma) % CURVE_P
    return (X3, Y3, Z3)


def point_mul_G(k: int) -> Point:
    """k * G via the fixed-base window table (same result as
    ``point_mul(k, G)``).  Accumulates in Jacobian coordinates — one
    modular inversion total instead of one per table add."""
    k %= CURVE_N  # table only spans 256 bits; also handles oversized keys
    if k == 0:
        return None
    k0 = k
    table = _g_table()
    acc = None  # Jacobian accumulator
    i = 0
    while k:
        d = k & 0xFF
        if d:
            x2, y2 = table[i][d - 1]
            if acc is None:
                acc = (x2, y2, 1)
            else:
                acc = _jac_madd(acc, (x2, y2))
                if acc is None:  # pragma: no cover
                    # Defensive only — PROVABLY unreachable: before the
                    # window-i add, acc = (k mod 2^(8i))·G and the entry
                    # is d·2^(8i)·G with both partial values strictly
                    # inside (0, n), so neither cancellation nor the
                    # doubling case can occur for any k in [1, n-1].
                    return _point_mul_G_affine(k0)
        k >>= 8
        i += 1
    if acc is None:
        return None
    return _jac_to_affine(acc)


def _point_mul_G_affine(k: int) -> Point:  # pragma: no cover
    """Affine fallback behind the provably-unreachable guard above
    (kept as defense in depth for the signing path)."""
    table = _g_table()
    result: Point = None
    i = 0
    while k:
        d = k & 0xFF
        if d:
            result = point_add(result, table[i][d - 1])
        k >>= 8
        i += 1
    return result


def keygen(rng: Optional[int] = None) -> Tuple[int, Tuple[int, int]]:
    """Return (private_key, public_point)."""
    d = (rng if rng is not None else secrets.randbelow(CURVE_N - 1)) % CURVE_N
    if d == 0:
        d = 1
    pub = point_mul_G(d)
    assert pub is not None
    return d, pub


def _bits2int(b: bytes) -> int:
    i = int.from_bytes(b, "big")
    blen = len(b) * 8
    qlen = CURVE_N.bit_length()
    if blen > qlen:
        i >>= blen - qlen
    return i


def _rfc6979_k(msg_hash: bytes, d: int) -> int:
    """Deterministic nonce per RFC 6979 with HMAC-SHA256."""
    qlen_bytes = (CURVE_N.bit_length() + 7) // 8
    h1 = _bits2int(msg_hash) % CURVE_N
    x_octets = d.to_bytes(qlen_bytes, "big")
    h1_octets = h1.to_bytes(qlen_bytes, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x_octets + h1_octets, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x_octets + h1_octets, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        t = b""
        while len(t) < qlen_bytes:
            v = hmac.new(k, v, hashlib.sha256).digest()
            t += v
        nonce = _bits2int(t[:qlen_bytes])
        if 0 < nonce < CURVE_N:
            return nonce
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(message: bytes, d: int) -> Tuple[int, int]:
    """ECDSA sign sha256(message) with deterministic RFC6979 nonce."""
    msg_hash = hashlib.sha256(message).digest()
    z = _bits2int(msg_hash)
    while True:
        k = _rfc6979_k(msg_hash, d)
        p = point_mul_G(k)
        assert p is not None
        r = p[0] % CURVE_N
        if r == 0:
            continue
        s = _inv(k, CURVE_N) * (z + r * d) % CURVE_N
        if s == 0:
            continue
        return (r, s)


def verify(signature: Tuple[int, int], message: bytes, pub: Tuple[int, int]) -> bool:
    """ECDSA verify (r, s) over sha256(message) against public point."""
    r, s = signature
    if not (0 < r < CURVE_N and 0 < s < CURVE_N):
        return False
    z = _bits2int(hashlib.sha256(message).digest())
    w = _inv(s, CURVE_N)
    u1 = z * w % CURVE_N
    u2 = r * w % CURVE_N
    p = point_add(point_mul_G(u1), point_mul(u2, pub))
    if p is None:
        return False
    return p[0] % CURVE_N == r % CURVE_N
