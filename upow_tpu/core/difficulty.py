"""Difficulty math and PoW validity — byte-exact with manager.py:26-151.

uPow's PoW rule: sha256(header) must *start with* the last
``int(difficulty)`` hex chars of the previous block's hash, and for a
fractional difficulty the next hex char must fall in a restricted charset
prefix of size ``ceil(16 * (1 - frac))``.
"""

from __future__ import annotations

from decimal import Decimal
from math import ceil, floor, log
from typing import Optional, Tuple

from .codecs import sha256_hex

BLOCK_TIME = 60  # seconds (manager.py:26)
BLOCKS_COUNT = Decimal(100)  # retarget window (manager.py:27)
START_DIFFICULTY = Decimal("6.0")  # manager.py:29
LAST_BLOCK_FOR_GENESIS_KEY = 10000  # manager.py:28

HEX_CHARSET = "0123456789abcdef"


def difficulty_to_hashrate_old(difficulty: Decimal) -> Decimal:
    decimal = difficulty % 1 or 1 / 16
    return Decimal(16 ** int(difficulty) * (16 * decimal))


def difficulty_to_hashrate(difficulty: Decimal) -> Decimal:
    """Expected hashes per block at a difficulty (manager.py:44-46)."""
    decimal = difficulty % 1
    return Decimal(16 ** int(difficulty) * (16 / ceil(16 * (1 - decimal))))


def hashrate_to_difficulty_old(hashrate) -> Decimal:
    difficulty = int(log(hashrate, 16))
    if hashrate == 16 ** difficulty:
        return Decimal(difficulty)
    return Decimal(difficulty + (hashrate / Decimal(16) ** difficulty) / 16)


def hashrate_to_difficulty(hashrate) -> Decimal:
    """Inverse map with 0.1-step fractional search (manager.py:67-80)."""
    difficulty = int(log(hashrate, 16))
    ratio = hashrate / 16 ** difficulty

    for i in range(0, 10):
        coeff = 16 / ceil(16 * (1 - i / 10))
        if coeff > ratio:
            decimal = (i - 1) / Decimal(10)
            return Decimal(difficulty + decimal)
        if coeff == ratio:
            decimal = i / Decimal(10)
            return Decimal(difficulty + decimal)

    return Decimal(difficulty) + Decimal("0.9")


def charset_count(difficulty) -> int:
    """Allowed-charset size for the fractional hex char (manager.py:145-146)."""
    decimal = Decimal(str(difficulty)) % 1
    return ceil(16 * (1 - decimal)) if decimal > 0 else 16


def pow_target(previous_hash: str, difficulty) -> Tuple[str, int, int]:
    """(required_prefix, int_difficulty, charset_count) for a template.

    The prefix is the last int(difficulty) hex chars of the previous hash
    (miner.py:43-56, manager.py:142-151).  Consensus quirk replicated
    exactly: at difficulty < 1 the reference's ``prev_hash[-0:]`` slice is
    the WHOLE previous hash, making sub-1 difficulties effectively
    unminable.
    """
    difficulty = Decimal(str(difficulty))
    int_difficulty = int(floor(difficulty))
    return previous_hash[-int_difficulty:], int_difficulty, charset_count(difficulty)


def check_pow_hash(block_hash: str, previous_hash: str, difficulty) -> bool:
    """Does an already-computed block hash satisfy the PoW rule?"""
    prefix, int_difficulty, count = pow_target(previous_hash, difficulty)
    if count < 16:
        return block_hash.startswith(prefix) and block_hash[int_difficulty] in HEX_CHARSET[:count]
    return block_hash.startswith(prefix)


def check_pow(block_content: str, previous_hash: Optional[str], difficulty) -> bool:
    """Full PoW validity check (manager.py:130-151).

    ``previous_hash=None`` mirrors the genesis case where the last block has
    no hash: anything is valid.
    """
    if previous_hash is None:
        return True
    return check_pow_hash(sha256_hex(block_content), previous_hash, difficulty)


def next_difficulty(last_block: Optional[dict], window_start_timestamp: Optional[int]) -> Decimal:
    """Retarget rule (manager.py:83-121), as a pure function.

    ``last_block`` needs keys id/timestamp/difficulty; the caller supplies
    the timestamp of block ``id - 99`` when ``id % 100 == 0`` (the only
    case it is read).  Returns the difficulty for the *next* block.
    """
    if last_block is None:
        return START_DIFFICULTY
    if last_block["id"] < BLOCKS_COUNT:
        return START_DIFFICULTY
    if last_block["id"] % BLOCKS_COUNT != 0:
        return Decimal(str(last_block["difficulty"]))

    elapsed = last_block["timestamp"] - window_start_timestamp
    average_per_block = elapsed / BLOCKS_COUNT
    last_difficulty = Decimal(str(last_block["difficulty"]))
    hashrate = difficulty_to_hashrate(last_difficulty)
    ratio = BLOCK_TIME / average_per_block
    if last_block["id"] >= 180_000:  # difficulty can at most double (manager.py:109-110)
        ratio = min(ratio, 2)
    hashrate *= ratio
    new_difficulty = hashrate_to_difficulty(hashrate)
    if new_difficulty < START_DIFFICULTY and last_block["id"] >= 590_600:
        return START_DIFFICULTY  # floor after block 590600 (manager.py:114-116)
    return new_difficulty
