"""Transaction wire codec — byte-identical to the reference, but pure.

Amounts are ints in smallest units (1e-8 coins) everywhere; the reference's
Decimal amounts appear only at the JSON/API boundary.  The codec never
touches a database: signature-to-input relinking for the ambiguous multi-sig
case takes an optional address resolver callback instead of the reference's
lazy ``Database`` imports (transaction.py:100,127 — the coupling SURVEY.md
§1 says to cut).

Wire layout (transaction.py:46-83):

    version(1) | n_inputs(1) | inputs | n_outputs(1) | outputs
    [ message_specifier | message ] [ signatures ] (full form only)

    input  = tx_hash(32) | index(1) | input_type(1)            (34 B)
    output = address(64 or 33) | amount_len(1) | amount(LE) | output_type(1)

Version 1 carries 64-byte addresses, version 3 carries 33-byte compressed
ones; message length is 1 byte for version <= 2 and 2 bytes LE for v3.
Signatures are 64-byte r||s (32-byte LE each), deduplicated by value
(transaction.py:76-82).  Coinbase txs use output-section specifier byte 36
and version 2 for compressed addresses (coinbase_transaction.py:22-44).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from io import BytesIO
from typing import Callable, List, Optional, Tuple, Union

from .codecs import (
    InputType,
    OutputType,
    TransactionType,
    byte_length,
    bytes_to_string,
    is_on_curve,
    sha256_hex,
    string_to_bytes,
    string_to_point,
    transaction_type_from_message,
)
from .constants import ENDIAN, SMALLEST

Signature = Tuple[int, int]


class AmbiguousSignatureError(ValueError):
    """Signature count matches neither 1 nor the input count: relinking
    needs the address resolver (transaction.py:148-163 resolves through
    the Database)."""


@dataclass
class TxInput:
    """A reference to a spendable output (transaction_input.py:11-98)."""

    tx_hash: str
    index: int
    input_type: InputType = InputType.REGULAR
    signature: Optional[Signature] = None

    def tobytes(self) -> bytes:
        return (
            bytes.fromhex(self.tx_hash)
            + self.index.to_bytes(1, ENDIAN)
            + int(self.input_type).to_bytes(1, ENDIAN)
        )

    def signature_hex(self) -> str:
        if self.signature is None:
            raise ValueError("cannot serialize an unsigned input")
        r, s = self.signature
        return r.to_bytes(32, ENDIAN).hex() + s.to_bytes(32, ENDIAN).hex()

    @property
    def outpoint(self) -> Tuple[str, int]:
        return (self.tx_hash, self.index)


@dataclass
class TxOutput:
    """address + amount (int smallest units) + type (transaction_output.py:7-26)."""

    address: str
    amount: int
    output_type: OutputType = OutputType.REGULAR

    def __post_init__(self):
        self.address_bytes = string_to_bytes(self.address)

    def tobytes(self) -> bytes:
        count = byte_length(self.amount)
        return (
            self.address_bytes
            + count.to_bytes(1, ENDIAN)
            + self.amount.to_bytes(count, ENDIAN)
            + int(self.output_type).to_bytes(1, ENDIAN)
        )

    def verify(self) -> bool:
        """amount > 0 and the address decodes to a point on P-256."""
        try:
            return self.amount > 0 and is_on_curve(string_to_point(self.address))
        except (ValueError, NotImplementedError):
            return False

    @property
    def is_stake(self) -> bool:
        return self.output_type == OutputType.STAKE


class Tx:
    """A regular transaction (transaction.py:21-238, codec parts only)."""

    def __init__(
        self,
        inputs: List[TxInput],
        outputs: List[TxOutput],
        message: Optional[bytes] = None,
        version: Optional[int] = None,
    ):
        if len(inputs) >= 256:
            raise ValueError(f"max 255 inputs, not {len(inputs)}")
        if len(outputs) >= 256:
            raise ValueError(f"max 255 outputs, not {len(outputs)}")
        self.inputs = inputs
        self.outputs = outputs
        self.message = message
        self.transaction_type = transaction_type_from_message(message)
        if version is None:
            if all(len(o.address_bytes) == 64 for o in outputs):
                version = 1
            elif all(len(o.address_bytes) == 33 for o in outputs):
                version = 3
            else:
                raise NotImplementedError("mixed address formats")
        if version > 3:
            raise NotImplementedError()
        self.version = version
        self._hash: Optional[str] = None
        self._hex_cache: dict = {}

    @property
    def is_coinbase(self) -> bool:
        return False

    def hex(self, full: bool = True) -> str:
        """Serialize; ``full=False`` is the signing form (transaction.py:46-83).

        Memoized per instance (like ``hash``): block accept serializes
        each tx several times (merkle sort, txid, size check, storage
        row).  ``sign`` drops the full-form entry; mutating inputs or
        outputs by hand after serializing is not supported — build or
        parse, then sign."""
        cached = self._hex_cache.get(full)
        if cached is not None:
            return cached
        out = [
            self.version.to_bytes(1, ENDIAN).hex(),
            len(self.inputs).to_bytes(1, ENDIAN).hex(),
            "".join(i.tobytes().hex() for i in self.inputs),
            len(self.outputs).to_bytes(1, ENDIAN).hex(),
            "".join(o.tobytes().hex() for o in self.outputs),
        ]
        hexstring = "".join(out)

        # v1/v2 sign over inputs+outputs only; v3 also signs the message.
        if not full and (self.version <= 2 or self.message is None):
            self._hex_cache[full] = hexstring
            return hexstring

        if self.message is not None:
            if self.version <= 2:
                hexstring += bytes([1, len(self.message)]).hex()
            else:
                hexstring += bytes([1]).hex()
                hexstring += len(self.message).to_bytes(2, ENDIAN).hex()
            hexstring += self.message.hex()
            if not full:
                self._hex_cache[full] = hexstring
                return hexstring
        else:
            hexstring += (0).to_bytes(1, ENDIAN).hex()

        # Signatures deduplicated by value: one per distinct (key, sig).
        seen = []
        for tx_input in self.inputs:
            signed = tx_input.signature_hex()
            if signed not in seen:
                seen.append(signed)
                hexstring += signed
        self._hex_cache[full] = hexstring
        return hexstring

    def hash(self) -> str:
        if self._hash is None:
            self._hash = sha256_hex(self.hex())
        return self._hash

    def fees(self, input_amount: int) -> int:
        """fee = inputs − outputs, excluding synthetic voting-power outputs
        (transaction.py:499-518).  ``input_amount`` comes from the state view."""
        if self.transaction_type != TransactionType.REGULAR:
            return 0
        output_amount = sum(
            o.amount
            for o in self.outputs
            if o.output_type
            not in (OutputType.VALIDATOR_VOTING_POWER, OutputType.DELEGATE_VOTING_POWER)
        )
        return input_amount - output_amount

    def sign(self, private_keys: List[int], pubkey_of: Callable[[TxInput], Tuple[int, int]]) -> "Tx":
        """Sign every input whose resolved pubkey matches one of the keys.

        ``pubkey_of`` maps an input to the public point of the output it
        spends (the reference resolves this through the Database;
        transaction.py:484-497).
        """
        from . import curve

        signing_bytes = bytes.fromhex(self.hex(False))
        key_by_point = {curve.point_mul_G(d): d for d in private_keys}
        for tx_input in self.inputs:
            pub = pubkey_of(tx_input)
            d = key_by_point.get(pub)
            if d is not None:
                tx_input.signature = curve.sign(signing_bytes, d)
        self._hex_cache.pop(True, None)  # signatures changed
        self._hash = None
        return self

    def __eq__(self, other):
        return isinstance(other, (Tx, CoinbaseTx)) and self.hex() == other.hex()


class CoinbaseTx:
    """The miner-reward transaction (coinbase_transaction.py:8-47).

    input = (block_hash, 0); output-section specifier byte 36; version 2
    (not 3) for compressed addresses.  Multi-output when inode rewards are
    appended (manager.py:694-700).
    """

    def __init__(self, block_hash: str, address: str, amount: int):
        self.block_hash = block_hash
        self.address = address
        self.amount = amount
        self.outputs = [TxOutput(address, amount)]
        self._hex: Optional[str] = None
        self.transaction_type = TransactionType.REGULAR
        self.message = None
        self.inputs: List[TxInput] = []

    @property
    def is_coinbase(self) -> bool:
        return True

    def hex(self, full: bool = True) -> str:
        if self._hex is not None:
            return self._hex
        hex_inputs = (
            bytes.fromhex(self.block_hash) + (0).to_bytes(1, ENDIAN)
        ).hex() + int(InputType.REGULAR).to_bytes(1, ENDIAN).hex()
        hex_outputs = "".join(o.tobytes().hex() for o in self.outputs)
        if all(len(o.address_bytes) == 64 for o in self.outputs):
            version = 1
        elif all(len(o.address_bytes) == 33 for o in self.outputs):
            version = 2
        else:
            raise NotImplementedError()
        self._hex = "".join(
            [
                version.to_bytes(1, ENDIAN).hex(),
                (1).to_bytes(1, ENDIAN).hex(),
                hex_inputs,
                len(self.outputs).to_bytes(1, ENDIAN).hex(),
                hex_outputs,
                (36).to_bytes(1, ENDIAN).hex(),
            ]
        )
        return self._hex

    def hash(self) -> str:
        return sha256_hex(self.hex())

    def fees(self, input_amount: int = 0) -> int:
        return 0


AddressResolver = Callable[[str, int], Optional[str]]


def tx_from_hex(
    hexstring: str,
    check_signatures: bool = True,
    resolve_address: Optional[AddressResolver] = None,
) -> Union[Tx, CoinbaseTx]:
    """Decode the wire format (transaction.py:520-592).

    When the signature count matches neither 1 nor the input count, the
    reference groups inputs by their (database-resolved) spending address
    and assigns the i-th signature to the i-th distinct address.  Callers
    that have state pass ``resolve_address(tx_hash, index) -> address`` for
    that case; with ``check_signatures=False`` the relinking is skipped.
    """
    stream = BytesIO(bytes.fromhex(hexstring))
    version = int.from_bytes(stream.read(1), ENDIAN)
    if version > 3:
        raise NotImplementedError()

    inputs_count = int.from_bytes(stream.read(1), ENDIAN)
    inputs = []
    for _ in range(inputs_count):
        tx_hash = stream.read(32).hex()
        index = int.from_bytes(stream.read(1), ENDIAN)
        input_type = int.from_bytes(stream.read(1), ENDIAN)
        inputs.append(TxInput(tx_hash, index, InputType(input_type)))

    outputs_count = int.from_bytes(stream.read(1), ENDIAN)
    outputs = []
    for _ in range(outputs_count):
        pubkey = stream.read(64 if version == 1 else 33)
        amount_length = int.from_bytes(stream.read(1), ENDIAN)
        amount = int.from_bytes(stream.read(amount_length), ENDIAN)
        output_type = int.from_bytes(stream.read(1), ENDIAN)
        outputs.append(TxOutput(bytes_to_string(pubkey), amount, OutputType(output_type)))

    specifier = int.from_bytes(stream.read(1), ENDIAN)
    if specifier == 36:
        assert len(inputs) == 1
        coinbase = CoinbaseTx(inputs[0].tx_hash, outputs[0].address, outputs[0].amount)
        if len(outputs) > 1:
            coinbase.outputs.extend(outputs[1:])
        return coinbase

    if specifier == 1:
        message_length = int.from_bytes(stream.read(1 if version <= 2 else 2), ENDIAN)
        message = stream.read(message_length)
    else:
        assert specifier == 0
        message = None

    signatures = []
    while True:
        r = int.from_bytes(stream.read(32), ENDIAN)
        s = int.from_bytes(stream.read(32), ENDIAN)
        if r == 0:
            break
        signatures.append((r, s))

    if len(signatures) == 1:
        for tx_input in inputs:
            tx_input.signature = signatures[0]
    elif len(inputs) == len(signatures):
        for tx_input, signed in zip(inputs, signatures):
            tx_input.signature = signed
    elif check_signatures:
        if resolve_address is None:
            raise AmbiguousSignatureError(
                "ambiguous signature layout needs an address resolver "
                f"({len(inputs)} inputs, {len(signatures)} signatures)"
            )
        index: dict = {}
        for tx_input in inputs:
            address = resolve_address(tx_input.tx_hash, tx_input.index)
            index.setdefault(address, []).append(tx_input)
        if len(signatures) > len(index):
            # the reference's relink would IndexError here
            # (transaction.py:148-163 groups by address then indexes by
            # signature position); reject the same inputs, cleanly
            raise ValueError(
                f"{len(signatures)} signatures for "
                f"{len(index)} distinct input addresses")
        for i, signed in enumerate(signatures):
            for tx_input in index[list(index.keys())[i]]:
                tx_input.signature = signed

    return Tx(inputs, outputs, message, version)
