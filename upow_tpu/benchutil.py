"""Shared benchmark plumbing for bench.py / bench_suite.py.

Two things both scoreboards need and must agree on:

* :func:`probe_platform` — backend detection that survives the axon TPU
  tunnel HANGING inside ``jax.devices()`` (observed >500 s with zero
  CPU; exceptions are the easy case).  The probe runs on a daemon
  thread; on timeout the caller decides (bench.py re-execs a
  scrubbed-env CPU child — once a thread is stuck inside the PJRT
  plugin no in-process fallback is reliable).
* :func:`python_loop_mhs` — the reference miner's hashlib-per-nonce
  loop (reference miner.py:83-98), the CPU baseline every
  ``vs_baseline`` field is computed against.
"""

from __future__ import annotations

import hashlib
import time
from typing import Optional


def boxed_call(fn, timeout: float):
    """DEPRECATED shim: the hang-survival idiom moved to
    :func:`upow_tpu.device.runtime.boxed_call` (the device-runtime
    service is the only sanctioned dispatcher — upowlint rule DR002
    flags new callers).  Kept delegating because bench tooling and
    tests monkeypatch ``benchutil.boxed_call`` to fake probe results;
    :func:`probe_platform` still resolves it through this module global
    so those seams keep intercepting.

    Returns ("ok", result) | ("err", exception) | ("timeout", None).
    """
    from .device.runtime import boxed_call as _boxed_call

    return _boxed_call(fn, timeout)


# Platform strings that mean "a real TPU answers": native libtpu
# reports "tpu"; the axon tunnel plugin registers its PJRT client under
# "axon" and only aliases the MLIR lowering tables to tpu's, so
# Device.platform / jax.default_backend() can read "axon" on the very
# hardware all the == "tpu" routing was written for.
TPU_PLATFORMS = ("tpu", "axon")


def text_fingerprint(text: str) -> str:
    """Short stable hash of diagnostic text (stderr tails, frame lists)
    so repeated arm failures can be grouped without comparing full
    tracebacks."""
    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()[:12]


def traceback_fingerprint(exc: BaseException) -> str:
    """Fingerprint of an exception's traceback SHAPE (file:function per
    frame, no line numbers or message text): two arm attempts that died
    on the same code path share a fingerprint even when addresses or
    timeouts in the message differ."""
    import traceback as _tb

    frames = _tb.extract_tb(exc.__traceback__) if exc.__traceback__ else []
    sig = "|".join("%s:%s" % (f.filename.rsplit("/", 1)[-1], f.name)
                   for f in frames[-8:])
    return text_fingerprint("%s|%s" % (type(exc).__name__, sig))


def probe_platform_detail(timeout: float = 90.0) -> dict:
    """Backend probe that KEEPS the failure: returns
    ``{status, platform, seconds, error, traceback_fingerprint}`` where
    ``status`` is the boxed_call outcome ("ok" / "err" / "timeout"),
    ``platform`` is the normalized name (None unless ok), and ``error``
    is the actual exception text — the thing every "hung/failed" log
    line used to throw away."""
    import jax

    # module-global boxed_call on purpose: tests monkeypatch it to fake
    # probe outcomes; jax.devices() here IS the probe the runtime arms
    # through, not a stray dispatch
    t0 = time.perf_counter()
    # RC001: loop-reachable only via Node.__init__'s one-time cached
    # device probe at startup, before the node serves traffic
    status, value = boxed_call(  # upowlint: disable=DR002,RC001
        lambda: jax.devices()[0].platform, timeout)  # upowlint: disable=DR001
    detail = {"status": status, "platform": None,
              "seconds": round(time.perf_counter() - t0, 3),
              "error": None, "traceback_fingerprint": None}
    if status == "ok":
        detail["platform"] = "tpu" if value in TPU_PLATFORMS else value
    elif status == "timeout":
        detail["error"] = ("backend init still inside jax.devices() after "
                           "%.0fs (native hang; no Python exception to "
                           "show)" % timeout)
    else:  # "err": value IS the exception boxed_call caught
        detail["error"] = repr(value)
        if isinstance(value, BaseException):
            detail["traceback_fingerprint"] = traceback_fingerprint(value)
    return detail


def probe_platform(timeout: float = 90.0) -> Optional[str]:
    """Platform string of jax.devices()[0]; None if init hung or failed.
    TPU-class platform aliases (axon tunnel) normalize to "tpu" so every
    downstream backend-routing comparison sees one canonical name."""
    return probe_platform_detail(timeout)["platform"]


# Arm-provenance env contract, shared by bench.py and the loadgen
# observatory: bench.py's scrubbed-env CPU child inherits WHY the
# parent lost the chip through these, and any artifact writer can
# stamp the same story without re-deriving it.
ARM_FAILURE_ENV = "UPOW_BENCH_ARM_FAILURE"
ARM_ATTEMPTED_ENV = "UPOW_BENCH_ATTEMPTED_BACKEND"
ARM_ATTEMPT_ENV = "UPOW_BENCH_ARM_ATTEMPT"
ARM_LADDER_ENV = "UPOW_BENCH_ARM_LADDER"


def arm_provenance_from_env(platform: Optional[str] = None) -> dict:
    """The arm story the environment carries: what backend was
    attempted (falling back to ``platform`` when unset), which arm
    attempt produced this process (``runtime`` / ``cpu-child`` / ...),
    the failure reason when the attempt lost the chip, and the full
    per-attempt ladder (JSON list with each rung's real exception text
    and traceback fingerprint) when the parent recorded one."""
    import json
    import os

    out = {
        "attempted_backend": os.environ.get(ARM_ATTEMPTED_ENV, platform),
        "arm_failure_reason": os.environ.get(ARM_FAILURE_ENV),
        "arm_attempt": os.environ.get(ARM_ATTEMPT_ENV),
    }
    raw = os.environ.get(ARM_LADDER_ENV)
    if raw:
        try:
            out["arm_ladder"] = json.loads(raw)
        except ValueError:
            out["arm_ladder"] = [{"attempt": "unparsed", "error": raw}]
    return out


_PROBE_CACHE: dict = {}


def probe_detail_cached(timeout: float = 90.0) -> dict:
    """One probe per process (see :func:`probed_platform_cached`), but
    returning the full :func:`probe_platform_detail` record so callers
    can surface the real failure text instead of a bare None."""
    if "detail" not in _PROBE_CACHE:
        _PROBE_CACHE["detail"] = probe_platform_detail(timeout)
        _PROBE_CACHE["platform"] = _PROBE_CACHE["detail"]["platform"]
    return _PROBE_CACHE["detail"]


def probed_platform_cached(timeout: float = 90.0) -> Optional[str]:
    """One probe per process, shared by every jax consumer that must not
    wedge on a dead tunnel (node signature dispatch, device UTXO index,
    bench) — so a hung backend costs the process ONE timeout, not one
    per subsystem."""
    if "platform" not in _PROBE_CACHE:
        _PROBE_CACHE["platform"] = probe_detail_cached(timeout)["platform"]
    return _PROBE_CACHE["platform"]


def python_loop_mhs(prefix: bytes, seconds: float = 1.0) -> float:
    """Reference-shaped loop: one hashlib sha256 per nonce (the
    difficulty-prefix compare costs nothing next to the hash)."""
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        for _ in range(2000):
            hashlib.sha256(prefix + n.to_bytes(4, "little")).hexdigest()
            n += 1
    return n / (time.perf_counter() - t0) / 1e6


def verify_fixture(n_lanes: int, n_unique: int = 128, rng_base: int = 7000):
    """Shared signature-verify bench fixture (bench.py and bench_suite
    config 3): ``n_unique`` distinct keypairs/messages tiled to
    ``n_lanes`` lanes.  Returns (digests, sigs, pubs, msgs)."""
    from .core import curve

    msgs, sigs, pubs = [], [], []
    for i in range(n_unique):
        d, pub = curve.keygen(rng=rng_base + i)
        m = i.to_bytes(4, "big") * 8
        sigs.append(curve.sign(m, d))
        msgs.append(m)
        pubs.append(pub)
    k = n_lanes // n_unique
    msgs, sigs, pubs = msgs * k, sigs * k, pubs * k
    digests = [hashlib.sha256(m).digest() for m in msgs]
    return digests, sigs, pubs, msgs


def python_verify_rate(msgs, sigs, pubs, seconds: float = 1.0) -> float:
    """Pure-python ECDSA verify rate on this host (the bench baseline
    convention for the reference's per-input fastecdsa loop)."""
    from .core import curve

    n_u = len(msgs)
    t0 = time.perf_counter()
    n = 0
    while time.perf_counter() - t0 < seconds:
        curve.verify(sigs[n % n_u], msgs[n % n_u], pubs[n % n_u])
        n += 1
    return n / (time.perf_counter() - t0)


def pipeline_verify_fixture(n_txs: int, n_unique: int = 128,
                            invalid_every: int = 13, rng_base: int = 9100):
    """Per-tx signature-check tuples (the txverify check shape:
    ``(digest, digest_hexform, sig, pub)``) with a deterministic mix of
    valid and invalid signatures — every ``invalid_every``-th check
    carries a corrupted ``s``, which fails BOTH verify passes (raw and
    hex-form digest) exactly like a forged wire signature would.
    ``n_unique`` keypairs/messages tiled to ``n_txs``, bench-cheap like
    :func:`verify_fixture`."""
    from .core import curve

    base = []
    for i in range(n_unique):
        d, pub = curve.keygen(rng=rng_base + i)
        m = (b"vp" + i.to_bytes(4, "big")) * 6
        digest = hashlib.sha256(m).digest()
        hexform = hashlib.sha256(m.hex().encode()).digest()
        base.append((digest, hexform, curve.sign(m, d), pub))
    checks = []
    for i in range(n_txs):
        digest, hexform, (r, s), pub = base[i % n_unique]
        if invalid_every and i % invalid_every == 0:
            s = s - 1 if s > 1 else s + 1
        checks.append((digest, hexform, (r, s), pub))
    return checks


def verify_pipeline_bench(seconds: float = 0.4, n_txs: int = 1024,
                          microbatch: int = 128) -> dict:
    """The ``verify_pipeline`` bench (ISSUE 7): pipelined engine vs the
    serial per-tx dispatch, same host backend, with a built-in
    differential check.

    * ``serial`` — one cache-bypassed ``run_sig_checks`` call per tx
      (the reference's profile: every hop re-verifies every signature
      through the same ``verify_batch_native_cpu`` host path, one tx at
      a time).
    * ``pipelined`` — micro-batched submissions coalesced through the
      shared dispatch front (verify/dispatch.py) with the verdict cache
      live, sustained over ``seconds`` after one cold populate pass —
      the engine's steady-state gossip profile, where block accept
      re-verifies intake-verified txs.  The cold pass computes every
      verdict through the identical host path, so the cache can never
      answer something the serial path would not.

    Returns serial/pipelined tx-verify/s, their ratio, and the
    differential verdict comparison over all ``n_txs`` checks (serial
    vs cold pipelined vs warm pipelined must be identical lists).
    """
    import asyncio

    from .verify import txverify
    from .verify.dispatch import get_front

    checks = pipeline_verify_fixture(n_txs)

    # serial reference: per-tx dispatch, no cache
    txverify.clear_sig_verdicts()
    t0 = time.perf_counter()
    serial_verdicts: list = []
    for c in checks:
        serial_verdicts.extend(txverify.run_sig_checks(
            [c], backend="host", use_cache=False))
    serial_rate = n_txs / (time.perf_counter() - t0)

    async def one_pass():
        front = get_front()
        outs = await asyncio.gather(*[
            front.submit(checks[i:i + microbatch], backend="host",
                         source="bench")
            for i in range(0, n_txs, microbatch)])
        return [v for out in outs for v in out]

    async def pipelined():
        txverify.clear_sig_verdicts()
        cold = await one_pass()  # intake populate pass, untimed
        t0 = time.perf_counter()
        reps, warm = 0, cold
        while time.perf_counter() - t0 < seconds:
            warm = await one_pass()
            reps += 1
        elapsed = time.perf_counter() - t0
        return cold, warm, (reps * n_txs / elapsed) if reps else 0.0

    cold_verdicts, warm_verdicts, pipe_rate = asyncio.run(pipelined())
    equal = serial_verdicts == cold_verdicts == warm_verdicts
    return {
        "serial_tx_s": round(serial_rate, 1),
        "pipelined_tx_s": round(pipe_rate, 1),
        "speedup": round(pipe_rate / serial_rate, 2) if serial_rate else None,
        "differential_txs": n_txs,
        "verdicts_equal": equal,
        "n_invalid": sum(1 for v in serial_verdicts if not v),
    }


def timed_reps(fn, seconds: float, max_reps: Optional[int] = None):
    """Repeat ``fn`` until the deadline (or ``max_reps``); returns
    (reps, elapsed).  The shared timed-loop plumbing for synchronous
    bench measurements."""
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < seconds and (
            max_reps is None or reps < max_reps):
        fn()
        reps += 1
    return reps, time.perf_counter() - t0


async def chain_with_utxo_fanout(n_fan: int, n_per: int, rng_key: int):
    """3-block in-memory chain fanning one coinbase into n_fan x n_per
    spendable leaf outputs — shared scaffolding for the bench_suite
    accept/intake configs and the loadgen funded-wallet fixture.
    Returns (state, manager, d, pub, addr, mids, mine_block) where
    ``mine_block(txs)`` accepts one more block and returns its accept
    seconds.  Mutates process-global difficulty/clock state; callers
    must ``clock.reset()`` when done (bench configs and the loadgen
    harness both do)."""
    import time
    from decimal import Decimal

    from .core import clock, curve, difficulty, point_to_string
    from .core.header import BlockHeader
    from .core.merkle import merkle_root
    from .core.tx import Tx, TxInput, TxOutput
    from .mine.engine import MiningJob, mine
    from .state import ChainState
    from .verify import BlockManager

    difficulty.START_DIFFICULTY = Decimal("1.0")
    genesis_prev = (18_884_643).to_bytes(32, "little").hex()

    state = ChainState()
    manager = BlockManager(state)
    d, pub = curve.keygen(rng=rng_key)
    addr = point_to_string(pub)
    pub_of = lambda _i: pub  # noqa: E731

    async def mine_block(txs):
        clock.advance(60)
        diff, last = await manager.calculate_difficulty()
        prev = last["hash"] if last else genesis_prev
        header = BlockHeader(
            previous_hash=prev, address=addr, merkle_root=merkle_root(txs),
            timestamp=clock.timestamp(), difficulty_x10=int(diff * 10),
            nonce=0)
        if last:
            r = mine(MiningJob(header.prefix_bytes(), prev, diff),
                     "python", batch=1 << 14, ttl=600)
            header.nonce = r.nonce
        errors = []
        t0 = time.perf_counter()
        ok = await manager.create_block(header.hex(), txs, errors=errors)
        dt = time.perf_counter() - t0
        assert ok, errors
        return dt

    await mine_block([])                      # block 1: coinbase to addr
    coin = (await state.get_spendable_outputs(addr))[0]
    reward = coin.amount

    per = reward // n_fan
    outs = [TxOutput(addr, per)] * (n_fan - 1)
    outs = outs + [TxOutput(addr, reward - per * (n_fan - 1))]
    fan = Tx([coin], outs).sign([d], pub_of)
    await mine_block([fan])

    mids = []
    for j in range(n_fan):
        amt = fan.outputs[j].amount
        sub = amt // n_per
        souts = [TxOutput(addr, sub)] * (n_per - 1)
        souts = souts + [TxOutput(addr, amt - sub * (n_per - 1))]
        mids.append(Tx([TxInput(fan.hash(), j)], souts).sign([d], pub_of))
    await mine_block(mids)
    return state, manager, d, pub, addr, mids, mine_block


def leaf_spends(parents, addr, d, pub):
    """One 1-in-1-out spend per output of each parent tx (the bench
    and loadgen push_tx payload generator)."""
    from .core.tx import Tx, TxInput, TxOutput

    out = []
    for m in parents:
        h = m.hash()
        for k, o in enumerate(m.outputs):
            out.append(Tx([TxInput(h, k)], [TxOutput(addr, o.amount)])
                       .sign([d], lambda _i: pub))
    return out


def accept_resident_bench(seconds: float = 0.4, n_fan: int = 255,
                          n_per: int = 32) -> dict:
    """Config 15: end-to-end 8k-tx block accept, host-round-trip path
    (per-table SQL membership) vs the HBM-resident fused accept path
    (state/device_index.py probes fused into the digest-prep dispatch),
    with the byte-identity differential — resident probe vs host shadow
    map vs SQL — checked after accept, after a FORCED REORG
    (remove_blocks), and after re-accepting the same block.  Shared by
    bench_suite config 15 and the loadgen observatory so ``make
    perf-smoke`` can enforce the same numbers.

    The speedup fields are ZEROED unless every differential passed —
    callers refuse to emit a headline from a diverged run."""
    import asyncio

    from .core import clock
    from .verify import txverify

    ABSENT = [("ff" * 32, i) for i in range(16)]

    async def scenario(resident: bool) -> dict:
        state, manager, d, pub, addr, mids, mine_block = \
            await chain_with_utxo_fanout(n_fan, n_per, 0xACC7)
        manager.fused_accept = resident
        if resident:
            state.enable_device_index()
            if not state.resident_indexes():
                raise RuntimeError("device UTXO index failed to arm")
        txs = leaf_spends(mids, addr, d, pub)
        spent = [i.outpoint for t in txs for i in t.inputs]
        created = [(t.hash(), 0) for t in txs]
        sample = spent + created + ABSENT
        pre_hash = await state.get_unspent_outputs_hash()
        txverify.clear_sig_verdicts()  # cold-signature accept, both paths
        dt = await mine_block(txs)
        out = {"n_txs": len(txs), "accept_seconds": dt,
               "utxo_hash": await state.get_unspent_outputs_hash()}

        async def parity() -> bool:
            """Resident probe vs host shadow map vs SQL, one sample."""
            idx = state.resident_indexes()["unspent_outputs"]
            dev = [bool(v) for v in idx.contains_batch(sample)]
            shadow = [bool(v) for v in idx.shadow_contains_batch(sample)]
            sql = [bool(v) for v in
                   await state.outpoints_exist(sample, "unspent_outputs")]
            return dev == shadow == sql

        # membership-scan micro-measure: the double-spend scan isolated
        # from rules/sig work — the serial path's per-accept SQL
        # round-trip vs one resident probe dispatch
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < seconds or reps == 0:
            if resident:
                state.resident_indexes()["unspent_outputs"] \
                    .contains_batch(sample)
            else:
                await state.outpoints_exist(sample, "unspent_outputs")
            reps += 1
        out["scan_tx_s"] = reps * len(sample) / (time.perf_counter() - t0)

        if resident:
            ok = await parity()
            # forced reorg: drop the 8k block, O(delta) index rollback —
            # the unspent-set fingerprint must return EXACTLY to its
            # pre-accept value
            await state.remove_blocks(4)
            ok = ok and await parity()
            ok = ok and pre_hash == await state.get_unspent_outputs_hash()
            # re-accept the same transactions (the re-mined header gets
            # a fresh timestamp, so its coinbase outpoint differs — the
            # three-way parity is the byte-identity check here)
            dt2 = await mine_block(txs)
            ok = ok and await parity()
            out["reaccept_seconds"] = dt2
            out["reorg_ok"] = bool(ok)
            stats = state.index_stats()
            out["shadow_consults"] = stats["shadow_consults"]
            out["twin_fingerprints"] = stats["twin_fingerprints"]
        state.close()
        return out

    # both paths must see identical per-block timestamps or the block
    # hashes (and therefore the coinbase outpoints) diverge and the
    # hash differential is meaningless — the clock base is wall time,
    # so a scenario crossing a wall-second boundary would skew the
    # second run.  Freeze to a fixed epoch before EACH path; advance(60)
    # per mined block still moves chain time on top of the frozen base.
    clock.freeze(1_700_000_000)
    serial = asyncio.run(scenario(False))
    clock.freeze(1_700_000_000)
    resident = asyncio.run(scenario(True))
    clock.reset()

    ok = bool(resident.get("reorg_ok")
              and serial["utxo_hash"] == resident["utxo_hash"]
              and serial["n_txs"] == resident["n_txs"])
    speedup = serial["accept_seconds"] / resident["accept_seconds"]
    scan_speedup = resident["scan_tx_s"] / serial["scan_tx_s"] \
        if serial["scan_tx_s"] else 0.0
    return {
        "n_txs": serial["n_txs"],
        "serial_tx_s": round(serial["n_txs"] / serial["accept_seconds"], 1),
        "resident_tx_s": round(
            resident["n_txs"] / resident["accept_seconds"], 1),
        "speedup": round(speedup, 2) if ok else 0.0,
        "scan_serial_tx_s": round(serial["scan_tx_s"], 1),
        "scan_resident_tx_s": round(resident["scan_tx_s"], 1),
        "scan_speedup": round(scan_speedup, 2) if ok else 0.0,
        "differential_ok": ok,
        "reaccept_seconds": round(resident["reaccept_seconds"], 4),
        "shadow_consults": resident["shadow_consults"],
        "twin_fingerprints": resident["twin_fingerprints"],
    }


def mining_mesh_bench(seconds: float = 0.4, n_jobs: int = 3,
                      batch_per_device: int = 1 << 12,
                      shard_counts=()) -> dict:
    """Config 16: resident mesh-sharded nonce search (mine/mesh_engine)
    vs the serial single-device jnp path, with the bit-identity
    differential built in: over ``n_jobs`` seeded jobs every mesh round
    must return EXACTLY the serial path's min-hit for the same window
    (full rounds AND a ragged tail round), and the engine's own dispatch
    accounting must show disjoint, gapless shard coverage.  Shared by
    bench_suite config 16 and the loadgen observatory so ``make
    perf-smoke`` enforces the same numbers.

    The sharded headline and the speedup are ZEROED unless every
    differential check passed — a diverged run trips the gate instead of
    reporting a fast wrong number.  ``shard_counts`` adds per-mesh-size
    hashrate rows (each extra size is one extra compile; the observatory
    smoke passes none)."""
    import random as _random
    from decimal import Decimal

    from .crypto import sha256 as sk
    from .mine.engine import MiningJob
    from .mine.mesh_engine import MeshEngine

    def seeded_job(seed: int) -> MiningJob:
        r = _random.Random(seed)
        prefix = bytes(r.randrange(256) for _ in range(104))
        prev = bytes(r.randrange(256) for _ in range(32)).hex()
        # difficulty 3: a hit lands roughly once per 4k nonces, so the
        # differential windows mix hits (at varying shards) and misses
        return MiningJob(prefix, prev, Decimal("3.0"))

    engine = MeshEngine(batch_per_device=batch_per_device)
    if not engine.arm()["armed"]:
        raise RuntimeError("mesh engine failed to arm: "
                           + (engine.arm_failure_reason or "unknown"))
    cap = engine.capacity

    ok, checks = True, 0
    template = spec = job = None
    for i in range(n_jobs):
        job = seeded_job(0xD1F0 + i)
        engine.set_job(job)
        template = sk.make_template(job.prefix)
        spec = sk.target_spec(job.previous_hash, job.difficulty)
        for start, count in ((0, cap), (1 << 20, cap),
                             (1 << 24, cap // 3 + 1)):
            got = int(engine.dispatch(start, count))
            want = int(sk.pow_search_jnp(template, spec,
                                         nonce_base=start, batch=count))
            ok = ok and got == want
            if got != int(sk.SENTINEL):
                ok = ok and job.check(got)
            checks += 1
    for rec in engine.stats()["rounds"]:
        shards = rec["shards"]
        ok = ok and shards[0][0] == rec["lo"] \
            and shards[-1][1] == rec["hi"] \
            and all(b == c for (_, b), (c, _) in zip(shards, shards[1:]))
        checks += 1

    def rate_of(dispatch_round, round_size) -> float:
        cursor = [0]

        def dispatch():
            r = dispatch_round(cursor[0], round_size)
            cursor[0] = (cursor[0] + round_size) % (1 << 31)
            return r

        int(dispatch())  # warm outside the timed window
        rounds, elapsed = pipelined_loop(dispatch, lambda r: int(r),
                                         seconds)
        return rounds * round_size / elapsed / 1e6

    sharded_mhs = rate_of(engine.dispatch, cap)
    serial_mhs = rate_of(
        lambda start, count: sk.pow_search_jnp(
            template, spec, nonce_base=start, batch=count), cap)

    rows = []
    for n in shard_counts:
        if not 1 <= n <= engine.n_devices:
            continue
        if n == engine.n_devices:
            rows.append({"shards": n, "mhs": round(sharded_mhs, 3)})
            continue
        sub = MeshEngine(mesh_devices=n,
                         batch_per_device=batch_per_device)
        if not sub.arm()["armed"]:
            continue
        sub.set_job(job)
        rows.append({"shards": n,
                     "mhs": round(rate_of(sub.dispatch, sub.capacity), 3)})

    speedup = sharded_mhs / serial_mhs if serial_mhs else 0.0
    return {
        "n_devices": engine.n_devices,
        "batch_per_device": engine.batch_per_device,
        "differential_ok": ok,
        "differential_checks": checks,
        "serial_mhs": round(serial_mhs, 3),
        "sharded_mhs": round(sharded_mhs, 3) if ok else 0.0,
        "speedup": round(speedup, 2) if ok else 0.0,
        "per_shard_counts": rows,
    }


def pipelined_loop(dispatch, finalize, seconds: float, depth: int = 2):
    """Keep up to ``depth`` async dispatches in flight until the deadline,
    then drain.  Returns (completed_rounds, elapsed) — elapsed includes
    the drain, so rate accounting stays honest.

    The canonical deadline/drain loop for device benchmarks (the mining
    engine pipelines the same way): JAX dispatch is async, so the host
    only blocks inside ``finalize`` on the oldest round while newer
    rounds execute."""
    t0 = time.perf_counter()
    done = 0
    inflight = []
    while time.perf_counter() - t0 < seconds or inflight:
        if len(inflight) < depth and time.perf_counter() - t0 < seconds:
            inflight.append(dispatch())
            continue
        if not inflight:  # deadline crossed between the two time checks
            break
        finalize(inflight.pop(0))
        done += 1
    return done, time.perf_counter() - t0
