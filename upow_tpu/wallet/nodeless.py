"""Nodeless wallet: HTTP-only flows (reference upow_wallet/nodeless_wallet.py).

Builds transactions purely from a remote node's ``get_address_info``
response (spendable outputs) and pushes them via ``push_tx`` — no local
chain state required.  Includes the reference's 255-input consolidation
guard (nodeless_wallet.py:97-111): when an address has more outputs than
one tx can spend, send batches of 255 back to yourself first.
"""

from __future__ import annotations

import asyncio
from decimal import Decimal
from typing import List, Optional, Tuple

import aiohttp

from ..core import curve
from ..core.codecs import point_to_string
from ..core.constants import SMALLEST
from ..core.tx import Tx, TxInput, TxOutput
from .builders import select_transaction_inputs, _to_units


class NodelessWallet:
    def __init__(self, node_url: str):
        self.node_url = node_url.rstrip("/")

    async def _get(self, path: str, params: dict) -> dict:
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=30)) as session:
            async with session.get(f"{self.node_url}/{path}",
                                   params=params) as resp:
                return await resp.json()

    async def get_address_info(self, address: str, **flags) -> dict:
        params = {"address": address}
        params.update({k: "true" for k, v in flags.items() if v})
        res = await self._get("get_address_info", params)
        if not res.get("ok"):
            raise RuntimeError(res.get("error", "get_address_info failed"))
        return res["result"]

    async def get_balance(self, address: str) -> Tuple[Decimal, Decimal]:
        info = await self.get_address_info(address)
        return Decimal(info["balance"]), Decimal(info["stake"])

    async def _spendable_inputs(self, address: str) -> List[TxInput]:
        info = await self.get_address_info(address, show_pending=True)
        pending_spent = {
            (o["tx_hash"], o["index"])
            for o in (info.get("pending_spent_outputs") or [])
        }
        inputs = []
        for o in info["spendable_outputs"]:
            if (o["tx_hash"], o["index"]) in pending_spent:
                continue
            i = TxInput(o["tx_hash"], o["index"])
            i.amount = int(Decimal(o["amount"]) * SMALLEST)
            inputs.append(i)
        return inputs

    async def create_transaction(self, private_key: int, receiving_address: str,
                                 amount, message: Optional[bytes] = None) -> Tx:
        units = _to_units(amount)
        pub = curve.point_mul(private_key, curve.G)
        sender = point_to_string(pub)
        inputs = await self._spendable_inputs(sender)
        if not inputs:
            raise ValueError("No spendable outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough funds")
        chosen = select_transaction_inputs(inputs, units)
        if len(chosen) > 255:
            raise ValueError(
                "Too many inputs for one transaction — consolidate first "
                "(see consolidate_outputs)")
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen, [TxOutput(receiving_address, units)], message)
        if total > units:
            tx.outputs.append(TxOutput(sender, total - units))
        return tx.sign([private_key], lambda _i: pub)

    async def consolidate_outputs(self, private_key: int,
                                  batch: int = 255) -> Optional[str]:
        """Merge up to ``batch`` outputs into one self-send
        (nodeless_wallet.py:97-111)."""
        pub = curve.point_mul(private_key, curve.G)
        sender = point_to_string(pub)
        inputs = await self._spendable_inputs(sender)
        if len(inputs) <= 1:
            return None
        chosen = inputs[:batch]
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen, [TxOutput(sender, total)])
        tx.sign([private_key], lambda _i: pub)
        return await self.push_tx(tx)

    async def push_tx(self, tx: Tx) -> str:
        res = await self._get("push_tx", {"tx_hex": tx.hex()})
        if not res.get("ok"):
            raise RuntimeError(res.get("error", "push_tx failed"))
        return res.get("tx_hash", tx.hash())

    async def send(self, private_key: int, to_address: str, amount,
                   message: Optional[bytes] = None) -> str:
        tx = await self.create_transaction(private_key, to_address, amount, message)
        return await self.push_tx(tx)


def main() -> int:  # minimal CLI parity with the reference script
    import argparse

    parser = argparse.ArgumentParser("upow_tpu nodeless wallet")
    parser.add_argument("command", choices=["balance", "send", "consolidate"])
    parser.add_argument("--node", required=True)
    parser.add_argument("--key", type=lambda s: int(s, 0), required=False)
    parser.add_argument("--address", required=False)
    parser.add_argument("-to", dest="to")
    parser.add_argument("-a", dest="amount")
    args = parser.parse_args()
    w = NodelessWallet(args.node)
    if args.command == "balance":
        address = args.address or point_to_string(
            curve.point_mul(args.key, curve.G))
        bal, stake = asyncio.run(w.get_balance(address))
        print(f"Balance: {bal}\nStake: {stake}")
    elif args.command == "send":
        print(asyncio.run(w.send(args.key, args.to, args.amount)))
    else:
        print(asyncio.run(w.consolidate_outputs(args.key)))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
