"""Wallet / client SDK (reference upow/upow_wallet/)."""

from .builders import WalletBuilder  # noqa: F401
from .keystore import KeyStore  # noqa: F401
