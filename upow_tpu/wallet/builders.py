"""The ten transaction builders (reference upow/upow_wallet/utils.py:11-604).

Construction rules replicated exactly — greedy coin selection (smallest
single sufficient input, else largest-first fill), the stake builder's
automatic 10-power delegate grant, registration amounts (1000 inode /
100 validator), vote range caps, the 48-hour revoke rule — but built
against this framework's :class:`ChainState` view with int smallest-unit
amounts and the pure ``Tx`` codec.

All builders take ``check_pending_txs=True`` views like the reference, so
outputs already referenced by mempool txs are never double-selected.
"""

from __future__ import annotations

from decimal import Decimal
from typing import List, Optional, Sequence, Tuple

from ..core import curve
from ..core.codecs import OutputType, TransactionType, point_to_string
from ..core.constants import MAX_INODES, SMALLEST
from ..core.tx import Tx, TxInput, TxOutput
from ..state.storage import ChainState


def _to_units(amount) -> int:
    units = Decimal(str(amount)) * SMALLEST
    if units != int(units):
        raise ValueError(f"amount {amount} has more than 8 decimals")
    return int(units)


def _type_message(tx_type: TransactionType) -> bytes:
    """Tx type is carried in the free-form message bytes
    (reference helpers.py:97-112 / utils.py string_to_bytes(str(value)))."""
    return str(int(tx_type)).encode()


def select_transaction_inputs(inputs: List[TxInput], amount: int) -> List[TxInput]:
    """Greedy selection (utils.py:594-604): smallest input that covers the
    whole amount, else fill largest-first."""
    chosen: List[TxInput] = []
    for tx_input in sorted(inputs, key=lambda i: i.amount):
        if tx_input.amount >= amount:
            chosen.append(tx_input)
            break
    for tx_input in sorted(inputs, key=lambda i: i.amount, reverse=True):
        if sum(i.amount for i in chosen) >= amount:
            break
        chosen.append(tx_input)
    return chosen


class WalletBuilder:
    """Builders over one ChainState (direct-DB wallet mode)."""

    def __init__(self, state: ChainState):
        self.state = state

    # ------------------------------------------------------------ helpers --
    @staticmethod
    def _address_of(private_key: int) -> Tuple[str, tuple]:
        pub = curve.point_mul(private_key, curve.G)
        return point_to_string(pub), pub

    def _signer(self, pub):
        return lambda tx_input: pub

    async def _power_inputs(self, table: str, address: str) -> List[TxInput]:
        """Voting-power / registration outputs as spendable TxInputs."""
        rows = await self.state.get_outputs_by_address(
            table, address, check_pending_txs=True)
        out = []
        for r in rows:
            i = TxInput(r["tx_hash"], r["index"])
            i.amount = r["amount"]
            out.append(i)
        return out

    async def _ballot_inputs(self, table: str, voter: str,
                             recipient: str) -> List[TxInput]:
        """Standing votes by ``voter`` for ``recipient`` as TxInputs."""
        votes = await self.state.get_votes_by_voter(
            table, voter, check_pending_txs=True)
        out = []
        for v in votes:
            if v["recipient"] != recipient:
                continue
            i = TxInput(v["tx_hash"], v["index"])
            i.amount = int(v["vote"] * SMALLEST)
            out.append(i)
        return out

    # ------------------------------------------------------------- send ----
    async def create_transaction(self, private_key: int, receiving_address: str,
                                 amount, message: Optional[bytes] = None,
                                 send_back_address: Optional[str] = None) -> Tx:
        """Plain send with greedy selection + change (utils.py:11-60)."""
        units = _to_units(amount)
        sender, pub = self._address_of(private_key)
        send_back_address = send_back_address or sender
        inputs = await self.state.get_spendable_outputs(
            sender, check_pending_txs=True)
        if not inputs:
            raise ValueError("No spendable outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough funds")
        chosen = select_transaction_inputs(inputs, units)
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen, [TxOutput(receiving_address, units)], message)
        if total > units:
            tx.outputs.append(TxOutput(send_back_address, total - units))
        return tx.sign([private_key], self._signer(pub))

    async def create_transaction_to_send_multiple_wallet(
            self, private_key: int, receiving_addresses: Sequence[str],
            amounts: Sequence, message: Optional[bytes] = None,
            send_back_address: Optional[str] = None) -> Tx:
        """Multi-recipient send (utils.py:63-120; largest-first selection)."""
        if len(receiving_addresses) != len(amounts):
            raise ValueError(
                "Receiving addresses length is different from amounts length")
        units = [_to_units(a) for a in amounts]
        total_amount = sum(units)
        sender, pub = self._address_of(private_key)
        send_back_address = send_back_address or sender
        inputs = await self.state.get_spendable_outputs(
            sender, check_pending_txs=True)
        if not inputs:
            raise ValueError("No spendable outputs")
        if sum(i.amount for i in inputs) < total_amount:
            raise ValueError("Error: You don't have enough funds")
        chosen: List[TxInput] = []
        input_amount = 0
        for tx_input in sorted(inputs, key=lambda i: i.amount, reverse=True):
            chosen.append(tx_input)
            input_amount += tx_input.amount
            if input_amount >= total_amount:
                break
        outputs = [TxOutput(addr, a)
                   for addr, a in zip(receiving_addresses, units)]
        change = input_amount - total_amount
        if change > 0:
            outputs.append(TxOutput(send_back_address, change))
        tx = Tx(chosen, outputs, message)
        return tx.sign([private_key], self._signer(pub))

    # ------------------------------------------------------------ staking --
    async def create_stake_transaction(self, private_key: int, amount,
                                       send_back_address: Optional[str] = None) -> Tx:
        """Stake + automatic first-time 10-power delegate grant
        (utils.py:123-192)."""
        units = _to_units(amount)
        sender, pub = self._address_of(private_key)
        send_back_address = send_back_address or sender
        inputs = await self.state.get_spendable_outputs(
            sender, check_pending_txs=True)
        if not inputs:
            raise ValueError("No spendable outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough funds")
        if await self.state.get_stake_outputs(sender):
            raise ValueError("Already staked")
        if await self.state.get_pending_stake_transactions(sender):
            raise ValueError("Already staked. Transaction is in pending")
        chosen = select_transaction_inputs(inputs, units)
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen, [TxOutput(sender, units, OutputType.STAKE)])
        if total > units:
            tx.outputs.append(TxOutput(send_back_address, total - units))
        if not await self.state.get_delegates_all_power(
                sender, check_pending_txs=True):
            tx.outputs.append(TxOutput(
                sender, 10 * SMALLEST, OutputType.DELEGATE_VOTING_POWER))
        return tx.sign([private_key], self._signer(pub))

    async def create_unstake_transaction(self, private_key: int) -> Tx:
        """Unstake the (single) stake output (utils.py:195-222)."""
        sender, pub = self._address_of(private_key)
        stake_inputs = await self.state.get_stake_outputs(
            sender, check_pending_txs=True)
        if not stake_inputs:
            raise ValueError("Error: There is nothing staked")
        if await self.state.get_delegates_spent_votes(sender):
            raise ValueError("Kindly release the votes.")
        if await self.state.get_pending_vote_as_delegate_transactions(sender):
            raise ValueError(
                "Kindly release the votes. Vote transaction is in pending")
        amount = stake_inputs[0].amount
        tx = Tx([stake_inputs[0]],
                [TxOutput(sender, amount, OutputType.UN_STAKE)])
        return tx.sign([private_key], self._signer(pub))

    # ----------------------------------------------------------- registry --
    async def create_inode_registration_transaction(self, private_key: int) -> Tx:
        """1000-coin inode registration (utils.py:225-287)."""
        units = 1000 * SMALLEST
        address, pub = self._address_of(private_key)
        inputs = await self.state.get_spendable_outputs(
            address, check_pending_txs=True)
        if not inputs:
            raise ValueError("No spendable outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough funds")
        if not await self.state.get_stake_outputs(address, check_pending_txs=True):
            raise ValueError("You are not a delegate. Become a delegate by staking.")
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            raise ValueError("This address is already registered as inode.")
        if await self.state.is_validator_registered(address, check_pending_txs=True):
            raise ValueError("This address is registered as validator and a "
                             "validator cannot be an inode.")
        if len(await self.state.get_active_inodes(check_pending_txs=True)) >= MAX_INODES:
            raise ValueError(f"{MAX_INODES} inodes are already registered.")
        chosen = select_transaction_inputs(inputs, units)
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen, [TxOutput(address, units, OutputType.INODE_REGISTRATION)])
        if total > units:
            tx.outputs.append(TxOutput(address, total - units))
        return tx.sign([private_key], self._signer(pub))

    async def create_inode_de_registration_transaction(self, private_key: int) -> Tx:
        """Spend the registration output back (utils.py:290-313)."""
        address, pub = self._address_of(private_key)
        inputs = await self._power_inputs("inode_registration_output", address)
        if not inputs:
            raise ValueError("This address is not registered as an inode.")
        active = await self.state.get_active_inodes(check_pending_txs=True)
        if any(e.get("wallet") == address for e in active):
            raise ValueError("This address is an active inode. Cannot de-register.")
        amount = inputs[0].amount
        tx = Tx(inputs, [TxOutput(address, amount)],
                _type_message(TransactionType.INODE_DE_REGISTRATION))
        return tx.sign([private_key], self._signer(pub))

    async def create_validator_registration_transaction(self, private_key: int) -> Tx:
        """100-coin validator registration + 10 voting power
        (utils.py:316-377)."""
        units = 100 * SMALLEST
        address, pub = self._address_of(private_key)
        inputs = await self.state.get_spendable_outputs(
            address, check_pending_txs=True)
        if not inputs:
            raise ValueError("No spendable outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough funds")
        if not await self.state.get_stake_outputs(address, check_pending_txs=True):
            raise ValueError("You are not a delegate. Become a delegate by staking.")
        if await self.state.is_validator_registered(address, check_pending_txs=True):
            raise ValueError("This address is already registered as validator.")
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            raise ValueError("This address is registered as inode and an inode "
                             "cannot be a validator.")
        chosen = select_transaction_inputs(inputs, units)
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen,
                [TxOutput(address, units, OutputType.VALIDATOR_REGISTRATION)],
                _type_message(TransactionType.VALIDATOR_REGISTRATION))
        tx.outputs.append(TxOutput(
            address, 10 * SMALLEST, OutputType.VALIDATOR_VOTING_POWER))
        if total > units:
            tx.outputs.append(TxOutput(address, total - units))
        return tx.sign([private_key], self._signer(pub))

    # ------------------------------------------------------------- voting --
    async def create_voting_transaction(self, private_key: int, vote_range,
                                        vote_receiving_address: str) -> Tx:
        """Dispatch by eligibility (utils.py:380-406)."""
        try:
            vote_int = int(vote_range)
        except (TypeError, ValueError):
            raise ValueError("Invalid voting range")
        if vote_int > 10:
            raise ValueError("Voting should be in range of 10")
        if vote_int <= 0:
            raise ValueError("Invalid voting range")
        address, _ = self._address_of(private_key)
        if await self.state.is_inode_registered(address, check_pending_txs=True):
            raise ValueError("This address is registered as inode. Cannot vote.")
        if await self.state.is_validator_registered(address, check_pending_txs=True):
            return await self.vote_as_validator(
                private_key, vote_int, vote_receiving_address)
        if await self.state.get_stake_outputs(address, check_pending_txs=True):
            return await self.vote_as_delegate(
                private_key, vote_int, vote_receiving_address)
        raise ValueError("Not eligible to vote")

    async def vote_as_validator(self, private_key: int, vote_range: int,
                                recipient: str) -> Tx:
        """Spend validator voting power into the inode ballot
        (utils.py:409-457)."""
        units = vote_range * SMALLEST
        address, pub = self._address_of(private_key)
        inputs = await self._power_inputs("validators_voting_power", address)
        if not inputs:
            raise ValueError("No voting outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough voting power left. "
                             "Kindly revoke some voting power.")
        if not await self.state.is_inode_registered(recipient, check_pending_txs=True):
            raise ValueError("Vote recipient is not registered as an inode.")
        chosen = select_transaction_inputs(inputs, units)
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen,
                [TxOutput(recipient, units, OutputType.VOTE_AS_VALIDATOR)],
                _type_message(TransactionType.VOTE_AS_VALIDATOR))
        if total > units:
            tx.outputs.append(TxOutput(
                address, total - units, OutputType.VALIDATOR_VOTING_POWER))
        return tx.sign([private_key], self._signer(pub))

    async def vote_as_delegate(self, private_key: int, vote_range: int,
                               recipient: str) -> Tx:
        """Spend delegate voting power into the validator ballot
        (utils.py:460-507)."""
        units = vote_range * SMALLEST
        address, pub = self._address_of(private_key)
        inputs = await self._power_inputs("delegates_voting_power", address)
        if not inputs:
            raise ValueError("No voting outputs")
        if sum(i.amount for i in inputs) < units:
            raise ValueError("Error: You don't have enough voting power left. "
                             "Kindly release some voting power.")
        if not await self.state.is_validator_registered(
                recipient, check_pending_txs=True):
            raise ValueError("Vote recipient is not registered as a validator.")
        chosen = select_transaction_inputs(inputs, units)
        total = sum(i.amount for i in chosen)
        tx = Tx(chosen,
                [TxOutput(recipient, units, OutputType.VOTE_AS_DELEGATE)],
                _type_message(TransactionType.VOTE_AS_DELEGATE))
        if total > units:
            tx.outputs.append(TxOutput(
                address, total - units, OutputType.DELEGATE_VOTING_POWER))
        return tx.sign([private_key], self._signer(pub))

    # ------------------------------------------------------------- revoke --
    async def create_revoke_transaction(self, private_key: int,
                                        revoke_from_address: str) -> Tx:
        """Dispatch by role (utils.py:510-522)."""
        address, _ = self._address_of(private_key)
        if await self.state.is_validator_registered(address, check_pending_txs=True):
            return await self.revoke_vote_as_validator(
                private_key, revoke_from_address)
        if await self.state.get_stake_outputs(address, check_pending_txs=True):
            return await self.revoke_vote_as_delegate(
                private_key, revoke_from_address)
        raise ValueError("Not eligible to revoke")

    async def revoke_vote_as_validator(self, private_key: int,
                                       inode_address: str) -> Tx:
        """Reclaim voting power from the inode ballot after 48 h
        (utils.py:525-557)."""
        address, pub = self._address_of(private_key)
        ballot_inputs = await self._ballot_inputs(
            "inodes_ballot", address, inode_address)
        if not ballot_inputs:
            raise ValueError("You have not voted.")
        valid = [await self.state.is_revoke_valid(i.tx_hash)
                 for i in ballot_inputs]
        if not any(valid):
            raise ValueError("You can revoke after 48 hrs of voting")
        total = sum(i.amount for i in ballot_inputs)
        tx = Tx(ballot_inputs,
                [TxOutput(address, total, OutputType.VALIDATOR_VOTING_POWER)],
                _type_message(TransactionType.REVOKE_AS_VALIDATOR))
        return tx.sign([private_key], self._signer(pub))

    async def revoke_vote_as_delegate(self, private_key: int,
                                      validator_address: str) -> Tx:
        """Reclaim delegate voting power from the validator ballot
        (utils.py:560-591)."""
        address, pub = self._address_of(private_key)
        ballot_inputs = await self._ballot_inputs(
            "validators_ballot", address, validator_address)
        if not ballot_inputs:
            raise ValueError("You have not voted.")
        valid = [await self.state.is_revoke_valid(i.tx_hash)
                 for i in ballot_inputs]
        if not any(valid):
            raise ValueError("You can revoke after 48 hrs of voting")
        total = sum(i.amount for i in ballot_inputs)
        tx = Tx(ballot_inputs,
                [TxOutput(address, total, OutputType.DELEGATE_VOTING_POWER)],
                _type_message(TransactionType.REVOKE_AS_DELEGATE))
        return tx.sign([private_key], self._signer(pub))
