"""Wallet CLI: the ten subcommands (reference upow_wallet/wallet.py:44-62).

``python -m upow_tpu.wallet.cli <command> [...]`` with the reference's
flags: ``-to`` recipient(s), ``-a`` amount(s), ``-m`` message, ``-r``
vote range, ``-from`` revoke source.  Transactions are pushed to the
configured node over HTTP; if that fails and a local chain DB is
configured, they are inserted directly into its mempool
(wallet.py:243-252's fallback).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from decimal import Decimal
from typing import Optional

from ..config import Config
from ..core.codecs import point_to_string
from ..core import curve
from ..state.storage import ChainState
from .builders import WalletBuilder
from .keystore import KeyStore


def _string_to_bytes(string: Optional[str]) -> Optional[bytes]:
    if string is None:
        return None
    try:
        return bytes.fromhex(string)
    except ValueError:
        return string.encode("utf-8")


async def push_tx(tx, node_url: str, state: Optional[ChainState]) -> None:
    if not node_url:
        # explicit local-only mode (--node ""): straight to the local
        # chain's mempool, no network attempt
        if state is None:
            raise RuntimeError("no node url and no local chain db")
        await state.add_pending_transaction(tx)
        print(f"Transaction added to local mempool. Hash: {tx.hash()}")
        return
    import aiohttp

    try:
        async with aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=20)) as session:
            async with session.get(f"{node_url.rstrip('/')}/push_tx",
                                   params={"tx_hex": tx.hex()}) as resp:
                res = await resp.json()
        if res.get("ok"):
            print(f"Transaction pushed. Hash: {tx.hash()}")
            return
        raise RuntimeError(res.get("error", "push failed"))
    except Exception as e:
        if state is None:
            raise
        print(f"node push failed ({e}); falling back to local mempool")
        await state.add_pending_transaction(tx)
        print(f"Transaction added to local mempool. Hash: {tx.hash()}")


async def amain(argv=None) -> int:
    parser = argparse.ArgumentParser("upow_tpu wallet")
    parser.add_argument("command", choices=[
        "createwallet", "balance", "send", "sendmany", "stake", "unstake",
        "register_inode", "de_register_inode", "register_validator",
        "vote", "revoke"])
    parser.add_argument("-to", metavar="recipient", type=str, required=False)
    parser.add_argument("-a", metavar="amount", type=str, required=False)
    parser.add_argument("-m", metavar="message", type=str, dest="message")
    parser.add_argument("-r", metavar="range", type=str, dest="range")
    parser.add_argument("-from", metavar="revoke_from", type=str,
                        dest="revoke_from")
    parser.add_argument("--wallet", type=str, default=None,
                        help="key_pair_list.json path")
    parser.add_argument("--db", type=str, default=None,
                        help="local chain db (direct mode)")
    parser.add_argument("--node", type=str, default=None, help="node URL")
    args = parser.parse_args(argv)

    cfg = Config.load()
    store = KeyStore(args.wallet)
    # an EXPLICIT --node "" means local-only (no fallback to the seed:
    # a test or air-gapped wallet must never push to the public API)
    node_url = cfg.node.seed_url if args.node is None else args.node
    db_path = args.db if args.db is not None else cfg.node.db_path
    # sole_writer=False: the node may be writing this file concurrently;
    # pay the per-read data_version pragma instead of risking 50 ms of
    # stale cached amounts (ADVICE r2).
    state = ChainState(db_path, sole_writer=False) if db_path else None

    if args.command == "createwallet":
        d, address = store.create_key()
        print(f"Private key: {hex(d)}\nAddress: {address}")
        return 0

    if not store.keys():
        print("No wallet keys — run createwallet first.")
        return 1

    if args.command == "balance":
        if state is None:
            print("balance needs a chain db (--db) or use the nodeless wallet")
            return 1
        total, total_pending = Decimal(0), Decimal(0)
        for pair in store.keys():
            d = int(pair["private_key"])
            address = point_to_string(curve.point_mul(d, curve.G))
            bal = Decimal(await state.get_address_balance(address)) / 10**8
            pend = Decimal(await state.get_address_balance(
                address, check_pending_txs=True)) / 10**8
            stake = await state.get_address_stake(address)
            total += bal
            total_pending += pend
            delta = pend - bal
            print(f"\nAddress: {address}\nPrivate key: {hex(d)}"
                  f"\nBalance: {bal}"
                  f"{f' ({delta} pending)' if delta else ''}"
                  f"\nStake: {stake}")
        print(f"\nTotal Balance: {total}"
              f"{f' ({total_pending - total} pending)' if total_pending != total else ''}")
        return 0

    if state is None:
        print("This command builds against chain state; pass --db or run a node.")
        return 1

    key = int(store.keys()[0]["private_key"])
    builder = WalletBuilder(state)
    try:
        if args.command == "send":
            tx = await builder.create_transaction(
                key, args.to, args.a, _string_to_bytes(args.message))
        elif args.command == "sendmany":
            tx = await builder.create_transaction_to_send_multiple_wallet(
                key, (args.to or "").split(","), (args.a or "").split(","),
                _string_to_bytes(args.message))
        elif args.command == "stake":
            tx = await builder.create_stake_transaction(key, args.a)
        elif args.command == "unstake":
            tx = await builder.create_unstake_transaction(key)
        elif args.command == "register_inode":
            tx = await builder.create_inode_registration_transaction(key)
        elif args.command == "de_register_inode":
            tx = await builder.create_inode_de_registration_transaction(key)
        elif args.command == "register_validator":
            tx = await builder.create_validator_registration_transaction(key)
        elif args.command == "vote":
            tx = await builder.create_voting_transaction(
                key, args.range, args.to)
        elif args.command == "revoke":
            tx = await builder.create_revoke_transaction(
                key, args.revoke_from)
        else:  # pragma: no cover
            return 2
    except ValueError as e:
        # builder refusals carry the user-facing reason (the reference
        # wallet prints these, utils.py raises the same strings) — a
        # clean message and exit code, not a traceback
        print(str(e))
        return 1
    await push_tx(tx, node_url, state)
    return 0


def main() -> int:
    return asyncio.run(amain())


if __name__ == "__main__":
    sys.exit(main())
