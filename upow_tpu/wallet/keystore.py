"""Wallet key storage: ``key_pair_list.json`` (reference wallet.py:75-88).

Same on-disk shape as the reference's pickledb file —
``{"keys": [{"private_key": <int>, "public_key": <address>}]}`` — so an
existing uPow wallet file drops in unchanged.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from ..core import curve
from ..core.codecs import point_to_string


class KeyStore:
    def __init__(self, path: Optional[str] = None):
        self.path = path or os.path.join(os.getcwd(), "key_pair_list.json")
        self._data: dict = {"keys": []}
        if os.path.exists(self.path):
            try:
                # RC001: the keystore is a tiny local JSON read once
                # per CLI invocation / faucet handler construction
                with open(self.path) as f:  # upowlint: disable=RC001
                    self._data = json.load(f)
            except (json.JSONDecodeError, OSError):
                pass
        self._data.setdefault("keys", [])

    def save(self) -> None:
        tmp = self.path + ".tmp"
        # RC001: few-KB atomic write; wallet CLI and devnet faucet only
        with open(tmp, "w") as f:  # upowlint: disable=RC001
            json.dump(self._data, f)
        os.replace(tmp, self.path)

    def create_key(self) -> Tuple[int, str]:
        """Generate, store, return (private_key, address)."""
        d, pub = curve.keygen()
        address = point_to_string(pub)
        self._data["keys"].append({"private_key": d, "public_key": address})
        self.save()
        return d, address

    def keys(self) -> List[dict]:
        return list(self._data["keys"])

    def addresses(self) -> List[str]:
        return [k["public_key"] for k in self._data["keys"]]

    def private_key_for_public(self, address: Optional[str]) -> Optional[int]:
        for k in self._data["keys"]:
            if k.get("public_key") == address:
                return int(k["private_key"])
        return None
