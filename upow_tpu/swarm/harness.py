"""Swarm assembly: N real nodes, one loop, loopback everything.

Each node is a full :class:`~upow_tpu.node.app.Node` — in-memory
sqlite state, host sig backend, its own PeerBook/breakers/mempool —
reachable at a virtual URL (``http://10.77.0.<i>:3006``).  The only
alteration is ``iface_factory``: outbound RPC goes through
:class:`~.transport.LoopbackInterface` and pays the
:class:`~.links.LinkMatrix` toll.  The scenario driver talks to nodes
with :meth:`Swarm.get`/:meth:`post` as an unregistered client (no link
shaping, local IP), mirroring how tests drive a real cluster.

Resilience knobs are tightened for simulation speed (milliseconds of
backoff, sub-second breaker reopen) — operational policy only, chain
state stays bit-identical to default-config nodes.
"""

from __future__ import annotations

import asyncio
import json
from decimal import Decimal
from typing import Callable, List, Optional

from .. import telemetry, trace
from ..config import Config
from ..logger import get_logger
from ..node.app import GENESIS_PREV_HASH, Node
from .links import LinkMatrix, LinkPolicy
from .transport import LoopbackHub, LoopbackInterface

log = get_logger("swarm")


def swarm_config(ws: bool = False, ws_queue_max: int = 0,
                 reorg_window: int = 0) -> Config:
    cfg = Config()
    cfg.node.db_path = ""           # in-memory sqlite per node
    cfg.node.seed_url = ""
    cfg.node.peers_file = ""        # peer book lives in memory
    cfg.node.ip_config_file = ""
    cfg.node.sync_fetch_interval = 0.0
    cfg.node.rate_limits_enabled = False
    if reorg_window:
        cfg.node.sync_reorg_window = reorg_window
    cfg.ws.enabled = ws
    if ws_queue_max:
        cfg.ws.send_queue_max = ws_queue_max
    cfg.device.sig_backend = "host"
    cfg.log.path = ""
    cfg.log.console = False
    # fast-simulation resilience policy (operational, not consensus)
    cfg.resilience.rpc_attempts = 2
    cfg.resilience.rpc_backoff_base = 0.005
    cfg.resilience.rpc_backoff_max = 0.02
    cfg.resilience.rpc_deadline = 2.0
    cfg.resilience.propagate_deadline = 1.0
    cfg.resilience.breaker_failure_threshold = 3
    cfg.resilience.breaker_open_secs = 0.25
    # swarm assertions read trace trees and events across many nodes;
    # default rings are sized for one
    cfg.telemetry.trace_recent = 512
    cfg.telemetry.events_buffer = 4096
    # every node gets its own metrics/SLO/events/trace registries —
    # 50 in-loop nodes must not clobber one process-global registry
    # (fleet scraper + scenario assertions read them per node)
    cfg.telemetry.instance_scope = True
    # every node is the sole writer of its in-memory state, so the
    # read cache never needs foreign-writer revalidation — leaving it
    # on would let the periodic re-anchor mask a missing invalidation
    # hook (the partition_heal assertion wants the HOOK, not the
    # backstop, to invalidate losers' caches after their reorg)
    cfg.cache.revalidate_interval = -1.0
    return cfg


class Swarm:
    """N loopback nodes over one LinkMatrix."""

    def __init__(self, n: int, seed: int = 0,
                 link: Optional[LinkPolicy] = None, ws: bool = False,
                 ws_queue_max: int = 0, reorg_window: int = 0,
                 cfg_hook: Optional[Callable[[int, Config], None]] = None):
        self.n = n
        self.seed = seed
        self.matrix = LinkMatrix(seed, default=link)
        self.hub = LoopbackHub(self.matrix)
        self.ws = ws
        self.ws_queue_max = ws_queue_max
        self.reorg_window = reorg_window
        self.cfg_hook = cfg_hook
        self.nodes: List[Node] = []
        self.urls: List[str] = []
        self.ips: List[str] = []
        self.driver = "http://driver.local"  # unregistered: no shaping
        # per-node black box (fleet/recorder.py): scenario drivers mark
        # phase boundaries; run_scenario dumps on failure/fault/breach
        from ..fleet.recorder import FlightRecorder
        self.recorder = FlightRecorder()

    # -------------------------------------------------------------- build --
    async def start(self, topology: str = "mesh") -> "Swarm":
        for i in range(self.n):
            ip = f"10.77.{i // 250}.{i % 250 + 1}"
            url = f"http://{ip}:3006"
            cfg = swarm_config(ws=self.ws, ws_queue_max=self.ws_queue_max,
                               reorg_window=self.reorg_window)
            if self.cfg_hook is not None:
                self.cfg_hook(i, cfg)
            node = Node(cfg)
            node.self_url = url
            node.started = True  # skip first-request bootstrap
            if node.telemetry_scope is not None:
                node.telemetry_scope.name = f"node{i}"
            node.iface_factory = self._factory(url)
            node.app.freeze()
            await node.app.startup()
            self.hub.register_node(url, node, ip)
            self.nodes.append(node)
            self.urls.append(url)
            self.ips.append(ip)
        if topology == "mesh":
            for i, node in enumerate(self.nodes):
                for j, url in enumerate(self.urls):
                    if i != j:
                        node.peers.add(url)
        return self

    def _factory(self, self_url: str):
        hub = self.hub

        def make(url, cfg=None, session=None, resilience=None):
            return LoopbackInterface(hub, self_url, url, cfg,
                                     session=session, resilience=resilience)

        return make

    async def close(self) -> None:
        for node in self.nodes:
            if node.ws_hub is not None:
                node.ws_hub.close()
            await node.close()
        self.nodes.clear()

    # ------------------------------------------------------------- client --
    def _headers(self) -> dict:
        headers = {}
        tid = trace.current_trace_id()
        if tid is not None:
            # driver requests propagate their trace like a peer RPC, so
            # a scenario step is ONE trace across every node it touches
            headers[trace.TRACE_HEADER] = tid
        return headers

    async def get(self, i: int, path: str,
                  params: Optional[dict] = None) -> dict:
        _, body = await self.hub.request(
            self.driver, self.urls[i], "GET", "/" + path.lstrip("/"),
            params=params, headers=self._headers())
        return json.loads(body or b"{}")

    async def post(self, i: int, path: str, json_body: dict) -> dict:
        _, body = await self.hub.request(
            self.driver, self.urls[i], "POST", "/" + path.lstrip("/"),
            json_body=json_body, headers=self._headers())
        return json.loads(body or b"{}")

    # -------------------------------------------------------------- chain --
    async def mine(self, i: int, address: str,
                   push_to: Optional[List[int]] = None,
                   _retried: bool = False) -> dict:
        """Drive the miner protocol against node ``i`` (the test-suite
        mine_via_api port): one BLOCK_TIME tick, template, deterministic
        python search, push.  ``push_to`` pushes the same solved block
        to extra nodes directly — scenarios that must not race gossip
        feed each partition member explicitly."""
        from ..core import clock
        from ..core.clock import timestamp
        from ..core.difficulty import BLOCK_TIME
        from ..core.header import BlockHeader
        from ..core.merkle import miner_merkle_root
        from ..mine.engine import MiningJob, mine

        if not _retried:
            clock.advance(BLOCK_TIME)
        info = (await self.get(i, "get_mining_info"))["result"]
        last_block = dict(info["last_block"])
        prev_hash = last_block.get("hash", GENESIS_PREV_HASH)
        pending_hashes = info["pending_transactions_hashes"]
        header = BlockHeader(
            previous_hash=prev_hash, address=address,
            merkle_root=miner_merkle_root(pending_hashes),
            timestamp=timestamp(),
            difficulty_x10=int(Decimal(str(info["difficulty"])) * 10),
            nonce=0)
        if last_block.get("hash"):
            job = MiningJob(header.prefix_bytes(), prev_hash,
                            Decimal(str(info["difficulty"])))
            result = mine(job, "python", batch=1 << 14, ttl=300)
            if result.nonce is None:
                raise RuntimeError("swarm mine: no nonce found")
            header.nonce = result.nonce
        payload = {"block_content": header.hex(), "txs": pending_hashes,
                   "block_no": last_block.get("id", 0) + 1}
        res = await self.post(i, "push_block", payload)
        if not res.get("ok") and not _retried:
            # same stale-template race as a real miner: the interval
            # mempool GC can evict a listed tx between template and push
            return await self.mine(i, address, push_to=push_to,
                                   _retried=True)
        for j in push_to or []:
            if j != i:
                # gossip may have delivered it already; that answer is
                # not a failure for the scenario
                await self.post(j, "push_block", payload)
        return res

    async def tips(self) -> List[dict]:
        out = []
        for i in range(len(self.nodes)):
            last = await self.nodes[i].state.get_last_block()
            out.append({"id": last["id"] if last else 0,
                        "hash": last["hash"] if last else GENESIS_PREV_HASH})
        return out

    async def converged(self) -> bool:
        tips = await self.tips()
        return len({t["hash"] for t in tips}) == 1

    async def wait_converged(self, rounds: int = 200,
                             delay: float = 0.02) -> bool:
        for _ in range(rounds):
            if await self.converged():
                return True
            await asyncio.sleep(delay)
        return await self.converged()

    async def settle(self, rounds: int = 3) -> None:
        """Let spawned gossip tasks drain (bounded; no wall-clock
        dependence beyond scheduler fairness)."""
        for _ in range(rounds):
            pending = [t for node in self.nodes for t in node._background
                       if not t.done()]
            if not pending:
                break
            await asyncio.gather(*pending, return_exceptions=True)
        await asyncio.sleep(0)

    # ---------------------------------------------------------- summaries --
    def slo_summary(self) -> dict:
        """Per-node client-side latency quantiles over every driver and
        peer dispatch that landed on that node."""
        from ..loadgen.runner import summarize_latencies

        per_node: dict = {}
        for (url, _path), vals in self.hub.latencies.items():
            per_node.setdefault(url, []).extend(vals)
        out = {}
        for i, url in enumerate(self.urls):
            vals = per_node.get(url)
            if vals:
                out[f"node{i}"] = summarize_latencies(vals)
        return out

    def breaker_summary(self) -> dict:
        return {f"node{i}": node.breakers.snapshot()
                for i, node in enumerate(self.nodes)}
