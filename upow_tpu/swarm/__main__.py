"""CLI entry: run one scenario or the whole matrix, emit the artifact.

    python -m upow_tpu.swarm --scenario partition_heal --nodes 10
    python -m upow_tpu.swarm --matrix fast --out swarm.json

Exit status is non-zero when any scenario's core assertions failed
(a core flag came back False), so CI can gate on the run directly.
"""

from __future__ import annotations

import argparse
import json
import sys

from .scenarios import SCENARIOS, run_matrix, run_scenario


def _core_ok(core: dict) -> bool:
    """Every boolean in core is an assertion; False means the scenario
    observed a violation the asserts upstream didn't already raise on."""
    return all(v for v in core.values() if isinstance(v, bool))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m upow_tpu.swarm",
        description="deterministic multi-node swarm scenarios")
    parser.add_argument("--scenario", choices=sorted(SCENARIOS),
                        help="run one scenario")
    parser.add_argument("--matrix", choices=("fast", "all"),
                        help="run every (fast) scenario")
    parser.add_argument("--nodes", type=int, default=None,
                        help="override the scenario's default swarm size")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--out", help="write the JSON artifact here")
    args = parser.parse_args(argv)
    if bool(args.scenario) == bool(args.matrix):
        parser.error("pass exactly one of --scenario / --matrix")

    if args.scenario:
        artifact = run_scenario(args.scenario, nodes=args.nodes,
                                seed=args.seed)
        runs = [artifact]
    else:
        artifact = run_matrix(args.matrix, seed=args.seed)
        runs = artifact["runs"]

    if args.out:
        from ..loadgen.observatory import write_artifact

        write_artifact(artifact, args.out)

    ok = True
    for run in runs:
        good = _core_ok(run["core"])
        ok = ok and good
        print(f"{'ok  ' if good else 'FAIL'} {run['scenario']:>16} "
              f"n={run['nodes']} seed={run['seed']} "
              f"{run['observed']['elapsed_s']:.2f}s "
              f"fp={run['fingerprint'][:16]}")
    print(json.dumps({"kind": artifact["kind"],
                      "fingerprint": artifact["fingerprint"]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
