"""In-memory HTTP/WS transport: real node apps, zero sockets.

:class:`LoopbackHub` is the wire.  A dispatch builds an aiohttp request
object (mocked transport carrying the caller's simulated IP, a real
StreamReader for POST bodies) and hands it to the destination app's own
``_handle`` — the full middleware chain, routing, rate limiter, IP
filter and handlers run exactly as they would behind a socket, and the
response body comes back as bytes.

:class:`LoopbackInterface` subclasses the production
:class:`~upow_tpu.node.peers.NodeInterface` and overrides ONLY the two
attempt closures (``request``/``get``): the breaker gate, fault
injection, retry policy, Sender-Node and X-Upow-Trace headers all run
through the inherited ``_resilient``/``_rpc_headers`` code.  A link
failure (:class:`~.links.LinkDown`) is a ``ConnectionError``, so peers
see retries, breaker flips and health-score decay with no node change.
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse
from typing import Awaitable, Callable, Dict, Optional, Tuple

from aiohttp import streams, web
from aiohttp.test_utils import make_mocked_request

from ..logger import get_logger
from ..node.peers import NodeInterface, _normalize
from .links import LinkMatrix

log = get_logger("swarm")

# an adversary endpoint: (method, path, params, json_body) -> (status, doc)
RawHandler = Callable[[str, str, dict, Optional[dict]],
                      Awaitable[Tuple[int, dict]]]


class _StreamProtocol:
    """Protocol stub keeping StreamReader flow control inert (the
    mocked request's payload has no real transport behind it)."""

    _reading_paused = False
    transport = None

    def pause_reading(self) -> None:
        pass

    def resume_reading(self) -> None:
        pass


class _FakeTransport:
    """Just enough transport for ``request.transport.get_extra_info``
    — the middleware reads the peer IP from ``peername``."""

    def __init__(self, peername: Tuple[str, int]):
        self._peername = peername

    def get_extra_info(self, name: str, default=None):
        return self._peername if name == "peername" else default


class LoopbackHub:
    """URL -> in-process listener registry + request dispatch."""

    def __init__(self, matrix: LinkMatrix):
        self.matrix = matrix
        self._nodes: Dict[str, object] = {}
        self._raw: Dict[str, RawHandler] = {}
        self._ips: Dict[str, str] = {}
        # client-side latency per (dst url, path): the per-node SLO
        # source — node-side telemetry is process-global in the swarm,
        # so per-destination numbers must be measured at the caller
        self.latencies: Dict[Tuple[str, str], list] = {}

    def register_node(self, url: str, node, ip: str) -> None:
        base = _normalize(url)
        self._nodes[base] = node
        self._ips[base] = ip
        self.matrix.register(base)

    def register_raw(self, url: str, handler: RawHandler,
                     ip: str = "") -> None:
        """Attach an adversary endpoint: answers RPCs without being a
        node (or raises to model a dead peer)."""
        base = _normalize(url)
        self._raw[base] = handler
        if ip:
            self._ips[base] = ip
        self.matrix.register(base)

    def register_client(self, url: str, ip: str) -> None:
        """A shaped client endpoint (e.g. a spammer): pays link tolls
        and carries a simulated source IP, but serves nothing."""
        base = _normalize(url)
        self._ips[base] = ip
        self.matrix.register(base)

    def node(self, url: str):
        return self._nodes[_normalize(url)]

    async def request(self, src: str, dst: str, method: str, path: str,
                      params: Optional[dict] = None,
                      json_body: Optional[dict] = None,
                      headers: Optional[dict] = None) -> Tuple[int, bytes]:
        """One simulated HTTP exchange src -> dst.  Raises LinkDown /
        ConnectionRefusedError for network-level failure; application
        errors come back as (status, body) like real HTTP."""
        src_base, base = _normalize(src), _normalize(dst)
        await self.matrix.transfer(src_base, base)
        raw = self._raw.get(base)
        if raw is not None:
            status, doc = await raw(method, path, dict(params or {}),
                                    json_body)
            return status, json.dumps(doc).encode()
        node = self._nodes.get(base)
        if node is None:
            raise ConnectionRefusedError(f"no swarm listener at {dst}")

        path_qs = path
        if params:
            path_qs += "?" + urllib.parse.urlencode(params)
        hdrs = {"Host": base.split("://", 1)[-1]}
        if headers:
            hdrs.update(headers)
        body = b""
        if json_body is not None:
            body = json.dumps(json_body).encode()
            hdrs.setdefault("Content-Type", "application/json")
            hdrs["Content-Length"] = str(len(body))
        payload = streams.StreamReader(_StreamProtocol(), limit=2 ** 16,
                                       loop=asyncio.get_event_loop())
        if body:
            payload.feed_data(body)
        payload.feed_eof()
        req = make_mocked_request(
            method, path_qs, headers=hdrs, payload=payload, app=node.app,
            transport=_FakeTransport(
                (self._ips.get(src_base, "127.0.0.1"), 40000)))
        t0 = time.perf_counter()
        try:
            resp = await node.app._handle(req)
        except web.HTTPException as e:
            resp = e  # an HTTPException IS a Response in aiohttp
        self.latencies.setdefault((base, path), []).append(
            time.perf_counter() - t0)
        out = resp.body
        if out is None:
            out = b""
        elif not isinstance(out, (bytes, bytearray)):
            out = (resp.text or "").encode()
        return resp.status, bytes(out)


class LoopbackInterface(NodeInterface):
    """NodeInterface whose wire is the LoopbackHub."""

    def __init__(self, hub: LoopbackHub, src: str, url: str, cfg=None,
                 session=None, resilience=None):
        # session is accepted for factory-signature parity and ignored:
        # there is no socket pool to share
        super().__init__(url, cfg, session=None, resilience=resilience)
        self._hub = hub
        self._src = src

    async def _call(self, method: str, path: str,
                    params: Optional[dict] = None,
                    json_body: Optional[dict] = None,
                    headers: Optional[dict] = None) -> dict:
        _, body = await self._hub.request(
            self._src, self.base_url, method, "/" + path.lstrip("/"),
            params=params, json_body=json_body, headers=headers)
        if len(body) > self.cfg.response_cap:
            raise ValueError("response too large")
        return json.loads(body or b"{}")

    async def request(self, path: str, args: dict,
                      sender_node: str = "") -> dict:
        headers = self._rpc_headers(sender_node)

        async def attempt() -> dict:
            if path in ("push_block", "push_tx"):
                return await self._call("POST", path, json_body=args,
                                        headers=headers)
            params = {k: str(v) for k, v in args.items()}
            return await self._call("GET", path, params=params,
                                    headers=headers)

        return await self._resilient(attempt, path)

    async def get(self, path: str, params: Optional[dict] = None,
                  sender_node: str = "", site: Optional[str] = None,
                  site_key: Optional[str] = None) -> dict:
        headers = self._rpc_headers(sender_node)

        async def attempt() -> dict:
            return await self._call("GET", path, params=params or {},
                                    headers=headers)

        return await self._resilient(attempt, path, site=site,
                                     site_key=site_key)


class LoopbackWsClient:
    """In-process WS subscriber sink for ``WsHub.connect_local``: the
    hub's writer task calls ``send_str``; frames land in ``received``.
    ``stall()`` models a consumer whose socket never drains — the
    writer blocks here while the connection's bounded queue sheds —
    and an optional (matrix, node, url) triple routes frames through
    swarm links so partitions cut WS push too."""

    def __init__(self, matrix: Optional[LinkMatrix] = None,
                 node_url: str = "", url: str = ""):
        self.received: list = []
        self._matrix = matrix
        self._node_url = _normalize(node_url)
        self._url = _normalize(url)
        if matrix is not None and self._url:
            matrix.register(self._url)
        self._stalled = False
        self._resume = asyncio.Event()
        self._resume.set()

    def stall(self) -> None:
        self._stalled = True
        self._resume.clear()

    def resume(self) -> None:
        self._stalled = False
        self._resume.set()

    async def send_str(self, payload: str) -> None:
        if self._stalled:
            await self._resume.wait()
        if self._matrix is not None and self._url:
            await self._matrix.transfer(self._node_url, self._url)
        self.received.append(json.loads(payload))

    def of_type(self, mtype: str) -> list:
        return [m for m in self.received if m.get("type") == mtype]
