"""Adversary actors for swarm scenarios.

Adversaries are *raw* loopback endpoints (``LoopbackHub.register_raw``)
— they answer peer RPCs without being nodes, so they can lie freely:

* :class:`EclipseAdversary` — a clique of fake peers that monopolise a
  victim's peer view.  While the eclipse holds they look perfectly
  healthy (probes succeed, ``get_nodes`` recommends only each other,
  ``get_blocks`` returns an empty page so sync "completes" without
  progress).  Once ``unmask()`` is called they go dark: every RPC
  raises ``ConnectionError``, which the victim's retry stack turns
  into breaker failures and health-score decay — exactly the signal
  ``peers.ranked()`` needs to resurface the honest peer.

  Adversary URLs sit in ``10.66.*`` so they sort *before* the honest
  ``10.77.*`` nodes on the ranked() URL tie-break: recovery in the
  eclipse scenario is earned through health scores, never through
  lexicographic luck.

* :class:`SpamAdversary` — a driver-side flooder pushing garbage and
  duplicate transactions at every node through its own (shaped) links.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .transport import LoopbackHub


class EclipseAdversary:
    """A clique of lying peers registered on the hub."""

    def __init__(self, hub: LoopbackHub, count: int = 4,
                 subnet: str = "10.66.0"):
        self.hub = hub
        self.unmasked = False
        self.calls = 0
        self.calls_after_unmask = 0
        self.urls: List[str] = []
        for k in range(count):
            url = f"http://{subnet}.{k + 1}:3006"
            self.urls.append(url)
            hub.register_raw(url, self._handler, ip=f"{subnet}.{k + 1}")

    def unmask(self) -> None:
        """The attack ends: the fake peers drop off the network."""
        self.unmasked = True

    async def _handler(self, method: str, path: str, params: dict,
                       json_body: Optional[dict]) -> Tuple[int, dict]:
        self.calls += 1
        if self.unmasked:
            self.calls_after_unmask += 1
            raise ConnectionResetError("eclipse adversary unmasked")
        if path == "/get_nodes":
            # recommend only the clique: keeps the victim's view closed
            return 200, {"ok": True, "result": list(self.urls)}
        if path == "/get_blocks":
            # an empty page means "you are up to date" — the stall that
            # makes an eclipse dangerous: sync SUCCEEDS without progress
            return 200, {"ok": True, "result": []}
        if path in ("/push_block", "/push_tx", "/add_node"):
            return 200, {"ok": True}  # swallow gossip silently
        return 200, {"ok": True, "result": "ok"}


class SpamAdversary:
    """Floods ``push_tx`` with garbage and duplicates via the hub.

    The spammer is a registered matrix endpoint, so partitions and drop
    policies apply to its traffic like anyone else's.
    """

    def __init__(self, hub: LoopbackHub, url: str = "http://10.66.9.9:3006",
                 ip: str = "10.66.9.9"):
        self.hub = hub
        self.url = url
        hub.register_client(url, ip)
        self.sent = 0
        self.accepted = 0
        self.rejected = 0

    async def _push(self, dst: str, tx_hex: str) -> bool:
        import json

        self.sent += 1
        try:
            _, body = await self.hub.request(
                self.url, dst, "GET", "/push_tx",
                params={"tx_hex": tx_hex})
            ok = bool(json.loads(body or b"{}").get("ok"))
        except (ConnectionError, OSError):
            ok = False
        if ok:
            self.accepted += 1
        else:
            self.rejected += 1
        return ok

    async def flood_garbage(self, targets: List[str], count: int) -> None:
        """Syntactically invalid transactions, round-robin."""
        for k in range(count):
            blob = (b"\xde\xad" + k.to_bytes(4, "big")).hex()
            await self._push(targets[k % len(targets)], blob)

    async def flood_duplicates(self, targets: List[str], tx_hex: str,
                               count: int) -> None:
        """The same valid transaction pushed over and over, everywhere."""
        for k in range(count):
            await self._push(targets[k % len(targets)], tx_hex)
