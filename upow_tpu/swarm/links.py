"""The simulated network plane: per-link policy, partitions, faults.

Every loopback RPC (and WS frame, when a scenario wires one through)
pays a :meth:`LinkMatrix.transfer` toll on its ordered (src, dst) link:

1. the ``swarm.link`` fault site fires (resilience/faultinject.py), so
   any installed spec — ``swarm.link:error:p=0.3`` — can kill traffic
   exactly like the rpc.* sites kill real HTTP;
2. a partition or isolation check — blocked links raise
   :class:`LinkDown`;
3. a seeded per-link drop draw — dropped links also raise LinkDown;
4. a latency + jitter sleep.

:class:`LinkDown` subclasses ``ConnectionError`` deliberately: it lands
inside ``peers.TRANSIENT_ERRORS``, so the caller's retry policy runs
and its circuit breaker records the failure — a partitioned peer looks
to the node EXACTLY like a dead TCP endpoint.

Determinism: each ordered link owns a ``random.Random`` seeded from
(master seed, src, dst), so drop/jitter draws depend only on that
link's own call sequence, never on cross-link interleaving.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import telemetry
from ..resilience import faultinject


class LinkDown(ConnectionError):
    """A blocked/dropped link — transient to the caller's retry stack."""

    def __init__(self, src: str, dst: str, reason: str):
        super().__init__(f"link {src} -> {dst} {reason}")
        self.src, self.dst, self.reason = src, dst, reason


@dataclass
class LinkPolicy:
    """Per-link shaping; the fast-matrix default is a perfect wire."""

    latency: float = 0.0   # one-way seconds added per transfer
    jitter: float = 0.0    # uniform extra [0, jitter) seconds
    drop: float = 0.0      # probability a transfer raises LinkDown


class LinkMatrix:
    """Ordered-pair link table with partition groups and counters."""

    def __init__(self, seed: int = 0, default: Optional[LinkPolicy] = None):
        self.seed = seed
        self.default = default or LinkPolicy()
        self._policies: Dict[Tuple[str, str], LinkPolicy] = {}
        self._rngs: Dict[Tuple[str, str], random.Random] = {}
        self._known: Set[str] = set()
        self._groups: Dict[str, int] = {}   # url -> partition group
        self._isolated: Set[str] = set()
        self.delivered = 0
        self.dropped = 0
        self.blocked = 0
        self.per_link: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------- setup --
    def register(self, url: str) -> None:
        """Only registered endpoints pay link tolls: the scenario driver
        (an unregistered 'client') must always reach every node."""
        self._known.add(url)

    def set_link(self, src: str, dst: str, policy: LinkPolicy,
                 symmetric: bool = True) -> None:
        self._policies[(src, dst)] = policy
        if symmetric:
            self._policies[(dst, src)] = policy

    def _rng(self, src: str, dst: str) -> random.Random:
        key = (src, dst)
        rng = self._rngs.get(key)
        if rng is None:
            digest = hashlib.sha256(
                f"{self.seed}:{src}->{dst}".encode()).digest()
            rng = random.Random(int.from_bytes(digest[:8], "big"))
            self._rngs[key] = rng
        return rng

    # -------------------------------------------------------- partitions --
    def partition(self, groups: Iterable[Iterable[str]]) -> None:
        """Split the swarm: traffic crossing group boundaries is blocked.
        Unlisted endpoints keep full connectivity to every group."""
        self._groups = {}
        for gid, members in enumerate(groups):
            for url in members:
                self._groups[url] = gid
        telemetry.event("swarm_partition",
                        groups=len(set(self._groups.values())),
                        members=len(self._groups))

    def heal(self) -> None:
        self._groups = {}
        self._isolated.clear()
        telemetry.event("swarm_heal")

    def isolate(self, url: str) -> None:
        """Cut every link touching ``url`` (eclipse victim / dead node)."""
        self._isolated.add(url)
        telemetry.event("swarm_isolate", url=url)

    def restore(self, url: str) -> None:
        self._isolated.discard(url)

    def _crosses_partition(self, src: str, dst: str) -> bool:
        if src in self._isolated or dst in self._isolated:
            return True
        if not self._groups:
            return False
        gsrc, gdst = self._groups.get(src), self._groups.get(dst)
        return gsrc is not None and gdst is not None and gsrc != gdst

    # ---------------------------------------------------------- transfer --
    def _count(self, src: str, dst: str, outcome: str) -> None:
        setattr(self, outcome, getattr(self, outcome) + 1)
        row = self.per_link.setdefault(f"{src}->{dst}", {
            "delivered": 0, "dropped": 0, "blocked": 0})
        row[outcome] += 1

    async def transfer(self, src: str, dst: str) -> None:
        """One message crossing the (src, dst) link; raises LinkDown or
        sleeps out the link latency.  Unregistered endpoints (the
        scenario driver) bypass shaping entirely."""
        if src not in self._known or dst not in self._known:
            return
        injector = faultinject.get_injector()
        if injector is not None:
            await injector.fire("swarm.link", f"{src}->{dst}")
        if self._crosses_partition(src, dst):
            self._count(src, dst, "blocked")
            raise LinkDown(src, dst, "partitioned")
        policy = self._policies.get((src, dst), self.default)
        if policy.drop > 0 and self._rng(src, dst).random() < policy.drop:
            self._count(src, dst, "dropped")
            raise LinkDown(src, dst, "dropped")
        delay = policy.latency
        if policy.jitter > 0:
            delay += self._rng(src, dst).random() * policy.jitter
        if delay > 0:
            await asyncio.sleep(delay)
        self._count(src, dst, "delivered")

    # ------------------------------------------------------------- views --
    def stats(self) -> dict:
        return {"delivered": self.delivered, "dropped": self.dropped,
                "blocked": self.blocked,
                "links_used": len(self.per_link)}

    def partitioned_pairs(self) -> List[str]:
        return sorted(
            f"{a}->{b}" for a in self._known for b in self._known
            if a != b and self._crosses_partition(a, b))
