"""Seeded swarm scenarios + the deterministic artifact contract.

Every scenario runs inside :func:`deterministic_world`: the consensus
clock is frozen (advanced only by the mining helper), START_DIFFICULTY
drops to 1.0 so the python searcher solves in microseconds, the global
``random`` is seeded (peer sampling), telemetry rings are cleared and
fault injection is uninstalled afterwards.  Wallet keys derive from
``(seed, tag)``, so every address — and therefore every block hash —
is a pure function of the seed.

The artifact splits in two:

* ``core`` — values that are a function of (scenario, seed) ONLY:
  convergence flags, heights, tip hashes, governance ballots, shed
  counts.  ``fingerprint`` is the sha256 of core's canonical JSON —
  same seed, byte-identical fingerprint (pinned by tests).
* ``observed`` — anything timing may wiggle: breaker snapshots, link
  counters, retry/round counts, wall-clock.  Diagnostics, not
  contract.

``slo.endpoints`` carries per-node client-side latency quantiles in the
exact shape the observatory gate's ``flatten()`` consumes, so swarm
artifacts merge into the perf pipeline unchanged.

See docs/SWARM.md for the catalog and determinism contract.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import os
import shutil
import tempfile
import time
from dataclasses import dataclass, field
from decimal import Decimal
from typing import Callable, Dict, List, Optional, Tuple

from .. import telemetry
from ..fleet import recorder as fleet_recorder
from ..fleet import scrape as fleet_scrape
from ..logger import get_logger
from ..resilience import faultinject
from .harness import Swarm

log = get_logger("swarm")

#: Frozen consensus-clock epoch every scenario starts from.
GENESIS_EPOCH = 1_753_791_000

#: Real-time pause after a heal so tripped breakers can reach half-open
#: (swarm_config pins breaker_open_secs=0.25; breakers run on monotonic
#: wall time, not the frozen consensus clock).
BREAKER_REOPEN_PAUSE = 0.35


def _wallet(seed: int, tag: str) -> Tuple[int, str]:
    """Deterministic (privkey, address) from (seed, tag)."""
    from ..core import curve, point_to_string

    digest = hashlib.sha256(f"swarm:{seed}:{tag}".encode()).digest()
    d, pub = curve.keygen(rng=int.from_bytes(digest[:8], "big") | 1)
    return d, point_to_string(pub)


@contextlib.contextmanager
def deterministic_world(seed: int):
    """Pin every nondeterminism source a scenario touches."""
    import random

    from ..core import clock, difficulty

    prev_difficulty = difficulty.START_DIFFICULTY
    difficulty.START_DIFFICULTY = Decimal("1.0")
    clock.freeze(GENESIS_EPOCH)
    random.seed(seed)
    telemetry.reset()
    try:
        yield
    finally:
        difficulty.START_DIFFICULTY = prev_difficulty
        clock.reset()
        faultinject.uninstall()


# ------------------------------------------------------------- helpers ----

async def _sync_from(swarm: Swarm, i: int, winner: int,
                     tries: int = 50) -> dict:
    """Drive node ``i`` to sync from ``winner``, absorbing the transient
    'already syncing' race with background gossip-triggered syncs."""
    res: dict = {}
    for _ in range(tries):
        res = await swarm.get(i, "sync_blockchain",
                              {"node_url": swarm.urls[winner]})
        if res.get("ok"):
            return res
        await asyncio.sleep(0.02)
    return res


def _breaker_flips(swarm: Swarm) -> int:
    return sum(peer["flips"]
               for snap in swarm.breaker_summary().values()
               for peer in snap.values())


def _roots_for(swarm: Swarm, trace_id: str) -> List[dict]:
    """Trace roots for one id across the whole fleet: with per-node
    registries the driver's buffer only holds driver-opened roots, so
    cross-node assertions must read the merged view."""
    return fleet_scrape.merged_trace_roots(swarm, trace_id=trace_id)


def core_ok(core: dict) -> bool:
    """True when every boolean assertion in a core dict held."""
    return all(v for v in core.values() if isinstance(v, bool))


# ----------------------------------------------------------- scenarios ----

async def scenario_partition_heal(swarm: Swarm, seed: int):
    """2-way split mines divergent chains; heal; everyone converges on
    the longer side; reorg + breaker evidence carries ONE trace id."""
    n = swarm.n
    everyone = list(range(n))
    half = n // 2
    a_idx, b_idx = everyone[:half], everyone[half:]
    # the genesis-key rule (verify/block.py emission gate): with no
    # inode ballot formed, ONLY block 1's miner address may mine — so
    # both halves mine to the same key; the chains still diverge
    # because the halves extend the fork at different (advancing)
    # consensus timestamps
    _, addr_shared = _wallet(seed, "shared")
    addr_a = addr_b = addr_shared

    # shared prefix deep enough for fork detection (window=4, tip>4)
    for _ in range(4):
        assert (await swarm.mine(0, addr_shared, push_to=everyone))["ok"]
    await swarm.settle()
    assert await swarm.converged(), "shared prefix did not converge"

    swarm.matrix.partition([[swarm.urls[i] for i in a_idx],
                            [swarm.urls[i] for i in b_idx]])
    for _ in range(3):
        assert (await swarm.mine(0, addr_a, push_to=a_idx))["ok"]
    for _ in range(2):
        assert (await swarm.mine(half, addr_b, push_to=b_idx))["ok"]
    await swarm.settle()
    tips = await swarm.tips()
    diverged = len({t["hash"] for t in tips}) == 2
    flips_during_partition = _breaker_flips(swarm)

    # warm every loser's hot-state read cache with fork-B answers: the
    # post-heal reads below must come back reorged, proving the
    # remove_blocks -> cache-generation hook fired (swarm nodes run
    # with foreign revalidation off, so ONLY the hook can invalidate)
    stale_balances = {}
    for i in b_idx:
        supply = await swarm.get(i, "get_supply_info", {})
        info = await swarm.get(i, "get_address_info",
                               {"address": addr_shared})
        stale_balances[i] = (supply["result"]["last_block"].get("hash"),
                             info["result"]["balance"])

    swarm.matrix.heal()
    await asyncio.sleep(BREAKER_REOPEN_PAUSE)
    heal_results = []
    with telemetry.request_trace("swarm.heal") as root:
        heal_tid = root.trace_id
        for i in b_idx:
            heal_results.append(await _sync_from(swarm, i, winner=0))
    await swarm.settle()
    converged = await swarm.wait_converged()
    tips = await swarm.tips()

    # same queries again, same (warm) caches: a loser still serving its
    # fork-B tip or balance here means its reorg never invalidated the
    # read cache — the exact stale-balance bug the generation anchor
    # exists to prevent
    winner_info = await swarm.get(0, "get_address_info",
                                  {"address": addr_shared})
    winner_balance = winner_info["result"]["balance"]
    healed_reads_fresh = True
    stale_differed = False
    for i in b_idx:
        supply = await swarm.get(i, "get_supply_info", {})
        info = await swarm.get(i, "get_address_info",
                               {"address": addr_shared})
        if supply["result"]["last_block"].get("hash") != tips[0]["hash"] \
                or info["result"]["balance"] != winner_balance:
            healed_reads_fresh = False
        if stale_balances[i][1] != winner_balance:
            stale_differed = True

    reorgs = fleet_scrape.merged_events(swarm, kind="reorg")
    roots = _roots_for(swarm, heal_tid)
    root_names = {t.get("name") for t in roots}
    core = {
        "diverged_during_partition": diverged,
        "converged_after_heal": converged,
        "final_height": tips[0]["id"],
        "final_tip": tips[0]["hash"],
        "losers_reorged": len(reorgs) >= len(b_idx),
        "reorgs_share_heal_trace": bool(reorgs) and all(
            e.get("trace_id") == heal_tid for e in reorgs),
        # loser-side sync roots AND winner-side block-serving roots
        # under one id: the trace crossed the swarm
        "trace_spans_nodes": ("http.sync_blockchain" in root_names
                              and "http.get_blocks" in root_names),
        "breakers_flipped_during_partition": flips_during_partition > 0,
        # both legs matter: the pre-heal answers really were different
        # (the check bites) AND the post-heal cached reads are fresh
        "loser_caches_invalidated": stale_differed and healed_reads_fresh,
    }
    observed = {
        "heal_trace_id": heal_tid,
        "heal_results": heal_results,
        "reorg_events": len(reorgs),
        "heal_trace_roots": len(roots),
        "breaker_flips": _breaker_flips(swarm),
        "winner_balance": winner_balance,
        "loser_cache_stats": {
            str(i): swarm.nodes[i].hotcache.stats()["foreign_bumps"]
            for i in b_idx},
    }
    return core, observed


async def scenario_reorg_storm(swarm: Swarm, seed: int):
    """Repeated partition/mine/heal cycles with the winning side
    alternating — every cycle forces the previous winners to reorg."""
    n = swarm.n
    everyone = list(range(n))
    half = n // 2
    a_idx, b_idx = everyone[:half], everyone[half:]
    a_urls = [swarm.urls[i] for i in a_idx]
    b_urls = [swarm.urls[i] for i in b_idx]
    _, addr_shared = _wallet(seed, "storm_base")

    for _ in range(4):
        assert (await swarm.mine(0, addr_shared, push_to=everyone))["ok"]
    await swarm.settle()

    cycles = []
    for c in range(2):
        a_wins = c % 2 == 0
        # same genesis-key constraint as partition_heal: every block
        # pays the block-1 miner until an inode ballot exists
        addr_a = addr_b = addr_shared
        swarm.matrix.partition([a_urls, b_urls])
        for _ in range(3 if a_wins else 2):
            assert (await swarm.mine(0, addr_a, push_to=a_idx))["ok"]
        for _ in range(2 if a_wins else 3):
            assert (await swarm.mine(half, addr_b, push_to=b_idx))["ok"]
        await swarm.settle()
        swarm.matrix.heal()
        await asyncio.sleep(BREAKER_REOPEN_PAUSE)
        winner = 0 if a_wins else half
        for i in (b_idx if a_wins else a_idx):
            await _sync_from(swarm, i, winner)
        await swarm.settle()
        converged = await swarm.wait_converged()
        tips = await swarm.tips()
        cycles.append({"cycle": c, "winner": "a" if a_wins else "b",
                       "converged": converged,
                       "height": tips[0]["id"], "tip": tips[0]["hash"]})

    core = {
        "cycles": cycles,
        "all_converged": all(c["converged"] for c in cycles),
        "reorged_every_cycle":
            len(fleet_scrape.merged_events(swarm, kind="reorg"))
            >= len(b_idx) * 2,
    }
    observed = {
        "reorg_events": len(fleet_scrape.merged_events(swarm,
                                                       kind="reorg")),
        "breaker_flips": _breaker_flips(swarm),
    }
    return core, observed


async def scenario_eclipse(swarm: Swarm, seed: int):
    """An adversary clique monopolises the victim's peer view; after the
    unmask, breaker health resurfaces the honest peer and the victim
    catches up — recovery earned through scores, not URL luck."""
    from .adversary import EclipseAdversary

    n = swarm.n
    victim, honest_idx = 0, list(range(1, n))
    honest_url = swarm.urls[1]
    adv = EclipseAdversary(swarm.hub, count=3)
    _, addr = _wallet(seed, "eclipse_miner")

    # peer views: honest nodes mesh among themselves (no victim); the
    # victim knows the clique plus ONE honest peer
    for i in honest_idx:
        for j in honest_idx:
            if i != j:
                swarm.nodes[i].peers.add(swarm.urls[j])
    for url in adv.urls:
        swarm.nodes[victim].peers.add(url)
    swarm.nodes[victim].peers.add(honest_url)

    for _ in range(2):
        assert (await swarm.mine(1, addr,
                                 push_to=list(range(n))))["ok"]
    await swarm.settle()
    assert await swarm.converged(), "pre-eclipse prefix did not converge"

    # eclipse on: victim + clique on one side, honest on the other
    swarm.matrix.partition([[swarm.urls[victim]] + adv.urls,
                            [swarm.urls[i] for i in honest_idx]])
    for _ in range(2):
        assert (await swarm.mine(1, addr, push_to=honest_idx))["ok"]
    await swarm.settle()
    eclipse_syncs = []
    for _ in range(3):
        eclipse_syncs.append(await swarm.get(victim, "sync_blockchain"))
    tips = await swarm.tips()
    eclipsed = tips[victim]["id"] < tips[1]["id"]

    # the attack ends: clique goes dark, links restore
    adv.unmask()
    swarm.matrix.heal()
    await asyncio.sleep(BREAKER_REOPEN_PAUSE)
    recovery_rounds = 0
    for _ in range(12):
        recovery_rounds += 1
        await swarm.get(victim, "sync_blockchain")
        tips = await swarm.tips()
        if tips[victim]["hash"] == tips[1]["hash"]:
            break
        await asyncio.sleep(0.05)
    recovered = tips[victim]["hash"] == tips[1]["hash"]

    # keep syncing until health ranking surfaces the honest peer first
    # (each round adds an honest success or an adversary failure, so
    # the ordering is monotone toward honest-first)
    peers = swarm.nodes[victim].peers
    ranked_rounds = 0
    for _ in range(20):
        if peers.ranked(peers.all_nodes())[0] == honest_url:
            break
        ranked_rounds += 1
        await swarm.get(victim, "sync_blockchain")
        await asyncio.sleep(0.02)
    ranked_first = peers.ranked(peers.all_nodes())[0]
    breakers = swarm.nodes[victim].breakers
    core = {
        "eclipsed": eclipsed,
        "recovered": recovered,
        "victim_height": tips[victim]["id"],
        "victim_tip": tips[victim]["hash"],
        "honest_ranked_first": ranked_first == honest_url,
        "adversaries_scored_below_honest": all(
            breakers.score(u) < breakers.score(honest_url)
            for u in adv.urls),
        "adversary_served_calls": adv.calls - adv.calls_after_unmask > 0,
    }
    observed = {
        "eclipse_syncs": eclipse_syncs,
        "recovery_rounds": recovery_rounds,
        "ranked_rounds": ranked_rounds,
        "adversary_calls": adv.calls,
        "adversary_calls_after_unmask": adv.calls_after_unmask,
        "victim_breakers": breakers.snapshot(),
    }
    return core, observed


async def scenario_spam(swarm: Swarm, seed: int):
    """A flooder pushes garbage + duplicate transactions at every node;
    pools stay clean (one honest tx), mining and convergence survive."""
    from ..wallet.builders import WalletBuilder
    from .adversary import SpamAdversary

    n = swarm.n
    everyone = list(range(n))
    d_f, addr_f = _wallet(seed, "spam_funder")
    _, addr_t = _wallet(seed, "spam_target")

    assert (await swarm.mine(0, addr_f, push_to=everyone))["ok"]
    await swarm.settle()
    builder = WalletBuilder(swarm.nodes[0].state)
    tx = await builder.create_transaction(d_f, addr_t, "1")

    spam = SpamAdversary(swarm.hub)
    await spam.flood_garbage(swarm.urls, 40)
    res = await swarm.get(0, "push_tx", {"tx_hex": tx.hex()})
    assert res.get("ok"), res
    await swarm.settle()  # gossip carries the honest tx everywhere
    await spam.flood_duplicates(swarm.urls, tx.hex(), 24)
    await swarm.settle()

    pools = []
    for i in everyone:
        res = await swarm.get(i, "get_pending_transactions")
        pools.append(res["result"])
    assert (await swarm.mine(0, addr_f, push_to=everyone))["ok"]
    await swarm.settle()
    converged = await swarm.wait_converged()
    confirm = await swarm.get(n - 1, "get_transaction",
                              {"tx_hash": tx.hash()})
    tips = await swarm.tips()
    core = {
        "spam_sent": spam.sent,
        "spam_accepted": spam.accepted,
        "pools_clean": all(p == [tx.hex()] for p in pools),
        "tx_confirmed_everywhere": bool(
            confirm.get("ok") and confirm["result"]["is_confirm"]),
        "converged": converged,
        "final_height": tips[0]["id"],
        "final_tip": tips[0]["hash"],
    }
    observed = {
        "spam_rejected": spam.rejected,
        "pool_depths": [len(p) for p in pools],
    }
    return core, observed


async def scenario_dpos_governance(swarm: Swarm, seed: int):
    """The full DPoS flow through the node API: stake → delegate vote →
    validator registration → inode registration → validator vote →
    a mined block whose coinbase splits 50/50 miner/inode — then a
    fresh node syncs the whole governance history."""
    from ..core.rewards import get_block_reward_decimal
    from ..wallet.builders import WalletBuilder

    d_g, a_g = _wallet(seed, "gov_validator")
    d_o, a_o = _wallet(seed, "gov_delegate")
    d_i, a_i = _wallet(seed, "gov_inode")
    builder = WalletBuilder(swarm.nodes[0].state)

    async def push(tx) -> None:
        res = await swarm.get(0, "push_tx", {"tx_hex": tx.hex()})
        assert res.get("ok"), res

    async def mine() -> None:
        assert (await swarm.mine(0, a_g))["ok"]

    for _ in range(22):            # validator registration needs 100
        await mine()
    await push(await builder.create_stake_transaction(d_g, "3"))
    await mine()
    await push(await builder.create_validator_registration_transaction(d_g))
    await mine()
    await push(await builder.create_transaction(d_g, a_o, "20"))
    await mine()
    await push(await builder.create_stake_transaction(d_o, "1"))
    await mine()
    await push(await builder.vote_as_delegate(d_o, 10, a_g))
    await mine()

    for _ in range(170):           # inode registration needs 1000
        await mine()
    for chunk in ("400", "400", "210"):   # <256 inputs per send
        await push(await builder.create_transaction(d_g, a_i, chunk))
        await mine()
    await push(await builder.create_stake_transaction(d_i, "1"))
    await mine()
    await push(await builder.create_inode_registration_transaction(d_i))
    await mine()
    await push(await builder.vote_as_validator(d_g, 10, a_i))
    await mine()

    validators = await swarm.get(0, "get_validators_info")
    delegates = await swarm.get(0, "get_delegates_info")
    dobby = await swarm.get(0, "dobby_info")

    # the reward-split block: empty mempool, so the only balance change
    # on the inode address is its coinbase share
    before = Decimal((await swarm.get(
        0, "get_address_info", {"address": a_i}))["result"]["balance"])
    await mine()
    after = Decimal((await swarm.get(
        0, "get_address_info", {"address": a_i}))["result"]["balance"])
    tips = await swarm.tips()
    height = tips[0]["id"]
    reward = get_block_reward_decimal(height)
    inode_share = after - before
    split_ok = inode_share == reward * Decimal("0.5")

    # a blank node replays the whole governance history from genesis
    sync = await _sync_from(swarm, 1, winner=0)
    converged = await swarm.converged()
    utxo_match = (await swarm.nodes[0].state.get_unspent_outputs_hash()
                  == await swarm.nodes[1].state.get_unspent_outputs_hash())
    core = {
        "validator": a_g,
        "delegate_votes": [
            {"delegate": d["delegate"],
             "voted_for": [v["wallet"] for v in d["vote"]],
             "total_stake": str(d["totalStake"])}
            for d in delegates],
        "inode_ballot": [
            {"validator": v["validator"],
             "voted_for": [x["wallet"] for x in v["vote"]]}
            for v in validators],
        "dobby_emissions": dobby.get("result"),
        "final_height": height,
        "final_tip": tips[0]["hash"],
        "block_reward": str(reward),
        "inode_coinbase_share": str(inode_share),
        "split_50_50": split_ok,
        "fresh_node_synced": bool(sync.get("ok")) and converged,
        "utxo_fingerprints_match": utxo_match,
    }
    observed = {"sync_result": sync}
    return core, observed


async def scenario_ws_churn(swarm: Swarm, seed: int):
    """A stalled WS subscriber must not block fan-out: the live client
    sees every block while the stalled one's bounded queue sheds oldest
    — counted and exported as upow_ws_dropped_messages."""
    from .transport import LoopbackWsClient

    _, addr = _wallet(seed, "ws_miner")
    hub = swarm.nodes[0].ws_hub
    assert hub is not None, "ws_churn needs ws=True"
    live = LoopbackWsClient()
    slow = LoopbackWsClient()
    hub.connect_local(live, ip="10.99.0.1", channels=("block",))
    hub.connect_local(slow, ip="10.99.0.2", channels=("block",))
    slow.stall()

    for _ in range(8):
        assert (await swarm.mine(0, addr,
                                 push_to=list(range(swarm.n))))["ok"]
        # the broadcast is a spawned task: drain it (and give the
        # writer a real suspension point) per block, as a socket would
        await swarm.settle()
        await asyncio.sleep(0.005)
    for _ in range(200):           # writer task drains asynchronously
        if len(live.of_type("new_block")) >= 8:
            break
        await asyncio.sleep(0.01)
    slow.resume()
    for _ in range(200):
        if hub.get_stats()["dropped_messages"] >= 3 and \
                len(slow.of_type("new_block")) >= 5:
            break
        await asyncio.sleep(0.01)

    status, body = await swarm.hub.request(
        swarm.driver, swarm.urls[0], "GET", "/metrics")
    text = body.decode()
    dropped = hub.get_stats()["dropped_messages"]
    metric_line = next(
        (ln for ln in text.splitlines()
         if ln.startswith("upow_ws_dropped_messages_total ")), "")
    tips = await swarm.tips()
    core = {
        "blocks_broadcast": 8,
        "live_client_delivered": len(live.of_type("new_block")),
        "slow_client_delivered": len(slow.of_type("new_block")),
        "dropped_messages": dropped,
        "metrics_export_dropped": bool(metric_line) and
            float(metric_line.split()[1]) == dropped,
        "final_height": tips[0]["id"],
        "final_tip": tips[0]["hash"],
    }
    observed = {"metrics_status": status,
                "ws_stats": hub.get_stats()}
    return core, observed


def _snapshot_churn_cfg(i: int, cfg) -> None:
    """One-block sync pages: full replay pays one RPC per block, so the
    snapshot-vs-replay RPC comparison bites at swarm chain lengths."""
    cfg.node.sync_page = 1


def _joiner_rpcs(swarm: Swarm, i: int) -> int:
    """Outbound RPC attempts node ``i`` has made (delivered + shed) —
    the per-ordered-link matrix counters, driver traffic excluded."""
    prefix = swarm.urls[i] + "->"
    return sum(row["delivered"] + row["dropped"] + row["blocked"]
               for link, row in swarm.matrix.per_link.items()
               if link.startswith(prefix))


async def scenario_snapshot_churn(swarm: Swarm, seed: int):
    """Crash-safe onboarding (docs/SNAPSHOT.md): a blank node restores
    from a snapshot while its serving peer is corrupted mid-chunk and
    then partitioned mid-transfer — it must fail over to the second
    source, resume from journaled chunks, and land on the byte-exact
    UTXO fingerprint; a second blank node measures the full-replay RPC
    baseline; a third faces permanently-poisoned chunks and must fall
    back to full replay with a structured reason instead of failing
    the join."""
    assert swarm.n >= 5, "snapshot_churn needs 5 nodes"
    urls = swarm.urls
    _, addr = _wallet(seed, "shared")
    tmp = tempfile.mkdtemp(prefix="snapshot-churn-")
    try:
        # nodes 0/1: servers; 2: snapshot joiner; 3: replay baseline;
        # 4: forced-integrity-failure joiner (isolated topology: only
        # the peers a phase names below exist for each node)
        for i in (0, 1, 2, 4):
            scfg = swarm.nodes[i].config.snapshot
            scfg.dir = os.path.join(tmp, f"n{i}")
            scfg.chunk_bytes = 2048  # multi-chunk transfers at swarm scale
            scfg.blocks_tail = 8
        swarm.nodes[4].peers.add(urls[1])  # replay-fallback source

        for _ in range(24):
            assert (await swarm.mine(0, addr, push_to=[0, 1]))["ok"]
        m0 = await swarm.nodes[0].build_snapshot()
        m1 = await swarm.nodes[1].build_snapshot()
        assert m0 is not None and m1 is not None

        # phase A — snapshot onboarding under fire: node 0 serves chunk
        # 1 corrupted twice (integrity retries must absorb it) and every
        # node-0 fetch is slowed so the transfer is still mid-flight
        # when the partition cuts node 0 away
        faultinject.install(
            "snapshot.serve:corrupt:times=2,key=chunk/1;"
            "snapshot.fetch:latency:delay=0.02,key=10.77.0.1", seed)
        base2 = _joiner_rpcs(swarm, 2)
        with swarm.nodes[2].telemetry_scope.activate():
            boot2 = asyncio.ensure_future(
                swarm.nodes[2].bootstrap_from_snapshot(
                    sources=[urls[0], urls[1]]))
        progress = swarm.nodes[2].snapshot_restore
        for _ in range(2000):
            if progress.get("verified", 0) >= 3:
                break
            await asyncio.sleep(0.002)
        partitioned_mid_transfer = \
            0 < progress.get("verified", 0) < progress.get("total", 0)
        swarm.matrix.partition([[urls[0]], urls[1:]])
        res2 = await boot2
        rpcs2 = _joiner_rpcs(swarm, 2) - base2
        faultinject.uninstall()

        # phase B — the same onboarding, the old way: full block replay
        base3 = _joiner_rpcs(swarm, 3)
        res3 = await _sync_from(swarm, 3, winner=1)
        rpcs3 = _joiner_rpcs(swarm, 3) - base3

        # phase C — every chunk from every source poisoned: the join
        # must degrade to replay with a structured reason, not fail
        faultinject.install("snapshot.serve:corrupt", seed + 1)
        with swarm.nodes[4].telemetry_scope.activate():
            res4 = await swarm.nodes[4].bootstrap_from_snapshot(
                sources=[urls[1]])
        faultinject.uninstall()

        fp0 = await swarm.nodes[0].state.get_unspent_outputs_hash()
        full0 = await swarm.nodes[0].state.get_full_state_hash()
        fp2 = await swarm.nodes[2].state.get_unspent_outputs_hash()
        full2 = await swarm.nodes[2].state.get_full_state_hash()
        tips = await swarm.tips()
        corrupt_events = fleet_scrape.merged_events(
            swarm, kind="snapshot_chunk_corrupt")
        fallback_events = fleet_scrape.merged_events(
            swarm, kind="snapshot_fallback")
        recommended = fleet_scrape.merged_events(
            swarm, kind="snapshot_recommended")
        core = {
            "servers_published_identical":
                m0["payload_sha256"] == m1["payload_sha256"],
            "snapshot_joiner_ok": bool(res2.get("ok"))
                and res2.get("method") == "snapshot",
            "partitioned_mid_transfer": partitioned_mid_transfer,
            "failed_over_to_second_source":
                res2.get("source") == urls[1],
            "resumed_journaled_chunks": res2.get("chunks_reused", 0) > 0,
            "corruption_caught_by_integrity": len(corrupt_events) >= 1,
            "joiner_fingerprint_exact": fp2 == fp0 and full2 == full0,
            "snapshot_fewer_rpcs_than_replay": rpcs2 < rpcs3,
            "replay_joiner_ok": bool(res3.get("ok")),
            "poisoned_join_fell_back": res4.get("method")
                == "replay_fallback" and bool(res4.get("ok")),
            "fallback_reason_structured": res4.get("reason")
                == "sources_exhausted" and len(fallback_events) >= 1,
            "snapshot_recommended_emitted": len(recommended) >= 1,
            "all_converged": len({t["hash"] for t in tips}) == 1,
            "final_height": tips[0]["id"],
            "final_tip": tips[0]["hash"],
            "utxo_fingerprint": fp0,
        }
        observed = {
            "snapshot_rpcs": rpcs2,
            "replay_rpcs": rpcs3,
            "snapshot_result": res2,
            "replay_result": {k: res3.get(k) for k in ("ok", "error")},
            "fallback_result": {k: res4.get(k)
                                for k in ("ok", "method", "reason")},
            "manifest_chunks": len(m0["chunks"]),
            "corrupt_events": len(corrupt_events),
            "restore_progress": dict(swarm.nodes[2].snapshot_restore),
        }
        return core, observed
    finally:
        faultinject.uninstall()
        # scenario nodes are still serving on this loop; a blocking
        # rmtree here would distort the very timings being measured
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: shutil.rmtree(tmp, ignore_errors=True))


def _archive_prune_cfg(i: int, cfg) -> None:
    """Tiny segments and a short safety window so a swarm-length chain
    spans several archive segments and the compactor actually prunes."""
    cfg.node.sync_page = 1
    cfg.archive.segment_blocks = 4
    cfg.archive.safety_window = 4


async def scenario_archive_prune(swarm: Swarm, seed: int):
    """Cold-block archival tier (docs/ARCHIVE.md): node 0 mines, builds
    a snapshot, and compacts its hot store into the content-addressed
    archive while node 1 keeps the full hot chain as an unpruned twin.
    Every read the archive now backs — get_block, get_blocks_details
    pages spanning the hot/archive seam, get_transaction, address
    history — must answer byte-identically on both nodes (canonical
    JSON fingerprints), before AND after a reorg inside the safety
    window.  Node 2 then mirrors the archive over /archive/* and the
    twin independently compacts its own copy to prove segments are a
    pure function of chain content."""
    assert swarm.n >= 3, "archive_prune needs 3 nodes"
    from ..archive import ArchiveReader
    from ..wallet.builders import WalletBuilder

    urls = swarm.urls
    d, addr = _wallet(seed, "shared")
    _, addr_sink = _wallet(seed, "archive_sink")
    tmp = tempfile.mkdtemp(prefix="archive-prune-")
    try:
        # node 0: pruned node; node 1: unpruned twin; node 2: mirror
        n0, n1, n2 = swarm.nodes[0], swarm.nodes[1], swarm.nodes[2]
        n0.config.snapshot.dir = os.path.join(tmp, "snap0")
        n0.config.snapshot.blocks_tail = 4
        for node, name in ((n0, "archive0"), (n2, "archive2")):
            acfg = node.config.archive
            acfg.dir = os.path.join(tmp, name)
            node.state.archive = ArchiveReader(
                acfg.dir, cache_segments=acfg.reader_cache_segments)

        for _ in range(20):
            assert (await swarm.mine(0, addr, push_to=[0, 1]))["ok"]
        # spend every early coinbase into a sink: those txs leave the
        # UTXO set, so their blocks fall out of the witness closure and
        # become prunable — a pure-coinbase chain keeps every block hot
        from ..core.constants import SMALLEST
        outputs = await n0.state.get_spendable_outputs(addr)
        balance = Decimal(sum(o.amount for o in outputs)) / SMALLEST
        tx = await WalletBuilder(n0.state).create_transaction(
            d, addr_sink, balance)
        for i in (0, 1):   # push_block ships tx HASHES; both mempools
            res = await swarm.get(i, "push_tx", {"tx_hex": tx.hex()})
            assert res.get("ok"), res
        for _ in range(8):
            assert (await swarm.mine(0, addr, push_to=[0, 1]))["ok"]

        hot_before = await n0.state.archive_hot_row_counts()
        assert (await n0.build_snapshot()) is not None
        with n0.telemetry_scope.activate():
            stats = await n0.compact_archive()
        hot_after = await n0.state.archive_hot_row_counts()
        through = stats.get("archived_through", 0)

        # the parity probe set: every archived block by height, pages
        # that straddle the hot/archive seam, every archived tx, and
        # the miner's full address history
        tx_hashes = []
        for h in range(1, through + 1):
            blk = await n1.state.get_block_by_id(h)
            tx_hashes.extend(
                await n1.state.get_block_transaction_hashes(blk["hash"]))
        probes = [("get_block", {"block": str(h),
                                 "full_transactions": "true"})
                  for h in range(1, through + 1)]
        probes += [("get_blocks_details",
                    {"offset": str(off), "limit": "8"})
                   for off in range(1, 28, 8)]
        probes += [("get_transaction", {"tx_hash": h}) for h in tx_hashes]
        probes += [("get_address_transactions",
                    {"address": addr, "page": str(p), "limit": "15"})
                   for p in (1, 2)]

        async def parity() -> bool:
            for path, params in probes:
                a = await swarm.get(0, path, params)
                b = await swarm.get(1, path, params)
                if not a.get("ok") or \
                        artifact_fingerprint(a) != artifact_fingerprint(b):
                    log.error("archive parity diverged on %s %s", path,
                              params)
                    return False
            return True

        parity_before_reorg = await parity()

        # reorg INSIDE the safety window: node 0 mines a private block,
        # the twin mines two, node 0 syncs over and must drop its own —
        # every row touched is above archived_through, so the archive
        # stays valid and parity must hold afterwards
        pre_reorg = (await swarm.tips())[0]
        assert (await swarm.mine(0, addr, push_to=[0]))["ok"]
        for _ in range(2):
            assert (await swarm.mine(1, addr, push_to=[1]))["ok"]
        res_sync = await _sync_from(swarm, 0, winner=1)
        tips = await swarm.tips()
        reorged = bool(res_sync.get("ok")) and \
            tips[0]["hash"] == tips[1]["hash"] and \
            tips[0]["hash"] != pre_reorg["hash"]
        parity_after_reorg = await parity()

        # a second cycle against the same snapshot generation must be a
        # no-op: nothing new to build, closure predicate matches nothing
        stats2 = await n0.compact_archive()

        # node 2 (blank hot store) mirrors the archive over /archive/*
        fetch = await n2.fetch_archive_from_peer(urls[0])
        cov2 = await n2.state.archive.coverage()

        # the twin compacts its OWN copy: overlapping segments must be
        # byte-identical (content-addressing is a pure function of
        # chain content).  Runs after every parity probe — it prunes.
        n1.config.snapshot.dir = os.path.join(tmp, "snap1")
        n1.config.snapshot.blocks_tail = 4
        n1.config.archive.dir = os.path.join(tmp, "archive1")
        n1.state.archive = ArchiveReader(
            n1.config.archive.dir,
            cache_segments=n1.config.archive.reader_cache_segments)
        assert (await n1.build_snapshot()) is not None
        stats_twin = await n1.compact_archive()
        m0 = await n0._archive_manifest()
        m1 = await n1._archive_manifest()
        shared = min(len(m0["segments"]), len(m1["segments"]))
        twin_segments_identical = shared > 0 and all(
            m0["segments"][k]["payload_sha256"]
            == m1["segments"][k]["payload_sha256"]
            and m0["segments"][k]["index_sha256"]
            == m1["segments"][k]["index_sha256"]
            for k in range(shared))

        compact_events = fleet_scrape.merged_events(
            swarm, kind="archive_compact_complete")
        core = {
            "compaction_ok": bool(stats.get("ok")),
            "archived_through": through,
            "segments_published": stats.get("segments", 0),
            "hot_blocks_before": hot_before["blocks"],
            "hot_blocks_after": hot_after["blocks"],
            "hot_txs_before": hot_before["txs"],
            "hot_txs_after": hot_after["txs"],
            "hot_rows_reduced":
                hot_after["blocks"] < hot_before["blocks"]
                and hot_after["txs"] < hot_before["txs"],
            "parity_before_reorg": parity_before_reorg,
            "reorg_inside_safety_window": reorged,
            "parity_after_reorg": parity_after_reorg,
            "recompaction_noop": bool(stats2.get("ok"))
                and stats2.get("segments_built") == 0
                and stats2.get("pruned_blocks") == 0,
            "mirror_fetch_ok": bool(fetch.get("ok"))
                and fetch.get("fetched", 0) > 0,
            "mirror_coverage_exact": cov2 == (1, through),
            "twin_segments_identical": twin_segments_identical,
            "fallthrough_reads_counted":
                n0.state.archive.fallthrough_reads > 0,
            "compact_event_emitted": len(compact_events) >= 1,
            "final_height": tips[1]["id"],
            "final_tip": tips[1]["hash"],
        }
        observed = {
            "compaction": stats,
            "recompaction": stats2,
            "twin_compaction": {k: stats_twin.get(k)
                                for k in ("ok", "archived_through",
                                          "segments_built")},
            "mirror_fetch": fetch,
            "reader_stats": n0.state.archive.stats(),
            "probes": len(probes),
            "sync_result": {k: res_sync.get(k) for k in ("ok", "error")},
        }
        return core, observed
    finally:
        faultinject.uninstall()
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: shutil.rmtree(tmp, ignore_errors=True))


def _watchtower_storm_cfg(i: int, cfg) -> None:
    """Arm the watchtower on every node with the evaluation cadence
    parked (the scenario pumps ``evaluate_once`` itself, so firing
    order is a function of the seed, not the event loop) and the storm
    rule tightened to swarm scale: 4 breaker opens page immediately."""
    wt = cfg.watchtower
    wt.enabled = True
    wt.interval = 3600.0          # background loop never ticks
    wt.for_fast = 0.0             # storm pages on the evaluation tick
    wt.breaker_storm_opens = 4
    wt.breaker_storm_window = 120.0


async def scenario_watchtower_storm(swarm: Swarm, seed: int):
    """Fault → alert → exemplar: every gossip RPC toward node 2 errors,
    so node 0's breaker trips and then re-trips on each half-open
    trial; the watchtower's ``breaker_flip_storm`` rule must reach
    *firing* with an exemplar trace id that stitches across >= 2 nodes
    (the guilty push propagated to node 1 fine), the flight recorder
    must dump with the alert — not the raw fault — as the trigger, and
    once the fault lifts and the event window ages out the alert must
    resolve.  docs/ALERTING.md walks this exact incident."""
    from ..wallet.builders import WalletBuilder

    assert swarm.n >= 3, "watchtower_storm needs 3 nodes"
    engine = swarm.nodes[0].watchtower
    assert engine is not None, "cfg hook did not enable the watchtower"

    d_f, addr_f = _wallet(seed, "storm_funder")
    _, addr_t = _wallet(seed, "storm_target")
    everyone = list(range(swarm.n))
    for _ in range(8):            # one coinbase per later push
        assert (await swarm.mine(0, addr_f, push_to=everyone))["ok"]
    await swarm.settle()

    # prime the streaming detectors: a clean tick must not page
    baseline = await engine.evaluate_once(now=time.time())
    baseline_clean = (baseline["firing"] == 0
                      and baseline["pending"] == 0)

    # every RPC whose peer key contains node 2's address errors; the
    # driver's own requests bypass the resilience wrapper, so only
    # node-to-node gossip feels it
    faultinject.install(f"rpc:error:key={swarm.ips[2]}", seed)
    builder = WalletBuilder(swarm.nodes[0].state)
    rounds = 0
    for k in range(7):
        tx = await builder.create_transaction(d_f, addr_t, "1")
        res = await swarm.get(0, "push_tx", {"tx_hex": tx.hex()})
        assert res.get("ok"), res
        rounds += 1
        # outlive breaker_open_secs (0.25) so the next push lands on a
        # half-open breaker and the failed trial re-opens it — each
        # round past the failure threshold is one more "open" event
        await asyncio.sleep(BREAKER_REOPEN_PAUSE)

    storm_now = time.time()
    counts = await engine.evaluate_once(now=storm_now)
    active = {a.rule.name: a for a in engine.alerts.active()}
    alert = active.get("breaker_flip_storm")
    # Alert objects mutate in place on later ticks — freeze the storm-
    # time view before the resolve leg flips it
    storm_state = alert.state if alert else None
    storm_opens = alert.value if alert else 0.0
    exemplar = alert.exemplars[0] if alert and alert.exemplars else None
    stitched_nodes = sorted({r["node"]
                             for r in _roots_for(swarm, exemplar)}) \
        if exemplar else []
    node0_events = swarm.nodes[0].telemetry_scope.events.snapshot()

    # lift the fault; aging the evaluation clock past the storm window
    # empties the open-event window and the alert must resolve
    faultinject.uninstall()
    fired_before = engine.stats()["fired_total"]
    await engine.evaluate_once(
        now=storm_now + engine.cfg.breaker_storm_window + 1.0)
    resolved = engine.stats()["resolved_total"] >= 1 and not any(
        a.rule.name == "breaker_flip_storm" for a in engine.alerts.active())

    await asyncio.sleep(BREAKER_REOPEN_PAUSE)  # node 2's breakers heal
    assert (await swarm.mine(0, addr_f, push_to=everyone))["ok"]
    await swarm.settle()
    converged = await swarm.wait_converged()
    tips = await swarm.tips()
    core = {
        "baseline_clean": baseline_clean,
        "storm_alert_fired": storm_state == "firing",
        "storm_rule": alert.rule.name if alert else None,
        "storm_severity": alert.rule.severity if alert else None,
        "exemplar_present": exemplar is not None,
        "exemplar_stitched": len(stitched_nodes) >= 2,
        "alert_event_emitted": any(
            e.get("kind") == "alert" and e.get("state") == "firing"
            and e.get("rule") == "breaker_flip_storm"
            for e in node0_events),
        "fault_events_seen": any(e.get("kind") == "fault_injected"
                                 for e in node0_events),
        "alert_resolved": resolved,
        "converged": converged,
        "final_height": tips[0]["id"],
        "final_tip": tips[0]["hash"],
    }
    observed = {
        "rounds": rounds,
        "firing_counts": counts,
        "breaker_opens_windowed": storm_opens,
        "exemplar": exemplar,
        "stitched_nodes": stitched_nodes,
        "fired_total": fired_before,
        "watchtower_stats": engine.stats(),
    }
    return core, observed


# ------------------------------------------------------------- registry ----

@dataclass(frozen=True)
class ScenarioSpec:
    fn: Callable
    nodes: int                # default swarm size
    fast: bool                # member of the CI fast matrix
    topology: str = "mesh"
    swarm_kwargs: dict = field(default_factory=dict)
    # flight-recorder SLO trigger: a per-node p99 above this dumps the
    # black box into the artifact (None = no latency trigger)
    p99_budget_ms: Optional[float] = None


SCENARIOS: Dict[str, ScenarioSpec] = {
    "partition_heal": ScenarioSpec(
        scenario_partition_heal, nodes=6, fast=True,
        swarm_kwargs={"reorg_window": 4}),
    "reorg_storm": ScenarioSpec(
        scenario_reorg_storm, nodes=6, fast=True,
        swarm_kwargs={"reorg_window": 4}),
    "eclipse": ScenarioSpec(
        scenario_eclipse, nodes=4, fast=True, topology="isolated"),
    "spam": ScenarioSpec(scenario_spam, nodes=4, fast=True),
    "dpos_governance": ScenarioSpec(
        scenario_dpos_governance, nodes=2, fast=True,
        topology="isolated"),
    "ws_churn": ScenarioSpec(
        scenario_ws_churn, nodes=2, fast=True,
        swarm_kwargs={"ws": True, "ws_queue_max": 4}),
    "snapshot_churn": ScenarioSpec(
        scenario_snapshot_churn, nodes=5, fast=True,
        topology="isolated",
        swarm_kwargs={"reorg_window": 4,
                      "cfg_hook": _snapshot_churn_cfg}),
    "archive_prune": ScenarioSpec(
        scenario_archive_prune, nodes=3, fast=True,
        topology="isolated",
        swarm_kwargs={"reorg_window": 4,
                      "cfg_hook": _archive_prune_cfg}),
    "watchtower_storm": ScenarioSpec(
        scenario_watchtower_storm, nodes=3, fast=True,
        swarm_kwargs={"cfg_hook": _watchtower_storm_cfg}),
}

# The geo soak lives in the fleet package (fleet/geosoak.py: continent
# latency matrix + churn + propagation quantiles) but registers here so
# the matrix/CLI/artifact machinery treats it like any other scenario.
# Import placed AFTER the registry: geosoak defers every swarm import
# to call time, so this is the only edge and cannot cycle.
from ..fleet.geosoak import geo_soak_cfg, scenario_geo_soak  # noqa: E402

SCENARIOS["geo_soak"] = ScenarioSpec(
    scenario_geo_soak, nodes=6, fast=True,
    swarm_kwargs={"reorg_window": 4, "cfg_hook": geo_soak_cfg},
    p99_budget_ms=2000.0)


# ------------------------------------------------------------- artifact ----

def artifact_fingerprint(core: dict) -> str:
    """sha256 over core's canonical JSON — THE determinism contract."""
    blob = json.dumps(core, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


async def _drive(spec: ScenarioSpec, n: int, seed: int):
    swarm = Swarm(n, seed=seed, **spec.swarm_kwargs)
    await swarm.start(topology=spec.topology)
    swarm.recorder.mark(swarm, label="start")
    try:
        core, observed = await spec.fn(swarm, seed)
        observed = dict(observed)
        observed["links"] = swarm.matrix.stats()
        observed["breakers"] = swarm.breaker_summary()
        slo = swarm.slo_summary()
        # black-box capture happens while the node scopes are live;
        # whether the dump lands in the artifact is decided later
        swarm.recorder.mark(swarm, label="final")
        fleet_events = fleet_scrape.merged_events(swarm)
    finally:
        await swarm.close()
    return core, observed, slo, {"events": fleet_events,
                                 "recorder": swarm.recorder}


def run_scenario(name: str, nodes: Optional[int] = None,
                 seed: int = 7) -> dict:
    """Run one scenario inside a deterministic world; return the
    artifact (core + fingerprint + observed + gate-shaped slo)."""
    spec = SCENARIOS[name]
    n = nodes or spec.nodes
    t0 = time.perf_counter()
    with deterministic_world(seed):
        core, observed, slo, blackbox = asyncio.run(_drive(spec, n, seed))
    elapsed = time.perf_counter() - t0
    core = {"scenario": name, "seed": seed, "nodes": n, **core}
    observed["elapsed_s"] = round(elapsed, 3)
    log.info("scenario %s (n=%d seed=%d) done in %.2fs", name, n, seed,
             elapsed)
    slo_rows = {f"swarm.{name}.{node}": row for node, row in slo.items()}
    artifact = {
        "kind": "swarm_scenario",
        "scenario": name,
        "seed": seed,
        "nodes": n,
        "core": core,
        "fingerprint": artifact_fingerprint(core),
        "observed": observed,
        "slo": {"endpoints": slo_rows},
    }
    # flight recorder: core failure / injected fault / SLO breach ⇒
    # the black box (per-node frames) lands next to the failure
    reason = fleet_recorder.trigger_reason(
        core_ok(core), blackbox["events"], slo_rows=slo_rows,
        p99_budget_ms=spec.p99_budget_ms)
    if reason is not None:
        artifact["flight_recorder"] = blackbox["recorder"].dump(reason)
        log.warning("scenario %s: flight recorder dumped (%s)", name,
                    reason)
    return artifact


def run_matrix(which: str = "fast", seed: int = 7) -> dict:
    """Run every (fast) scenario at its default size; the matrix
    fingerprint chains the per-scenario fingerprints in name order."""
    runs = []
    for name in sorted(SCENARIOS):
        if which != "all" and not SCENARIOS[name].fast:
            continue
        runs.append(run_scenario(name, seed=seed))
    chained = hashlib.sha256(
        "".join(r["fingerprint"] for r in runs).encode()).hexdigest()
    return {"kind": "swarm_matrix", "which": which, "seed": seed,
            "scenarios": [r["scenario"] for r in runs],
            "fingerprint": chained, "runs": runs}
