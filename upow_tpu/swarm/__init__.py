"""In-process multi-node network simulator (ROADMAP item 5).

Spins 10-50 REAL node apps — full middleware, breakers, mempool,
telemetry — inside one event loop, with every peer RPC and WS frame
routed through an in-memory :class:`LinkMatrix` that models per-link
latency, jitter, drop, partitions and the ``swarm.link`` fault site.
``node/app.py`` and ``node/peers.py`` run unmodified: the only seam is
``Node.iface_factory``, swapped for :class:`LoopbackInterface`.

On top sits a seeded scenario runner (:mod:`.scenarios`) with adversary
actors (:mod:`.adversary`) and a DPoS governance traffic generator;
each run emits a structured artifact whose deterministic core is
fingerprinted — same seed, byte-identical fingerprint.

    python -m upow_tpu.swarm --scenario partition_heal --nodes 10
    python -m upow_tpu.swarm --matrix fast --out swarm.json

See docs/SWARM.md for the scenario catalog and determinism contract.
"""

from .links import LinkDown, LinkMatrix, LinkPolicy  # noqa: F401
from .transport import LoopbackHub, LoopbackInterface  # noqa: F401
from .harness import Swarm  # noqa: F401
from .scenarios import (SCENARIOS, artifact_fingerprint,  # noqa: F401
                        run_matrix, run_scenario)
