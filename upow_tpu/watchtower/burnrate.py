"""Multi-window multi-burn-rate SLO evaluation (SRE-workbook style).

Burn rate is the observed error ratio divided by the SLO's error
budget: burn 1.0 consumes exactly the whole budget over the SLO
period, burn 14.4 exhausts 2% of a 30-day budget in one hour.  The
canonical pairing — page when both the 5 m and 1 h windows burn at
>= 14.4×, ticket when both the 30 m and 6 h windows burn at >= 6× —
balances detection speed against false positives: the short window
makes the alert resolve quickly, the long window keeps a blip from
paging.

The evaluator is fed cumulative per-route request/error counts (from
the scope's ``slo.http.<route>.requests`` / ``.errors`` counters) at
each evaluation tick and answers burn rates over trailing windows by
diffing against a ring of retained snapshots.  Retention is
time-bounded by the longest configured window (the slow pair's 6 h
long window at the current ``window_scale``) rather than
count-bounded, so the long-window baseline always survives no matter
the feed cadence; ``max_snapshots`` is only an optional hard backstop
against pathologically fast feeders.  ``window_scale`` compresses the
canonical windows so tests and seeded scenarios can exercise the math
in milliseconds; production keeps 1.0.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Tuple

# Canonical (short, long) window pairs, seconds, at scale 1.0.
WINDOWS = {
    "fast": (5 * 60.0, 60 * 60.0),        # page: 5 m AND 1 h
    "slow": (30 * 60.0, 6 * 60 * 60.0),   # ticket: 30 m AND 6 h
}


class BurnRateEvaluator:
    """Trailing-window burn rates over cumulative route counters."""

    def __init__(self, slo_target: float = 0.999,
                 fast_burn: float = 14.4, slow_burn: float = 6.0,
                 window_scale: float = 1.0,
                 max_snapshots: Optional[int] = None) -> None:
        if not 0.0 < slo_target < 1.0:
            raise ValueError(f"slo_target out of range: {slo_target}")
        self.slo_target = float(slo_target)
        self.budget = 1.0 - self.slo_target
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.window_scale = float(window_scale)
        # ring of (ts, {route: (requests, errors)}); time-pruned in
        # record(), maxlen only as an optional overflow backstop
        self._snaps: deque = deque(maxlen=max_snapshots)

    def window(self, pair: str) -> Tuple[float, float]:
        short, long_ = WINDOWS[pair]
        return short * self.window_scale, long_ * self.window_scale

    def retention(self) -> float:
        """Longest trailing window, seconds — the slow pair's long
        window at the current scale.  Snapshots older than this (bar
        one baseline) can never be read by burn()."""
        return max(long_ for _, long_ in WINDOWS.values()) * self.window_scale

    def record(self, now: float,
               counts: Dict[str, Tuple[float, float]]) -> None:
        """Retain one snapshot of cumulative (requests, errors) by route."""
        now = float(now)
        self._snaps.append((now, dict(counts)))
        # Prune by age, not count: always keep exactly one snapshot
        # at-or-before the longest window's start so the 6 h baseline
        # survives regardless of feed cadence (a count cap at a 5 s
        # cadence retains ~43 min and the slow pair never evaluates).
        horizon = now - self.retention()
        while len(self._snaps) >= 2 and self._snaps[1][0] <= horizon:
            self._snaps.popleft()

    def _at_or_before(self, ts: float) -> Optional[Tuple[float, dict]]:
        """Newest retained snapshot with snap_ts <= ts (window start)."""
        best = None
        for snap in self._snaps:
            if snap[0] <= ts:
                best = snap
            else:
                break
        return best

    def burn(self, route: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Burn rate for ``route`` over the trailing ``window_s`` seconds.

        None when there is no baseline snapshot old enough or no
        requests happened inside the window (no traffic is not an SLO
        violation).
        """
        if not self._snaps:
            return None
        if now is None:
            now = self._snaps[-1][0]
        start = self._at_or_before(now - window_s)
        if start is None:
            return None
        cur = self._snaps[-1][1]
        base = start[1]
        req0, err0 = base.get(route, (0.0, 0.0))
        req1, err1 = cur.get(route, (0.0, 0.0))
        dreq, derr = req1 - req0, err1 - err0
        if dreq <= 0:
            return None
        ratio = max(0.0, derr) / dreq
        return ratio / self.budget

    def evaluate(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Evaluate every route seen in the newest snapshot.

        Returns {route: {"fast_short", "fast_long", "slow_short",
        "slow_long", "page", "ticket", "budget_remaining"}} where the
        burn fields may be None (insufficient data) and page/ticket are
        booleans requiring *both* windows of the pair to burn hot.
        """
        if not self._snaps:
            return {}
        if now is None:
            now = self._snaps[-1][0]
        out: Dict[str, dict] = {}
        for route in sorted(self._snaps[-1][1]):
            fs, fl = self.window("fast")
            ss, sl = self.window("slow")
            b_fs = self.burn(route, fs, now)
            b_fl = self.burn(route, fl, now)
            b_ss = self.burn(route, ss, now)
            b_sl = self.burn(route, sl, now)
            page = (b_fs is not None and b_fl is not None
                    and b_fs >= self.fast_burn and b_fl >= self.fast_burn)
            ticket = (b_ss is not None and b_sl is not None
                      and b_ss >= self.slow_burn and b_sl >= self.slow_burn)
            out[route] = {
                "fast_short": b_fs, "fast_long": b_fl,
                "slow_short": b_ss, "slow_long": b_sl,
                "page": page, "ticket": ticket,
                "budget_remaining": self.budget_remaining(route, now),
            }
        return out

    def budget_remaining(self, route: str,
                         now: Optional[float] = None) -> Optional[float]:
        """Fraction of error budget left over the slow long window.

        1.0 = untouched budget, 0.0 = exactly exhausted, negative =
        overspent; None without enough data.
        """
        _, sl = self.window("slow")
        b = self.burn(route, sl, now)
        if b is None:
            return None
        return 1.0 - b
