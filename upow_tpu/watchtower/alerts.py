"""Alert state machine: pending → firing → resolved.

``AlertManager.observe(rule, active, now, ...)`` is the single entry
point: the engine calls it once per rule (or per dedup key for
per-route rules) on every evaluation tick with the rule's boolean
condition.  The machine applies the rule's ``for``-duration (a
condition must hold continuously before it pages), dedups by key,
tracks severity and exemplar trace ids, and keeps a bounded history
ring of firing/resolved transitions.  Silence and ack are operator
knobs surfaced on /debug/alerts: a silenced alert still tracks state
but suppresses emission; ack just annotates a firing alert.

Timestamps are injected (``now``) so scenarios and golden tests drive
transitions deterministically.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

SEVERITIES = ("info", "warning", "critical")


@dataclass(frozen=True)
class AlertRule:
    """Static description of one alert rule."""
    name: str
    severity: str = "warning"
    for_s: float = 0.0
    description: str = ""

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity: {self.severity!r}")


@dataclass
class Alert:
    """Live state for one dedup key."""
    rule: AlertRule
    key: str
    state: str
    since: float                  # when the condition first went active
    fired_at: Optional[float] = None
    value: Optional[float] = None
    exemplars: List[str] = field(default_factory=list)
    fields: dict = field(default_factory=dict)
    acked: bool = False

    def to_dict(self) -> dict:
        return {
            "rule": self.rule.name,
            "key": self.key,
            "severity": self.rule.severity,
            "state": self.state,
            "since": round(self.since, 6),
            "fired_at": (round(self.fired_at, 6)
                         if self.fired_at is not None else None),
            "for_s": self.rule.for_s,
            "value": self.value,
            "exemplars": list(self.exemplars),
            "fields": dict(self.fields),
            "acked": self.acked,
        }


class AlertManager:
    """Dedup'd alert states with a bounded transition history."""

    MAX_EXEMPLARS = 4

    def __init__(self, history: int = 64,
                 emit: Optional[Callable[[str, Alert], None]] = None) -> None:
        self._states: Dict[str, Alert] = {}
        self._history: deque = deque(maxlen=max(1, int(history)))
        self._silenced: Dict[str, float] = {}   # key -> silence expiry ts
        self._emit = emit
        self.fired_total = 0
        self.resolved_total = 0

    # -- evaluation -------------------------------------------------

    def observe(self, rule: AlertRule, active: bool, now: float,
                value: Optional[float] = None,
                exemplars: Sequence[str] = (),
                fields: Optional[dict] = None,
                key: Optional[str] = None) -> Optional[Alert]:
        """Feed one rule condition sample; returns the live Alert or None."""
        k = key or rule.name
        st = self._states.get(k)
        if not active:
            if st is None:
                return None
            if st.state == FIRING:
                self._transition(st, RESOLVED, now)
                self.resolved_total += 1
            # pending that never fired just evaporates
            del self._states[k]
            return None

        if st is None:
            st = Alert(rule=rule, key=k, state=PENDING, since=now)
            self._states[k] = st
        st.value = value
        if fields:
            st.fields.update(fields)
        for tid in exemplars:
            if tid and tid not in st.exemplars:
                st.exemplars.append(tid)
        del st.exemplars[:-self.MAX_EXEMPLARS]
        if st.state == PENDING and (now - st.since) >= rule.for_s:
            st.fired_at = now
            self._transition(st, FIRING, now)
            self.fired_total += 1
        return st

    def _transition(self, st: Alert, state: str, now: float) -> None:
        st.state = state
        rec = st.to_dict()
        rec["ts"] = round(now, 6)
        self._history.append(rec)
        if self._emit is not None and not self.is_silenced(st.key, now):
            self._emit(state, st)

    # -- operator knobs ---------------------------------------------

    def silence(self, key: str, until: float) -> None:
        self._silenced[key] = float(until)

    def unsilence(self, key: str) -> None:
        self._silenced.pop(key, None)

    def is_silenced(self, key: str, now: float) -> bool:
        until = self._silenced.get(key)
        if until is None:
            return False
        if now >= until:
            del self._silenced[key]
            return False
        return True

    def ack(self, key: str) -> bool:
        st = self._states.get(key)
        if st is None or st.state != FIRING:
            return False
        st.acked = True
        return True

    # -- introspection ----------------------------------------------

    def counts(self, now: float) -> dict:
        firing = pending = silenced = with_exemplars = 0
        for st in self._states.values():
            if self.is_silenced(st.key, now):
                silenced += 1
                continue
            if st.state == FIRING:
                firing += 1
                if st.exemplars:
                    with_exemplars += 1
            elif st.state == PENDING:
                pending += 1
        return {"firing": firing, "pending": pending,
                "silenced": silenced, "firing_with_exemplars": with_exemplars}

    def active(self) -> List[Alert]:
        return sorted(self._states.values(), key=lambda s: s.key)

    def history(self) -> List[dict]:
        return list(self._history)
