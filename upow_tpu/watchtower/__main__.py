"""CLI entry: alert smoke — golden units, then the storm scenario.

    python -m upow_tpu.watchtower                      # all legs
    python -m upow_tpu.watchtower --units-only         # skip the swarm leg
    python -m upow_tpu.watchtower --check-determinism  # scenario twice, cmp fp

Three legs, any failure exits non-zero (CI's ``alert-smoke`` job gates
on the run directly):

1. **Detector goldens** — hand-built series through the stdlib
   streaming detectors (rate, EWMA z-score, stuck gauge, spike) with
   the exact fire points asserted.  No jax, no aiohttp: this leg runs
   even where the accelerator stack is absent.
2. **Burn-rate worked examples** — the SRE-workbook multi-window
   pairing fed synthetic counter snapshots: a 100% error burst pages
   the fast pair, a slow 0.5% drizzle tickets the slow pair, and a
   recovered route resolves.  Plus the alert state machine:
   for-duration, dedup, resolve, silence expiry.
3. **Scenario** — the ``watchtower_storm`` swarm scenario (injected
   gossip faults must page ``breaker_flip_storm`` with a cross-node
   exemplar trace and a flight-recorder dump whose trigger is the
   alert); with ``--check-determinism`` it runs twice and the core
   fingerprints must match byte-identically.
"""

from __future__ import annotations

import argparse
import sys

from .alerts import AlertManager, AlertRule
from .burnrate import BurnRateEvaluator
from .detectors import EwmaZScore, RateTracker, SpikeDetector, StuckGauge


def _check(failures: list, cond: bool, label: str) -> None:
    if not cond:
        failures.append(label)


def _detector_goldens() -> list:
    failures: list = []

    r = RateTracker()
    _check(failures, r.update(0.0, 100.0) is None, "rate: first sample")
    _check(failures, r.update(10.0, 150.0) == 5.0, "rate: 50/10s = 5/s")
    _check(failures, r.update(20.0, 40.0) is None, "rate: counter reset")
    _check(failures, r.update(30.0, 60.0) == 2.0, "rate: recovers post-reset")

    z = EwmaZScore(alpha=0.3, z_threshold=6.0, min_samples=8,
                   direction="drop", min_sigma=0.25)
    for _ in range(10):
        out = z.update(10.0)
        _check(failures, not out["fire"], "zscore: steady series quiet")
    out = z.update(0.0)
    _check(failures, out["fire"] and out["z"] <= -6.0,
           "zscore: collapse to 0 fires drop")
    spike_only = EwmaZScore(min_samples=2, direction="spike")
    for v in (5.0, 5.0, 0.0):
        out = spike_only.update(v)
    _check(failures, not out["fire"], "zscore: drop ignored in spike mode")

    g = StuckGauge(deadline_s=60.0)
    _check(failures, not g.update(0.0, 5.0), "stuck: first sample unarmed")
    _check(failures, not g.update(1000.0, 5.0), "stuck: never moved != stuck")
    _check(failures, not g.update(1010.0, 6.0), "stuck: movement arms")
    _check(failures, not g.update(1069.0, 6.0), "stuck: 59s < deadline")
    _check(failures, g.update(1070.0, 6.0), "stuck: 60s hits deadline")
    _check(failures, not g.update(1071.0, 7.0), "stuck: movement resolves")

    s = SpikeDetector(ratio=8.0, floor=100.0, min_samples=4)
    for v in (10.0, 10.0, 10.0, 10.0):
        out = s.update(v)
        _check(failures, not out["fire"], "spike: baseline build quiet")
    _check(failures, not s.update(50.0)["fire"], "spike: 5x under floor")
    _check(failures, s.update(900.0)["fire"], "spike: 8x over floor fires")
    idle = SpikeDetector(ratio=8.0, floor=0.0, min_samples=4)
    for _ in range(6):
        out = idle.update(0.0)
    _check(failures, not out["fire"], "spike: all-zero series quiet")
    return failures


def _burnrate_goldens() -> list:
    failures: list = []
    # window_scale 1/300: fast pair (1s, 12s), slow pair (6s, 72s) —
    # the worked example runs in simulated seconds, same math
    ev = BurnRateEvaluator(slo_target=0.999, window_scale=1.0 / 300.0)
    # 100 req/s clean for 80s, then 50% errors for 13s: both fast
    # windows blow past 14.4x (0.5/0.001 = 500x burn), pages
    req = err = 0.0
    t = 0.0
    for _ in range(80):
        t += 1.0
        req += 100.0
        ev.record(t, {"push_tx": (req, err)})
    res = ev.evaluate(t)["push_tx"]
    _check(failures, res["fast_short"] == 0.0 and not res["page"],
           "burn: clean traffic burns 0")
    _check(failures, res["budget_remaining"] == 1.0,
           "burn: clean budget untouched")
    for _ in range(13):
        t += 1.0
        req += 100.0
        err += 50.0
        ev.record(t, {"push_tx": (req, err)})
    res = ev.evaluate(t)["push_tx"]
    _check(failures, res["page"] and res["fast_short"] >= 14.4
           and res["fast_long"] >= 14.4, "burn: 50% errors page fast pair")
    _check(failures, res["budget_remaining"] is not None
           and res["budget_remaining"] < 0.0,
           "burn: error burst overspends the budget")

    # 0.5% drizzle = 5x burn: tickets the slow pair (>= 6x? no — 5x
    # stays under slow_burn 6.0, so a 0.8% drizzle = 8x does ticket
    # while never reaching the 14.4x page line)
    ev2 = BurnRateEvaluator(slo_target=0.999, window_scale=1.0 / 300.0)
    req = err = 0.0
    t = 0.0
    for _ in range(80):
        t += 1.0
        req += 1000.0
        err += 8.0
        ev2.record(t, {"sync": (req, err)})
    res = ev2.evaluate(t)["sync"]
    _check(failures, res["ticket"] and not res["page"],
           "burn: 0.8% drizzle tickets, never pages")
    # no traffic inside the window is not an SLO violation
    ev3 = BurnRateEvaluator(slo_target=0.999, window_scale=1.0 / 300.0)
    for tick in range(40):
        ev3.record(float(tick), {"idle": (100.0, 0.0)})
    _check(failures, ev3.burn("idle", 12.0, 39.0) is None,
           "burn: zero traffic in window -> None")
    return failures


def _state_machine_goldens() -> list:
    failures: list = []
    seen: list = []
    mgr = AlertManager(history=8, emit=lambda st, a: seen.append((st, a.key)))
    rule = AlertRule("r", severity="critical", for_s=10.0)

    st = mgr.observe(rule, True, 100.0, value=1.0)
    _check(failures, st is not None and st.state == "pending" and not seen,
           "sm: active goes pending, no emission")
    mgr.observe(rule, True, 109.0)
    _check(failures, mgr.counts(109.0)["firing"] == 0, "sm: 9s < for 10s")
    mgr.observe(rule, True, 110.0, exemplars=["t1", "t1", "t2"])
    c = mgr.counts(110.0)
    _check(failures, c["firing"] == 1 and c["firing_with_exemplars"] == 1
           and seen == [("firing", "r")], "sm: fires at the for-duration")
    _check(failures, mgr.active()[0].exemplars == ["t1", "t2"],
           "sm: exemplars dedup'd")
    _check(failures, mgr.ack("r") and mgr.active()[0].acked, "sm: ack")
    mgr.observe(rule, False, 120.0)
    _check(failures, seen[-1] == ("resolved", "r")
           and mgr.counts(120.0)["firing"] == 0
           and mgr.fired_total == 1 and mgr.resolved_total == 1,
           "sm: inactive resolves")

    # pending that never fired evaporates silently
    mgr.observe(rule, True, 200.0)
    mgr.observe(rule, False, 205.0)
    _check(failures, mgr.resolved_total == 1 and len(mgr.active()) == 0,
           "sm: pending evaporates without resolve")

    # dedup keys: one rule, two routes, independent state
    burn = AlertRule("burn", for_s=0.0)
    mgr.observe(burn, True, 300.0, key="burn:a")
    mgr.observe(burn, True, 300.0, key="burn:b")
    _check(failures, mgr.counts(300.0)["firing"] == 2
           and [a.key for a in mgr.active()] == ["burn:a", "burn:b"],
           "sm: per-key dedup")

    # silence suppresses emission but keeps state; expires on its own
    mgr.silence("burn:a", until=400.0)
    before = len(seen)
    mgr.observe(burn, False, 350.0, key="burn:a")
    _check(failures, len(seen) == before
           and mgr.counts(350.0)["silenced"] == 0,
           "sm: silenced resolve suppressed")
    mgr.silence("burn:b", until=360.0)
    _check(failures, mgr.counts(355.0)["silenced"] == 1
           and mgr.counts(365.0)["silenced"] == 0,
           "sm: silence expires")
    return failures


def _print_scenario(artifact: dict) -> bool:
    from ..swarm.scenarios import core_ok

    core = artifact["core"]
    good = core_ok(core)
    print(f"{'ok  ' if good else 'FAIL'} {artifact['scenario']:>16} "
          f"n={artifact['nodes']} seed={artifact['seed']} "
          f"{artifact['observed']['elapsed_s']:.2f}s "
          f"fp={artifact['fingerprint'][:16]}")
    if not good:
        for key, val in sorted(core.items()):
            if isinstance(val, bool) and not val:
                print(f"     core failed: {key}", file=sys.stderr)
    print(f"     rule={core.get('storm_rule')} "
          f"opens={artifact['observed'].get('breaker_opens_windowed')} "
          f"stitched={artifact['observed'].get('stitched_nodes')} "
          f"recorder={artifact.get('flight_recorder', {}).get('reason')}")
    return good


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m upow_tpu.watchtower",
        description="alert smoke: detector/burn-rate/state-machine "
                    "goldens and the watchtower_storm scenario")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--units-only", action="store_true",
                        help="skip the swarm scenario leg")
    parser.add_argument("--check-determinism", action="store_true",
                        help="run the scenario twice with the same seed "
                             "and fail unless the core fingerprints are "
                             "identical")
    args = parser.parse_args(argv)

    ok = True
    for label, leg in (("detectors", _detector_goldens),
                       ("burnrate", _burnrate_goldens),
                       ("state-machine", _state_machine_goldens)):
        failures = leg()
        print(f"{'ok  ' if not failures else 'FAIL'} {label} goldens")
        for f in failures:
            print(f"     {f}", file=sys.stderr)
        ok = ok and not failures

    if not args.units_only:
        from ..swarm.scenarios import run_scenario

        artifact = run_scenario("watchtower_storm", seed=args.seed)
        ok = _print_scenario(artifact) and ok
        if args.check_determinism:
            again = run_scenario("watchtower_storm", seed=args.seed)
            same = again["fingerprint"] == artifact["fingerprint"]
            print(f"{'ok  ' if same else 'FAIL'} determinism "
                  f"fp1={artifact['fingerprint'][:16]} "
                  f"fp2={again['fingerprint'][:16]}")
            ok = ok and same

    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
