"""Stdlib-only streaming anomaly detectors.

Every detector is a small pure-python state machine fed one sample at a
time with an explicit timestamp where time matters, so golden tests can
replay hand-built series and assert the exact fire points.  Nothing
here imports jax, aiohttp or even the telemetry package — the engine
wires detectors to registries; the detectors only see numbers.
"""

from __future__ import annotations

import math
from typing import Optional


class RateTracker:
    """Turn a cumulative counter into a per-second rate.

    ``update(now, value)`` returns the rate over the interval since the
    previous sample, or ``None`` on the first sample / when the counter
    went backwards (registry reset) / when no time elapsed.
    """

    def __init__(self) -> None:
        self._last_t: Optional[float] = None
        self._last_v: Optional[float] = None

    def update(self, now: float, value: float) -> Optional[float]:
        last_t, last_v = self._last_t, self._last_v
        self._last_t, self._last_v = now, value
        if last_t is None or last_v is None:
            return None
        dt = now - last_t
        if dt <= 0 or value < last_v:
            return None
        return (value - last_v) / dt


class EwmaZScore:
    """EWMA mean/variance z-score detector.

    Keeps an exponentially-weighted mean and variance of the series and
    scores each new sample against the *previous* estimate (the sample
    never judges itself).  Fires when ``|z| >= z_threshold`` in the
    configured direction after at least ``min_samples`` samples have
    seeded the baseline.

    direction: "both" | "spike" (only z >= +t) | "drop" (only z <= -t).
    """

    def __init__(self, alpha: float = 0.3, z_threshold: float = 6.0,
                 min_samples: int = 8, direction: str = "both",
                 min_sigma: float = 1e-6) -> None:
        if direction not in ("both", "spike", "drop"):
            raise ValueError(f"bad direction: {direction!r}")
        self.alpha = float(alpha)
        self.z_threshold = float(z_threshold)
        self.min_samples = int(min_samples)
        self.direction = direction
        self.min_sigma = float(min_sigma)
        self.mean = 0.0
        self.var = 0.0
        self.samples = 0

    def update(self, value: float) -> dict:
        """Feed one sample; returns {"fire", "z", "mean", "sigma"}."""
        value = float(value)
        fire = False
        z = 0.0
        sigma = math.sqrt(self.var) if self.var > 0 else 0.0
        if self.samples >= self.min_samples:
            z = (value - self.mean) / max(sigma, self.min_sigma)
            if self.direction == "spike":
                fire = z >= self.z_threshold
            elif self.direction == "drop":
                fire = z <= -self.z_threshold
            else:
                fire = abs(z) >= self.z_threshold
        out = {"fire": fire, "z": z, "mean": self.mean, "sigma": sigma}
        # Standard EWMA mean/variance recursion (West 1979 flavour).
        if self.samples == 0:
            self.mean = value
            self.var = 0.0
        else:
            delta = value - self.mean
            incr = self.alpha * delta
            self.mean += incr
            self.var = (1.0 - self.alpha) * (self.var + delta * incr)
        self.samples += 1
        return out


class StuckGauge:
    """Fire when a must-move signal stops moving past a deadline.

    Arms only after the gauge has moved at least once (an idle node
    whose height never advanced is not "stuck", it just never started).
    After arming, fires when ``now - last_movement >= deadline_s`` while
    the value has not moved by more than ``min_delta``.  Resolves as
    soon as the value moves again.
    """

    def __init__(self, deadline_s: float, min_delta: float = 0.0) -> None:
        self.deadline_s = float(deadline_s)
        self.min_delta = float(min_delta)
        self._last_value: Optional[float] = None
        self._last_move_t: Optional[float] = None
        self._armed = False

    def update(self, now: float, value: float) -> bool:
        value = float(value)
        if self._last_value is None:
            self._last_value = value
            self._last_move_t = now
            return False
        if abs(value - self._last_value) > self.min_delta:
            self._armed = True
            self._last_value = value
            self._last_move_t = now
            return False
        if not self._armed or self._last_move_t is None:
            return False
        return (now - self._last_move_t) >= self.deadline_s


class SpikeDetector:
    """Rate-of-change spike: value >> its own recent baseline.

    Fires when a sample exceeds both an absolute ``floor`` and
    ``ratio ×`` the EWMA baseline built from at least ``min_samples``
    prior samples.  Firing samples still update the baseline, so a
    sustained plateau stops firing once the baseline catches up —
    this detector flags the *transition*, the alert machine's
    for-duration decides whether the transition matters.
    """

    def __init__(self, ratio: float = 8.0, floor: float = 0.0,
                 alpha: float = 0.3, min_samples: int = 4) -> None:
        self.ratio = float(ratio)
        self.floor = float(floor)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.baseline = 0.0
        self.samples = 0

    def update(self, value: float) -> dict:
        value = float(value)
        fire = False
        baseline = self.baseline
        if self.samples >= self.min_samples:
            fire = (value > 0 and value >= self.floor
                    and value >= self.ratio * baseline)
        if self.samples == 0:
            self.baseline = value
        else:
            self.baseline += self.alpha * (value - self.baseline)
        self.samples += 1
        return {"fire": fire, "baseline": baseline, "value": value}
