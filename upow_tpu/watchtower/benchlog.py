"""Bench-harness alert sink: ``alert_fired`` lines in .bench_events.jsonl.

When a bench-driven node fires an alert, the incident belongs next to
the bench's own event stream (``bench_arm_failed``, ``bench_step_killed``
— tpu_watch.py / bench.py format) so the trajectory tooling sees the
regression and its exemplar trace in one place.  Same record shape and
the same size-capped keep-newest-half rotation as the harnesses.

The engine calls :func:`record` from its evaluation task; the write is
a tiny O(100 B) append on an alert *transition* — rare by construction
(for-durations + dedup) — so it stays inline rather than dragging in
an executor hop.
"""

from __future__ import annotations

import json
import os
import time

from ..logger import get_logger

log = get_logger("watchtower")

MAX_BYTES = 1 << 20   # matches tpu_watch.py / bench.py _EVENTS_MAX


def _rotate_keep_tail(path: str, max_bytes: int) -> None:
    """Size-cap an append-only log: past ``max_bytes``, keep the newest
    half aligned to a line boundary (atomic replace, never raises)."""
    try:
        if os.path.getsize(path) <= max_bytes:
            return
        with open(path, "rb") as f:  # upowlint: disable=RC001
            f.seek(-(max_bytes // 2), os.SEEK_END)
            tail = f.read()
        cut = tail.find(b"\n")
        if cut >= 0:
            tail = tail[cut + 1:]
        tmp = path + ".rot"
        with open(tmp, "wb") as f:  # upowlint: disable=RC001
            f.write(tail)
        os.replace(tmp, path)
    except OSError:
        pass


def record(path: str, alert) -> None:
    """Append one ``alert_fired`` record; never raises into the engine."""
    entry = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "kind": "alert_fired",
        "rule": alert.rule.name,
        "severity": alert.rule.severity,
        "key": alert.key,
        "value": alert.value,
        "exemplar_trace_id": (alert.exemplars[0]
                              if alert.exemplars else None),
        "source": "watchtower",
    }
    try:
        _rotate_keep_tail(path, MAX_BYTES)
        # RC001: rare O(100 B) append on an alert transition; the
        # engine's tick cadence dwarfs the write.
        with open(path, "a") as f:  # upowlint: disable=RC001
            f.write(json.dumps(entry, sort_keys=True) + "\n")
    except OSError as e:
        log.warning("alert_fired record not written to %s: %s", path, e)
