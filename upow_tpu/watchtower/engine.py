"""Watchtower engine: the default rule pack on a cadence.

One ``WatchtowerEngine`` per node evaluates the standing rule pack
(docs/ALERTING.md) over that node's telemetry registries — the scope's
``MetricsRegistry`` / ``EventRing`` / ``TraceBuffer`` when the node
runs under a ``TelemetryScope`` (swarm fleets), the process globals
otherwise.  The engine never relies on the ambient scope contextvar:
it holds direct registry references, so the background task needs no
scope activation and swarm nodes alert strictly independently.

Inputs:

- **probes** — named callables (sync or async) the node registers at
  wiring time for live gauges the registry does not store (block
  height, mempool depth, sync lag, cumulative ws drops).  A probe
  raising is counted, never fatal.
- **counters** — registry counter snapshots turned into rates
  (``pipeline.front.submissions`` → verify throughput).
- **events** — consumed incrementally via the ring's ``since`` cursor
  (breaker trips, degrade transitions); rotated-away records the
  cursor missed are counted into ``telemetry.events.rotated_unseen``.
- **SLO counters** — ``slo.http.<route>.requests`` / ``.errors`` fed
  to the burn-rate evaluator.

Timestamps are injectable (``evaluate_once(now=...)``) so scenarios
drive for-durations and window aging deterministically; production
runs ``run()`` from the node's background task set.
"""

from __future__ import annotations

import asyncio
import inspect
import math
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..logger import get_logger
from ..telemetry import events as events_mod
from ..telemetry import metrics as metrics_mod
from ..telemetry import tracing as tracing_mod
from ..telemetry.events import ROTATED_UNSEEN
from .alerts import AlertManager, AlertRule
from .burnrate import WINDOWS, BurnRateEvaluator
from .detectors import EwmaZScore, RateTracker, SpikeDetector, StuckGauge
from . import benchlog

log = get_logger("watchtower")

_SLO_PREFIX = "slo.http."

#: event kinds that feed the device arm-flap rule
_ARM_FLAP_KINDS = ("degrade", "bench_arm_failed", "arm_failed")


class WatchtowerEngine:
    """Streaming rule evaluation over one node's telemetry registries."""

    def __init__(self, cfg, scope=None, name: str = "node") -> None:
        self.cfg = cfg
        self.name = name
        if scope is not None:
            self._metrics = scope.metrics
            self._events = scope.events
            self._traces = scope.traces
        else:
            self._metrics = metrics_mod._global
            self._events = events_mod._global
            self._traces = tracing_mod._buffer
        self._probes: Dict[str, Callable] = {}
        self._mgr = AlertManager(history=cfg.history,
                                 emit=self._on_transition)
        # Snapshot backstop sized from the windows: one snapshot per
        # tick across the longest (6 h) window plus slack.  The
        # evaluator prunes by age; the cap only guards a runaway feeder
        # and must never undercut the long-window baseline (a fixed 512
        # cap at the 5 s default retained ~43 min, so the slow burn
        # pair — and paging — could never evaluate in production).
        retention = (max(l for _, l in WINDOWS.values())
                     * cfg.window_scale)
        self._burn = BurnRateEvaluator(
            slo_target=cfg.slo_target, fast_burn=cfg.fast_burn,
            slow_burn=cfg.slow_burn, window_scale=cfg.window_scale,
            max_snapshots=math.ceil(
                retention / max(cfg.interval, 1e-6)) + 16)
        # streaming detector state
        self._verify_rate = RateTracker()
        # min_sigma floors the z denominator: a perfectly steady rate
        # must not page on a 1% wobble just because its variance is ~0
        self._verify_z = EwmaZScore(z_threshold=cfg.verify_z,
                                    direction="drop", min_sigma=0.25)
        self._mempool_spike = SpikeDetector(ratio=cfg.mempool_spike_ratio,
                                            floor=cfg.mempool_spike_floor)
        self._ws_rate = RateTracker()
        self._stuck_height = StuckGauge(cfg.stuck_height_deadline,
                                        min_delta=0.0)
        # event-window state: (ts, trace_id) per family, pruned by window
        self._breaker_opens: deque = deque(maxlen=1024)
        self._arm_flaps: deque = deque(maxlen=1024)
        self._cursor = 0
        self._last_burn: Dict[str, dict] = {}
        self.evaluations = 0
        self.probe_errors = 0
        self.eval_errors = 0
        self._last_eval_ts: Optional[float] = None
        self._last_lag = 0.0
        self.on_fire: List[Callable] = []
        self._rules = self._build_rules()
        # the rotated-unseen counter exports from scrape #1 even if the
        # cursor never falls behind
        self._metrics.ensure_counter(ROTATED_UNSEEN)

    # ------------------------------------------------------- rule pack ---

    def _build_rules(self) -> Dict[str, AlertRule]:
        c = self.cfg
        rules = [
            AlertRule("verify_throughput_collapse", "critical", c.for_fast,
                      "verify submission rate collapsed vs its own EWMA "
                      f"baseline (z <= -{c.verify_z}, baseline >= "
                      f"{c.verify_min_rate}/s)"),
            AlertRule("mempool_depth_spike", "warning", c.for_fast,
                      f"mempool depth >= {c.mempool_spike_ratio}x its EWMA "
                      f"baseline and >= {c.mempool_spike_floor}"),
            AlertRule("sync_lag", "warning", c.for_slow,
                      f"node tip >= {c.sync_lag_limit}s behind wall clock"),
            AlertRule("breaker_flip_storm", "critical", c.for_fast,
                      f">= {c.breaker_storm_opens} breaker open transitions "
                      f"within {c.breaker_storm_window}s"),
            AlertRule("ws_drop_rate", "warning", c.for_fast,
                      f"ws hub dropping >= {c.ws_drop_limit} msgs/s"),
            AlertRule("arm_flaps", "warning", c.for_slow,
                      f">= {c.arm_flaps} device degrade/arm-failure events "
                      f"within {c.arm_flap_window}s"),
            AlertRule("stuck_height", "critical", 0.0,
                      "block height stopped moving for "
                      f"{c.stuck_height_deadline}s after having moved"),
            AlertRule("slo_burn_fast", "critical", 0.0,
                      f"route error-budget burn >= {c.fast_burn}x over both "
                      "fast windows (page)"),
            AlertRule("slo_burn_slow", "warning", 0.0,
                      f"route error-budget burn >= {c.slow_burn}x over both "
                      "slow windows (ticket)"),
        ]
        return {r.name: r for r in rules}

    @property
    def rules(self) -> Dict[str, AlertRule]:
        return dict(self._rules)

    @property
    def alerts(self) -> AlertManager:
        return self._mgr

    def register_probe(self, name: str, fn: Callable) -> None:
        """Register a live gauge source; ``fn`` may be sync or async."""
        self._probes[name] = fn

    # ------------------------------------------------------ evaluation ---

    async def run(self) -> None:
        """Cadence loop for the node's background task set."""
        while True:
            await asyncio.sleep(self.cfg.interval)
            try:
                await self.evaluate_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the sentry must outlive any single bad tick
                self.eval_errors += 1
                log.exception("watchtower evaluation failed")

    async def _read_probes(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, fn in self._probes.items():
            try:
                v = fn()
                if inspect.isawaitable(v):
                    v = await v
                out[name] = float(v)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # a dead probe degrades one rule, never the engine
                self.probe_errors += 1
                log.debug("watchtower probe %s failed: %s", name, e)
        return out

    async def evaluate_once(self, now: Optional[float] = None) -> dict:
        """One evaluation tick; ``now`` injectable for determinism."""
        if now is None:
            now = time.time()
        t0 = time.monotonic()
        self.evaluations += 1
        probes = await self._read_probes()
        counters = self._metrics.counters()
        self._consume_events(now)
        self._eval_streaming(now, probes, counters)
        self._eval_burnrate(now, counters)
        self._last_eval_ts = now
        self._last_lag = time.monotonic() - t0
        return self._mgr.counts(now)

    def _consume_events(self, now: float) -> None:
        got = self._events.since(self._cursor)
        self._cursor = got["next_seq"]
        if got["missed"]:
            self._metrics.inc(ROTATED_UNSEEN, got["missed"])
        for e in got["events"]:
            kind = e.get("kind")
            if kind == "breaker" and e.get("state") == "open":
                self._breaker_opens.append((e["ts"], e.get("trace_id")))
            elif kind in _ARM_FLAP_KINDS:
                self._arm_flaps.append((e["ts"], e.get("trace_id")))
        _prune(self._breaker_opens, now - self.cfg.breaker_storm_window)
        _prune(self._arm_flaps, now - self.cfg.arm_flap_window)

    def _eval_streaming(self, now: float, probes: Dict[str, float],
                        counters: Dict[str, int]) -> None:
        c = self.cfg
        mgr, rules = self._mgr, self._rules

        # verify throughput collapse: counter -> rate -> z-score drop
        rate = self._verify_rate.update(
            now, float(counters.get("pipeline.front.submissions", 0)))
        if rate is not None:
            r = self._verify_z.update(rate)
            collapsed = (r["fire"] and r["mean"] >= c.verify_min_rate
                         and rate <= 0.5 * r["mean"])
            mgr.observe(rules["verify_throughput_collapse"], collapsed,
                        now, value=rate, fields={"z": round(r["z"], 3)})

        # mempool depth spike
        if "mempool_depth" in probes:
            r = self._mempool_spike.update(probes["mempool_depth"])
            mgr.observe(rules["mempool_depth_spike"], r["fire"], now,
                        value=probes["mempool_depth"],
                        fields={"baseline": round(r["baseline"], 3)})

        # sync lag threshold
        if "sync_lag" in probes:
            mgr.observe(rules["sync_lag"],
                        probes["sync_lag"] >= c.sync_lag_limit,
                        now, value=probes["sync_lag"])

        # breaker flip storm (event window); exemplars are the trace ids
        # the breaker transitions fired under — i.e. the guilty requests
        opens = len(self._breaker_opens)
        exemplars = [tid for _, tid in self._breaker_opens if tid]
        mgr.observe(rules["breaker_flip_storm"],
                    opens >= c.breaker_storm_opens, now,
                    value=float(opens), exemplars=exemplars[-4:])

        # ws drop rate
        if "ws_dropped" in probes:
            wrate = self._ws_rate.update(now, probes["ws_dropped"])
            if wrate is not None:
                mgr.observe(rules["ws_drop_rate"],
                            wrate >= c.ws_drop_limit, now, value=wrate)

        # device arm flaps (event window)
        flaps = len(self._arm_flaps)
        mgr.observe(rules["arm_flaps"], flaps >= c.arm_flaps, now,
                    value=float(flaps),
                    exemplars=[t for _, t in self._arm_flaps if t][-4:])

        # stuck block height
        if "block_height" in probes:
            stuck = self._stuck_height.update(now, probes["block_height"])
            mgr.observe(rules["stuck_height"], stuck, now,
                        value=probes["block_height"])

    def _eval_burnrate(self, now: float, counters: Dict[str, int]) -> None:
        counts = {}
        for name, v in counters.items():
            if name.startswith(_SLO_PREFIX) and name.endswith(".requests"):
                route = name[len(_SLO_PREFIX):-len(".requests")]
                err = counters.get(_SLO_PREFIX + route + ".errors", 0)
                counts[route] = (float(v), float(err))
        self._burn.record(now, counts)
        self._last_burn = self._burn.evaluate(now)
        for route, res in self._last_burn.items():
            ex = self._route_exemplars(route)
            self._mgr.observe(
                self._rules["slo_burn_fast"], res["page"], now,
                value=res["fast_short"], exemplars=ex,
                fields={"route": route}, key=f"slo_burn_fast:{route}")
            self._mgr.observe(
                self._rules["slo_burn_slow"], res["ticket"], now,
                value=res["slow_short"], exemplars=ex,
                fields={"route": route}, key=f"slo_burn_slow:{route}")

    def _route_exemplars(self, route: str) -> List[str]:
        """Trace ids of the slowest/erroring requests for ``route`` from
        the tracing slowest-ring (erroring first, then slowest)."""
        try:
            slowest = self._traces.snapshot().get("slowest", [])
        except Exception as e:
            log.debug("exemplar lookup failed: %s", e)  # best-effort
            return []
        hits = []
        for t in slowest:
            nm = t.get("name", "")
            if not nm.startswith("http."):
                continue
            if nm[len("http."):].replace("/", "_") != route:
                continue
            tid = t.get("trace_id")
            if tid:
                hits.append((bool(t.get("error")),
                             t.get("duration_ms", 0.0), tid))
        hits.sort(key=lambda h: (not h[0], -h[1]))
        out = []
        for _, _, tid in hits:
            if tid not in out:
                out.append(tid)
        return out[:4]

    # -------------------------------------------------------- emission ---

    def _on_transition(self, state: str, alert) -> None:
        exemplar = alert.exemplars[0] if alert.exemplars else None
        self._events.emit(
            "alert", rule=alert.rule.name, state=state,
            severity=alert.rule.severity, key=alert.key,
            value=alert.value, exemplar=exemplar, node=self.name)
        if state == "firing":
            if self.cfg.bench_events:
                benchlog.record(self.cfg.bench_events, alert)
            for cb in self.on_fire:
                try:
                    cb(alert)
                except Exception:
                    # observer bugs must not break alerting
                    log.exception("on_fire callback failed")

    # --------------------------------------------------- introspection ---

    def silence(self, key: str, seconds: float,
                now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self._mgr.silence(key, now + max(0.0, seconds))

    def ack(self, key: str) -> bool:
        return self._mgr.ack(key)

    def stats(self) -> dict:
        return {
            "evaluations": self.evaluations,
            "eval_errors": self.eval_errors,
            "probe_errors": self.probe_errors,
            "fired_total": self._mgr.fired_total,
            "resolved_total": self._mgr.resolved_total,
            "eval_lag_seconds": round(self._last_lag, 6),
        }

    def metric_rows(self, now: Optional[float] = None) -> dict:
        """The upow_alert_* family values for /metrics."""
        now = time.time() if now is None else now
        c = self._mgr.counts(now)
        return {
            "firing": c["firing"], "pending": c["pending"],
            "silenced": c["silenced"],
            "firing_with_exemplars": c["firing_with_exemplars"],
            "evaluations": self.evaluations,
            "fired_total": self._mgr.fired_total,
            "resolved_total": self._mgr.resolved_total,
            "eval_lag_seconds": self._last_lag,
        }

    def snapshot(self, now: Optional[float] = None) -> dict:
        """/debug/alerts payload."""
        now = time.time() if now is None else now
        return {
            "node": self.name,
            "interval": self.cfg.interval,
            "counts": self._mgr.counts(now),
            "stats": self.stats(),
            "rules": [{"name": r.name, "severity": r.severity,
                       "for_s": r.for_s, "description": r.description}
                      for r in self._rules.values()],
            "active": [a.to_dict() for a in self._mgr.active()],
            "history": self._mgr.history(),
            "burnrate": self._last_burn,
        }


def _prune(dq: deque, cutoff: float) -> None:
    while dq and dq[0][0] < cutoff:
        dq.popleft()
