"""Watchtower — in-process streaming judgment over the telemetry stack.

The telemetry registries (metrics, events, tracing, SLO histograms)
record everything but judge nothing: a verify-throughput collapse or a
breaker flip storm is only visible if an operator stares at /metrics.
Watchtower closes that loop in-process:

- ``detectors``  — stdlib-only streaming primitives (EWMA z-score,
  stuck-gauge, rate-of-change spike) with deterministic fire points.
- ``burnrate``   — multi-window multi-burn-rate SLO evaluation over the
  per-route latency histograms and error counters.
- ``alerts``     — pending→firing→resolved state machine with
  for-durations, dedup keys, severity, silence/ack and a bounded
  history ring; every firing alert captures exemplar trace ids.
- ``engine``     — one background task per node evaluating the default
  rule pack on a cadence, scoped per TelemetryScope so swarm nodes
  alert independently.

See docs/ALERTING.md for the rule pack and operational guide.
"""

from .alerts import Alert, AlertManager, AlertRule
from .burnrate import BurnRateEvaluator, WINDOWS
from .detectors import EwmaZScore, RateTracker, SpikeDetector, StuckGauge
from .engine import WatchtowerEngine

__all__ = [
    "Alert",
    "AlertManager",
    "AlertRule",
    "BurnRateEvaluator",
    "EwmaZScore",
    "RateTracker",
    "SpikeDetector",
    "StuckGauge",
    "WatchtowerEngine",
    "WINDOWS",
]
