"""upow-tpu: a TPU-native framework with the capabilities of upowai/upow.

A wire-compatible uPow blockchain node, miner, and wallet whose two hot
kernels — sha256 nonce search and batched NIST P-256 ECDSA / UTXO block
validation — run on TPU via JAX/XLA/Pallas, with a pure consensus core,
backend-abstracted crypto (``device=cpu|tpu``), and a thin asyncio HTTP /
sqlite shell that stays endpoint- and schema-compatible with the reference.

Layering (bottom-up), mirroring SURVEY.md §1 but with the DB knot cut:

- ``core``   — pure protocol kernel: codecs, tx/header wire formats,
               difficulty, rewards, merkle.  No I/O, no DB, no JAX.
- ``crypto`` — backend-abstracted primitives (sha256 batch, P-256 ECDSA),
               CPU (hashlib/OpenSSL/C++) and TPU (Pallas/jnp) backends.
- ``mine``   — TPU nonce search: midstate-split Pallas sha256 kernel,
               sharded over a device mesh; host mining loop.
- ``state``  — chain state store (sqlite, Postgres-schema-compatible) +
               device-resident UTXO set.
- ``verify`` — batched block validation pipeline (device) + DPoS rules
               against an abstract state view (host).
- ``node``   — asyncio HTTP shell, gossip, sync; ``ws`` — WebSocket push.
- ``wallet`` — key management, tx builders, CLI.
"""

__version__ = "0.1.0"
