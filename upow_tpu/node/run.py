"""Launcher: ``python -m upow_tpu.node.run [--config cfg.json]``
(reference run_node.py / upow/node/run.py)."""

import argparse

from ..config import Config
from .app import run


def main() -> None:
    parser = argparse.ArgumentParser("upow_tpu node")
    parser.add_argument("--config", default=None)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--db", default=None)
    args = parser.parse_args()
    overrides = {}
    if args.port is not None:
        overrides["node__port"] = args.port
    if args.db is not None:
        overrides["node__db_path"] = args.db
    run(Config.load(args.config, **overrides))


if __name__ == "__main__":
    main()
