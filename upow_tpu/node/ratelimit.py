"""Per-IP endpoint rate limiting (reference slowapi limits, main.py:55
and the @limiter.limit decorators).

Sliding-window counters keyed by (ip, endpoint); limits are the
reference's strings ("15/second", "30/minute").  Exceeding answers HTTP
429 like slowapi.  Windows are pruned lazily, so memory is bounded by
active (ip, endpoint) pairs within the largest window.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

# endpoint -> reference limit (main.py:267-1056 decorator per route)
DEFAULT_LIMITS = {
    "/": "3/minute",
    "/sync_blockchain": "10/minute",
    "/get_mining_info": "30/minute",
    "/get_address_info": "15/second",
    "/add_node": "10/minute",
    "/get_transaction": "2/second",
    "/get_block": "30/minute",
    "/get_block_details": "10/minute",
    "/get_blocks": "40/minute",
    "/get_blocks_details": "10/minute",
    "/dobby_info": "20/minute",
    "/get_supply_info": "20/minute",
    # snapshot sync surface (docs/SNAPSHOT.md): served straight from
    # on-disk chunk files, so the budgets are about network fairness,
    # not database load — a restoring peer pulls many chunks back to
    # back, a manifest poll is one small JSON read
    "/snapshot/manifest": "30/minute",
    "/snapshot/chunk": "20/second",
    # archive serving (docs/ARCHIVE.md): same fairness stance — every
    # /archive/segment/{i} collapses into ONE "/archive/segment"
    # bucket, so per-index windows cannot multiply the budget
    "/archive/manifest": "30/minute",
    "/archive/segment": "10/second",
}

_PERIODS = {"second": 1.0, "minute": 60.0, "hour": 3600.0}


def parse_limit(spec: str) -> Tuple[int, float]:
    count, _, period = spec.partition("/")
    return int(count), _PERIODS[period]


class RateLimiter:
    def __init__(self, limits: Optional[Dict[str, str]] = None,
                 enabled: bool = True):
        self.enabled = enabled
        self.limits = {
            path: parse_limit(spec)
            for path, spec in (limits or DEFAULT_LIMITS).items()
        }
        self._hits: Dict[Tuple[str, str], Deque[float]] = {}
        self._calls = 0

    def _bucket(self, endpoint: str) -> str:
        """Collapse a dynamic-suffix path onto its registered limit:
        ``/snapshot/chunk/17`` shares ``/snapshot/chunk``'s window (one
        budget for the whole chunk space — per-index windows would let
        a scanner multiply its allowance by the chunk count)."""
        probe = endpoint
        while probe and probe not in self.limits:
            probe = probe.rsplit("/", 1)[0]
        return probe or endpoint

    def allow(self, ip: str, endpoint: str) -> bool:
        """True if this request is within the endpoint's budget."""
        if not self.enabled:
            return True
        endpoint = self._bucket(endpoint)
        if endpoint not in self.limits:
            return True
        count, period = self.limits[endpoint]
        now = time.monotonic()
        self._calls += 1
        if self._calls % 4096 == 0:
            self._sweep(now)
        window = self._hits.setdefault((ip, endpoint), deque())
        while window and now - window[0] > period:
            window.popleft()
        if len(window) >= count:
            return False
        window.append(now)
        return True

    def _sweep(self, now: float) -> None:
        """Drop fully-expired windows so a scan from many source IPs
        cannot grow the dict unboundedly."""
        for key in list(self._hits):
            window = self._hits[key]
            _, period = self.limits.get(key[1], (0, 3600.0))
            while window and now - window[0] > period:
                window.popleft()
            if not window:
                del self._hits[key]
