"""Node: HTTP API, P2P gossip, chain sync (reference upow/node/)."""

from .app import Node, run  # noqa: F401
from .peers import NodeInterface, PeerBook  # noqa: F401
