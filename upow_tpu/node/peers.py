"""Peer book + HTTP RPC client (reference upow/node/nodes_manager.py:24-210).

Semantics replicated: a JSON peer file guarded by a file lock; peers are
"active" if they messaged us within 7 days, pruned after 90 days of
silence, capped at 100; the propagate set is a sample of up to 10 active
plus up to 10 never-seen peers; RPC requests carry a ``Sender-Node``
header as the return address and responses are capped at 20 MB.

Transport is aiohttp (the reference uses httpx) — one shared session per
process, created lazily on the running loop.

Resilience: every :class:`NodeInterface` RPC can run under a
:class:`~upow_tpu.resilience.ResilienceContext` — per-peer circuit
breaker gate, deterministic fault injection, then retry with jittered
backoff under a total deadline.  Without a context (standalone clients,
older tests) behaviour is exactly the single-attempt original.  The
:class:`PeerBook` carries the breaker registry so gossip/sync peer
selection can skip open circuits and prefer high-score peers.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from typing import Dict, List, Optional

import aiohttp
from filelock import FileLock

from ..config import NodeConfig
from ..logger import get_logger
from .. import trace
from ..resilience import (BreakerRegistry, CircuitOpenError,
                          ResilienceContext, call_with_retry, faultinject)

# Exceptions worth retrying: transport-level trouble, not peer-side
# application errors (an HTTP error body parses fine and is NOT retried).
TRANSIENT_ERRORS = (aiohttp.ClientError, asyncio.TimeoutError,
                    ConnectionError, OSError)

log = get_logger("peers")


def _normalize(url: str) -> str:
    url = (url or "").strip().strip("/")
    if url and not url.startswith("http"):
        url = "http://" + url
    return url


class PeerBook:
    """Durable peer registry with active/unseen classes and pruning."""

    def __init__(self, cfg: Optional[NodeConfig] = None,
                 breakers: Optional[BreakerRegistry] = None):
        self.cfg = cfg or NodeConfig()
        # Health scores for selection; a default registry keeps
        # standalone PeerBooks working with every peer reading healthy.
        self.breakers = breakers if breakers is not None else \
            BreakerRegistry()
        self.path = self.cfg.peers_file
        self._lock = FileLock(self.path + ".lock") if self.path else None
        self._data: Dict[str, dict] = {}
        self._load()
        if not self._data and self.cfg.seed_url:
            self.add(self.cfg.seed_url)

    # ------------------------------------------------------- persistence --
    def _load(self) -> None:
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    self._data = json.load(f).get("nodes", {})
            except (json.JSONDecodeError, OSError):
                self._data = {}

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            tmp = self.path + ".tmp"
            # RC001: the peer book is a few KB; the synchronous
            # write-then-rename under the lock is what keeps add/prune
            # atomic against concurrent savers
            with open(tmp, "w") as f:  # upowlint: disable=RC001
                json.dump({"nodes": self._data}, f)
            os.replace(tmp, self.path)

    # ------------------------------------------------------------ updates --
    def add(self, url: str) -> bool:
        url = _normalize(url)
        if not url or url in self._data:
            return False
        if len(self._data) >= self.cfg.max_peers:
            self.prune()
            if len(self._data) >= self.cfg.max_peers:
                return False
        self._data[url] = {"added": int(time.time()), "last_message": 0}
        self.save()
        return True

    def update_last_message(self, url: str) -> None:
        url = _normalize(url)
        if url in self._data:
            self._data[url]["last_message"] = int(time.time())
            self.save()

    def remove(self, url: str) -> None:
        if self._data.pop(_normalize(url), None) is not None:
            self.save()

    def prune(self) -> None:
        """Drop peers silent for prune_after (but keep never-seen entries
        younger than that, by their added time)."""
        now = time.time()
        doomed = [
            u for u, meta in self._data.items()
            if now - max(meta.get("last_message", 0), meta.get("added", 0))
            > self.cfg.prune_after
        ]
        for u in doomed:
            del self._data[u]
        if doomed:
            self.save()

    # ------------------------------------------------------------- reads --
    def all_nodes(self) -> List[str]:
        return list(self._data)

    def recent_nodes(self) -> List[str]:
        """Peers that messaged us within the active window; falls back to
        everything known when nobody has (fresh node bootstrapping from
        the seed)."""
        now = time.time()
        active = [
            u for u, meta in self._data.items()
            if now - meta.get("last_message", 0) < self.cfg.active_within
            and meta.get("last_message", 0) > 0
        ]
        return active or list(self._data)

    def _healthy_sample(self, pool: List[str], k: int) -> List[str]:
        """Sample ``k`` peers, skipping open circuits and preferring the
        high-score tier.  With no breaker history every peer scores 1.0
        and this is exactly the reference's ``random.sample``."""
        pool = [u for u in pool if self.breakers.usable(u)]
        good = [u for u in pool if self.breakers.score(u) >= 0.5]
        weak = [u for u in pool if self.breakers.score(u) < 0.5]
        picks = random.sample(good, min(k, len(good)))
        if len(picks) < k:
            picks += random.sample(weak, min(k - len(picks), len(weak)))
        return picks

    def propagate_nodes(self) -> List[str]:
        """≤10 active + ≤10 never-seen (nodes_manager.py:144-149), healthy
        first.

        "Active" is the 7-day window (the reference samples
        get_recent_nodes here): a peer last heard from BEYOND the window
        is neither active nor never-seen and is not gossiped to.  On top
        of the reference semantics, peers whose circuit is open are
        skipped and degraded-score peers only fill leftover slots."""
        k = self.cfg.propagate_sample
        now = time.time()
        active = [
            u for u, meta in self._data.items()
            if meta.get("last_message", 0) > 0
            and now - meta["last_message"] < self.cfg.active_within
        ]
        unseen = [u for u, meta in self._data.items()
                  if meta.get("last_message", 0) == 0]
        picks = self._healthy_sample(active, k)
        picks += self._healthy_sample(unseen, k)
        # Health-ranked fan-out, consistent with sync_blockchain's
        # candidate ordering: the sampled set keeps the reference's
        # gossip diversity, but sends go to the healthiest peers first
        # so a degraded peer's slow/failing RPC is the last in line,
        # not an equal-odds first pick.
        return self.ranked(picks)

    def ranked(self, urls: List[str]) -> List[str]:
        """Sort candidate peers by descending health score with open
        circuits pushed to the back (sync source ordering / gossip
        fan-out order).  Equal-health peers tie-break on URL so the
        ordering is a pure function of breaker state — swarm scenarios
        and operators replaying a /debug/breakers snapshot see the
        same decision."""
        return sorted(urls, key=lambda u: (
            0 if self.breakers.usable(u) else 1,
            -self.breakers.score(u), u))

    def contains(self, url: str) -> bool:
        return _normalize(url) in self._data


class NodeInterface:
    """RPC client for one remote node (nodes_manager.py:174-210)."""

    def __init__(self, url: str, cfg: Optional[NodeConfig] = None,
                 session: Optional[aiohttp.ClientSession] = None,
                 resilience: Optional[ResilienceContext] = None):
        self.base_url = _normalize(url)
        self.url = self.base_url
        self.cfg = cfg or NodeConfig()
        self._session = session
        self._own_session = session is None  # close() only closes what we made
        self._resilience = resilience

    async def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.cfg.http_timeout))
            self._own_session = True
        return self._session

    async def close(self) -> None:
        if (self._own_session and self._session is not None
                and not self._session.closed):
            await self._session.close()

    async def _read_capped(self, resp: aiohttp.ClientResponse) -> dict:
        buf = b""
        async for chunk in resp.content.iter_chunked(64 * 1024):
            buf += chunk
            if len(buf) > self.cfg.response_cap:
                raise ValueError("response too large")
        return json.loads(buf or b"{}")

    async def _resilient(self, attempt, label: str,
                         site: Optional[str] = None,
                         site_key: Optional[str] = None):
        """Run one RPC attempt factory under the breaker → fault-injection
        → retry stack.  Without a ResilienceContext this is a transparent
        single attempt (standalone clients keep the original behaviour).
        ``site`` renames the fault-injection site away from the default
        ``rpc.<label>`` (the snapshot bootstrap fires ``snapshot.fetch``
        so chaos specs can target restore traffic without touching the
        ordinary RPC plane)."""
        ctx = self._resilience
        if ctx is None:
            return await attempt()
        breaker = ctx.breakers.get(self.base_url)
        if not breaker.available():
            trace.inc("resilience.breaker_rejected")
            raise CircuitOpenError(self.base_url)

        async def guarded():
            injector = faultinject.get_injector()
            if injector is not None:
                await injector.fire(site or f"rpc.{label}",
                                    site_key or self.base_url)
            return await attempt()

        def on_retry(exc, retry_no):
            trace.inc("resilience.rpc_retries")
            log.debug("retry %d for %s %s: %s", retry_no, self.base_url,
                      label, exc)

        try:
            out = await call_with_retry(
                guarded, ctx.policy, retry_on=TRANSIENT_ERRORS,
                rng=ctx.rng, on_retry=on_retry)
        except TRANSIENT_ERRORS:
            breaker.record_failure()
            raise
        breaker.record_success()
        return out

    async def request(self, path: str, args: dict,
                      sender_node: str = "") -> dict:
        """Wire-compatible RPC: POST json for push_block/push_tx, GET with
        query params for everything else (reference
        nodes_manager.py:192-209) — so e.g. gossiped ``add_node`` lands on
        peers' GET routes."""
        headers = self._rpc_headers(sender_node)

        async def attempt() -> dict:
            session = await self._get_session()
            if path in ("push_block", "push_tx"):
                async with session.post(f"{self.base_url}/{path}",
                                        json=args, headers=headers) as resp:
                    return await self._read_capped(resp)
            params = {k: str(v) for k, v in args.items()}
            async with session.get(f"{self.base_url}/{path}", params=params,
                                   headers=headers) as resp:
                return await self._read_capped(resp)

        return await self._resilient(attempt, path)

    @staticmethod
    def _rpc_headers(sender_node: str) -> dict:
        """Common outbound headers: peer identity plus the current trace
        ID, so a gossiped tx/block keeps one trace across nodes (the
        receiving middleware adopts X-Upow-Trace)."""
        headers = {"Sender-Node": sender_node} if sender_node else {}
        tid = trace.current_trace_id()
        if tid is not None:
            headers[trace.TRACE_HEADER] = tid
        return headers

    async def get(self, path: str, params: Optional[dict] = None,
                  sender_node: str = "", site: Optional[str] = None,
                  site_key: Optional[str] = None) -> dict:
        headers = self._rpc_headers(sender_node)

        async def attempt() -> dict:
            session = await self._get_session()
            async with session.get(f"{self.base_url}/{path}",
                                   params=params or {},
                                   headers=headers) as resp:
                return await self._read_capped(resp)

        return await self._resilient(attempt, path, site=site,
                                     site_key=site_key)

    @staticmethod
    def _result(res: dict):
        """Unwrap an RPC envelope; a peer's error/rate-limit body becomes
        a readable error instead of a bare KeyError."""
        if "result" not in res:
            raise RuntimeError(
                f"peer error: {res.get('error', res)!s:.200}")
        return res["result"]

    async def get_block(self, block_no: int) -> dict:
        return self._result(await self.get(
            "get_block", {"block": str(block_no),
                          "full_transactions": "false"}))

    async def get_blocks(self, offset: int, limit: int) -> list:
        return self._result(await self.get(
            "get_blocks", {"offset": str(offset), "limit": str(limit)}))

    async def get_nodes(self) -> list:
        return self._result(await self.get("get_nodes"))

    # ------------------------------------------------------- snapshots ----
    # Both run under the ordinary breaker/retry stack but fire the
    # dedicated ``snapshot.fetch`` site (keyed per document) so a chaos
    # spec can fault restore traffic — or one specific chunk — without
    # touching the rpc.* plane.

    async def snapshot_manifest(self) -> dict:
        return self._result(await self.get(
            "snapshot/manifest", site="snapshot.fetch",
            site_key=f"{self.base_url}#manifest"))

    async def snapshot_chunk(self, i: int) -> bytes:
        doc = self._result(await self.get(
            f"snapshot/chunk/{int(i)}", site="snapshot.fetch",
            site_key=f"{self.base_url}#chunk/{int(i)}"))
        return bytes.fromhex(doc["data"])
