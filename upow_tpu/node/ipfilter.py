"""IP filtering + local-address guard.

Mirrors the reference's two small pieces of endpoint-surface policy:

* ``ip_config.json`` hot-reloaded whitelist / blocklist / per-endpoint
  blocks (upow/node/ip_manager.py:8-52), reload every 300 s.
* the private-range table guarding the custodial ``send_to_address``
  endpoint (upow/node/utils.py:4-31).
"""

from __future__ import annotations

import ipaddress
import json
import os
import time
from typing import Optional

_PRIVATE_NETS = [
    ipaddress.ip_network(n)
    for n in (
        # the reference's full list (node/utils.py:9-27): RFC1918 plus
        # every special-purpose v4 range — none of these is a routable
        # public peer
        "127.0.0.0/8",      # loopback
        "10.0.0.0/8",       # RFC1918
        "172.16.0.0/12",
        "192.168.0.0/16",
        "0.0.0.0/8",        # "this network"
        "100.64.0.0/10",    # CGNAT
        "169.254.0.0/16",   # link-local
        "192.0.0.0/24",     # IETF protocol assignments
        "192.0.2.0/24",     # TEST-NET-1
        "192.88.99.0/24",   # 6to4 relay (deprecated)
        "198.18.0.0/15",    # benchmarking
        "198.51.100.0/24",  # TEST-NET-2
        "203.0.113.0/24",   # TEST-NET-3
        "224.0.0.0/4",      # multicast
        "233.252.0.0/24",   # MCAST-TEST-NET
        "240.0.0.0/4",      # reserved
        "255.255.255.255/32",
        # v6 equivalents (beyond the reference, which is v4-only)
        "::1/128",
        "fc00::/7",
        "fe80::/10",
    )
]


def is_local_ip(ip: str) -> bool:
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return False
    return any(addr in net for net in _PRIVATE_NETS)


class IpFilter:
    """Reference ip_manager.py semantics, hot-reloaded: a NON-EMPTY
    whitelist is exclusive (only listed IPs pass; the blocklist is then
    irrelevant — ip_manager.py:42-44's ``ip in whitelist or (ip not in
    blocklist and not whitelist)``); with no whitelist, the blocklist
    denies; endpoint blocks apply to every caller, whitelisted or not
    (main.py:306 checks them after the IP gate with no bypass)."""

    def __init__(self, path: str = "ip_config.json",
                 reload_every: float = 300.0):
        self.path = path
        self.reload_every = reload_every
        self._loaded_at = 0.0
        self.whitelist: set = set()
        self.blocklist: set = set()
        self.block_endpoints: set = set()
        self._maybe_reload(force=True)

    def _maybe_reload(self, force: bool = False) -> None:
        now = time.time()
        if not force and now - self._loaded_at < self.reload_every:
            return
        self._loaded_at = now
        if not self.path or not os.path.exists(self.path):
            return
        try:
            # RC001: tiny admin JSON, re-read at most once per
            # reload_every seconds — not worth an executor hop in the
            # middleware hot path
            with open(self.path) as f:  # upowlint: disable=RC001
                data = json.load(f)
            self.whitelist = set(data.get("whitelist", []))
            self.blocklist = set(data.get("blocklist", []))
            # normalize: config entries may be written with or without a
            # leading slash; matching strips both sides
            self.block_endpoints = {
                str(e).strip("/") for e in data.get("block_endpoints", [])
            }
        except (json.JSONDecodeError, OSError):
            pass

    def allowed(self, ip: str, endpoint: Optional[str] = None) -> bool:
        self._maybe_reload()
        if self.whitelist:
            if ip not in self.whitelist:
                return False
        elif ip in self.blocklist:
            return False
        if endpoint is not None and endpoint.strip("/") in self.block_endpoints:
            return False
        return True
