"""The node: HTTP API + P2P gossip + chain sync (reference upow/node/main.py).

aiohttp implementation of the full 20-endpoint surface, the gossip
``propagate`` fan-out, the Sender-Node peer-learning middleware, tx intake
with a 100-entry dedup cache, push_block with sync-on-gap triggers, and
``sync_blockchain`` with the 500-block reorg window — all against one
:class:`~upow_tpu.state.storage.ChainState` + :class:`BlockManager`.

Request/response wire shapes match the reference endpoint-for-endpoint
(main.py:461-1102): every handler returns the ``{"ok": bool, ...}``
envelope, accepts both GET query params and POST JSON bodies where the
reference does, and reads/sets the ``Sender-Node`` header.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import random
import re
import sys
import time
from collections import deque
from decimal import Decimal
from typing import List, Optional

from aiohttp import web

from .. import telemetry, trace
from ..config import Config
from ..core.constants import (ENDIAN, MAX_BLOCK_SIZE_HEX, MAX_SUPPLY,
                              SMALLEST, VERSION)
from ..core.clock import timestamp
from ..core.rewards import get_circulating_supply
from ..core.header import block_to_bytes, split_block_content
from ..core.merkle import merkle_root
from ..core.tx import AmbiguousSignatureError, CoinbaseTx, Tx, tx_from_hex
from ..logger import get_logger, setup_logging
from ..mempool import IntakeCoordinator, Mempool, MiningInfoCache, TTLSet
from ..resilience import (BreakerRegistry, ResilienceContext, faultinject)
from ..state.storage import ChainState
from ..verify.block import BlockManager
from ..verify.txverify import TxVerifier, run_sig_checks_async
from .ipfilter import IpFilter, is_local_ip
from .peers import NodeInterface, PeerBook, _normalize

log = get_logger("node")

GENESIS_PREV_HASH = (18_884_643).to_bytes(32, ENDIAN).hex()


class _BadParam(Exception):
    """Malformed query parameter — answered as a 422 validation error
    (the reference's FastAPI layer rejects type mismatches the same
    way; a raw int() here used to 500)."""


def _int_q(q, name: str, default: int, cap: int = None) -> int:
    raw = q.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        raise _BadParam(name) from None
    # clamp into [0, int64 max]: a 10^40 offset would overflow the
    # sqlite INTEGER binding into a 500, and NEGATIVE values are worse
    # than an error — sqlite treats LIMIT -1 as "no limit" (an
    # unbounded table dump) and postgres rejects it mid-handler
    value = max(0, min(value, 2 ** 63 - 1))
    return min(value, cap) if cap is not None else value

# the one banned address (main.py:426-430)
_BANNED_ADDRESSES = {"DgQKikeDqS2Fzue23KuA36L4eJSFh649zA9jJ6zwbzUMp"}

# any value in this header skips the hot-state cache for one request
# (the response is computed fresh and NOT stored) — the loadgen
# differential and operators diagnosing a suspected stale read use it;
# correctness never depends on it because entries are generation-keyed
_CACHE_BYPASS_HEADER = "X-Upow-Cache-Bypass"

# every get_address_info query flag that shapes the response — the
# cache key must carry all of them (a hit is only valid for the exact
# flag combination it was computed under)
_ADDRESS_INFO_FLAGS = (
    "show_pending", "verify", "stake_outputs", "delegate_spent_votes",
    "delegate_unspent_votes", "inode_registration_outputs",
    "validator_unspent_votes", "validator_spent_votes", "address_state")


def _fmt_amount(smallest_units: int) -> str:
    return "{:f}".format(Decimal(smallest_units) / SMALLEST)


class Node:
    """One node instance: state + manager + peers + HTTP app.

    In-process instantiable (the multi-node integration harness runs
    several against isolated sqlite files and wires their HTTP apps
    together via aiohttp's test utilities).
    """

    def __init__(self, config: Optional[Config] = None, state=None):
        self.config = config or Config()
        setup_logging(self.config.log)
        telemetry.configure(self.config.telemetry)
        # Instance-scoped registries (swarm fleets): every request this
        # node handles — and every task spawned underneath, contextvars
        # travel with ensure_future — reports into this node's private
        # metrics/events/traces instead of the process globals.  Default
        # (single-node) keeps the globals: scope stays None.
        self.telemetry_scope = None
        if self.config.telemetry.instance_scope:
            self.telemetry_scope = telemetry.TelemetryScope.from_config(
                self.config.telemetry)
        self.config.device.apply_kernel_overrides()
        if state is not None:
            # injected backend (tests: the pg backend over the mock
            # driver; a live server would come through config instead)
            self.state = state
        elif self.config.node.db_backend == "postgres":
            # reference-ecosystem interop: run against an existing uPow
            # PostgreSQL database (schema.sql) via asyncpg
            from ..state.pg import PgChainState

            self.state = PgChainState(
                self.config.node.pg_dsn,
                # reference default sidecar filename (pickledb)
                emission_path="emission_details.json")
            self.state.ensure_schema()
            if self.config.device.utxo_index:
                self.state.enable_device_index()
        elif self.config.node.db_backend == "sqlite":
            self.state = ChainState(
                self.config.node.db_path or None,
                device_index=self.config.device.utxo_index)
        else:
            raise ValueError(
                f"node.db_backend must be 'sqlite' or 'postgres', not"
                f" {self.config.node.db_backend!r}")
        self.manager = BlockManager(
            self.state, sig_backend=self.config.device.sig_backend,
            verify_pad_block=self.config.device.verify_pad_block,
            verify_device_timeout=self.config.device.verify_device_timeout,
            verify_mesh_devices=self.config.device.mesh_devices,
            verify_microbatch=self.config.device.verify_microbatch,
            txid_backend=self.config.device.txid_backend,
            txid_min_batch=self.config.device.txid_min_batch)
        rcfg = self.config.resilience
        self.breakers = BreakerRegistry(
            failure_threshold=rcfg.breaker_failure_threshold,
            open_secs=rcfg.breaker_open_secs,
            half_open_max=rcfg.breaker_half_open_max)
        if rcfg.faults:
            faultinject.install(rcfg.faults, rcfg.faults_seed)
        self.resilience = ResilienceContext.from_config(
            rcfg, breakers=self.breakers)
        # device degradation knobs land on the process-wide manager the
        # verify dispatch consults (verify/txverify.py)
        from ..verify.txverify import DEGRADE

        DEGRADE.configure(rcfg.device_failure_limit, rcfg.device_cooldown)
        self.peers = PeerBook(self.config.node, breakers=self.breakers)
        self.ip_filter = IpFilter(self.config.node.ip_config_file)
        from .ratelimit import RateLimiter

        self.rate_limiter = RateLimiter(
            enabled=self.config.node.rate_limits_enabled)
        self.is_syncing = False
        self.started = False
        self.self_url = self.config.node.self_url
        # micro-batched mempool subsystem (docs/MEMPOOL.md): in-memory
        # fee-priority pool is the read authority, the SQL
        # pending_transactions table is demoted to write-behind journal
        mcfg = self.config.mempool
        self.pool = Mempool(max_bytes_hex=mcfg.max_pool_bytes_hex,
                            tx_ttl=mcfg.tx_ttl, allow_rbf=mcfg.allow_rbf)
        if mcfg.enabled:
            # block acceptance / mempool GC drop mined and doomed txs
            # from the pool directly — templates stop serving a mined
            # tx the moment its block commits, with the stamp-driven
            # sync() kept as the reconciliation backstop
            self.manager.on_pending_removed = self.pool.remove
        self.intake = IntakeCoordinator(self, _BANNED_ADDRESSES)
        self.mining_cache = MiningInfoCache()
        self.state.reinject_reorg_txs = bool(mcfg.enabled
                                             and mcfg.reinject_on_reorg)
        # generation-anchored hot-state read cache (state/hotcache.py,
        # docs/CACHING.md): read endpoints serve stored response BYTES
        # keyed by a generation the hooks below advance after every
        # committed write, so a hit never reflects a stale tip
        from ..state.hotcache import HotStateCache

        self.hotcache = HotStateCache(self.state, self.config.cache)
        if self.config.cache.enabled:
            self.manager.on_state_committed = self.hotcache.bump
            self.state.on_blocks_removed = \
                lambda _from_id: self.hotcache.bump("reorg")
            # chain the mempool hook: GC evictions and mined-tx removals
            # change the pending journal, which read responses (pending
            # tx lists, show_pending balances) depend on
            pool_remove = self.manager.on_pending_removed

            def _pending_removed(hashes, _base=pool_remove):
                if _base is not None:
                    _base(hashes)
                self.hotcache.bump("pending_removed")

            self.manager.on_pending_removed = _pending_removed
        # push_tx dedup: config-sized TTL set — the reference's 100-entry
        # deque cycles out in milliseconds at target intake rates,
        # reopening the duplicate-propagation window it exists to close
        self.tx_cache = (TTLSet(mcfg.tx_cache_size, mcfg.tx_cache_ttl)
                         if mcfg.enabled else deque(maxlen=100))
        self._last_mempool_clean: Optional[float] = None  # monotonic
        self._closing = False
        self._background: set = set()
        self._services: set = set()  # perpetual loops (watchtower)
        self._http_session = None  # shared gossip/RPC session, lazy
        self.ws_hub = None  # set by ws.attach(...) when enabled
        # Outbound RPC client seam: everything that talks to a peer
        # builds its client through this factory (signature-compatible
        # with NodeInterface).  The swarm harness swaps in a loopback
        # implementation that routes through the in-memory LinkMatrix,
        # so peer logic — breakers, retries, trace headers — runs
        # unmodified over a simulated network (upow_tpu/swarm/).
        self.iface_factory = NodeInterface
        # snapshot bootstrap progress (upow_tpu/snapshot/client.py
        # mutates it in place; /metrics exports it) + startup
        # housekeeping: bound on-disk generations and sweep staging
        # dirs a crashed builder left behind (never raises)
        self.snapshot_restore: dict = {}
        if self.config.snapshot.dir:
            from ..snapshot import layout as snapshot_layout

            snapshot_layout.prune_generations(self.config.snapshot.dir,
                                              keep=self.config.snapshot.keep)
        # cold-block archival tier (upow_tpu/archive/, docs/ARCHIVE.md):
        # attach the read-fallthrough seam to the storage backend.
        # Archived rows are immutable, so the hotcache generation is
        # untouched — cached responses are byte-identical either way.
        self.archive_compact: dict = {}
        if self.config.archive.dir:
            from ..archive import ArchiveReader

            self.state.archive = ArchiveReader(
                self.config.archive.dir,
                cache_segments=self.config.archive.reader_cache_segments)
        # background snapshot rebuild cadence (SnapshotConfig.
        # rebuild_interval_blocks): every committed block ticks a
        # counter; at interval + per-node jitter a rebuild (and the
        # archive compaction it arms) is spawned off the hook.  The
        # jitter is a deterministic hash of the node's identity so a
        # fleet started together doesn't rebuild in lockstep.
        self._snapshot_rebuild_inflight = False
        self._blocks_since_rebuild = 0
        scfg = self.config.snapshot
        if scfg.dir and scfg.rebuild_interval_blocks > 0:
            ident = (self.config.node.self_url
                     or f"{self.config.node.host}:{self.config.node.port}")
            jitter = max(0, scfg.rebuild_jitter_blocks)
            self._rebuild_target = scfg.rebuild_interval_blocks + (
                int.from_bytes(
                    hashlib.sha256(ident.encode()).digest()[:4], "big")
                % (jitter + 1))
            base_committed = self.manager.on_state_committed

            def _committed(_base=base_committed):
                if _base is not None:
                    _base()
                self._snapshot_rebuild_tick()

            self.manager.on_state_committed = _committed
        # Watchtower (docs/ALERTING.md): streaming anomaly detection +
        # SLO burn-rate alerting over this node's telemetry registries.
        # The engine holds direct registry references (scope or process
        # globals), so swarm nodes alert strictly independently; live
        # gauges the registries don't store come in through probes.
        self.watchtower = None
        if self.config.watchtower.enabled:
            from ..watchtower import WatchtowerEngine

            self.watchtower = WatchtowerEngine(
                self.config.watchtower, scope=self.telemetry_scope,
                name=(self.telemetry_scope.name
                      if self.telemetry_scope else "node"))
            self._register_watchtower_probes()
        self.app = self._build_app()

    def _register_watchtower_probes(self) -> None:
        wt = self.watchtower

        async def block_height() -> float:
            return float(await self.state.get_next_block_id() - 1)

        async def sync_lag() -> float:
            last = await self.state.get_last_block()
            return float(max(0, timestamp() - last["timestamp"])) \
                if last else 0.0

        wt.register_probe("block_height", block_height)
        wt.register_probe("sync_lag", sync_lag)
        if self.config.mempool.enabled:
            wt.register_probe("mempool_depth",
                              lambda: float(len(self.pool)))

        def ws_dropped() -> float:
            # ws_hub attaches later in _build_app; resolve per call
            hub = self.ws_hub
            return float(hub.get_stats()["dropped_messages"]) if hub else 0.0

        wt.register_probe("ws_dropped", ws_dropped)

    # ----------------------------------------------------------- plumbing --
    def _spawn(self, coro) -> None:
        """Fire-and-forget background task (FastAPI BackgroundTasks role).
        Refused once close() has begun — a request draining through the
        server during shutdown must not start work against a database
        that is about to be (or already is) closed."""
        if self._closing:
            coro.close()
            return
        task = asyncio.ensure_future(coro)
        self._background.add(task)
        task.add_done_callback(self._background.discard)

    def _spawn_service(self, coro) -> None:
        """Long-lived service loop (watchtower cadence).  Tracked apart
        from ``_background``: drain-style waiters (Swarm.settle) gather
        the background set and a loop that never returns would deadlock
        them.  Services only end via cancellation in close()."""
        if self._closing:
            coro.close()
            return
        task = asyncio.ensure_future(coro)
        self._services.add(task)
        task.add_done_callback(self._services.discard)

    async def close(self) -> None:
        self._closing = True
        # cancel AND await: a cancelled task only unwinds at its next
        # suspension point — closing the db before it does would hand a
        # still-running task a closed connection.  Bounded: a task stuck
        # inside run_in_executor (device verify) cannot be cancelled
        # until the executor call returns, and shutdown must not wait
        # out a 240 s device timeout.
        closing = list(self._background) + list(self._services)
        for task in closing:
            task.cancel()
        done, stragglers = set(), set()
        if closing:
            done, stragglers = await asyncio.wait(closing, timeout=5.0)
            for task in stragglers:
                log.warning("background task still running at close: %r",
                            task)
        if self._http_session is not None and not self._http_session.closed:
            await self._http_session.close()
        self.state.close()
        # A straggler that resumes after state.close() (e.g. a sync that
        # was blocked in the executor on a device verify) will hit
        # "Cannot operate on a closed database"; retrieve its exception
        # quietly instead of letting asyncio log it as never-retrieved.
        # `done` members may also have errored while unwinding their
        # cancellation (asyncio.wait never retrieves) — cover both.
        for task in done | stragglers:
            task.add_done_callback(
                lambda t: t.cancelled() or t.exception())

    def _session(self):
        """Shared aiohttp session for all outbound RPC (one connection
        pool per process, not one per gossip target per message)."""
        import aiohttp

        if self._http_session is None or self._http_session.closed:
            self._http_session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=self.config.node.http_timeout))
        return self._http_session

    @staticmethod
    def _peer_ip(request: web.Request) -> str:
        peername = request.transport.get_extra_info("peername") if request.transport else None
        return peername[0] if peername else ""

    def _client_ip(self, request: web.Request) -> str:
        """Proxy headers are only trusted behind a proxy (config flag):
        the reference always honours X-Forwarded-For (main.py:375-390)
        because it assumes the NGINX.md deployment, which lets any direct
        client spoof its way past the IP filter."""
        if self.config.node.trust_proxy_headers:
            xff = request.headers.get("x-forwarded-for", "")
            if xff:
                # rightmost entry: the one OUR proxy appended.  Leftmost
                # is client-supplied under the standard append-style
                # proxy config and would let anyone spoof 127.0.0.1.
                return xff.split(",")[-1].strip()
            real = request.headers.get("x-real-ip")
            if real:
                return real
        return self._peer_ip(request)

    async def _params(self, request: web.Request) -> dict:
        """Merge query params with a JSON body (reference Body(False))."""
        params = dict(request.rel_url.query)
        if request.method == "POST" and request.can_read_body:
            try:
                body = await request.json()
                if isinstance(body, dict):
                    params.update(body)
            except (json.JSONDecodeError, UnicodeDecodeError):
                pass
        return params

    # ----------------------------------------------------------- gossip ---
    async def propagate(self, path: str, args: dict,
                        ignore_url: Optional[str] = None,
                        nodes: Optional[List[str]] = None) -> None:
        """Fan-out to the propagate set (main.py:79-94).

        Each peer send is bounded by ``propagate_deadline`` so one hung
        peer cannot stall gossip to everyone else; the fan-out itself
        stays fully concurrent and a timed-out send only marks THAT
        peer's breaker (via the RPC wrapper) and a counter."""
        self_base = _normalize(self.self_url)
        ignore_base = _normalize(ignore_url or "")
        deadline = self.config.resilience.propagate_deadline
        aws = []
        session = self._session()
        for node_url in nodes if nodes is not None else self.peers.propagate_nodes():
            iface = self.iface_factory(node_url, self.config.node,
                                       session=session,
                                       resilience=self.resilience)
            if iface.base_url in (self_base, ignore_base):
                continue
            aws.append(self._propagate_one(iface, path, args, self_base,
                                           deadline))
        await asyncio.gather(*aws)

    async def _propagate_one(self, iface: NodeInterface, path: str,
                             args: dict, self_base: str,
                             deadline: float) -> None:
        try:
            await asyncio.wait_for(iface.request(path, args, self_base),
                                   deadline or None)
        except asyncio.TimeoutError:
            trace.inc("resilience.propagate_timeouts")
            # the wrapper's breaker bookkeeping never ran (cancelled
            # mid-attempt) — a hang is the strongest failure signal
            self.breakers.record_failure(iface.base_url)
            log.debug("propagate to %s timed out after %.1fs",
                      iface.base_url, deadline)
        except Exception as e:
            log.debug("propagate error: %s", e)

    async def _propagate_old_transactions(self) -> None:
        txs = await self.state.get_need_propagate_transactions()
        for tx_hex in txs:
            tx_hash = hashlib.sha256(bytes.fromhex(tx_hex)).hexdigest()
            await self.state.update_pending_transaction_propagation(tx_hash)
            await self.propagate("push_tx", {"tx_hex": tx_hex})

    # -------------------------------------------------------- middleware --
    @web.middleware
    async def _middleware(self, request: web.Request, handler):
        # bind this node's telemetry scope around the WHOLE request —
        # including /metrics and /debug reads, so each node serves its
        # own registries even with 50 nodes in one process
        if self.telemetry_scope is not None:
            with self.telemetry_scope.activate():
                return await self._middleware_inner(request, handler)
        return await self._middleware_inner(request, handler)

    async def _middleware_inner(self, request: web.Request, handler):
        client_ip = self._client_ip(request)
        if not self.ip_filter.allowed(client_ip):
            return web.json_response(
                {"ok": False, "error": "Access forbidden."}, status=403)
        normalized = re.sub("/+", "/", request.path) or "/"
        if normalized != request.path:
            query = request.rel_url.query_string
            raise web.HTTPFound(normalized + ("?" + query if query else ""))
        if normalized != "/" and not self.ip_filter.allowed(
                client_ip, endpoint=normalized):
            return web.json_response(
                {"ok": False, "error": "Access forbidden temporarily."},
                status=403)
        if not self.rate_limiter.allow(client_ip, normalized):
            return web.json_response(
                {"ok": False, "error": "Rate limit exceeded"}, status=429)

        sender = request.headers.get("Sender-Node")
        if sender:
            self.peers.add(sender)

        host = request.host.split(":")[0] if request.host else ""
        # Hardening divergence: the reference gates this custodial endpoint
        # on the attacker-controlled Host header (main.py:315-322, safe
        # only behind the NGINX.md proxy).  Gate on the client IP — the
        # socket peer, or the proxy-reported address when
        # trust_proxy_headers says the proxy is trusted (otherwise a
        # proxied deployment would see every client as 127.0.0.1).
        if normalized == "/send_to_address" and not (
                client_ip and is_local_ip(client_ip)):
            return web.json_response(
                {"ok": False, "error": "Access forbidden. This endpoint can "
                 "only be accessed from localhost."}, status=403)

        # first-request bootstrap: learn peers-of-peers, discover self URL,
        # announce ourselves (main.py:324-361)
        if normalized != "/get_nodes" and not self.started and \
                not (is_local_ip(host) or host == "localhost"):
            self.started = True
            if not self.self_url:
                self.self_url = f"{request.scheme}://{request.host}"
            self._spawn(self._bootstrap())

        # request-scoped trace root: inbound gossip hops adopt the
        # peer's X-Upow-Trace ID so one push_tx/push_block is one trace
        # across nodes; scrape/debug/ws endpoints stay untraced (they
        # would drown the recency ring)
        traced = self.config.telemetry.trace_requests and not (
            normalized in ("/metrics", "/ws")
            or normalized.startswith("/debug"))
        # SLO latency capture: only registered routes (the fixed set
        # built in _build_app) get a series — deriving names from raw
        # paths would let a scanner consume the metric cardinality cap
        slo_t0 = time.perf_counter() if normalized in self._slo_paths \
            else None
        trace_id = None
        try:
            if traced:
                with telemetry.request_trace(
                        "http." + (normalized.strip("/") or "root"),
                        trace_id=request.headers.get(telemetry.TRACE_HEADER),
                        ) as troot:
                    trace_id = troot.trace_id
                    response = await handler(request)
            else:
                response = await handler(request)
        except web.HTTPException:
            raise
        except _BadParam as e:
            if slo_t0 is not None:
                telemetry.slo.observe_request(
                    normalized, time.perf_counter() - slo_t0, 422,
                    trace_id=trace_id)
            return web.json_response(
                {"ok": False, "error": f"Invalid integer parameter {e}"},
                status=422)
        except Exception as e:  # exception envelope (main.py:394-406)
            log.error("Error on %s, %s: %s", request.path, type(e).__name__,
                      e, exc_info=True)
            if slo_t0 is not None:
                telemetry.slo.observe_request(
                    normalized, time.perf_counter() - slo_t0, 500,
                    trace_id=trace_id)
            return web.json_response(
                {"ok": False, "error": f"Uncaught {type(e).__name__} exception"},
                status=500)
        if slo_t0 is not None:
            telemetry.slo.observe_request(
                normalized, time.perf_counter() - slo_t0, response.status,
                trace_id=trace_id)
        response.headers["Access-Control-Allow-Origin"] = "*"
        if trace_id is not None:
            response.headers[telemetry.TRACE_HEADER] = trace_id
        self._spawn(self._propagate_old_transactions())
        return response

    async def _bootstrap(self) -> None:
        try:
            seeds = self.peers.recent_nodes()
            if not seeds:
                return
            iface = self.iface_factory(seeds[0], self.config.node,
                                       session=self._session(),
                                       resilience=self.resilience)
            for url in await iface.get_nodes():
                self.peers.add(url)
            self.peers.remove(self.self_url)
            await self.propagate("add_node", {"url": self.self_url})
            # catch up immediately after (re)start instead of waiting for
            # a push_block to reveal the gap (the reference only syncs on
            # gap detection, main.py:551-579 — a restarted node there
            # serves a stale chain until the next block arrives)
            self._spawn(self.sync_blockchain())
        except Exception as e:
            log.debug("bootstrap failed: %s", e)

    # ------------------------------------------------------- tx intake ----
    def make_tx_verifier(self) -> TxVerifier:
        """One verifier wired with this node's device knobs (shared by
        the serial path and the batched intake)."""
        return TxVerifier(
            self.state,
            verify_pad_block=self.config.device.verify_pad_block,
            verify_device_timeout=self.config.device.verify_device_timeout,
            verify_mesh_devices=self.config.device.mesh_devices)

    async def accept_tx_effects(self, tx: Tx, tx_hash: str,
                                first_address: Optional[str],
                                sender: Optional[str]) -> None:
        """Post-acceptance side effects, shared by the serial path and
        the batched intake: peer bookkeeping, gossip fan-out, WS
        broadcast, dedup cache, log line."""
        if sender:
            self.peers.update_last_message(sender)
        # first-seen stamp for the fleet propagation tracker: one event
        # per node per accepted tx (duplicates are rejected upstream)
        telemetry.event("tx_seen", hash=tx_hash)
        self._spawn(self.propagate("push_tx", {"tx_hex": tx.hex()}))
        if self.ws_hub is not None:
            amount = sum(o.amount for o in tx.outputs)
            self._spawn(self.ws_hub.broadcast_new_transaction({
                "tx_hash": tx_hash,
                "from": first_address,
                "to": [o.address for o in tx.outputs],
                "amount": _fmt_amount(amount),
                "fees": _fmt_amount(await self.state.tx_fees(tx)),
            }))
        self.tx_cache.append(tx_hash)
        log.info("Transaction has been accepted: %s", tx_hash)

    async def _submit_tx(self, tx: Tx, sender: Optional[str]) -> dict:
        """Route one tx into admission: the coalescing intake when the
        mempool subsystem is on (this request joins the current
        micro-batch and shares its signature dispatch), else the serial
        reference path."""
        if self.config.mempool.enabled:
            result = await self.intake.submit(tx, sender)
        else:
            result = await self._verify_and_push_tx(tx, sender)
        if result.get("ok"):
            # the pending journal gained a row — cached pending-tx
            # lists and show_pending address views are now stale
            self.hotcache.bump("pending_added")
        return result

    async def _verify_and_push_tx(self, tx: Tx,
                                  sender: Optional[str]) -> dict:
        # a coinbase is only ever built by block acceptance — a pushed one
        # would pass every input-based check vacuously, poison the mempool
        # (no inputs -> GC never clears it) and break every mined block
        # (reference database.py:93-96 rejects it explicitly); unsigned
        # inputs would crash serialization below instead of rejecting
        if getattr(tx, "is_coinbase", False) or any(
                i.signature is None for i in tx.inputs):
            return {"ok": False, "error": "Transaction has not been added"}
        tx_hash = tx.hash()
        if tx_hash in self.tx_cache:
            return {"ok": False, "error": "Transaction just added"}
        first_address = None
        if tx.inputs:
            first_address = await self.state.resolve_output_address(
                tx.inputs[0].tx_hash, tx.inputs[0].index)
        if first_address in _BANNED_ADDRESSES:
            return {"ok": False, "error": "Access forbidden temporarily."}
        if await self.state.pending_transaction_exists(tx_hash):
            return {"ok": False, "error": "Transaction already present"}
        # full verification BEFORE the mempool (the reference's
        # add_pending_transaction(verify=True) → Transaction.verify_pending,
        # database.py:93-111): rules + signatures + pending double spend.
        # Without this, any parseable garbage enters the mempool and gets
        # handed to miners, whose blocks then fail acceptance.
        try:
            with telemetry.span("push_tx.verify"):
                ok = await self.make_tx_verifier().verify_pending(
                    tx, sig_backend=self.config.device.sig_backend)
        except Exception as e:
            log.info("tx verify error %s: %s", tx_hash, e)
            ok = False
        if not ok:
            return {"ok": False, "error": "Transaction has not been added"}
        try:
            with telemetry.span("push_tx.journal_write"):
                await self.state.add_pending_transaction(tx)
        except Exception as e:
            log.info("tx rejected %s: %s", tx_hash, e)
            return {"ok": False, "error": "Transaction has not been added"}
        with telemetry.span("push_tx.effects"):
            await self.accept_tx_effects(tx, tx_hash, first_address, sender)
        return {"ok": True, "result": "Transaction has been accepted",
                "tx_hash": tx_hash}

    # ------------------------------------------------------- mining info --
    async def _mining_info_result(self) -> dict:
        self.manager.invalidate_difficulty()
        difficulty, last_block = await self.manager.get_difficulty()
        # mempool-GC timer on the MONOTONIC clock: the consensus
        # timestamp() the reference keys this off tracks the wall
        # clock, so an NTP step either fires a clear per poll or
        # suppresses clears entirely
        now = time.monotonic()
        if (self._last_mempool_clean is None
                or now - self._last_mempool_clean
                > self.config.node.mempool_clean_interval):
            self._last_mempool_clean = now
            self._spawn(self.manager.clear_pending_transactions())
        last_json = _json_block(last_block)
        key = None
        if self.config.mempool.enabled:
            await self.pool.sync(self.state)
            key = (self.pool.generation, (last_json or {}).get("hash"),
                   float(difficulty))
            cached = self.mining_cache.get(key)
            if cached is not None:
                return cached
            # the pool slice IS the reference query (pool.py docstring);
            # no SQL on the miner polling hot path
            pending = sorted(self.pool.select_hex(MAX_BLOCK_SIZE_HEX))
        else:
            pending = sorted(await self.state.get_pending_transactions_limit(
                hex_only=True))
        result = {
            "difficulty": float(difficulty),
            "last_block": last_json,
            "pending_transactions": pending[:10],
            "pending_transactions_hashes": [
                hashlib.sha256(bytes.fromhex(t)).hexdigest() for t in pending],
            "merkle_root": merkle_root(
                [tx_from_hex(t, check_signatures=False) for t in pending[:10]]),
        }
        if key is not None:
            self.mining_cache.put(key, result)
        return result

    # ------------------------------------------------------- read cache ---
    async def _cached(self, request: web.Request, entry_class: str,
                      key: tuple, build, dumps=json.dumps) -> web.Response:
        """Serve a read endpoint through the hot-state cache.

        ``build()`` produces the JSON-clean payload; what gets cached is
        the ENCODED body (``dumps(payload).encode("utf-8")`` — exactly
        the bytes ``web.json_response`` would have sent), so a hit skips
        both SQL and encoding and is byte-identical to the uncached
        response by construction.  Disabled cache or a
        ``X-Upow-Cache-Bypass`` header fall through to the plain path
        without touching the store."""
        if not self.hotcache.enabled or \
                _CACHE_BYPASS_HEADER in request.headers:
            return web.json_response(await build(), dumps=dumps)

        async def produce() -> bytes:
            return dumps(await build()).encode("utf-8")

        body = await self.hotcache.get_bytes(entry_class, key, produce)
        return web.Response(body=body, content_type="application/json",
                            charset="utf-8")

    # --------------------------------------------------------- handlers ---
    async def h_root(self, request: web.Request) -> web.Response:
        """Health probe (reference main.py:266-275) + additive timing
        stats from the span registry (trace.py) — same shape the
        reference's required keys take, extra key ignored by peers."""
        fingerprint = await self.state.get_unspent_outputs_hash()
        return web.json_response({
            "ok": True, "version": VERSION,
            "unspent_outputs_hash": fingerprint,
            "timings": trace.stats(),
            "counters": trace.counters(),
        })

    async def h_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition — beyond-reference observability
        (SURVEY §5 notes the reference has "No Prometheus/StatsD").
        Gauges for chain/mempool/peer/WS state plus the span registry as
        per-section count/total/max series, resilience event counters
        (``upow_<name>_total``), per-state breaker counts, kernel
        occupancy/compile telemetry, and the device-verify health gauge.
        Rendering and name sanitization live in telemetry/exposition.py;
        the format is pinned by tests/test_telemetry.py's validator."""
        from ..compile_cache import entry_count
        from ..verify.txverify import sig_verdict_stats

        e = telemetry.exposition.Exposition()
        e.gauge("block_height", await self.state.get_next_block_id() - 1,
                "Height of the last accepted block")
        e.gauge("mempool_transactions",
                await self.state.get_pending_transactions_count(),
                "Transactions waiting in the mempool")
        last_block = await self.state.get_last_block()
        lag = max(0, timestamp() - last_block["timestamp"]) \
            if last_block else 0
        e.gauge("sync_lag_seconds", lag,
                "Seconds since the tip block's consensus timestamp")
        if self.config.mempool.enabled:
            e.gauge("mempool_pool_depth", len(self.pool),
                    "Transactions in the in-memory fee-priority pool")
            e.gauge("mempool_pool_bytes_hex", self.pool.total_bytes_hex,
                    "Total hex chars held by the in-memory pool")
            e.counter("mining_info_cache_hits", self.mining_cache.hits,
                      "Mining-info requests served from the"
                      " generation-keyed cache")
            e.counter("mining_info_cache_misses", self.mining_cache.misses,
                      "Mining-info requests that rebuilt the template")
        e.gauge("peers_known", len(self.peers.all_nodes()),
                "Peers in the peer book")
        e.gauge("peers_active", len(self.peers.recent_nodes()),
                "Peers messaged within the activity window")
        e.gauge("node_syncing", int(bool(self.is_syncing)),
                "1 while a chain sync is in progress")
        snapshot_gen = self._snapshot_gen()
        if snapshot_gen is not None:
            m = snapshot_gen[1]
            e.gauge("snapshot_published_height", m["anchor_height"],
                    "Anchor height of the published snapshot generation")
            e.gauge("snapshot_published_chunks", len(m["chunks"]),
                    "Chunks in the published snapshot generation")
            e.gauge("snapshot_published_bytes", m["payload_bytes"],
                    "Payload bytes of the published snapshot generation")
        if self.snapshot_restore:
            sr = self.snapshot_restore
            e.gauge("snapshot_restore_chunks_total",
                    sr.get("total", 0),
                    "Chunks the in-progress/last snapshot restore needs")
            e.gauge("snapshot_restore_chunks_verified",
                    sr.get("verified", 0),
                    "Chunks verified by the current restore pass")
            e.gauge("snapshot_restore_chunks_reused",
                    sr.get("reused", 0),
                    "Verified chunks reused from the journal (not"
                    " re-downloaded) by the current restore pass")
        # archive families are emitted unconditionally (zeros when the
        # tier is disabled) so make metrics-check can pin their names
        ast = self.state.archive.stats() if self.state.archive else {}
        e.gauge("archive_segments", ast.get("segments", 0),
                "Published cold-archive segments")
        e.gauge("archive_archived_blocks", ast.get("archived_blocks", 0),
                "Blocks held by the published archive manifest")
        e.gauge("archive_archived_txs", ast.get("archived_txs", 0),
                "Transactions held by the published archive manifest")
        e.counter("archive_hot_rows_pruned",
                  (self.archive_compact.get("pruned_blocks", 0)
                   + self.archive_compact.get("pruned_txs", 0))
                  if self.archive_compact.get("ok") else 0,
                  "Hot block+tx rows deleted by the last compaction")
        e.counter("archive_fallthrough_reads",
                  ast.get("fallthrough_reads", 0),
                  "Reads served from archive segments after a hot miss")
        sig = sig_verdict_stats()
        e.gauge("sig_cache_entries", sig["size"],
                "Entries in the signature-verdict cache")
        e.counter("sig_cache_hits", sig["hits"],
                  "Signature checks answered from the verdict cache")
        e.counter("sig_cache_misses", sig["misses"],
                  "Signature checks that required verification")
        if self.hotcache.enabled:
            cs = self.hotcache.stats()
            e.counter("hotcache_hits", cs["hits"],
                      "Read responses served from the hot-state cache")
            e.counter("hotcache_misses", cs["misses"],
                      "Read responses rebuilt from storage")
            e.counter("hotcache_evictions", cs["evictions"],
                      "Entries evicted by per-class LRU byte caps")
            e.counter("hotcache_singleflight_coalesced",
                      cs["singleflight_coalesced"],
                      "Concurrent identical misses that shared one"
                      " storage trip")
            e.counter("hotcache_generation_bumps", cs["bumps"],
                      "Local generation advances (block accept, reorg,"
                      " pending-journal change)")
            e.counter("hotcache_foreign_bumps", cs["foreign_bumps"],
                      "Generation advances forced by another worker's"
                      " write (journal-stamp revalidation)")
            e.gauge("hotcache_generation", cs["generation"],
                    "Current read-cache generation epoch")
            e.gauge("hotcache_generation_age_seconds",
                    cs["generation_age_seconds"],
                    "Seconds since the generation last advanced")
            e.gauge("hotcache_entries",
                    sum(c["entries"] for c in cs["classes"].values()),
                    "Entries across all hot-state cache classes")
            e.gauge("hotcache_bytes",
                    sum(c["bytes"] for c in cs["classes"].values()),
                    "Encoded response bytes held by the hot-state cache")
        if self.ws_hub is not None:
            ws = self.ws_hub.get_stats()
            e.gauge("ws_connections", ws["total_connections"],
                    "Open WebSocket push connections")
            e.gauge("ws_messages_out", ws["messages_out"],
                    "WebSocket messages delivered")
            e.counter("ws_connects_total", ws["connects_total"],
                      "WebSocket connections accepted since start")
            e.counter("ws_disconnects_total", ws["disconnects_total"],
                      "WebSocket connections dropped since start")
            e.counter("ws_dropped_messages", ws["dropped_messages"],
                      "Broadcast messages shed by per-subscriber bounded"
                      " send queues (drop-slowest policy)")
            e.gauge("ws_send_queue_hwm", ws["send_queue_hwm"],
                    "Deepest any subscriber send queue has ever been"
                    " (high-watermark, including reaped connections)")
        for state_name, count in sorted(self.breakers.state_counts().items()):
            e.gauge(f"breaker_{state_name}_peers", count,
                    f"Peers whose circuit breaker is {state_name}")
        e.gauge("device_verify_health",
                self.manager.device_health()["gauge"],
                "Device verify path: 0=ok 1=degraded(CPU) 2=poisoned")
        index_stats = getattr(self.state, "index_stats", lambda: None)()
        if index_stats is not None:
            e.gauge("utxo_index_entries", index_stats["entries"],
                    "Live outpoints across the HBM-resident UTXO"
                    " index tables")
            e.gauge("utxo_index_resident_bytes",
                    index_stats["resident_bytes"],
                    "Device bytes held by the resident UTXO index")
            e.gauge("utxo_index_twin_fingerprints",
                    index_stats["twin_fingerprints"],
                    "Fingerprints that ever held two live outpoints"
                    " (forces shadow consult on hit)")
            e.counter("utxo_index_probes", index_stats["probes"],
                      "Resident-index membership probe dispatches")
            e.counter("utxo_index_shadow_consults",
                      index_stats["shadow_consults"],
                      "Probes answered by the host shadow map"
                      " (ambiguity; steady-state target is zero)")
        # mesh_engine (via crypto.sha256) imports jax — a host-path node
        # must not pay that on a scrape, so read stats only when the
        # mining subsystem already loaded the module itself
        mesh_mod = sys.modules.get("upow_tpu.mine.mesh_engine")
        mesh_stats = mesh_mod.engine_stats() if mesh_mod else None
        if mesh_stats is not None:
            e.gauge("mine_mesh_shards", mesh_stats["devices"],
                    "Devices in the resident mesh search program"
                    " (0 = engine built but not yet armed)")
            e.gauge("mine_mesh_batch_per_shard",
                    mesh_stats["batch_per_device"],
                    "Nonces per shard per round in the resident"
                    " search program")
            e.gauge("mine_mesh_armed", int(mesh_stats["armed"]),
                    "Resident mesh engine armed (compiled + warm)")
            e.counter("mine_mesh_rounds", mesh_stats["dispatches"],
                      "Mesh search rounds dispatched through the"
                      " device runtime")
        e.gauge("mine_mesh_configured_devices",
                self.config.device.mesh_devices,
                "config.device.mesh_devices (0 = all visible)")
        cache_entries = entry_count()
        if cache_entries >= 0:
            e.gauge("compile_cache_persistent_entries", cache_entries,
                    "Entries in the persistent jit compile cache")
        for label, mem in sorted(telemetry.device.device_memory().items()):
            for key, value in sorted(mem.items()):
                e.gauge(f"device_{label}_{key}", value,
                        "Best-effort device memory_stats() value")
        # XLA cost-analysis estimates (upow_tpu/profiling.analyze_cost),
        # next to the compile-cache counters they contextualize
        for kern, costs in sorted(telemetry.device.cost_estimates().items()):
            for key, value in sorted(costs.items()):
                e.gauge(f"kernel_{kern}_cost_{key}", value,
                        "XLA compiled.cost_analysis() estimate")
        # alert families are emitted unconditionally (zeros when the
        # watchtower is off) so make metrics-check can pin their names
        wt = self.watchtower
        wrow = wt.metric_rows() if wt is not None else {}
        e.gauge("alert_firing", wrow.get("firing", 0),
                "Alerts currently firing (docs/ALERTING.md)")
        e.gauge("alert_pending", wrow.get("pending", 0),
                "Alert conditions inside their for-duration")
        e.gauge("alert_silenced", wrow.get("silenced", 0),
                "Active alerts suppressed by an operator silence")
        e.gauge("alert_exemplars_firing",
                wrow.get("firing_with_exemplars", 0),
                "Firing alerts carrying at least one exemplar trace id")
        e.gauge("alert_eval_lag_seconds",
                wrow.get("eval_lag_seconds", 0.0),
                "Wall seconds the last watchtower evaluation tick took")
        e.counter("alert_evaluations", wrow.get("evaluations", 0),
                  "Watchtower evaluation ticks since start")
        e.counter("alert_fired", wrow.get("fired_total", 0),
                  "pending->firing transitions since start")
        e.counter("alert_resolved", wrow.get("resolved_total", 0),
                  "firing->resolved transitions since start")
        if wt is not None:
            by_rule: dict = {}
            for a in wt.alerts.active():
                d = by_rule.setdefault(a.rule.name,
                                       {"firing": 0, "pending": 0})
                if a.state in d:
                    d[a.state] += 1
            for rname, rule in sorted(wt.rules.items()):
                d = by_rule.get(rname, {"firing": 0, "pending": 0})
                e.gauge(f"alert_rule_{rname}_{rule.severity}_firing",
                        d["firing"],
                        f"Firing alerts for rule {rname}")
                e.gauge(f"alert_rule_{rname}_{rule.severity}_pending",
                        d["pending"],
                        f"Pending alerts for rule {rname}")
        for name, value in sorted(trace.counters().items()):
            e.counter(name, value)
        for name, s in sorted(trace.stats().items()):
            e.span_stats(name, s)
        for name, h in sorted(trace.histograms().items()):
            e.histogram(name, h["bounds"], h["counts"],
                        h["count"], h["sum"],
                        exemplars=h.get("exemplars"))
        resp = web.Response(text=e.render())
        # full 0.0.4 content type (Prometheus requires the version
        # parameter; aiohttp's ctor only takes the bare mime type)
        resp.headers["Content-Type"] = telemetry.exposition.CONTENT_TYPE
        return resp

    # cap on debug ``limit`` params: far above any configurable ring
    # size, so a clamped value never truncates a legitimate request
    _DEBUG_LIMIT_CAP = 100_000

    @classmethod
    def _debug_limit(cls, params, default: int = 0):
        """Parse a debug endpoint's ``limit``: (value, None) or
        (None, 400 response).  Unlike ``_int_q`` (422 via middleware),
        debug endpoints answer bad input directly with a 400 — they are
        operator surface, not reference wire surface.  Negative values
        clamp to 0 (= everything) and oversized ones to the cap, so no
        raw user integer ever reaches a slice."""
        raw = params.get("limit")
        if raw is None or raw == "":
            return default, None
        try:
            value = int(raw)
        except ValueError:
            return None, web.json_response(
                {"ok": False, "error": "limit must be an integer"},
                status=400)
        return max(0, min(value, cls._DEBUG_LIMIT_CAP)), None

    @staticmethod
    def _page_param(q, name: str, default: int, cap: int):
        """Public pagination param, hardened the same way as
        ``_debug_limit``: (value, None) or (None, 400 response).
        Negative values clamp to 0 and oversized ones to ``cap`` — an
        unclamped limit on the uncached SQL path is an easy self-DoS —
        and non-integers answer a clean 400 instead of the generic
        ``_int_q`` 422, naming the offending parameter."""
        raw = q.get(name)
        if raw is None or raw == "":
            return default, None
        try:
            value = int(raw)
        except ValueError:
            return None, web.json_response(
                {"ok": False, "error": f"{name} must be an integer"},
                status=400)
        return max(0, min(value, cap)), None

    async def h_debug_traces(self, request: web.Request) -> web.Response:
        """Completed trace trees: recency ring + slowest top-N
        (telemetry/tracing.py TraceBuffer).  ``limit`` bounds both
        lists (0 = all)."""
        limit, err = self._debug_limit(request.rel_url.query)
        if err is not None:
            return err
        result = telemetry.traces()
        if limit:
            result = {"recent": result.get("recent", [])[-limit:],
                      "slowest": result.get("slowest", [])[:limit]}
        return web.json_response({"ok": True, "result": result})

    async def h_debug_events(self, request: web.Request) -> web.Response:
        """Structured event ring: reorgs, breaker trips, degrade
        transitions, fault injections, alerts — oldest first, each
        stamped with the trace ID active when it fired and a monotonic
        ``seq``.  ``since=<seq>`` turns the poll incremental: only
        records beyond the cursor return, plus ``next_seq`` (the next
        cursor) and ``missed`` (records that rotated out of the ring
        before this cursor saw them; also counted into the
        ``telemetry.events.rotated_unseen`` counter)."""
        params = request.rel_url.query
        limit, err = self._debug_limit(params)
        if err is not None:
            return err
        kind = params.get("kind")
        since_raw = params.get("since")
        if since_raw is not None and since_raw != "":
            try:
                since_v = int(since_raw)
            except ValueError:
                return web.json_response(
                    {"ok": False, "error": "since must be an integer"},
                    status=400)
            got = telemetry.events.since(since_v, limit=limit or None,
                                         kind=kind)
            return web.json_response({
                "ok": True, "result": got["events"],
                "next_seq": got["next_seq"], "missed": got["missed"]})
        return web.json_response({
            "ok": True,
            "result": telemetry.events.snapshot(limit=limit or None,
                                                kind=kind)})

    async def h_debug_alerts(self, request: web.Request) -> web.Response:
        """Watchtower surface (docs/ALERTING.md), read-only: the rule
        pack, active alert states with exemplar trace ids, the
        firing/resolved history ring, and burn-rate readings.
        ``{"enabled": false}`` when the watchtower is off
        (UPOW_WATCHTOWER_ENABLED=1 turns it on).  The operator knobs
        (silence/unsilence/ack) live on POST — a side-effecting GET
        could be triggered by any prefetcher or dashboard refresh."""
        wt = self.watchtower
        if wt is None:
            return web.json_response(
                {"ok": True, "result": {"enabled": False}})
        result = wt.snapshot()
        result["enabled"] = True
        return web.json_response({"ok": True, "result": result})

    async def h_debug_alerts_post(self,
                                  request: web.Request) -> web.Response:
        """Watchtower operator knobs: ``silence=<key>&seconds=<s>``,
        ``unsilence=<key>``, ``ack=<key>`` — as query parameters or a
        JSON body (body wins).  Answers the post-action snapshot plus
        an ``actions`` record of what was applied."""
        wt = self.watchtower
        if wt is None:
            return web.json_response(
                {"ok": True, "result": {"enabled": False}})
        q = dict(request.rel_url.query)
        if request.can_read_body:
            try:
                body = await request.json()
            except ValueError:
                return web.json_response(
                    {"ok": False, "error": "body must be JSON"},
                    status=400)
            if not isinstance(body, dict):
                return web.json_response(
                    {"ok": False, "error": "body must be a JSON object"},
                    status=400)
            q.update({k: v for k, v in body.items() if v is not None})
        actions = {}
        key = q.get("silence")
        if key:
            try:
                secs = float(q.get("seconds", 300))
            except (TypeError, ValueError):
                return web.json_response(
                    {"ok": False, "error": "seconds must be a number"},
                    status=400)
            wt.silence(str(key), secs)
            actions["silenced"] = key
        key = q.get("unsilence")
        if key:
            wt.alerts.unsilence(str(key))
            actions["unsilenced"] = key
        key = q.get("ack")
        if key:
            actions["acked"] = wt.ack(str(key))
        result = wt.snapshot()
        result["enabled"] = True
        if actions:
            result["actions"] = actions
        return web.json_response({"ok": True, "result": result})

    async def h_debug_cache(self, request: web.Request) -> web.Response:
        """Hot-state read cache introspection: per-class entry counts
        and byte usage, hit/miss/eviction/coalesce counters, and the
        current generation + its age — everything an operator needs to
        size the ``UPOW_CACHE_*`` caps or confirm invalidations fire."""
        return web.json_response(
            {"ok": True, "result": self.hotcache.stats()})

    async def h_debug_breakers(self, request: web.Request) -> web.Response:
        """Per-peer circuit state + EWMA health score, exactly what
        gossip/sync peer ranking reads (PeerBook.ranked /
        propagate_nodes) — so an operator (or a swarm assertion) can see
        WHY a peer was skipped or tried last."""
        return web.json_response({"ok": True, "result": {
            "peers": self.breakers.snapshot(),
            "state_counts": self.breakers.state_counts(),
        }})

    async def h_debug_profile(self, request: web.Request) -> web.Response:
        """Opt-in jax.profiler capture control (ProfilingConfig):
        ``?action=start|stop|status``.  Route exists only when both
        telemetry.debug_endpoints and profile.enabled say so.

        The profiling calls run in an executor: a cold
        ``jax.profiler.start_trace`` initializes the profiler plugin and
        can block for seconds, which would stall every other request on
        this loop (caught by the concurrency sanitizer)."""
        from .. import profiling

        pcfg = self.config.profile
        action = request.rel_url.query.get("action", "status")
        loop = asyncio.get_running_loop()
        if action == "start":
            result = await loop.run_in_executor(
                None, profiling.start, pcfg.trace_dir,
                pcfg.max_capture_seconds)
        elif action == "stop":
            result = await loop.run_in_executor(None, profiling.stop)
        elif action == "status":
            result = await loop.run_in_executor(None, profiling.status)
        else:
            return web.json_response(
                {"ok": False,
                 "error": "action must be start, stop or status"},
                status=400)
        return web.json_response({"ok": "error" not in result,
                                  "result": result})

    # ------------------------------------------------------- snapshots ---
    # Serving reads ONLY the published on-disk generation (manifest +
    # chunk files) — never the database: a restoring peer hammering
    # /snapshot/chunk must not contend with block accept.  Deliberately
    # NOT routed through _cached (tests pin this): the chunk bytes are
    # already static files, and a cache-bypass header must never be
    # needed to get authoritative snapshot bytes.

    def _snapshot_gen(self):
        """(gen dir, manifest) of the published generation, or None."""
        from ..snapshot import layout as snapshot_layout

        root = self.config.snapshot.dir
        if not root:
            return None
        gen = snapshot_layout.current_gen_dir(root)
        if gen is None:
            return None
        manifest = snapshot_layout.read_manifest(
            os.path.join(gen, snapshot_layout.MANIFEST_NAME))
        if manifest is None:
            return None
        return gen, manifest

    @staticmethod
    async def _snapshot_serve_fault(key: str):
        """Fire the ``snapshot.serve`` chaos site; a 503 keeps an
        injected serve fault inside ordinary peer-error handling."""
        injector = faultinject.get_injector()
        if injector is not None:
            try:
                await injector.fire("snapshot.serve", key)
            except faultinject.FaultInjected:
                return web.json_response(
                    {"ok": False,
                     "error": "snapshot temporarily unavailable"},
                    status=503)
        return None

    async def h_snapshot_manifest(self,
                                  request: web.Request) -> web.Response:
        fault = await self._snapshot_serve_fault("manifest")
        if fault is not None:
            return fault
        gen = self._snapshot_gen()
        if gen is None:
            return web.json_response(
                {"ok": False, "error": "no snapshot available"},
                status=404)
        trace.inc("snapshot.manifest_served")
        return web.json_response({"ok": True, "result": gen[1]})

    async def h_snapshot_chunk(self, request: web.Request) -> web.Response:
        from ..snapshot import layout as snapshot_layout

        try:
            i = int(request.match_info["i"])
        except (KeyError, ValueError):
            return web.json_response(
                {"ok": False, "error": "chunk index must be an integer"},
                status=422)
        fault = await self._snapshot_serve_fault(f"chunk/{i}")
        if fault is not None:
            return fault
        gen = self._snapshot_gen()
        if gen is None or not 0 <= i < len(gen[1]["chunks"]):
            return web.json_response(
                {"ok": False, "error": "no such chunk"}, status=404)
        try:
            # chunks are up to 16 MiB; a loop-thread read would stall
            # every other handler while the disk seeks
            chunk_file = os.path.join(gen[0], snapshot_layout.chunk_name(i))
            data = await asyncio.get_running_loop().run_in_executor(
                None, lambda: open(chunk_file, "rb").read())
        except OSError:
            return web.json_response(
                {"ok": False, "error": "no such chunk"}, status=404)
        injector = faultinject.get_injector()
        if injector is not None:  # corrupt-kind rules rewrite payloads
            data = injector.fire_mutate("snapshot.serve", f"chunk/{i}",
                                        data)
        trace.inc("snapshot.chunks_served")
        return web.json_response(
            {"ok": True, "result": {"i": i, "data": data.hex()}})

    async def build_snapshot(self):
        """Build + publish a generation under config.snapshot.dir
        (None when the subsystem is disabled or the chain is empty)."""
        scfg = self.config.snapshot
        if not scfg.dir:
            return None
        from ..snapshot.builder import build_snapshot as _build

        return await _build(self.state, scfg.dir,
                            chunk_bytes=scfg.chunk_bytes,
                            blocks_tail=scfg.blocks_tail, keep=scfg.keep)

    async def bootstrap_from_snapshot(self, sources=None) -> dict:
        """Onboard this node from a peer snapshot, falling back to full
        block replay (sync_blockchain) with a structured reason when
        snapshot restore cannot complete.  ``sources`` overrides peer
        selection; by default peers are ordered by the same breaker/
        health rank sync_blockchain uses."""
        from ..snapshot.client import (SnapshotError,
                                       bootstrap_from_snapshot)

        scfg = self.config.snapshot
        if sources is None:
            sources = self.peers.ranked(self.peers.recent_nodes())
        reason = detail = ""
        if not scfg.dir:
            reason = "snapshot_disabled"
        elif not sources:
            reason = "no_sources"
        else:
            ifaces = [self.iface_factory(url, self.config.node,
                                         session=self._session(),
                                         resilience=self.resilience)
                      for url in sources]
            try:
                result = await bootstrap_from_snapshot(
                    self.state, ifaces, scfg.dir,
                    chunk_retries=scfg.chunk_retries,
                    progress=self.snapshot_restore,
                    max_chunks=scfg.max_chunks,
                    max_chunk_bytes=scfg.max_chunk_bytes,
                    max_payload_bytes=scfg.max_payload_bytes)
                # restored state invalidates everything derived from it
                self.hotcache.bump("snapshot_restore")
                self.manager.invalidate_difficulty()
                return {"ok": True, **result}
            except SnapshotError as e:
                reason, detail = e.reason, e.detail
                if reason == "restored_state_mismatch":
                    # the client wiped the committed-but-unproven
                    # restore back to a blank chain — derived caches
                    # must not outlive it
                    self.hotcache.bump("snapshot_restore")
                    self.manager.invalidate_difficulty()
            finally:
                for iface in ifaces:
                    await iface.close()
        trace.inc("snapshot.fallbacks")
        telemetry.event("snapshot_fallback", reason=reason,
                        detail=detail or None)
        log.warning("snapshot bootstrap failed (%s); falling back to"
                    " full replay", reason)
        sync = await self.sync_blockchain()
        return {"ok": bool(sync.get("ok")), "method": "replay_fallback",
                "reason": reason, "sync": sync}

    # --------------------------------------------------------- archive ---
    # Disk-only serving, mirroring /snapshot/*: authoritative bytes
    # come straight from the published manifest + segment files (NOT
    # routed through _cached — peers verifying content hashes need the
    # store's truth, and tests pin the no-cache-bypass property).

    async def _archive_manifest(self) -> Optional[dict]:
        reader = self.state.archive
        if not self.config.archive.dir or reader is None:
            return None
        return await asyncio.get_running_loop().run_in_executor(
            None, reader.store.current_manifest)

    async def h_archive_manifest(self,
                                 request: web.Request) -> web.Response:
        manifest = await self._archive_manifest()
        if manifest is None:
            return web.json_response(
                {"ok": False, "error": "no archive available"},
                status=404)
        trace.inc("archive.manifest_served")
        return web.json_response({"ok": True, "result": manifest})

    async def h_archive_segment(self, request: web.Request) -> web.Response:
        try:
            i = int(request.match_info["i"])
        except (KeyError, ValueError):
            return web.json_response(
                {"ok": False, "error": "segment index must be an integer"},
                status=422)
        manifest = await self._archive_manifest()
        if manifest is None or not 0 <= i < len(manifest["segments"]):
            return web.json_response(
                {"ok": False, "error": "no such segment"}, status=404)
        record = manifest["segments"][i]
        try:
            # segments can be tens of MB; a loop-thread read would
            # stall every other handler while the disk seeks
            data = await asyncio.get_running_loop().run_in_executor(
                None, self.state.archive.store.read_payload,
                record["name"])
        except OSError:
            return web.json_response(
                {"ok": False, "error": "no such segment"}, status=404)
        trace.inc("archive.segments_served")
        return web.json_response(
            {"ok": True, "result": {"i": i, "name": record["name"],
                                    "data": data.hex()}})

    async def h_debug_archive(self, request: web.Request) -> web.Response:
        reader = self.state.archive
        if reader is None:
            return web.json_response(
                {"ok": False, "error": "archive disabled"}, status=404)
        await reader.coverage()  # stats() reads the cached manifest
        return web.json_response({"ok": True, "result": {
            "reader": reader.stats(),
            "last_compaction": self.archive_compact,
            "hot_rows": await self.state.archive_hot_row_counts(),
        }}, dumps=_json_dumps)

    async def compact_archive(self) -> dict:
        """One compaction cycle against the newest published snapshot
        generation (archive/compactor.py; crash-safe, idempotent)."""
        acfg = self.config.archive
        if not acfg.dir or not self.config.snapshot.dir:
            return {"ok": False, "reason": "archive_disabled"}
        from ..archive import compactor

        stats = await compactor.compact(self.state, acfg.dir,
                                        self.config.snapshot.dir, acfg,
                                        reader=self.state.archive)
        self.archive_compact = stats
        return stats

    async def fetch_archive_from_peer(self, source: str) -> dict:
        """Mirror a peer's archive (deep-history sync/replay feed)."""
        acfg = self.config.archive
        if not acfg.dir:
            return {"ok": False, "reason": "archive_disabled"}
        from ..archive.reader import ArchiveFetchError, fetch_archive

        iface = self.iface_factory(source, self.config.node,
                                   session=self._session(),
                                   resilience=self.resilience)
        try:
            result = await fetch_archive(
                iface, acfg.dir,
                max_segment_bytes=acfg.max_segment_bytes,
                max_segments=acfg.max_segments)
        except (ArchiveFetchError, ConnectionError, asyncio.TimeoutError,
                OSError) as e:
            return {"ok": False, "reason": str(e)}
        finally:
            await iface.close()
        if self.state.archive is not None:
            self.state.archive.invalidate()
        return result

    def _snapshot_rebuild_tick(self) -> None:
        """Committed-block hook: arm a background snapshot rebuild (and
        the archive compaction it enables) every rebuild_interval_blocks
        + jitter blocks."""
        self._blocks_since_rebuild += 1
        if (self._blocks_since_rebuild >= self._rebuild_target
                and not self._snapshot_rebuild_inflight):
            self._blocks_since_rebuild = 0
            self._snapshot_rebuild_inflight = True
            self._spawn(self._snapshot_rebuild())

    async def _snapshot_rebuild(self) -> None:
        try:
            manifest = await self.build_snapshot()
            if manifest is not None:
                trace.inc("snapshot.auto_rebuilds")
            if self.config.archive.dir:
                await self.compact_archive()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.warning("background snapshot rebuild failed: %s", e)
        finally:
            self._snapshot_rebuild_inflight = False

    async def h_push_tx(self, request: web.Request) -> web.Response:
        if self.is_syncing:
            return web.json_response(
                {"ok": False, "error": "Node is already syncing"})
        params = await self._params(request)
        tx_hex = params.get("tx_hex")
        if not tx_hex:
            return web.json_response(
                {"ok": False, "error": "Missing tx_hex"}, status=422)
        try:
            tx = await self._parse_tx(tx_hex)
        except Exception as e:
            log.debug("push_tx: rejecting unparseable tx: %s", e)
            return web.json_response(
                {"ok": False, "error": f"Invalid transaction: {e}"})
        result = await self._submit_tx(
            tx, request.headers.get("Sender-Node"))
        return web.json_response(result)

    async def _parse_tx(self, tx_hex: str, overlay: Optional[dict] = None):
        """Decode with the ambiguous-signature relink resolved against state
        (core/tx.py tx_from_hex needs a sync resolver).  The resolver is
        only consulted when the signature count matches neither 1 nor the
        input count, so the common case is ONE parse; only the ambiguous
        layout pays the signature-free pre-parse that gathers input
        addresses.  ``overlay`` maps tx_hash -> parsed Tx for sources not
        yet in state (earlier blocks of the same sync page)."""
        try:
            return tx_from_hex(tx_hex, check_signatures=True)
        except AmbiguousSignatureError:
            pass
        tx = tx_from_hex(tx_hex, check_signatures=False)
        addrs = {}
        for i in tx.inputs:
            src = overlay.get(i.tx_hash) if overlay else None
            if src is not None and 0 <= i.index < len(src.outputs):
                addrs[(i.tx_hash, i.index)] = src.outputs[i.index].address
            else:
                addrs[(i.tx_hash, i.index)] = (
                    await self.state.resolve_output_address(i.tx_hash, i.index))
        return tx_from_hex(
            tx_hex, check_signatures=True,
            resolve_address=lambda h, idx: addrs.get((h, idx)))

    async def h_push_block(self, request: web.Request) -> web.Response:
        if self.is_syncing:
            return web.json_response(
                {"ok": False, "error": "Node is already syncing"})
        params = await self._params(request)
        if "id" in params:
            return web.json_response({"ok": False, "error": "Deprecated"})
        block_content = params.get("block_content", "")
        txs = params.get("txs", "")
        block_no = params.get("block_no")
        sender = request.headers.get("Sender-Node")
        if isinstance(txs, str):
            txs = txs.split(",")
            if txs == [""]:
                txs = []
        try:
            previous_hash = split_block_content(block_content)[0]
        except Exception as e:
            log.debug("push_block: malformed block content from %s: %s",
                      sender, e)
            return web.json_response(
                {"ok": False, "error": f"malformed block content: {e}"})
        next_block_id = await self.state.get_next_block_id()
        if block_no is None:
            previous_block = await self.state.get_block(previous_hash)
            if previous_block is None:
                if sender:
                    self._spawn(self.sync_blockchain(sender))
                    return web.json_response({
                        "ok": False,
                        "error": "Previous hash not found, had to sync "
                                 "according to sender node, block may have "
                                 "been accepted"})
                return web.json_response(
                    {"ok": False, "error": "Previous hash not found"})
            block_no = previous_block["id"] + 1
        else:
            try:
                block_no = int(block_no)
                if not (0 <= block_no <= 2 ** 63 - 1):
                    raise ValueError
            except (ValueError, TypeError):
                # a miner sending garbage must get a clean rejection,
                # not a 500 (same class as the _int_q GET hardening)
                return web.json_response(
                    {"ok": False, "error": "Invalid block_no"}, status=422)
        if next_block_id < block_no:
            self._spawn(self.sync_blockchain(sender))
            return web.json_response({
                "ok": False,
                "error": "Blocks missing, had to sync according to sender "
                         "node, block may have been accepted"})
        if next_block_id > block_no:
            return web.json_response({"ok": False, "error": "Too old block"})

        final_transactions: List[Tx] = []
        hashes: List[str] = []
        for tx_hex in txs:
            if len(tx_hex) == 64:
                hashes.append(tx_hex)
            else:
                final_transactions.append(await self._parse_tx(tx_hex))
        if hashes:
            found = await self.state.get_pending_transactions_by_hash(hashes)
            if len(found) < len(hashes):
                if sender:
                    self._spawn(self.sync_blockchain(sender))
                    return web.json_response({
                        "ok": False,
                        "error": "Transaction hash not found, had to sync "
                                 "according to sender node, block may have "
                                 "been accepted"})
                return web.json_response(
                    {"ok": False, "error": "Transaction hash not found"})
            for h in found:
                final_transactions.append(await self._parse_tx(h))

        errors: list = []
        if not await self.manager.create_block(
                block_content, final_transactions, errors=errors):
            return web.json_response(
                {"ok": False, "error": errors[0]} if errors else {"ok": False})

        if self.ws_hub is not None:
            block_hash = hashlib.sha256(bytes.fromhex(block_content)).hexdigest()
            info = await self._mining_info_result()
            self._spawn(self.ws_hub.broadcast_new_block({
                "block_no": block_no,
                "block_hash": block_hash,
                "transactions_count": len(final_transactions),
                "timestamp": timestamp(),
                **info,
            }))
        if sender:
            self.peers.update_last_message(sender)
        self._spawn(self.propagate("push_block", {
            "block_content": block_content,
            "txs": ([tx.hex() for tx in final_transactions]
                    if len(final_transactions) < 10 else txs),
            "block_no": block_no,
        }))
        return web.json_response({"ok": True})

    async def h_sync_blockchain(self, request: web.Request) -> web.Response:
        if self.is_syncing:
            return web.json_response(
                {"ok": False, "error": "Node is already syncing"})
        node_url = request.rel_url.query.get("node_url")
        resp = await self.sync_blockchain(node_url)
        body = {"ok": resp["ok"]}
        if not resp["ok"]:
            body["error"] = resp["error"]
        if resp["peer"]:
            body["peer"] = resp["peer"]  # additive: which source was used
        return web.json_response(body)

    async def h_get_mining_info(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"ok": True, "result": await self._mining_info_result()})

    async def h_get_validators_info(self, request: web.Request) -> web.Response:
        """Inode ballot grouped by voting validator (main.py:698-725)."""
        q = request.rel_url.query
        inode = q.get("inode")
        offset = _int_q(q, "offset", 0)
        limit = _int_q(q, "limit", 100, cap=1000)

        async def build():
            rows = await self.state.get_ballots(
                "inodes_ballot", inode, offset=offset, limit=limit)
            by_validator: dict = {}
            stakes: dict = {}  # one stake computation per validator
            for row in rows:
                ent = by_validator.setdefault(row["voter"], {
                    "validator": row["voter"], "vote": []})
                ent["vote"].append({
                    "wallet": row["recipient"],
                    "vote_count": str(row["vote"]),
                    "tx_hash": row["tx_hash"],
                    "index": row["index"],
                })
                if row["voter"] not in stakes:
                    stakes[row["voter"]] = str(
                        await self.state.get_validators_stake(
                            row["voter"], check_pending_txs=True))
                ent["totalStake"] = stakes[row["voter"]]
            return list(by_validator.values())

        return await self._cached(request, "governance",
                                  ("validators", inode, offset, limit),
                                  build)

    async def h_get_delegates_info(self, request: web.Request) -> web.Response:
        """Validator ballot grouped by voting delegate, batch stake
        (main.py:727-764)."""
        q = request.rel_url.query
        validator = q.get("validator")
        offset = _int_q(q, "offset", 0)
        limit = _int_q(q, "limit", 100, cap=1000)

        async def build():
            rows = await self.state.get_ballots(
                "validators_ballot", validator, offset=offset, limit=limit)
            stakes = await self.state.get_multiple_address_stakes(
                {row["voter"] for row in rows if row["voter"]},
                check_pending_txs=True)
            by_delegate: dict = {}
            for row in rows:
                ent = by_delegate.setdefault(row["voter"], {
                    "delegate": row["voter"], "vote": [],
                    "totalStake": "0"})
                ent["vote"].append({
                    "wallet": row["recipient"],
                    "vote_count": str(row["vote"]),
                    "tx_hash": row["tx_hash"],
                    "index": row["index"],
                })
                ent["totalStake"] = str(stakes.get(row["voter"],
                                                   Decimal(0)))
            return list(by_delegate.values())

        return await self._cached(request, "governance",
                                  ("delegates", validator, offset, limit),
                                  build)

    async def h_get_address_info(self, request: web.Request) -> web.Response:
        q = request.rel_url.query
        address = q.get("address")
        if not address:
            return web.json_response(
                {"ok": False, "error": "Missing address"}, status=422)

        def flag(name):
            return q.get(name, "false").lower() in ("1", "true", "yes")

        async def build():
            outputs = await self.state.get_spendable_outputs(address)
            stake = await self.state.get_address_stake(address)
            balance = sum(o.amount for o in outputs)

            def out_list(rows):
                return [{"amount": _fmt_amount(r["amount"]),
                         "tx_hash": r["tx_hash"], "index": r["index"]}
                        for r in rows]

            result = {
                "balance": _fmt_amount(balance),
                "stake": str(stake),
                "spendable_outputs": [
                    {"amount": _fmt_amount(o.amount), "tx_hash": o.tx_hash,
                     "index": o.index} for o in outputs],
                "pending_transactions": None,
                "pending_spent_outputs": None,
                "stake_outputs": None,
                "delegate_spent_votes": None,
                "delegate_unspent_votes": None,
                "inode_registration_outputs": None,
                "validator_unspent_votes": None,
                "validator_spent_votes": None,
                "is_inode": None,
                "is_inode_active": None,
                "is_validator": None,
            }

            def vote_list(rows):
                return [{"amount": str(r["vote"]), "tx_hash": r["tx_hash"],
                         "index": r["index"]} for r in rows]

            if flag("show_pending"):
                pending = await self.state.get_address_pending_transactions(address)
                result["pending_transactions"] = [
                    await self.state.get_nice_transaction(
                        tx.hash(), address if flag("verify") else None)
                    for tx in pending
                ]
                result["pending_spent_outputs"] = [
                    {"tx_hash": h, "index": i}
                    for h, i in await self.state.get_address_pending_spent_outpoints(address)
                ]
            if flag("stake_outputs"):
                result["stake_outputs"] = out_list(
                    await self.state.get_outputs_by_address(
                        "unspent_outputs", address, is_stake=True))
            if flag("delegate_spent_votes"):
                result["delegate_spent_votes"] = vote_list(
                    await self.state.get_delegates_spent_votes(address))
            if flag("delegate_unspent_votes"):
                result["delegate_unspent_votes"] = out_list(
                    await self.state.get_outputs_by_address(
                        "delegates_voting_power", address))
            if flag("inode_registration_outputs"):
                result["inode_registration_outputs"] = out_list(
                    await self.state.get_outputs_by_address(
                        "inode_registration_output", address))
            if flag("validator_unspent_votes"):
                result["validator_unspent_votes"] = out_list(
                    await self.state.get_outputs_by_address(
                        "validators_voting_power", address))
            if flag("validator_spent_votes"):
                result["validator_spent_votes"] = vote_list(
                    await self.state.get_validators_spent_votes(address))
            if flag("address_state"):
                is_inode = await self.state.is_inode_registered(address)
                result["is_inode"] = is_inode
                if is_inode:
                    active = await self.manager.get_active_inodes_cached()
                    result["is_inode_active"] = any(
                        e.get("wallet") == address for e in active)
                else:
                    result["is_inode_active"] = False
                result["is_validator"] = await self.state.is_validator_registered(address)
            return {"ok": True, "result": result}

        key = (address,) + tuple(flag(n) for n in _ADDRESS_INFO_FLAGS)
        return await self._cached(request, "address", key, build)

    async def h_get_address_transactions(self, request: web.Request) -> web.Response:
        q = request.rel_url.query
        address = q.get("address")
        page, err = self._page_param(q, "page", 1, 2 ** 63 - 1)
        if err is None:
            limit, err = self._page_param(q, "limit", 5, 1000)
        if err is not None:
            return err
        page = max(page, 1)
        # the PRODUCT can overflow int64 even with both factors clamped
        offset = min((page - 1) * limit, 2 ** 63 - 1)

        async def build():
            rows = await self.state.get_address_transactions(
                address, limit=limit, offset=offset)
            return {"ok": True, "result": {
                "transactions": [
                    await self.state.get_nice_transaction(r["tx_hash"])
                    for r in rows]
            }}

        return await self._cached(request, "history",
                                  (address, limit, offset), build)

    async def h_add_node(self, request: web.Request) -> web.Response:
        url = request.rel_url.query.get("url", "").strip("/")
        if not url:
            return web.json_response(
                {"ok": False, "error": "Missing url"}, status=422)
        if _normalize(url) == _normalize(self.self_url):
            return web.json_response(
                {"ok": False, "error": "Recursively adding node"})
        if self.peers.contains(url):
            return web.json_response(
                {"ok": False, "error": "Node already present"})
        # no resilience ctx: the probe of a candidate peer should stay a
        # quick single attempt and not seed a breaker entry for a URL we
        # may never admit to the book
        iface = self.iface_factory(url, self.config.node,
                                   session=self._session())
        try:
            await iface.get("")
        except Exception as e:
            log.debug("add_node: probe of %s failed: %s", url, e)
            return web.json_response(
                {"ok": False, "error": "Could not add node"})
        self._spawn(self.propagate("add_node", {"url": url}, ignore_url=url))
        self.peers.add(url)
        return web.json_response({"ok": True, "result": "Node added"})

    async def h_get_nodes(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"ok": True, "result": self.peers.recent_nodes()[:100]})

    async def h_get_pending_transactions(self, request: web.Request) -> web.Response:
        async def build():
            txs = await self.state.get_pending_transactions_limit(
                hex_only=True)
            return {"ok": True, "result": txs}

        return await self._cached(request, "pending", (), build)

    async def h_get_transaction(self, request: web.Request) -> web.Response:
        tx_hash = request.rel_url.query.get("tx_hash", "")

        async def build():
            tx = await self.state.get_nice_transaction(tx_hash)
            if tx is None:
                return {"ok": False, "error": "Transaction not found"}
            return {"ok": True, "result": tx}

        return await self._cached(request, "tx", (tx_hash,), build)

    async def _block_lookup(self, block: str) -> Optional[dict]:
        if block.isdecimal():
            # length gate first: int() itself raises past ~4300 digits
            # (python 3.12 conversion limit); int64 max has 19 digits
            if len(block) > 19 or int(block) > 2 ** 63 - 1:
                return None  # beyond any storable id (the sqlite
                # INTEGER binding would otherwise overflow into a 500)
            return await self.state.get_block_by_id(int(block))
        return await self.state.get_block(block)

    async def h_get_block(self, request: web.Request) -> web.Response:
        q = request.rel_url.query
        block = q.get("block", "")
        full = q.get("full_transactions", "false").lower() in ("1", "true")

        async def build():
            info = await self._block_lookup(block)
            if not info:
                return {"ok": False, "error": "Block not found"}
            block_hash = info["hash"]
            return {"ok": True, "result": {
                "block": _json_block(info),
                "transactions": (
                    await self.state.get_block_transactions(block_hash,
                                                            hex_only=True)
                    if not full else None),
                "full_transactions": (
                    await self.state.get_block_nice_transactions(block_hash)
                    if full else None),
            }}

        return await self._cached(request, "block", ("block", block, full),
                                  build)

    async def h_get_block_details(self, request: web.Request) -> web.Response:
        block = request.rel_url.query.get("block", "")

        async def build():
            info = await self._block_lookup(block)
            if not info:
                return {"ok": False, "error": "Block not found"}
            # the views helper drops reorg-raced Nones (never embed null)
            txs = await self.state.get_block_nice_transactions(info["hash"])
            return {"ok": True, "result": {
                "block": _json_block(info),
                "transactions": txs,
            }}

        return await self._cached(request, "block", ("details", block),
                                  build)

    async def h_get_blocks(self, request: web.Request) -> web.Response:
        q = request.rel_url.query
        offset, err = self._page_param(q, "offset", 0, 2 ** 63 - 1)
        if err is None:
            limit, err = self._page_param(q, "limit", 100, 1000)
        if err is not None:
            return err

        async def build():
            blocks = await self.state.get_blocks(offset, limit,
                                                 size_capped=True)
            return {"ok": True, "result": blocks}

        return await self._cached(request, "blocks",
                                  ("blocks", offset, limit), build)

    async def h_get_blocks_details(self, request: web.Request) -> web.Response:
        q = request.rel_url.query
        offset, err = self._page_param(q, "offset", 0, 2 ** 63 - 1)
        if err is None:
            limit, err = self._page_param(q, "limit", 100, 1000)
        if err is not None:
            return err

        async def build():
            blocks = await self.state.get_blocks(offset, limit,
                                                 tx_details=True,
                                                 size_capped=True)
            return {"ok": True, "result": blocks}

        return await self._cached(request, "blocks",
                                  ("details", offset, limit), build)

    async def h_dobby_info(self, request: web.Request) -> web.Response:
        inodes = await self.manager.get_active_inodes_cached()
        data = [
            {**item, "emission": f"{item['emission']:.2f}%"
             if isinstance(item["emission"], Decimal)
             else str(item["emission"]) + "%"}
            for item in inodes
        ]
        return web.json_response({"ok": True, "result": data},
                                 dumps=_json_dumps)

    async def h_get_supply_info(self, request: web.Request) -> web.Response:
        async def build():
            last_block = await self.state.get_last_block()
            last_id = last_block["id"] if last_block else 0
            return {"ok": True, "result": {
                "max_supply": float(MAX_SUPPLY),
                "circulating_supply": float(get_circulating_supply(last_id)),
                "last_block": _json_block(last_block),
            }}

        return await self._cached(request, "supply", (), build)

    async def h_send_to_address(self, request: web.Request) -> web.Response:
        """Localhost-only custodial send (main.py:481-518): looks up the
        wallet keystore by the Authorization pubkey, builds + pushes."""
        params = await self._params(request)
        to_address = params.get("to_address")
        amount = params.get("amount")
        if not to_address or not amount:
            return web.json_response(
                {"ok": False, "error": "Missing required params."}, status=422)
        auth = request.headers.get("Authorization")
        from ..wallet.keystore import KeyStore

        store = KeyStore()
        private_key = store.private_key_for_public(auth)
        if private_key is None:
            return web.json_response({"ok": False, "error": "Unauthorized"})
        from ..wallet.builders import WalletBuilder

        builder = WalletBuilder(self.state)
        try:
            tx = await builder.create_transaction(
                private_key, to_address, Decimal(str(amount)))
        except Exception as e:
            log.debug("send_to_address: tx build failed: %s", e)
            return web.json_response({"ok": False, "error": str(e)})
        result = await self._submit_tx(
            tx, request.headers.get("Sender-Node"))
        return web.json_response(result)

    # ------------------------------------------------------------ sync ----
    @staticmethod
    def _sync_result(outcome, peer: Optional[str]) -> dict:
        """Normalize _sync_blockchain's True|str|Exception outcome into
        the structured {ok, error, peer} shape — callers (and the HTTP
        handler) never see a raw exception object."""
        if outcome is True:
            return {"ok": True, "error": None, "peer": peer}
        if isinstance(outcome, BaseException):
            error = f"{type(outcome).__name__}: {outcome}"
        else:
            error = str(outcome)
        return {"ok": False, "error": error, "peer": peer}

    async def sync_blockchain(self, node_url: Optional[str] = None) -> dict:
        """Guarded wrapper (main.py:230-243) returning a structured
        ``{ok, error, peer}`` dict.  When no peer is named, up to 3
        distinct peers are tried before giving up — the reference picks
        ONE random peer per call (main.py:158-166), so a single dead
        seed (or its own unreachable CORE_URL default) makes that sync
        attempt a no-op even with healthy peers in the book.  The
        sampled candidates are then ordered by breaker health so a peer
        that has been failing all day is the LAST one tried, not an
        equal-odds first pick."""
        if self.is_syncing:
            return self._sync_result("Node is already syncing", None)
        self.is_syncing = True
        self.manager.is_syncing = True
        try:
            if node_url:
                return self._sync_result(
                    await self._sync_blockchain(node_url), node_url)
            nodes = self.peers.recent_nodes()
            if not nodes:
                return self._sync_result("No nodes found.", None)
            result = self._sync_result("no peers tried", None)
            candidates = random.sample(nodes, min(3, len(nodes)))
            for url in self.peers.ranked(candidates):
                try:
                    outcome = await self._sync_blockchain(url)
                except Exception as e:
                    # a dead peer raises from the fork-detection fetches
                    # before the paged loop's own error handling — it
                    # must advance the retry, not abort it
                    outcome = e
                result = self._sync_result(outcome, url)
                if result["ok"]:
                    return result
                log.warning("sync from %s did not complete (%s); trying "
                            "another peer", url, result["error"])
            return result
        except Exception as e:
            log.warning("sync_blockchain error: %s: %s",
                        type(e).__name__, e)
            return self._sync_result(e, node_url)
        finally:
            self.is_syncing = False
            self.manager.is_syncing = False

    async def _sync_blockchain(self, node_url: str):
        """Fork detection + paged download (main.py:153-227), against one
        named peer."""
        cfg = self.config.node
        iface = self.iface_factory(node_url, cfg, session=self._session(),
                                   resilience=self.resilience)
        prefetch: Optional[asyncio.Task] = None
        prefetch_from = None
        try:
            _, last_block = await self.manager.calculate_difficulty()
            starting_from = i = await self.state.get_next_block_id()
            # advisory probe (docs/SNAPSHOT.md): when the peer's tip is
            # further ahead than the reorg window can ever bridge
            # block-by-block cheaply, surface a structured hint that
            # snapshot onboarding would be the better path.  Best
            # effort — a probe failure must not abort the sync.
            try:
                info = (await iface.get("get_mining_info")).get(
                    "result") or {}
                remote_height = int(
                    (info.get("last_block") or {}).get("id") or 0)
            except Exception as e:
                log.debug("tip probe of %s failed: %s", node_url, e)
                remote_height = 0
            if remote_height - (i - 1) > cfg.sync_reorg_window:
                trace.inc("snapshot_recommended")
                telemetry.event(
                    "snapshot_recommended", peer=node_url,
                    local_height=i - 1, remote_height=remote_height,
                    lag=remote_height - (i - 1))
            local_cache = None
            last_common_block = 0
            if last_block and last_block.get("id", 0) > cfg.sync_reorg_window:
                remote_last = (await iface.get_block(i - 1))["block"]
                if remote_last["hash"] != last_block["hash"]:
                    offset = i - cfg.sync_reorg_window
                    remote_blocks = await iface.get_blocks(
                        offset, cfg.sync_reorg_window)
                    local_blocks = await self.state.get_blocks(
                        offset, cfg.sync_reorg_window)
                    # pair by block id, not list index: the peer's page
                    # may be size-truncated (reference-compatible cap),
                    # so index alignment is not guaranteed
                    remote_by_id = {rb["block"]["id"]: rb
                                    for rb in remote_blocks}
                    local_blocks.reverse()
                    for n, local in enumerate(local_blocks):
                        remote = remote_by_id.get(local["block"]["id"])
                        if remote is not None and \
                                local["block"]["hash"] == remote["block"]["hash"]:
                            last_common_block = local["block"]["id"]
                            local_cache = local_blocks[:n]
                            local_cache.reverse()
                            await self.state.remove_blocks(last_common_block + 1)
                            break
            errors: list = []
            # pipelined download: while page k is verified/accepted, page
            # k+1 is already in flight (accept work and peer I/O overlap;
            # the reference fetches and accepts strictly serially,
            # main.py:188-192).  The prefetch targets the EXPECTED next
            # offset; if accept rejects part of a page the speculative
            # fetch is discarded.
            last_fetch = [0.0]

            async def fetch_page(offset):
                # pace below the peer's server-side 40/min get_blocks
                # limit (ratelimit.py:26) — pipelining would otherwise
                # raise the request rate to one per max(fetch, accept)
                wait = cfg.sync_fetch_interval - (
                    time.monotonic() - last_fetch[0])
                if wait > 0:
                    await asyncio.sleep(wait)
                last_fetch[0] = time.monotonic()
                return await iface.get_blocks(offset, cfg.sync_page)

            while True:
                i = await self.state.get_next_block_id()
                try:
                    if prefetch is not None and prefetch_from == i:
                        try:
                            blocks = await prefetch
                        except Exception as e:
                            # a transient blip on the SPECULATIVE fetch
                            # must not abort a multi-thousand-block sync;
                            # one direct retry at consumption time
                            log.info("prefetch of page %s failed (%s); "
                                     "retrying directly", i, e)
                            blocks = await fetch_page(i)
                    else:
                        if prefetch is not None:
                            # retrieve the discarded fetch's outcome via a
                            # callback, not an await: awaiting a task we
                            # just cancelled is indistinguishable from our
                            # OWN cancellation arriving at that suspension
                            # point, and swallowing that would let sync
                            # outlive close()
                            prefetch.cancel()
                            prefetch.add_done_callback(
                                lambda t: t.cancelled() or t.exception())
                        blocks = await fetch_page(i)
                    prefetch = None
                    if len(blocks) == cfg.sync_page:
                        prefetch_from = i + cfg.sync_page
                        prefetch = asyncio.ensure_future(
                            fetch_page(prefetch_from))
                except Exception as e:
                    # a failed page (peer down, response cap, or the
                    # peer's 40/minute get_blocks rate limit on a long
                    # catch-up) must NOT fall through to the success
                    # return below — report it so callers retry
                    log.error("sync fetch failed: %s", e)
                    return f"sync fetch failed: {e}"
                try:
                    _, last_block = await self.manager.calculate_difficulty()
                    if not blocks:
                        log.info("syncing complete")
                        if last_block and last_block.get("id", 0) > starting_from:
                            self.peers.update_last_message(node_url)
                            tip = await self.state.get_last_block()
                            if tip and timestamp() - tip["timestamp"] < 86400:
                                hashes = await self.state.get_block_transaction_hashes(
                                    tip["hash"])
                                await self.propagate("push_block", {
                                    "block_content": tip["content"],
                                    "txs": hashes,
                                    "block_no": tip["id"],
                                }, ignore_url=node_url)
                        return True
                    assert await self.create_blocks(blocks, errors)
                except Exception as e:
                    log.error("sync failed: %s", errors[0] if errors else e)
                    if local_cache is not None:
                        log.info("reverting to previous chain")
                        await self.state.remove_blocks(last_common_block + 1)
                        await self.create_blocks(local_cache, [])
                    return errors[0] if errors else e
            # unreachable: the loop exits only via the returns above
        finally:
            if prefetch is not None:
                # same callback pattern as the mid-loop discard: never
                # await a task we cancelled from inside a finally that
                # may itself be unwinding a cancellation
                prefetch.cancel()
                prefetch.add_done_callback(
                    lambda t: t.cancelled() or t.exception())
            await iface.close()

    async def create_blocks(self, blocks: list,
                            errors: Optional[list] = None,
                            _allow_device_txids: bool = True) -> bool:
        """Batch ingest for sync (main.py:97-150): recompute the merkle,
        rebuild content when absent, accept via the sync path that trusts
        the embedded coinbase.

        TPU-first divergence from the reference: all signature checks of
        the PAGE are collected up front (intra-page input references
        resolve against the parsed page txs themselves) and verified in
        ONE batched dispatch; the per-block accept then reads those
        verdicts instead of paying a device round trip per block."""
        errors = errors if errors is not None else []
        _, last_block = await self.manager.calculate_difficulty()
        last_id = last_block["id"] if last_block else 0
        last_hash = last_block["hash"] if last_block else GENESIS_PREV_HASH
        i = last_id + 1
        # batched txids for the whole page (SURVEY §2.2): one device (or
        # hashlib) batch seeds every tx's hash memo instead of a
        # per-instance sha256 on first .hash() — guarded below by a
        # round-trip identity check (payload == what hash() would
        # digest), by the per-batch roaming integrity sample inside
        # txid_batch, and deterministically by check_block's merkle
        # comparison, whose leaves ARE the seeded memos (core/merkle.py)
        txid_prefill: dict = {}
        dev_cfg = self.config.device
        if dev_cfg.txid_backend != "host" and _allow_device_txids:
            try:
                all_hex = [t for b in blocks
                           for t in b.get("transactions", ())]
                if len(all_hex) >= dev_cfg.txid_min_batch:
                    import functools

                    from ..crypto.sha256 import txid_batch

                    # executor: the first auto-measurement may block for
                    # minutes against a hung device; the per-block parse
                    # loop below must stay the error boundary, so any
                    # failure here (bad hex from the peer included) just
                    # skips the prefill
                    digests = await asyncio.get_event_loop() \
                        .run_in_executor(None, functools.partial(
                            txid_batch,
                            [bytes.fromhex(h) for h in all_hex],
                            backend=dev_cfg.txid_backend,
                            min_batch=dev_cfg.txid_min_batch))
                    txid_prefill = dict(zip(all_hex, digests))
            except Exception as e:
                log.info("txid prefill skipped: %s", e)
        parsed, overlay = [], {}
        parse_error = None
        for block_info in blocks:
            try:
                block = dict(block_info["block"])
                txs = []
                for t in block_info["transactions"]:
                    tx = await self._parse_tx(t, overlay=overlay)
                    seed = txid_prefill.get(t)
                    # seed only when re-serialization is byte-identical
                    # to the wire form (txid = sha256 of the
                    # re-serialized hex — consensus; hex() is memoized
                    # and needed later by storage, so this costs nothing)
                    if seed is not None and getattr(tx, "_hash", "x") is None \
                            and tx.hex() == t:
                        tx._hash = seed
                    txs.append(tx)
            except Exception as e:
                # keep the valid prefix: the accept loop below still
                # commits every block parsed so far (the interleaved
                # reference loop made the same forward progress)
                log.debug("sync: stopping page at unparseable block: %s", e)
                parse_error = f"block parse failed: {e}"
                break
            coinbase = None
            for tx in txs:
                if isinstance(tx, CoinbaseTx):
                    txs.remove(tx)
                    coinbase = tx
                    break
            for tx in txs:
                overlay[tx.hash()] = tx
            if coinbase is not None:
                overlay[coinbase.hash()] = coinbase
            parsed.append((block, txs, coinbase))

        self.manager.page_sig_verdicts = await self._page_sig_prefill(
            parsed, overlay)
        try:
            for block, txs, coinbase in parsed:
                block["merkle_tree"] = merkle_root(txs)
                content = block.get("content")
                if not content:
                    # the rebuilt header must NOT embed the memo-derived
                    # root: check_block compares the header root against
                    # merkle_root's memo leaves, so embedding the memo
                    # root would compare a corrupt device seed with
                    # itself — hash the raw hexes (host) for the header
                    # and the backstop stays deterministic
                    block["merkle_tree"] = merkle_root(
                        [tx.hex() for tx in txs])
                    content = block_to_bytes(last_hash, block).hex()
                if int(block["id"]) != i:
                    errors.append(f"unexpected block id {block['id']} != {i}")
                    return False
                if coinbase is None:
                    errors.append(f"block {i} has no coinbase")
                    return False
                if not await self.manager.create_block_syncing(
                        content, txs, coinbase, errors=errors):
                    if (txid_prefill and
                            any("merkle" in e for e in errors[-2:])):
                        # a wrong device-seeded txid surfaces here as a
                        # merkle mismatch; the integrity sample can miss
                        # a faulty lane, and retrying the page through
                        # the same device would wedge catch-up for as
                        # long as the fault lasts — redo the remaining
                        # blocks with host hashing (fresh parse, no
                        # seeds) before giving up
                        log.warning(
                            "sync accept hit a merkle mismatch with "
                            "device-seeded txids at block %d; retrying "
                            "the page with host hashing", i)
                        self.manager.page_sig_verdicts = None
                        remaining = [
                            b for b in blocks
                            if int(b["block"]["id"]) >= i]
                        errors.append(
                            f"retrying {len(remaining)} blocks with "
                            "host txids after device-seeded merkle "
                            "mismatch")
                        return await self.create_blocks(
                            remaining, errors,
                            _allow_device_txids=False)
                    return False
                last_hash = block["hash"]
                i += 1
        finally:
            self.manager.page_sig_verdicts = None
        if parse_error:
            errors.append(parse_error)
            return False
        return True

    def _prefill_worthwhile(self, n_inputs: int) -> bool:
        """Page-level batching only pays when the checks would go to a
        device (collapsing per-block round trips into one dispatch); on
        the host path it would just double address-resolution reads."""
        from ..verify.txverify import _resolve_backend

        return _resolve_backend(
            self.config.device.sig_backend, n_inputs) != "host"

    async def _page_sig_prefill(self, parsed, overlay) -> Optional[dict]:
        """One batched signature dispatch for a whole sync page.  Checks
        that fail to collect here (unresolvable inputs, malformed txs)
        are simply left out — the per-block accept recomputes anything
        missing and reports the real error.  Skipped entirely when the
        backend resolves to the host path: there the per-block batch is
        already cheap and the prefill would only double the per-input
        address-resolution reads."""
        n_inputs = sum(len(tx.inputs)
                       for _b, txs, _cb in parsed for tx in txs)
        if n_inputs == 0 or not self._prefill_worthwhile(n_inputs):
            return None
        verifier = TxVerifier(
            self.manager.state, is_syncing=True,
            verify_pad_block=self.config.device.verify_pad_block,
            verify_device_timeout=self.config.device.verify_device_timeout,
            tx_overlay=overlay,
            verify_mesh_devices=self.config.device.mesh_devices)
        checks = []
        for _block, txs, _cb in parsed:
            for tx in txs:
                try:
                    c = await verifier.collect_sig_checks(tx)
                except Exception as e:
                    # prefill is best-effort; the accept loop re-verifies
                    log.debug("sig-check prefill skipped a tx: %s", e)
                    c = None
                if c:
                    checks.extend(c)
        if not checks:
            return None
        checks = list(dict.fromkeys(checks))  # dedup, keep order
        verdicts = await run_sig_checks_async(
            checks, backend=self.config.device.sig_backend,
            pad_block=self.config.device.verify_pad_block,
            device_timeout=self.config.device.verify_device_timeout,
            mesh_devices=self.config.device.mesh_devices)
        return dict(zip(checks, verdicts))

    # --------------------------------------------------------- app build --
    def _build_app(self) -> web.Application:
        app = web.Application(middlewares=[self._middleware],
                              client_max_size=self.config.node.response_cap)
        r = app.router
        r.add_get("/", self.h_root)
        for path, handler in [
            ("/push_tx", self.h_push_tx),
            ("/push_block", self.h_push_block),
            ("/send_to_address", self.h_send_to_address),
        ]:
            r.add_get(path, handler)
            r.add_post(path, handler)
        for path, handler in [
            ("/sync_blockchain", self.h_sync_blockchain),
            ("/get_mining_info", self.h_get_mining_info),
            ("/get_validators_info", self.h_get_validators_info),
            ("/get_delegates_info", self.h_get_delegates_info),
            ("/get_address_info", self.h_get_address_info),
            ("/get_address_transactions", self.h_get_address_transactions),
            ("/add_node", self.h_add_node),
            ("/get_nodes", self.h_get_nodes),
            ("/get_pending_transactions", self.h_get_pending_transactions),
            ("/get_transaction", self.h_get_transaction),
            ("/get_block", self.h_get_block),
            ("/get_block_details", self.h_get_block_details),
            ("/get_blocks", self.h_get_blocks),
            ("/get_blocks_details", self.h_get_blocks_details),
            ("/dobby_info", self.h_dobby_info),
            ("/get_supply_info", self.h_get_supply_info),
            ("/snapshot/manifest", self.h_snapshot_manifest),
            ("/archive/manifest", self.h_archive_manifest),
            ("/metrics", self.h_metrics),
        ]:
            r.add_get(path, handler)
        r.add_get("/snapshot/chunk/{i}", self.h_snapshot_chunk)
        r.add_get("/archive/segment/{i}", self.h_archive_segment)
        if self.config.telemetry.debug_endpoints:
            r.add_get("/debug/traces", self.h_debug_traces)
            r.add_get("/debug/events", self.h_debug_events)
            r.add_get("/debug/alerts", self.h_debug_alerts)
            r.add_post("/debug/alerts", self.h_debug_alerts_post)
            r.add_get("/debug/breakers", self.h_debug_breakers)
            r.add_get("/debug/cache", self.h_debug_cache)
            r.add_get("/debug/archive", self.h_debug_archive)
            if self.config.profile.enabled:
                r.add_get("/debug/profile", self.h_debug_profile)
        if self.config.ws.enabled:
            from ..ws.hub import WsHub

            self.ws_hub = WsHub(self.config.ws)
            r.add_get("/ws", self.ws_hub.handle)
        # SLO latency series for the fixed route set (not /ws — a
        # socket's "latency" is its lifetime — and not /debug/*, which
        # would meter the metering).  Preregistered so every endpoint
        # exports an all-zero family from scrape #1.
        self._slo_paths = {
            res.canonical for res in r.resources()
            if res.canonical.startswith("/")
            and not res.canonical.startswith(("/ws", "/debug"))}
        if self.telemetry_scope is not None:
            with self.telemetry_scope.activate():
                telemetry.slo.preregister(self._slo_paths)
        else:
            telemetry.slo.preregister(self._slo_paths)
        if self.watchtower is not None:
            # the cadence task starts with the app (TestServer/AppRunner
            # both run on_startup) and dies with the service set in
            # close(); scenarios that pump evaluate_once() manually set
            # a huge interval so this loop never races them
            async def _start_watchtower(_app) -> None:
                self._spawn_service(self.watchtower.run())

            app.on_startup.append(_start_watchtower)
        return app


def _json_block(block: Optional[dict]) -> dict:
    """Blocks carry Decimal difficulty/reward; make them JSON-clean the way
    the reference's FastAPI encoder does (floats/strings)."""
    if not block:
        return {}
    out = dict(block)
    if "difficulty" in out:
        out["difficulty"] = float(out["difficulty"])
    if "reward" in out:
        out["reward"] = str(out["reward"])
    return out


def _json_dumps(obj) -> str:
    def default(o):
        if isinstance(o, Decimal):
            return str(o)
        raise TypeError(type(o))
    return json.dumps(obj, default=default)


def run(config: Optional[Config] = None) -> None:
    """Launcher (reference run_node.py): serve the node app."""
    node = Node(config)
    web.run_app(node.app, host=node.config.node.host,
                port=node.config.node.port)
