"""Whole-package analysis substrate for upowlint: symbol table, call
graph, and event-loop/thread coloring.

The per-file rules catch what one AST shows; the RC (race/concurrency)
family needs what the *package* shows: which functions run on the
asyncio event loop, which run on background threads, and where those
worlds touch the same state.  This module builds that picture once per
lint run:

* **Symbol table** — every function/method in the linted set, keyed by
  ``"<rel-path>::<qualname>"`` (nested defs included), plus per-class
  attribute *types* inferred from ``self.x = threading.Lock()``-style
  constructor assignments (locks, asyncio queues/events, executors).
* **Call graph** — call sites resolved through import aliases
  (``from ..verify import txverify`` → ``verify/txverify.py`` defs),
  ``self.meth`` dispatch (with by-name base-class lookup), local
  nested defs, and ``Class(...)`` → ``__init__``.  Unresolvable calls
  (dynamic dispatch, third-party code) produce no edge — the analysis
  is deliberately under-approximate, never speculative.
* **Coloring** — ``LOOP`` seeds at every ``async def``; ``THREAD``
  seeds at every function handed to a thread boundary
  (``threading.Thread(target=...)``, ``boxed_call``/``run_boxed``/
  ``submit_call``, ``run_in_executor``, ``asyncio.to_thread``,
  executor ``.submit``).  Colors propagate along *plain* call edges to
  a fixpoint; boundary calls do NOT propagate LOOP into their target
  (that is the point of the boundary).

Everything here is stdlib-``ast`` only, like the rest of the linter.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

LOOP = "loop"
THREAD = "thread"

# ---------------------------------------------------------------------------
# Knowledge bases shared by the AS and RC rule families.
# ---------------------------------------------------------------------------

#: The original AS001 table: calls that block the event loop, flagged
#: lexically inside ``async def`` bodies in node/ws.
AS_BLOCKING: Dict[str, str] = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "urllib.request.urlopen": "use the shared aiohttp session",
    "socket.create_connection": "use asyncio streams / aiohttp",
    "socket.getaddrinfo": "use loop.getaddrinfo",
    "subprocess.run": "use asyncio.create_subprocess_exec",
    "subprocess.call": "use asyncio.create_subprocess_exec",
    "subprocess.check_call": "use asyncio.create_subprocess_exec",
    "subprocess.check_output": "use asyncio.create_subprocess_exec",
    "subprocess.Popen": "use asyncio.create_subprocess_exec",
    "os.system": "use asyncio.create_subprocess_shell",
}

#: RC001's superset: adds file I/O (invisible at µs scale, lethal at
#: fsync/GiB scale) and blocking cross-thread waits.  Deliberately does
#: NOT list sqlite3 — the state backend runs synchronous sqlite inside
#: async methods by documented design (state/storage.py).
BLOCKING_CALLS: Dict[str, str] = dict(AS_BLOCKING)
BLOCKING_CALLS.update({
    "open": "move file I/O to run_in_executor",
    "os.fsync": "run the durable write in an executor",
    "os.replace": "run the journal commit in an executor",
    "shutil.rmtree": "run tree removal in an executor",
    "shutil.copytree": "run the copy in an executor",
    "shutil.copyfileobj": "run the copy in an executor",
})

#: Bare method names that block the calling thread waiting on another
#: thread; matched on the last dotted segment so receiver spelling
#: (``runtime.run_boxed`` / ``self._rt.boxed_call``) does not matter.
BLOCKING_WAIT_METHODS: Dict[str, str] = {
    "boxed_call": "boxed_call joins a worker thread; await an "
                  "executor-wrapped call instead",
    "run_boxed": "run_boxed blocks on the drainer; route through "
                 "run_in_executor from coroutine context",
}

BLOCKING_PREFIXES: Tuple[str, ...] = ("requests.",)


def blocking_reason(canon: str) -> Optional[str]:
    """Why ``canon`` (a canonicalized call name) blocks, or None."""
    if canon in BLOCKING_CALLS:
        return BLOCKING_CALLS[canon]
    for prefix in BLOCKING_PREFIXES:
        if canon.startswith(prefix):
            return "use the shared aiohttp session"
    last = canon.rsplit(".", 1)[-1]
    if last in BLOCKING_WAIT_METHODS:
        return BLOCKING_WAIT_METHODS[last]
    return None


#: Thread boundaries: call name (canonical, or a bare method name) ->
#: position of the callable argument ("target" = Thread's keyword).
SPAWN_APIS: Dict[str, object] = {
    "threading.Thread": "target",
    "asyncio.to_thread": 0,
    "boxed_call": 0,
    "run_boxed": 0,
    "submit_call": 0,
    "run_in_executor": 1,           # loop.run_in_executor(None, fn)
    "submit": 0,                    # only on executor-typed receivers
}

#: APIs that legitimately carry work or results across the thread/loop
#: boundary; calls to these are exempt from RC005.
BOUNDARY_APIS = {
    "call_soon_threadsafe",
    "run_coroutine_threadsafe",
    "run_in_executor",
    "to_thread",
}

#: Constructor canonical name -> attribute type tag.
ATTR_CTORS: Dict[str, str] = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Condition": "lock",
    "asyncio.Lock": "async_lock",
    "asyncio.Condition": "async_lock",
    "asyncio.Semaphore": "async_lock",
    "asyncio.Queue": "asyncio_queue",
    "asyncio.LifoQueue": "asyncio_queue",
    "asyncio.PriorityQueue": "asyncio_queue",
    "asyncio.Event": "asyncio_event",
    "threading.Event": "mt_event",
    "queue.Queue": "mt_queue",
    "queue.SimpleQueue": "mt_queue",
    "collections.deque": "deque",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
}

LOCK_KINDS = {"lock"}

#: asyncio surfaces that are loop-affine: touching them from a plain
#: thread either raises far away or silently targets the wrong loop.
LOOP_AFFINE_CALLS: Dict[str, str] = {
    "asyncio.create_task": "schedule via run_coroutine_threadsafe",
    "asyncio.ensure_future": "schedule via run_coroutine_threadsafe",
    "asyncio.get_event_loop": "from a thread this returns/creates the "
                              "WRONG loop; pass the loop in explicitly",
}

#: Methods on asyncio-typed attributes that are loop-affine when the
#: caller runs on a thread.
LOOP_AFFINE_ATTR_KINDS = {"asyncio_queue", "asyncio_event"}


# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------

@dataclass
class CallSite:
    name: str                   # dotted name as written ("self.flush")
    canon: str                  # canonicalized through imports
    lineno: int
    col: int
    awaited: bool = False
    is_stmt: bool = False       # the call IS the statement (Expr node)
    target: Optional[str] = None        # resolved fid (filled by link())
    node: Optional[ast.Call] = None


@dataclass
class SpawnSite:
    api: str                    # boundary name ("threading.Thread", ...)
    target_name: str            # dotted name of the callable handed over
    lineno: int
    col: int
    target: Optional[str] = None        # resolved fid


@dataclass
class AttrWrite:
    attr: str
    fid: str
    lineno: int
    col: int
    guards: Tuple[Tuple[str, ...], ...]  # lock-ish descriptors in scope
    in_init: bool


@dataclass
class HeldAwait:
    """An ``await`` executed while a ``with <lock>`` is held inside an
    ``async def`` (RC003 raw material)."""
    lock: Tuple[str, ...]       # descriptor, e.g. ("self", "_lock")
    lineno: int                 # line of the await
    col: int


@dataclass
class FunctionInfo:
    fid: str
    rel: str
    modkey: Tuple[str, ...]
    name: str
    qualname: str
    cls: Optional[str]
    is_async: bool
    lineno: int
    col: int
    parent: Optional[str] = None
    children: Dict[str, str] = field(default_factory=dict)
    calls: List[CallSite] = field(default_factory=list)
    spawns: List[SpawnSite] = field(default_factory=list)
    held_awaits: List[HeldAwait] = field(default_factory=list)
    local_types: Dict[str, str] = field(default_factory=dict)
    local_ctors: Dict[str, str] = field(default_factory=dict)
    colors: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    name: str
    rel: str
    modkey: Tuple[str, ...]
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, str] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    attr_ctors: Dict[str, str] = field(default_factory=dict)
    attr_writes: List[AttrWrite] = field(default_factory=list)


@dataclass
class ModuleInfo:
    rel: str
    key: Tuple[str, ...]
    # local name -> ("ext", "dotted.name") | ("proj", modkey, symbol|None)
    imports: Dict[str, tuple] = field(default_factory=dict)
    functions: Dict[str, str] = field(default_factory=dict)
    classes: Dict[str, "ClassInfo"] = field(default_factory=dict)


class ProjectContext:
    """The linked whole-package view handed to project-scope rules."""

    def __init__(self) -> None:
        self.modules: Dict[Tuple[str, ...], ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[Tuple[Tuple[str, ...], str], ClassInfo] = {}
        self._by_rel: Dict[str, ModuleInfo] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, files: Sequence) -> "ProjectContext":
        """``files``: FileContext-likes exposing ``rel``, ``parts``,
        ``tree``."""
        proj = cls()
        for fc in files:
            _scan_module(proj, fc.rel, fc.parts, fc.tree)
        proj._link()
        return proj

    # -- lookups -----------------------------------------------------------

    def module_for(self, rel: str) -> Optional[ModuleInfo]:
        return self._by_rel.get(rel)

    def function(self, fid: Optional[str]) -> Optional[FunctionInfo]:
        if fid is None:
            return None
        return self.functions.get(fid)

    def iter_functions(self) -> Iterable[FunctionInfo]:
        return self.functions.values()

    def canonical(self, modkey: Tuple[str, ...], name: str) -> str:
        """Resolve the head of a dotted name through the module's import
        aliases: ``th.Thread`` -> ``threading.Thread``.  Project-module
        targets render as ``a/b.symbol`` — a spelling that cannot
        collide with external dotted names."""
        mod = self.modules.get(modkey)
        if mod is None or not name:
            return name
        head, _, rest = name.partition(".")
        target = mod.imports.get(head)
        if target is None:
            return name
        if target[0] == "ext":
            return target[1] + ("." + rest if rest else "")
        modkey2, symbol = target[1], target[2]
        base = "/".join(modkey2) + (("." + symbol) if symbol else "")
        return base + ("." + rest if rest else "")

    # -- resolution --------------------------------------------------------

    def resolve_call(self, fn: FunctionInfo, name: str) -> Optional[str]:
        """Map a dotted call name inside ``fn`` to a function id, or
        None when the target is outside the linted set / dynamic."""
        if not name:
            return None
        parts = name.split(".")
        mod = self.modules.get(fn.modkey)
        if parts[0] == "self" and fn.cls and len(parts) == 2:
            return self._resolve_method(fn.modkey, fn.cls, parts[1])
        if parts[0] == "self" and fn.cls and len(parts) == 3:
            # self.attr.meth() through a ctor-typed attribute
            ctor = self._attr_ctor(fn, parts[1])
            if ctor is not None:
                key = self._class_key(fn.modkey, ctor)
                if key is not None:
                    return self._resolve_method(key[0], key[1], parts[2])
            return None
        if len(parts) == 2:
            # local.meth() through a ctor-typed local variable
            scope: Optional[FunctionInfo] = fn
            while scope is not None:
                if parts[0] in scope.local_ctors:
                    key = self._class_key(
                        fn.modkey, scope.local_ctors[parts[0]])
                    if key is not None:
                        return self._resolve_method(key[0], key[1],
                                                    parts[1])
                    break
                scope = self.functions.get(scope.parent) \
                    if scope.parent else None
        if len(parts) == 1:
            n = parts[0]
            scope: Optional[FunctionInfo] = fn
            while scope is not None:
                if n in scope.children:
                    return scope.children[n]
                scope = self.functions.get(scope.parent) \
                    if scope.parent else None
            if mod is not None:
                if n in mod.functions:
                    return mod.functions[n]
                if n in mod.classes:           # Class() -> __init__
                    return self._resolve_method(fn.modkey, n, "__init__")
                imp = mod.imports.get(n)
                if imp is not None and imp[0] == "proj" and imp[2]:
                    return self._resolve_in_module(imp[1], imp[2])
            return None
        if mod is None:
            return None
        imp = mod.imports.get(parts[0])
        if imp is not None and imp[0] == "proj":
            if imp[2] is None:
                # module alias: txverify.fn() / txverify.Class.meth()
                if len(parts) == 2:
                    return self._resolve_in_module(imp[1], parts[1])
                if len(parts) == 3:
                    return self._resolve_method(imp[1], parts[1], parts[2])
            elif len(parts) == 2:
                # from .mod import Class ; Class.meth(...)
                return self._resolve_method(imp[1], imp[2], parts[1])
        if parts[0] in mod.classes and len(parts) == 2:
            return self._resolve_method(fn.modkey, parts[0], parts[1])
        return None

    def _attr_ctor(self, fn: FunctionInfo, attr: str) -> Optional[str]:
        seen: Set[Tuple[Tuple[str, ...], str]] = set()
        stack = [(fn.modkey, fn.cls or "")]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.classes.get(key)
            if ci is None:
                continue
            if attr in ci.attr_ctors:
                return ci.attr_ctors[attr]
            stack.extend((ci.modkey, b) for b in ci.bases)
        return None

    def _class_key(self, modkey: Tuple[str, ...],
                   ctor: str) -> Optional[Tuple[Tuple[str, ...], str]]:
        """Resolve a constructor name as written (``_Journal`` /
        ``mod.Cls``) to the (modkey, class-name) that defines it."""
        mod = self.modules.get(modkey)
        if mod is None:
            return None
        parts = ctor.split(".")
        if len(parts) == 1:
            if parts[0] in mod.classes:
                return (modkey, parts[0])
            imp = mod.imports.get(parts[0])
            if imp is not None and imp[0] == "proj" and imp[2]:
                tgt = self.modules.get(imp[1])
                if tgt is not None and imp[2] in tgt.classes:
                    return (imp[1], imp[2])
            return None
        if len(parts) == 2:
            imp = mod.imports.get(parts[0])
            if imp is not None and imp[0] == "proj" and imp[2] is None:
                tgt = self.modules.get(imp[1])
                if tgt is not None and parts[1] in tgt.classes:
                    return (imp[1], parts[1])
        return None

    def _resolve_in_module(self, modkey: Tuple[str, ...],
                           symbol: str) -> Optional[str]:
        mod = self.modules.get(modkey)
        if mod is None:
            return None
        if symbol in mod.functions:
            return mod.functions[symbol]
        if symbol in mod.classes:
            return self._resolve_method(modkey, symbol, "__init__")
        return None

    def _resolve_method(self, modkey: Tuple[str, ...], cls_name: str,
                        meth: str, _depth: int = 0) -> Optional[str]:
        if _depth > 8:
            return None
        ci = self.classes.get((modkey, cls_name))
        if ci is None:
            mod = self.modules.get(modkey)
            if mod is not None:
                imp = mod.imports.get(cls_name)
                if imp is not None and imp[0] == "proj" and imp[2]:
                    return self._resolve_method(imp[1], imp[2], meth,
                                                _depth + 1)
            return None
        if meth in ci.methods:
            return ci.methods[meth]
        for base in ci.bases:
            found = self._resolve_method(ci.modkey, base, meth, _depth + 1)
            if found is not None:
                return found
        return None

    def attr_type(self, fn: FunctionInfo,
                  desc: Tuple[str, ...]) -> Optional[str]:
        """Type tag for a descriptor: ("self", "_lock") via the
        enclosing class (by-name base walk), ("local", name) via a
        function-local constructor assignment."""
        if len(desc) == 2 and desc[0] == "self" and fn.cls:
            seen: Set[Tuple[Tuple[str, ...], str]] = set()
            stack = [(fn.modkey, fn.cls)]
            while stack:
                key = stack.pop()
                if key in seen:
                    continue
                seen.add(key)
                ci = self.classes.get(key)
                if ci is None:
                    continue
                if desc[1] in ci.attr_types:
                    return ci.attr_types[desc[1]]
                stack.extend((ci.modkey, b) for b in ci.bases)
            return None
        if len(desc) == 2 and desc[0] == "local":
            scope: Optional[FunctionInfo] = fn
            while scope is not None:
                if desc[1] in scope.local_types:
                    return scope.local_types[desc[1]]
                scope = self.functions.get(scope.parent) \
                    if scope.parent else None
        return None

    def class_of(self, fn: FunctionInfo) -> Optional[ClassInfo]:
        if fn.cls is None:
            return None
        return self.classes.get((fn.modkey, fn.cls))

    # -- linking & coloring ------------------------------------------------

    def _link(self) -> None:
        for fn in list(self.functions.values()):
            for call in fn.calls:
                call.target = self.resolve_call(fn, call.name)
            for spawn in fn.spawns:
                spawn.target = self.resolve_call(fn, spawn.target_name)
        self._color()

    def _color(self) -> None:
        work: List[str] = []
        for fn in self.functions.values():
            if fn.is_async:
                fn.colors.add(LOOP)
                work.append(fn.fid)
        for fn in self.functions.values():
            for spawn in fn.spawns:
                tgt = self.functions.get(spawn.target or "")
                if tgt is not None and THREAD not in tgt.colors:
                    tgt.colors.add(THREAD)
                    work.append(tgt.fid)
        # Propagate along plain call edges (caller color -> sync
        # callee).  Async callees are independently LOOP-seeded; spawn
        # boundaries were handled above and add only THREAD.
        while work:
            fid = work.pop()
            fn = self.functions[fid]
            for call in fn.calls:
                tgt = self.functions.get(call.target or "")
                if tgt is None or tgt.is_async:
                    continue
                added = fn.colors - tgt.colors
                if added:
                    tgt.colors |= added
                    work.append(tgt.fid)


# ---------------------------------------------------------------------------
# Per-module scanning
# ---------------------------------------------------------------------------

def _module_key(parts: Tuple[str, ...]) -> Tuple[str, ...]:
    """("node", "app.py") -> ("node", "app"); packages drop __init__."""
    key = list(parts)
    if key and key[-1].endswith(".py"):
        key[-1] = key[-1][:-3]
    if key and key[-1] == "__init__":
        key = key[:-1]
    return tuple(key)


def _import_target(modkey: Tuple[str, ...], base: str, level: int,
                   symbol: Optional[str]) -> tuple:
    """Classify one import binding as project-internal (relative, or
    absolute under ``upow_tpu.``) or external."""
    if level > 0:
        pkg = list(modkey[:-1]) if modkey else []
        up = level - 1
        if up:
            pkg = pkg[: max(0, len(pkg) - up)]
        target = tuple(pkg) + tuple(p for p in base.split(".") if p)
        return ("proj", target, symbol)
    headparts = [p for p in base.split(".") if p]
    if headparts and headparts[0] == "upow_tpu":
        return ("proj", tuple(headparts[1:]), symbol)
    if symbol is None:
        return ("ext", base, None)
    return ("ext", (base + "." + symbol) if base else symbol, None)


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _callable_name(node: ast.AST) -> str:
    """Name of a callable handed to a spawn API; unwraps
    ``functools.partial(fn, ...)`` one level."""
    if isinstance(node, ast.Call):
        if _dotted(node.func).rsplit(".", 1)[-1] == "partial" and node.args:
            return _callable_name(node.args[0])
        return ""
    return _dotted(node)


def _scan_module(proj: ProjectContext, rel: str, parts: Tuple[str, ...],
                 tree: ast.Module) -> None:
    key = _module_key(parts)
    mod = ModuleInfo(rel=rel, key=key)
    proj.modules[key] = mod
    proj._by_rel[rel] = mod
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    mod.imports[alias.asname] = _import_target(
                        key, alias.name, 0, None)
                else:
                    head = alias.name.split(".")[0]
                    mod.imports.setdefault(
                        head, _import_target(key, head, 0, None))
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                mod.imports[local] = _import_target(
                    key, node.module or "", node.level, alias.name)
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(proj, mod, stmt, prefix="", cls=None, parent=None)
        elif isinstance(stmt, ast.ClassDef):
            _scan_class(proj, mod, stmt)


def _scan_class(proj: ProjectContext, mod: ModuleInfo,
                node: ast.ClassDef) -> None:
    ci = ClassInfo(name=node.name, rel=mod.rel, modkey=mod.key,
                   bases=[_dotted(b).split(".")[-1]
                          for b in node.bases if _dotted(b)])
    mod.classes[node.name] = ci
    proj.classes[(mod.key, node.name)] = ci
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ci.methods[stmt.name] = _scan_function(
                proj, mod, stmt, prefix=node.name + ".", cls=node.name,
                parent=None, classinfo=ci)


def _scan_function(proj: ProjectContext, mod: ModuleInfo, node,
                   prefix: str, cls: Optional[str], parent: Optional[str],
                   classinfo: Optional[ClassInfo] = None) -> str:
    qualname = prefix + node.name
    fid = f"{mod.rel}::{qualname}"
    info = FunctionInfo(
        fid=fid, rel=mod.rel, modkey=mod.key, name=node.name,
        qualname=qualname, cls=cls,
        is_async=isinstance(node, ast.AsyncFunctionDef),
        lineno=node.lineno, col=node.col_offset, parent=parent)
    proj.functions[fid] = info
    if parent is None and cls is None:
        mod.functions.setdefault(node.name, fid)

    lock_stack: List[Tuple[str, ...]] = []
    nested: List[ast.AST] = []

    def descriptor(expr: ast.AST) -> Optional[Tuple[str, ...]]:
        name = _dotted(expr)
        if not name:
            return None
        dparts = name.split(".")
        if dparts[0] == "self" and len(dparts) == 2:
            return ("self", dparts[1])
        if len(dparts) == 1:
            return ("local", dparts[0])
        return ("name", name)

    # pre-pass: awaited calls and statement-expression calls by node id
    awaited: Set[int] = set()
    stmt_calls: Set[int] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Await) and isinstance(sub.value, ast.Call):
            awaited.add(id(sub.value))
        if isinstance(sub, ast.Expr) and isinstance(sub.value, ast.Call):
            stmt_calls.add(id(sub.value))

    def visit(n: ast.AST) -> None:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested.append(n)
            return
        if isinstance(n, (ast.Lambda, ast.ClassDef)):
            return
        if isinstance(n, ast.Await) and lock_stack and info.is_async:
            for lock in list(lock_stack):
                info.held_awaits.append(HeldAwait(
                    lock=lock, lineno=n.lineno, col=n.col_offset))
        if isinstance(n, ast.With):
            pushed = 0
            for item in n.items:
                if isinstance(item.context_expr, ast.Call):
                    visit(item.context_expr)
                else:
                    desc = descriptor(item.context_expr)
                    if desc is not None:
                        lock_stack.append(desc)
                        pushed += 1
            for child in n.body:
                visit(child)
            for _ in range(pushed):
                lock_stack.pop()
            return
        if isinstance(n, ast.Call):
            name = _dotted(n.func)
            if name:
                canon = proj.canonical(mod.key, name)
                info.calls.append(CallSite(
                    name=name, canon=canon, lineno=n.lineno,
                    col=n.col_offset, awaited=id(n) in awaited,
                    is_stmt=id(n) in stmt_calls, node=n))
                _spawns_from_call(proj, info, n, name, canon)
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            _record_writes(proj, mod, classinfo, info, n,
                           tuple(lock_stack))
        for child in ast.iter_child_nodes(n):
            visit(child)

    for child in node.body:
        visit(child)

    for sub in nested:
        child_fid = _scan_function(
            proj, mod, sub, prefix=qualname + ".<locals>.", cls=cls,
            parent=fid, classinfo=classinfo)
        info.children[sub.name] = child_fid
    return fid


def _spawns_from_call(proj: ProjectContext, info: FunctionInfo,
                      call: ast.Call, name: str, canon: str) -> None:
    arg_pos = None
    api = None
    if canon in SPAWN_APIS and canon != "submit":
        api, arg_pos = canon, SPAWN_APIS[canon]
    else:
        last = name.rsplit(".", 1)[-1]
        if last in ("boxed_call", "run_boxed", "submit_call",
                    "run_in_executor"):
            api, arg_pos = last, SPAWN_APIS[last]
        elif last == "submit" and "." in name:
            # executor.submit(fn) — only when the receiver is typed
            recv = name.rsplit(".", 1)[0]
            rparts = recv.split(".")
            desc = None
            if rparts[0] == "self" and len(rparts) == 2:
                desc = ("self", rparts[1])
            elif len(rparts) == 1:
                desc = ("local", rparts[0])
            if desc is not None and \
                    proj.attr_type(info, desc) == "executor":
                api, arg_pos = "submit", 0
    if api is None:
        return
    target_expr = None
    if arg_pos == "target":
        for kw in call.keywords:
            if kw.arg == "target":
                target_expr = kw.value
    elif isinstance(arg_pos, int) and len(call.args) > arg_pos:
        target_expr = call.args[arg_pos]
    if target_expr is None:
        return
    tname = _callable_name(target_expr)
    if tname:
        info.spawns.append(SpawnSite(api=api, target_name=tname,
                                     lineno=call.lineno,
                                     col=call.col_offset))


def _record_writes(proj: ProjectContext, mod: ModuleInfo,
                   classinfo: Optional[ClassInfo], info: FunctionInfo,
                   node, guards: Tuple[Tuple[str, ...], ...]) -> None:
    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
    value = node.value
    for tgt in targets:
        elts = list(tgt.elts) if isinstance(tgt, ast.Tuple) else [tgt]
        for t in elts:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and classinfo is not None:
                classinfo.attr_writes.append(AttrWrite(
                    attr=t.attr, fid=info.fid, lineno=t.lineno,
                    col=t.col_offset, guards=guards,
                    in_init=info.name in ("__init__", "__post_init__")))
                if isinstance(value, ast.Call):
                    ctor = _dotted(value.func)
                    canon = proj.canonical(mod.key, ctor)
                    tag = ATTR_CTORS.get(canon)
                    if tag is not None:
                        classinfo.attr_types.setdefault(t.attr, tag)
                    elif ctor:
                        classinfo.attr_ctors.setdefault(t.attr, ctor)
            elif isinstance(t, ast.Name) and isinstance(value, ast.Call):
                ctor = _dotted(value.func)
                canon = proj.canonical(mod.key, ctor)
                tag = ATTR_CTORS.get(canon)
                if tag is not None:
                    info.local_types.setdefault(t.id, tag)
                elif ctor:
                    info.local_ctors.setdefault(t.id, ctor)
