"""CLI entry point: ``python -m upow_tpu.lint [paths ...]``."""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .engine import run_lint
from .rules import ALL_RULES


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m upow_tpu.lint",
        description="upowlint: consensus-safety & JAX-purity static analysis")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the upow_tpu package)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json includes suppressed findings)")
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids or family prefixes to run "
             "(e.g. CE001,JP001 or RC)")
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="known-findings file (from --write-baseline); matching "
             "findings are reported as baselined and do not gate the "
             "exit code")
    parser.add_argument(
        "--write-baseline", default=None, metavar="FILE",
        help="record every current finding's fingerprint to FILE and "
             "exit 0 (see docs/STATIC_ANALYSIS.md, baseline workflow)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.rule_id}  [{rule.severity:7s}]  {rule.description}")
        return 0

    paths = args.paths or [str(Path(__file__).resolve().parent.parent)]
    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}

    baseline = None
    if args.baseline:
        try:
            with open(args.baseline, "r", encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"upowlint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        baseline = data.get("fingerprints", data) \
            if isinstance(data, dict) else {}

    result = run_lint(paths, select=select, baseline=baseline)

    if args.write_baseline:
        payload = {
            "version": 1,
            "select": sorted(select) if select else None,
            "fingerprints": result.fingerprint_counts,
        }
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"upowlint: baseline with "
              f"{sum(result.fingerprint_counts.values())} finding(s) "
              f"written to {args.write_baseline}")
        return 0

    print(result.to_json() if args.format == "json" else result.to_text())
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
